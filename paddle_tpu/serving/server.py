"""Threaded TCP/JSON serving front (line-JSON, ``master/rpc.py`` idiom).

One request per line: ``{"method": ..., "params": {...}}`` ->
``{"result": ...}`` | ``{"error": ...}``. Deliberately dependency-free
(socketserver), mirroring how the master's RPC spawns a real server in
tests and drives a client against it. Methods:

* ``predict`` — params ``{"feeds": {name: {"data": nested-list,
  "dtype": "float32"} | nested-list}, "deadline_ms": remaining-budget}``;
  arrays include the leading batch dim. The handler submits to the
  micro-batcher and blocks THAT connection thread on the future
  (socketserver gives one thread per connection), so slow requests never
  stall the accept loop. Every failure answers with a TYPED structured
  error (errors.py wire codes): ``rejected`` (queue_full / shedding /
  draining — retryable), ``unavailable`` (transient fault — retryable),
  ``deadline_exceeded`` (terminal). ``deadline_ms`` is a RELATIVE budget
  (client and server clocks are never compared); the server pins it to its
  own monotonic clock on receipt and the batcher sheds the request at
  coalesce time if it expires before dispatch.
* ``healthz`` — liveness + model identity + the health state machine:
  ``healthy`` / ``degraded`` (queue or recent-error pressure; degraded
  servers shed probabilistically) / ``draining`` (graceful shutdown).
* ``stats`` — ``ServingStats.snapshot()`` merged with compile-cache,
  queue, health, and weights-version gauges.
* ``reload`` — hot weight reload from a re-exported inference dir
  (``ServingEngine.reload_params``): zero-downtime atomic swap.

``close()`` is a graceful drain by default: stop taking new predicts
(answer ``draining``), serve everything already queued, resolve in-flight
futures, then tear the listener down. ``install_signal_handlers()`` wires
SIGTERM/SIGINT to that same path.
"""
from __future__ import annotations

import json
import random
import signal
import socket
import socketserver
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .batcher import MicroBatcher
from .engine import ServingEngine
from .errors import (DeadlineExceeded, LoadShedError, RetryBudgetExceeded,
                     ServingError, ServingRejected, ServingUnavailable,
                     ShuttingDown, error_from_wire, error_info)
from .stats import ServingStats


def _decode_feed(name: str, spec) -> np.ndarray:
    if isinstance(spec, dict):
        return np.asarray(spec["data"], dtype=spec.get("dtype"))
    return np.asarray(spec)


def _encode_fetch(arr: np.ndarray) -> Dict[str, Any]:
    arr = np.asarray(arr)
    return {"data": arr.tolist(), "shape": list(arr.shape),
            "dtype": str(arr.dtype)}


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        while True:
            line = self.rfile.readline()
            if not line:
                return
            srv: "ServingServer" = self.server  # type: ignore[assignment]
            if srv.chaos is not None and getattr(srv.chaos, "partitioned",
                                                 False):
                # fleet chaos: this replica is network-partitioned — hang
                # up without answering ANY request (data or scrape)
                return
            if line[:4] in (b"GET ", b"HEAD"):
                # a Prometheus scraper (or curl) talking plain HTTP on the
                # line-JSON port: answer GET /metrics | /healthz and close
                self._http(srv, line)
                return
            try:
                req = json.loads(line.decode())
                method = req["method"]
                params = req.get("params") or {}
                if method == "predict":
                    if srv.chaos is not None and srv.chaos.drop_connection():
                        return  # injected fault: hang up without answering
                    resp = self._predict(srv, params)
                elif method == "generate":
                    if srv.chaos is not None and srv.chaos.drop_connection():
                        return
                    resp = self._generate(srv, params)
                elif method == "healthz":
                    resp = {"result": srv.healthz()}
                elif method == "stats":
                    resp = {"result": srv.stats_snapshot()}
                elif method == "metrics":
                    resp = {"result": {"text": srv.metrics_text()}}
                elif method == "reload":
                    resp = {"result": srv.reload(params["dirname"])}
                else:
                    raise ValueError(f"unknown method {method!r}")
            except Exception as e:  # report, keep serving
                resp = {"error": f"{type(e).__name__}: {e}"}
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()

    def _http(self, srv: "ServingServer", request_line: bytes) -> None:
        """Minimal HTTP/1.0 responder so /metrics is scrape-able without a
        second listener. Drains the request headers, answers, hangs up."""
        try:
            path = request_line.split()[1].decode(errors="replace")
        except IndexError:
            path = "/"
        while True:  # consume headers up to the blank line
            h = self.rfile.readline()
            if not h or h in (b"\r\n", b"\n"):
                break
        if path.split("?", 1)[0] == "/metrics":
            status, ctype = "200 OK", "text/plain; version=0.0.4; charset=utf-8"
            body = srv.metrics_text().encode()
        elif path.split("?", 1)[0] == "/healthz":
            status, ctype = "200 OK", "application/json"
            body = (json.dumps(srv.healthz()) + "\n").encode()
        else:
            status, ctype = "404 Not Found", "text/plain"
            body = b"not found\n"
        self.wfile.write(
            (f"HTTP/1.0 {status}\r\nContent-Type: {ctype}\r\n"
             f"Content-Length: {len(body)}\r\n"
             f"Connection: close\r\n\r\n").encode() + body)
        self.wfile.flush()

    @staticmethod
    def _predict(srv: "ServingServer", params: Dict) -> Dict:
        # shed BEFORE decode/validate work: a draining or overloaded server
        # answers in O(1), it does not burn CPU on requests it won't serve
        state = srv.health_state()
        if state == "draining":
            return {"error": ShuttingDown("server draining").info()}
        if state == "degraded" and srv.should_shed():
            srv.stats.record_shed()
            if srv._events.enabled:
                srv._events.emit("load_shed", severity="warn",
                                 endpoint=srv.endpoint, state=state,
                                 queue_depth=srv.batcher.queue_depth)
            return {"error": LoadShedError(
                state, srv.batcher.queue_depth,
                srv.batcher.queue_capacity).info()}
        feeds = {n: _decode_feed(n, spec)
                 for n, spec in params.get("feeds", {}).items()}
        deadline = None
        wait = srv.request_timeout
        deadline_ms = params.get("deadline_ms")
        if deadline_ms is not None:
            # relative budget -> THIS host's monotonic clock; never compare
            # client and server wall clocks
            deadline = time.monotonic() + float(deadline_ms) / 1e3
            # the future resolves with DeadlineExceeded at coalesce time;
            # the +1s slack means a typed answer beats the handler timeout
            wait = min(wait, float(deadline_ms) / 1e3 + 1.0)
        # trace-id propagation (docs/design.md §15): "trace": true asks the
        # server to mint an id; a string is the CLIENT's id and rides every
        # span + the response, so client and server timelines correlate
        trace = params.get("trace")
        trace_id = None
        if trace:
            from ..obs import new_trace_id

            trace_id = trace if isinstance(trace, str) else new_trace_id()
        try:
            fut = srv.batcher.submit(feeds, deadline=deadline,
                                     trace_id=trace_id)
            outs = fut.result(timeout=wait)
        except ServingError as e:
            # error_info, not e.info(): a re-raised ServingRejected (dict
            # property, see errors.py) must not TypeError the handler
            return {"error": error_info(e)}
        except FuturesTimeout:
            # the handler gave up waiting before the batcher resolved the
            # future (e.g. a multi-second compile ahead of it) — still a
            # TYPED answer: terminal deadline_exceeded ONLY when the
            # client's deadline really passed (wait may have been capped
            # by request_timeout instead), else a retryable unavailable
            # (inference is stateless, a duplicate dispatch is safe)
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                e = DeadlineExceeded(now - deadline, "server wait")
            else:
                e = ServingUnavailable(
                    f"request timed out after {wait:.1f}s server-side")
            return {"error": e.info()}
        if srv.capture_every:
            with srv._capture_lock:
                srv._capture_n += 1
                take = srv._capture_n % srv.capture_every == 0
            if take:
                req = getattr(fut, "request", None)
                srv._flight.capture_predict(
                    srv.engine.dirname, feeds, outs,
                    weights_version=getattr(req, "weights_version", None),
                    trace_id=trace_id)
        result: Dict[str, Any] = {
            "fetches": [_encode_fetch(o) for o in outs]}
        if trace_id is not None:
            req = getattr(fut, "request", None)
            # copy defensively: the completion thread owns this dict
            timings = dict(getattr(req, "timings", None) or {})
            result["trace"] = {
                "trace_id": trace_id,
                "stages_ms": {k: v * 1e3 for k, v in timings.items()}}
        return {"result": result}

    @staticmethod
    def _generate(srv: "ServingServer", params: Dict) -> Dict:
        """Autoregressive generation over the decode engine (continuous
        batching: the request joins the in-flight batch at the next token
        boundary). Same edge behavior as predict: O(1) shed while
        draining/degraded, relative deadline pinned to this host's clock,
        typed structured errors."""
        if srv.gen_batcher is None:
            return {"error": f"ValueError: this server was built without "
                             f"decode serving (pass decode=... to "
                             f"ServingServer)"}
        state = srv.health_state()
        if state == "draining":
            return {"error": ShuttingDown("server draining").info()}
        if state == "degraded" and srv.should_shed():
            srv.stats.record_shed()
            if srv._events.enabled:
                srv._events.emit("load_shed", severity="warn",
                                 endpoint=srv.endpoint, state=state,
                                 plane="decode")
            return {"error": LoadShedError(
                state, srv.gen_batcher.queue_depth,
                srv.gen_batcher.queue_capacity).info()}
        tokens = np.asarray(params.get("tokens", []), np.int64)
        deadline = None
        wait = srv.request_timeout
        deadline_ms = params.get("deadline_ms")
        if deadline_ms is not None:
            deadline = time.monotonic() + float(deadline_ms) / 1e3
            wait = min(wait, float(deadline_ms) / 1e3 + 1.0)
        trace = params.get("trace")
        trace_id = None
        if trace:
            from ..obs import new_trace_id

            trace_id = trace if isinstance(trace, str) else new_trace_id()
        try:
            fut = srv.gen_batcher.submit(
                tokens,
                max_new_tokens=params.get("max_new_tokens"),
                eos_id=params.get("eos_id"),
                deadline=deadline, trace_id=trace_id,
                temperature=float(params.get("temperature", 0.0)),
                top_k=int(params.get("top_k", 0)),
                top_p=float(params.get("top_p", 1.0)),
                seed=params.get("seed"),
                logprobs=bool(params.get("logprobs", False)))
            res = fut.result(timeout=wait)
        except ServingError as e:
            return {"error": error_info(e)}
        except FuturesTimeout:
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                e = DeadlineExceeded(now - deadline, "server wait")
            else:
                e = ServingUnavailable(
                    f"generation timed out after {wait:.1f}s server-side")
            return {"error": e.info()}
        if srv.capture_every:
            with srv._capture_lock:
                srv._gen_capture_n += 1
                take = srv._gen_capture_n % srv.capture_every == 0
            if take:
                srv._flight.capture_generate(
                    srv.decode_engine.dirname, tokens,
                    params.get("max_new_tokens"), params.get("eos_id"),
                    res.tokens, weights_version=res.weights_version,
                    trace_id=trace_id)
        result: Dict[str, Any] = {
            "tokens": [int(t) for t in res.tokens],
            "ttft_ms": res.ttft_s * 1e3,
            "finish_reason": res.finish_reason,
            "weights_version": res.weights_version,
        }
        if res.logprobs is not None:
            result["logprobs"] = [float(x) for x in res.logprobs]
        if trace_id is not None:
            result["trace"] = {"trace_id": trace_id}
        return {"result": result}


class ServingServer(socketserver.ThreadingTCPServer):
    """Dynamic-batching model server. ``with ServingServer(model_dir) as s:
    s.endpoint`` — serves on background threads until ``close()``."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, model: Any, host: str = "127.0.0.1", port: int = 0,
                 max_batch_size: Optional[int] = None,
                 batch_timeout_ms: float = 5.0,
                 queue_capacity: int = 64, request_timeout: float = 60.0,
                 warmup: bool = False, stats: Optional[ServingStats] = None,
                 start_batcher: bool = True, pipeline_depth: int = 2,
                 degraded_queue_ratio: float = 0.75,
                 degraded_error_ratio: float = 0.5,
                 health_window_s: float = 5.0,
                 shed_prob: Optional[float] = None, shed_seed: int = 0,
                 drain_timeout: float = 30.0, chaos=None,
                 handle_signals: bool = False, decode=None, mesh=None,
                 log_json: bool = False, capture_every: int = 0,
                 quantize=None, **engine_kwargs):
        super().__init__((host, port), _Handler)
        self.batcher = None
        self.decode_engine = None
        self.gen_batcher = None
        try:
            # weight-only quantized serving (serving/quant.py, docs §20):
            # None falls back to the serving_quantize flag; "auto" adopts
            # the export's measured cpu_tuned.json (perf_lab cpu writes it
            # only on a >5% closed-loop win); "int8"/"bf16" force the mode
            from ..flags import get_flag
            from .quant import adopt_tuned, resolve_quantize

            # memory ledger (obs/mem.py, docs §28): arm from flags BEFORE
            # any engine builds — weight stores and KV pools register at
            # engine construction
            from ..obs.mem import init_from_flags as mem_from_flags

            mem_from_flags()
            if quantize is None:
                # the flag is a fleet-wide default for dirname-built
                # servers ONLY: a prebuilt engine (possibly already
                # quantized) must keep working with the flag set
                quantize = (get_flag("serving_quantize") or None) \
                    if isinstance(model, str) else None
            if quantize and not isinstance(model, str):
                raise ValueError(
                    "quantize= quantizes the exported dir's weight store "
                    "(pass the model dirname, or prebuild a "
                    "QuantizedServingEngine without quantize=)")
            if quantize == "auto" and isinstance(model, str):
                # full adoption of the measured config: thread shaping is
                # applied by adopt_tuned; the tuned bucket cap lands here
                # unless the caller pinned one explicitly
                tuned = adopt_tuned(model)
                if tuned and max_batch_size is None \
                        and tuned.get("max_batch_size"):
                    max_batch_size = int(tuned["max_batch_size"])
            self.quant_mode = resolve_quantize(
                model if isinstance(model, str) else None, quantize)
            # mesh (docs/design.md §18): span ONE model over dp*tp devices.
            # int N = {"dp": 1, "tp": N} (the one-model-across-N-chips
            # headline); a dict names both axes; a PlacementPlan carries a
            # searcher choice (its dp/tp are used and the plan rides the
            # engine for comm attribution).
            self.mesh_spec = None
            if mesh is not None:
                from .placement import PlacementPlan
                from .sharded import ShardedServingEngine

                plan = None
                if isinstance(mesh, PlacementPlan):
                    plan, mesh = mesh, {"dp": mesh.dp, "tp": mesh.tp}
                if isinstance(mesh, int):
                    mesh = {"dp": 1, "tp": mesh}
                unknown = set(mesh) - {"dp", "tp"}
                if unknown:
                    raise ValueError(f"unknown mesh axes {sorted(unknown)} "
                                     f"(serving meshes are dp x tp)")
                self.mesh_spec = {"dp": int(mesh.get("dp", 1)),
                                  "tp": int(mesh.get("tp", 1))}
                if not isinstance(model, str):
                    raise ValueError(
                        "mesh= builds a ShardedServingEngine from the "
                        "exported dir (pass the model dirname, or pass a "
                        "prebuilt ShardedServingEngine without mesh=)")
                self._mesh_model_dir = model
                model = ShardedServingEngine(
                    model, dp=self.mesh_spec["dp"],
                    tp=self.mesh_spec["tp"], plan=plan,
                    quantize=self.quant_mode,
                    max_batch_size=engine_kwargs.pop("max_batch_size",
                                                     None)
                    or max_batch_size or 32, **engine_kwargs)
                engine_kwargs = {}
            elif self.quant_mode is not None:
                from .quant import QuantizedServingEngine

                self._mesh_model_dir = model  # decode= still needs the dir
                model = QuantizedServingEngine(
                    model, mode=self.quant_mode,
                    max_batch_size=engine_kwargs.pop("max_batch_size",
                                                     None)
                    or max_batch_size or 32, **engine_kwargs)
                engine_kwargs = {}
            if isinstance(model, ServingEngine):
                if engine_kwargs:
                    raise ValueError(
                        f"engine kwargs {sorted(engine_kwargs)} have no "
                        f"effect on a prebuilt ServingEngine — pass them to "
                        f"its constructor")
                self.engine = model
                # follow the engine's ladder unless explicitly capped lower
                batcher_max = (self.engine.max_batch_size
                               if max_batch_size is None else
                               min(max_batch_size,
                                   self.engine.max_batch_size))
            else:
                self.engine = ServingEngine(
                    model, max_batch_size=max_batch_size or 32,
                    **engine_kwargs)
                batcher_max = self.engine.max_batch_size
            self.stats = stats or ServingStats(qps_window_s=health_window_s)
            # start_batcher=False accepts (and queues) traffic without
            # serving it — pre-fill before opening, deterministic
            # backpressure tests
            self.batcher = MicroBatcher(
                self.engine, max_batch_size=batcher_max,
                batch_timeout_ms=batch_timeout_ms,
                queue_capacity=queue_capacity,
                stats=self.stats, pipeline_depth=pipeline_depth,
                start=start_batcher)
            # decode serving (docs/design.md §16): ``decode`` arms the
            # generation path next to one-shot predict. True = defaults;
            # a dict carries DecodeEngine/GenerationBatcher knobs
            # (max_slots, kv_buckets, prefill_chunk, gen_queue_capacity,
            # default_max_new_tokens, pipeline_depth, scheduler); a
            # prebuilt DecodeEngine is taken as-is.
            self.decode_engine = None
            self.gen_batcher = None
            # truthiness would read decode={} ("all defaults") as OFF and
            # surface only at the first generate() call — arm on anything
            # but the explicit not-armed spellings
            if decode is not None and decode is not False:
                from .decode import DecodeEngine, GenerationBatcher

                dcfg = dict(decode) if isinstance(decode, dict) else {}
                if isinstance(decode, DecodeEngine):
                    self.decode_engine = decode
                else:
                    decode_dir = model if isinstance(model, str) else \
                        getattr(self, "_mesh_model_dir", None)
                    if not isinstance(decode_dir, str):
                        raise ValueError(
                            "decode serving needs the exported dir (pass "
                            "the model dirname, or decode=DecodeEngine)")
                    dknobs = dict(
                        max_slots=dcfg.pop("max_slots", None),
                        max_len=dcfg.pop("max_len", None),
                        kv_buckets=dcfg.pop("kv_buckets", None),
                        prefill_chunk=dcfg.pop("prefill_chunk", None))
                    # paged KV pool + radix prefix cache (docs §22):
                    # "paged": True arms it; the page knobs imply it
                    page_knobs = {k: dcfg.pop(k) for k in
                                  ("page_len", "pool_pages", "overcommit",
                                   "evict_watermark", "prefix_cache")
                                  if k in dcfg}
                    paged = bool(dcfg.pop("paged", False)) or bool(page_knobs)
                    if paged:
                        dknobs.update(page_knobs)
                    if self.mesh_spec and self.mesh_spec["tp"] > 1:
                        # decode rides the tp axis only: the slot pool IS
                        # the batch; its dp story is fleet replicas (§18)
                        if paged:
                            from .kvcache import ShardedPagedDecodeEngine \
                                as _Dec
                        else:
                            from .sharded import ShardedDecodeEngine as _Dec
                        self.decode_engine = _Dec(
                            decode_dir, tp=self.mesh_spec["tp"],
                            quantize=self.quant_mode, **dknobs)
                    elif self.quant_mode is not None:
                        if paged:
                            from .kvcache import QuantizedPagedDecodeEngine \
                                as _Dec
                        else:
                            from .quant import QuantizedDecodeEngine as _Dec
                        self.decode_engine = _Dec(
                            decode_dir, mode=self.quant_mode, **dknobs)
                    elif paged:
                        from .kvcache import PagedDecodeEngine

                        self.decode_engine = PagedDecodeEngine(decode_dir,
                                                               **dknobs)
                    else:
                        self.decode_engine = DecodeEngine(decode_dir,
                                                          **dknobs)
                # speculative decoding (docs/design.md §25): "spec_draft"
                # names the draft export dir, "spec_k" the propose depth
                spec = None
                spec_draft = dcfg.pop("spec_draft", None)
                spec_k = dcfg.pop("spec_k", 4)
                spec_adaptive = dcfg.pop("spec_adaptive", True)
                if spec_draft:
                    from .spec import SpecDecoder

                    spec = SpecDecoder(spec_draft, k=int(spec_k),
                                       adaptive=bool(spec_adaptive))
                self.gen_batcher = GenerationBatcher(
                    self.decode_engine,
                    queue_capacity=dcfg.pop("gen_queue_capacity",
                                            queue_capacity),
                    stats=self.stats,
                    scheduler=dcfg.pop("scheduler", None),
                    pipeline_depth=dcfg.pop("pipeline_depth",
                                            pipeline_depth),
                    default_max_new_tokens=dcfg.pop(
                        "default_max_new_tokens", 64),
                    spec=spec,
                    start=start_batcher)
                if dcfg:
                    raise ValueError(f"unknown decode knobs {sorted(dcfg)}")
            self.request_timeout = request_timeout
            # observability plumbing: honor PT_FLAG_OBS_TRACE, and register
            # pull-gauges into the stats registry so GET /metrics carries
            # queue/pipeline/compile/weights state without push traffic
            from ..obs import init_from_flags
            from ..obs.events import (enable_json_logging, get_event_log,
                                      init_from_flags as events_from_flags)

            init_from_flags()
            events_from_flags()  # PT_FLAG_OBS_EVENTS turns the black box on
            # goodput accounting (docs §23): flag-armed, bound to THIS
            # server's stats registry so GET /metrics carries
            # pt_goodput_ratio / pt_badput_seconds_total{category} per
            # replica (scraped_gauges rolls them up fleet-wide); the
            # batchers' default process accountant is rebound here
            from ..flags import get_flag as _get_flag
            from ..obs.goodput import GoodputAccountant

            self.accountant = None
            if _get_flag("obs_goodput"):
                self.accountant = GoodputAccountant(
                    registry=self.stats.registry).enable()
                self.batcher.accountant = self.accountant
                if self.gen_batcher is not None:
                    self.gen_batcher.accountant = self.accountant
            # memory ledger (docs §28): pt_mem_* pull gauges on THIS
            # server's /metrics page (scraped_gauges rolls occupancy /
            # unattributed bytes / kv share fleet-wide)
            from ..obs.mem import get_ledger as _get_mem_ledger

            self._mem_ledger = _get_mem_ledger()
            if self._mem_ledger.enabled:
                self._mem_ledger.export_gauges(self.stats.registry)
            if log_json:
                # structured-logging bridge: every event (health
                # transitions, sheds, reload commits, faults) becomes one
                # JSON line through stdlib logging — faults were silently
                # counted before, now they are grep-able
                enable_json_logging()
            self._events = get_event_log()
            self._last_health = "healthy"
            self._health_lock = threading.Lock()
            # sampled request capture for the flight recorder (docs §19):
            # 1-in-N successful predicts/generates land in the bundle with
            # enough state (inputs, bucket signature, seed, weights
            # version) to replay bit-identically
            self.capture_every = max(0, int(capture_every))
            self._capture_n = 0
            self._gen_capture_n = 0
            self._capture_lock = threading.Lock()
            from ..obs import flight as obs_flight

            self._flight = obs_flight.get_recorder()
            self._flight_provider = None  # named after the port binds
            # sharded engine: the §18 shard plane — shard count scales the
            # MFU denominator (gauges AGGREGATE across the mesh; a fleet
            # router must not read shard 0 only), per-device HBM residency
            # is published per shard, and the engine attributes its
            # collective time into this stats object per dispatch
            from .sharded import ShardedServingEngine as _Sharded

            if isinstance(self.engine, _Sharded):
                if self.mesh_spec is None:  # prebuilt sharded engine
                    self.mesh_spec = {"dp": self.engine.dp,
                                      "tp": self.engine.tp}
                self.engine.stats = self.stats
                if self.decode_engine is not None and \
                        hasattr(self.decode_engine, "tp"):
                    # the sharded decode engine attributes its own
                    # gathers — a decode-only replica's collective
                    # instruments must move too
                    self.decode_engine.stats = self.stats
                self.stats.set_shard_count(self.engine.dp * self.engine.tp)
                plan = self.engine.plan
                cap = plan.inventory.hbm_bytes if plan is not None and \
                    plan.inventory is not None else None
                self.stats.set_shard_hbm(self.engine.shard_hbm_bytes(),
                                         capacity_bytes=cap)
            r = self.stats.registry
            r.gauge("pt_serving_queue_depth",
                    "Requests queued (incl. carry)",
                    callback=lambda: self.batcher.queue_depth)
            r.gauge("pt_serving_queue_capacity", "Bounded queue capacity",
                    callback=lambda: self.batcher.queue_capacity)
            r.gauge("pt_serving_in_flight",
                    "Batches dispatched but not completed",
                    callback=lambda: self.batcher.in_flight)
            r.gauge("pt_serving_pending",
                    "Accepted requests not yet resolved",
                    callback=lambda: self.batcher.pending)
            r.gauge("pt_serving_weights_version",
                    "Params version (bumped by hot reload)",
                    callback=lambda: self.engine.params_version)
            # quantized-serving surfaces (docs §20): mode encodes 0=f32 /
            # 1=int8 / 2=bf16 (quant.QUANT_MODE_GAUGE — scraped_gauges and
            # the paddle_cli fleet table decode it); bytes is the LIVE
            # resident weight store (predict + decode param sets), so a
            # quantized replica's 4x-smaller footprint is scrapeable
            from .quant import QUANT_MODE_GAUGE

            self.quant_mode = self.engine.quant_mode or self.quant_mode
            r.gauge("pt_serving_quant_mode",
                    "Weight-only quantization mode (0=f32 1=int8 2=bf16)",
                    callback=lambda: QUANT_MODE_GAUGE.get(
                        self.engine.quant_mode, 0.0))
            r.gauge("pt_serving_weights_bytes",
                    "Resident serving weight bytes (quantized store when "
                    "armed; decode params included)",
                    callback=lambda: float(
                        self.engine.weights_bytes()
                        + (self.decode_engine.weights_bytes()
                           if self.decode_engine is not None else 0)))
            r.gauge("pt_serving_compile_cache_hits",
                    "Serving compile-cache hits",
                    callback=lambda: self.engine.cache_hits)
            r.gauge("pt_serving_compile_cache_misses",
                    "Serving compile-cache misses (an XLA compile each)",
                    callback=lambda: self.engine.cache_misses)
            r.gauge("pt_serving_healthy",
                    "1 healthy / 0.5 degraded / 0 draining",
                    callback=lambda: {"healthy": 1.0, "degraded": 0.5,
                                      "draining": 0.0}[self.health_state()])
            if self.gen_batcher is not None:
                r.gauge("pt_serving_decode_queue_depth",
                        "Generations queued for a KV slot",
                        callback=lambda: self.gen_batcher.queue_depth)
                r.gauge("pt_serving_decode_pending",
                        "Accepted generations not yet resolved",
                        callback=lambda: self.gen_batcher.pending)
            if hasattr(self.decode_engine, "kv_pages_info"):
                # paged KV pool + prefix cache (docs §22): page states
                # feed capacity-aware routing, the hit gauges feed
                # session-affinity scoring (a replica already holding a
                # session's prefix serves its next turn cheapest)
                _eng = self.decode_engine
                kvg = r.gauge("pt_serving_kv_pages",
                              "Paged KV pool pages by state",
                              labelnames=("state",))
                for st in ("free", "active", "cached"):
                    kvg.labels(state=st).set_callback(
                        lambda s=st: _eng.kv_pages_info()[s])
                r.gauge("pt_serving_prefix_hits_total",
                        "Admissions that reused a cached prefix",
                        callback=lambda: _eng.prefix_hits)
                r.gauge("pt_serving_prefix_hit_tokens_total",
                        "Prompt tokens served from cached KV instead of "
                        "prefill",
                        callback=lambda: _eng.prefix_hit_tokens)
                r.gauge("pt_serving_prefix_hit_rate",
                        "prefix hits / prefix queries",
                        callback=lambda: (_eng.prefix_hits
                                          / _eng.prefix_queries
                                          if _eng.prefix_queries else 0.0))
            # health state machine + probabilistic load shedding
            self.degraded_queue_ratio = degraded_queue_ratio
            self.degraded_error_ratio = degraded_error_ratio
            # a caller-supplied stats object may retain less history than
            # the requested health window; judge over what actually exists
            self.health_window_s = min(health_window_s,
                                       self.stats.qps_window_s)
            self.shed_prob = shed_prob  # None = proportional to overload
            self._shed_rng = random.Random(shed_seed)
            self.drain_timeout = drain_timeout
            self._draining = False
            self._closed = False
            self._close_lock = threading.Lock()
            self._t0 = time.monotonic()
            if warmup:
                self.engine.warmup()
                if self.decode_engine is not None:
                    self.decode_engine.warmup()
                if (self.gen_batcher is not None
                        and self.gen_batcher.spec is not None):
                    self.gen_batcher.spec.warmup()
            # chaos hooks attach AFTER warmup: the ladder pre-compile is
            # deployment plumbing, not traffic the harness should fault
            self.chaos = chaos
            if chaos is not None:
                self.engine.chaos = chaos
                self.batcher.chaos = chaos
                if self.decode_engine is not None:
                    self.decode_engine.chaos = chaos
                    self.gen_batcher.chaos = chaos
        except Exception:
            # the port bound before setup failed: release it (and any live
            # batcher worker) instead of leaking until GC
            if getattr(self, "gen_batcher", None) is not None:
                self.gen_batcher.close(drain=False)
            if self.batcher is not None:
                self.batcher.close()
            self.server_close()
            raise
        if handle_signals:
            self.install_signal_handlers()
        # every bundle the flight recorder dumps carries this server's
        # identity, weights version, placement plan, and metric page
        self._flight_provider = self._flight.register_provider(
            f"serving:{self.endpoint}", self._flight_info)
        self._thread = threading.Thread(target=self.serve_forever, daemon=True)
        self._thread.start()

    def _flight_info(self) -> Dict[str, Any]:
        """Provider snapshot for postmortem bundles (obs/flight.py)."""
        info: Dict[str, Any] = {
            "endpoint": self.endpoint,
            "model_dir": self.engine.dirname,
            "health": self.health_state(),
            "weights_version": self.engine.params_version,
            "queue_depth": self.batcher.queue_depth,
            "queue_capacity": self.batcher.queue_capacity,
            "compile_cache": self.engine.cache_info(),
            "placement": self.mesh_spec,
            "metrics": self.stats.expose(),
        }
        if self.decode_engine is not None:
            info["decode_weights_version"] = self.decode_engine.params_version
        if self.accountant is not None:
            info["goodput"] = self.accountant.summary()
        return info

    @property
    def endpoint(self) -> str:
        host, port = self.server_address[:2]
        return f"{host}:{port}"

    # -- health state machine --
    def health_state(self) -> str:
        """``draining`` (shutdown in progress) > ``degraded`` (queue above
        the high-water mark, or the recent window is mostly rejects /
        failures / deadline misses) > ``healthy``. Window counters decay,
        so a server left alone after a fault burst RETURNS to healthy."""
        if self._draining:
            return self._note_health("draining")
        cap = self.batcher.queue_capacity
        if cap and self.batcher.queue_depth / cap >= self.degraded_queue_ratio:
            return self._note_health("degraded")
        w = self.health_window_s
        bad = (self.stats.recent("rejected", w)
               + self.stats.recent("failed", w)
               + self.stats.recent("deadline_exceeded", w))
        good = self.stats.recent("completed", w)
        if bad and bad >= self.degraded_error_ratio * (bad + good):
            return self._note_health("degraded")
        return self._note_health("healthy")

    def _note_health(self, state: str) -> str:
        """Emit a typed event on every health-state TRANSITION (the PR-2
        machine finally leaves a record; the counters alone could never
        say when it degraded). The compare-and-swap is locked — handler
        threads and scrapes call ``health_state()`` concurrently, and a
        transition must be emitted exactly once with the true ``frm``."""
        with self._health_lock:
            prev, self._last_health = self._last_health, state
            changed = prev != state
        if changed and self._events.enabled:
            self._events.emit("health_transition",
                              severity="warn" if state != "healthy"
                              else "info",
                              endpoint=self.endpoint, frm=prev, to=state)
        return state

    def shed_probability(self) -> float:
        """How aggressively a degraded server sheds: proportional to how
        far the queue is past the high-water mark, floor 0.25 when degraded
        by error rate alone. A FULL queue does not shed here — the submit
        path's ``QueueFullError`` is deterministic and carries the depth /
        capacity the client's operator wants. ``shed_prob`` overrides with
        a fixed value (deterministic tests)."""
        if self.shed_prob is not None:
            return self.shed_prob
        cap = self.batcher.queue_capacity
        ratio = self.batcher.queue_depth / cap if cap else 0.0
        thr = self.degraded_queue_ratio
        if ratio >= 1.0:
            return 0.0  # let QueueFullError speak
        if ratio >= thr and thr < 1.0:
            return min(0.9, max(0.25, (ratio - thr) / (1.0 - thr)))
        return 0.25

    def should_shed(self) -> bool:
        return self._shed_rng.random() < self.shed_probability()

    def healthz(self) -> Dict[str, Any]:
        state = self.health_state()
        h = {"ok": state != "draining", "state": state,
             "uptime_s": time.monotonic() - self._t0,
             "model_dir": self.engine.dirname,
             "feeds": list(self.engine.feed_names),
             "fetches": list(self.engine.fetch_names),
             "queue_depth": self.batcher.queue_depth,
             "queue_capacity": self.batcher.queue_capacity,
             "weights_version": self.engine.params_version,
             "quantize": self.engine.quant_mode or "f32"}
        if self.mesh_spec is not None:
            h["shards"] = {"dp": self.mesh_spec["dp"],
                           "tp": self.mesh_spec["tp"],
                           "devices": self.mesh_spec["dp"]
                           * self.mesh_spec["tp"]}
        if self.gen_batcher is not None:
            h["decode"] = {
                "max_slots": self.decode_engine.max_slots,
                "active_slots": self.decode_engine.active_slots,
                "queue_depth": self.gen_batcher.queue_depth,
                "weights_version": self.decode_engine.params_version}
            if hasattr(self.decode_engine, "kv_pages_info"):
                h["decode"]["kv_pages"] = self.decode_engine.kv_pages_info()
                h["decode"]["prefix"] = self.decode_engine.prefix_info()
        return h

    def metrics_text(self) -> str:
        """Prometheus text exposition (the ``GET /metrics`` body): the
        stats registry — counters, histograms, and the pull-gauges
        registered at construction."""
        return self.stats.expose()

    def stats_snapshot(self) -> Dict[str, Any]:
        extra = {
            "state": self.health_state(),
            "queue_depth": self.batcher.queue_depth,
            "queue_capacity": self.batcher.queue_capacity,
            "compile_cache": self.engine.cache_info(),
            "weights_version": self.engine.params_version,
            "pipeline_depth": self.batcher.pipeline_depth,
            "in_flight": self.batcher.in_flight,
            "quantize": self.engine.quant_mode or "f32",
            "weights_bytes": self.engine.weights_bytes(),
        }
        if self.mesh_spec is not None:
            extra["placement"] = {
                "dp": self.mesh_spec["dp"], "tp": self.mesh_spec["tp"],
                "collectives_per_dispatch":
                    self.engine.expected_collectives_per_dispatch,
                "shard_hbm_bytes": self.engine.shard_hbm_bytes()}
        if self.gen_batcher is not None:
            extra["decode_compile_cache"] = self.decode_engine.cache_info()
            extra["decode_queue_depth"] = self.gen_batcher.queue_depth
            if hasattr(self.decode_engine, "kv_pages_info"):
                extra["decode_kv_pages"] = self.decode_engine.kv_pages_info()
                extra["decode_prefix"] = self.decode_engine.prefix_info()
        if self.chaos is not None:
            extra["chaos"] = self.chaos.snapshot()
        if self.accountant is not None:
            # the goodput breakdown (docs §23): cumulative per-category
            # request-seconds + the live ratio — serve_bench prints this
            extra["goodput"] = self.accountant.summary()
        return self.stats.snapshot(extra=extra)

    # -- hot weight reload --
    def reload(self, dirname: str) -> Dict[str, Any]:
        """Swap serving weights from a re-exported dir; zero downtime (no
        request is rejected because of the reload — traffic keeps flowing
        on the old weights until the atomic swap). The swap happens at a
        clean pipeline boundary: ``flush()`` waits out any in-flight
        dispatches first, so every batch dispatched before the reload has
        fully completed on the old weights and every later one snapshots
        the new — per-dispatch atomicity (one params snapshot per batch)
        holds regardless; the barrier additionally pins the ORDER of
        weights versions across the pipeline. The SLOW half of the reload
        (disk read, validation, device_put) runs BEFORE the barrier with
        traffic flowing on the old weights; only the one-attribute-store
        commit runs inside it (microseconds of pause). If the pipeline
        fails to quiesce the reload is REFUSED with a retryable
        ``unavailable`` rather than swapping mid-flight."""
        if self._events.enabled:
            self._events.emit("reload_stage", endpoint=self.endpoint,
                              dirname=dirname)
        staged = self.engine.stage_params(dirname)  # slow; traffic flows
        swapped: Dict[str, int] = {}

        def _swap():
            swapped["version"] = self.engine.commit_params(staged)

        if not self.batcher.flush(then=_swap):
            raise ServingUnavailable(
                "reload: dispatch pipeline did not quiesce within the "
                "barrier timeout — retry")
        self.stats.record_reload()
        if self._events.enabled:
            self._events.emit("reload_commit", endpoint=self.endpoint,
                              version=swapped["version"])
        out = {"weights_version": swapped["version"]}
        if self.gen_batcher is not None:
            # decode reloads at its own barrier — a token boundary with no
            # generation in flight, so every generation stays wholly on
            # the version pinned at its admission (ServingUnavailable if
            # the barrier cannot clear; the one-shot swap above stands —
            # the two engines version independently)
            out["decode_weights_version"] = self.gen_batcher.reload(
                dirname, record=False)  # one RPC = one counted reload
        return out

    # -- graceful shutdown --
    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop accepting new predicts (they answer ``draining``) and wait
        until every accepted request has been answered. True = fully
        drained within the timeout."""
        self._draining = True
        deadline = time.monotonic() + (
            self.drain_timeout if timeout is None else timeout)
        while time.monotonic() < deadline:
            if self.batcher.queue_depth == 0 and self.batcher.pending == 0 \
                    and (self.gen_batcher is None
                         or self.gen_batcher.pending == 0):
                return True
            time.sleep(0.005)
        return False

    def close(self, drain: bool = True, timeout: Optional[float] = None):
        """Graceful by default: reject new work, drain the queue, answer
        in-flight requests, then stop the listener. ``drain=False`` skips
        the wait (queued requests resolve with ``ShuttingDown``)."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        if self._flight_provider is not None:
            self._flight.unregister_provider(self._flight_provider)
        self._draining = True
        if drain:
            self.drain(timeout)
        if self.gen_batcher is not None:
            # in-flight generations finish (drain=True) or resolve typed
            self.gen_batcher.close(drain=drain)
        self.batcher.close()  # serves anything still queued, then stops
        self.shutdown()
        self.server_close()
        # memory-ledger hygiene (leak gate c): a closed replica's stores
        # drop off the ledger — remove_replica(drain=True) returns the
        # fleet's attributed bytes to baseline
        for eng in (self.engine, self.decode_engine):
            release = getattr(eng, "_mem_release", None)
            if release is not None:
                release()

    def install_signal_handlers(self, signals=(signal.SIGTERM, signal.SIGINT)):
        """SIGTERM/SIGINT -> graceful drain + close. Main thread only (a
        CPython constraint on signal.signal)."""
        for s in signals:
            signal.signal(s, self._on_signal)

    def _on_signal(self, signum, frame):
        # never block inside a signal handler: drain on a worker thread
        threading.Thread(target=self.close, daemon=True,
                         name="paddle-tpu-serving-drain").start()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class ServingClient:
    """Blocking line-JSON client (``master/rpc.py`` MasterRPCClient shape)
    with typed errors, deadlines, and budget-capped retry.

    ``predict`` returns one np.ndarray per fetch target. Failures are
    TYPED: a structured backpressure answer raises ``ServingRejected``
    (retryable), a transient server fault ``ServingUnavailable``
    (retryable), a missed deadline ``DeadlineExceeded`` (terminal), server
    bugs ``RuntimeError`` (terminal), transport faults
    ``ConnectionError``/``OSError`` (retryable; the next attempt
    reconnects automatically).

    With ``retries > 0``, retryable errors are retried with exponential
    backoff + full jitter (seeded via ``retry_seed`` for determinism) up
    to the budget; exhaustion raises the terminal ``RetryBudgetExceeded``
    carrying the last underlying error — nothing is ever swallowed.
    ``predict(..., timeout_ms=...)`` attaches a deadline that rides the
    wire (the server sheds the request if it expires before dispatch) and
    also caps the retry loop client-side.
    """

    def __init__(self, endpoint: str, timeout: float = 60.0,
                 retries: int = 0, backoff_base_ms: float = 20.0,
                 backoff_max_ms: float = 2000.0,
                 retry_seed: Optional[int] = None):
        host, port = endpoint.rsplit(":", 1)
        self.addr: Tuple[str, int] = (host, int(port))
        self.timeout = timeout
        self.retries = int(retries)
        self.backoff_base_s = backoff_base_ms / 1e3
        self.backoff_max_s = backoff_max_ms / 1e3
        self._rng = random.Random(retry_seed)
        self.retries_total = 0  # lifetime retry count (serve_bench reports)
        self.close_errors = 0  # OSErrors discarded while closing the socket
        self.last_trace: Optional[Dict[str, Any]] = None  # predict(trace=)
        self._deadline: Optional[float] = None  # remaining_deadline_ms()
        self._sock: Optional[socket.socket] = None
        self._file = None
        self._lock = threading.Lock()

    def _connect(self):
        self._sock = socket.create_connection(self.addr, timeout=self.timeout)
        self._file = self._sock.makefile("rwb")

    def call(self, method: str, params: Optional[Dict] = None) -> Any:
        """One attempt, no retry: the raw RPC with typed error mapping."""
        with self._lock:
            try:
                if self._sock is None:
                    self._connect()
                self._file.write(
                    (json.dumps({"method": method, "params": params or {}})
                     + "\n").encode())
                self._file.flush()
                line = self._file.readline()
            except OSError:
                self.close()
                raise
            if not line:
                self.close()
                raise ConnectionError("serving server closed connection")
            resp = json.loads(line.decode())
            if "error" in resp:
                err = resp["error"]
                if isinstance(err, dict):
                    raise error_from_wire(err)
                raise RuntimeError(f"serving error: {err}")
            return resp["result"]

    def call_with_retries(self, method: str, params: Optional[Dict] = None,
                          deadline: Optional[float] = None,
                          attempt: int = 0) -> Any:
        """``call`` under the retry budget. ``deadline`` (absolute
        monotonic seconds) rides each attempt as a fresh remaining-budget
        ``deadline_ms`` and bounds the backoff sleeps.

        ``attempt`` is the number of retry-budget units ALREADY consumed
        upstream (a fleet router supplies its running failover count):
        the attempts counter starts there, so router-side and
        client-side budgets COMPOSE into one shared budget instead of
        multiplying — with ``retries=B``, a call entering at
        ``attempt=k`` has ``B - k`` retries left, and the hop count
        rides the wire as the ``attempt`` param (docs/design.md §17)."""
        attempts = int(attempt)
        delay = self.backoff_base_s
        base_params = params
        self._deadline = deadline
        while True:
            params = dict(base_params or {})
            if attempts:
                params["attempt"] = attempts
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise DeadlineExceeded(-remaining, "client send")
                params["deadline_ms"] = remaining * 1e3
            try:
                return self.call(method, params)
            except (ServingError, OSError) as e:
                retryable = getattr(e, "retryable", True)  # OSError: yes
                if not retryable:
                    raise
                if attempts >= self.retries:
                    if self.retries == 0:
                        raise  # no retry layer engaged: the raw typed error
                    raise RetryBudgetExceeded(attempts + 1, e) from e
                attempts += 1
                self.retries_total += 1
                sleep = self._rng.uniform(0, delay)  # full jitter
                if deadline is not None:
                    sleep = min(sleep, max(0.0, deadline - time.monotonic()))
                time.sleep(sleep)
                # init_from_flags, not get_accountant: a client process
                # has no server/trainer to honor obs_goodput for it
                from ..obs.goodput import init_from_flags as _goodput_flags

                acct = _goodput_flags()
                if acct.enabled:
                    # caller-side badput: seconds this request spent
                    # sleeping between attempts (docs §23 retry_backoff)
                    acct.account_retry_backoff(sleep)
                delay = min(delay * 2, self.backoff_max_s)

    def remaining_deadline_ms(self) -> Optional[float]:
        """Milliseconds left on the deadline of the current / most recent
        deadline-carrying call (``None`` if it carried none). A router
        failing a request over to another replica consults this to budget
        the retry-from-scratch attempt with what the CALLER has left,
        not a fresh timeout."""
        d = self._deadline
        if d is None:
            return None
        return max(0.0, (d - time.monotonic()) * 1e3)

    def predict(self, feeds: Dict[str, Any],
                timeout_ms: Optional[float] = None,
                trace=False, attempt: int = 0) -> List[np.ndarray]:
        """``trace=True`` mints a trace id client-side (a string passes
        YOUR id); the id rides the wire, tags every server-side span, and
        the per-stage timings come back on ``self.last_trace``
        (``{"trace_id": ..., "stages_ms": {stage: ms}}``) — the return
        value stays one np.ndarray per fetch either way. ``attempt`` is
        the upstream-consumed retry count (see ``call_with_retries``)."""
        from ..obs import new_trace_id

        enc = {}
        for n, v in feeds.items():
            arr = np.asarray(v)
            enc[n] = {"data": arr.tolist(), "dtype": str(arr.dtype)}
        params: Dict[str, Any] = {"feeds": enc}
        if trace:
            params["trace"] = trace if isinstance(trace, str) \
                else new_trace_id()
        deadline = (time.monotonic() + timeout_ms / 1e3
                    if timeout_ms is not None else None)
        result = self.call_with_retries("predict", params, deadline=deadline,
                                        attempt=attempt)
        self.last_trace = result.get("trace") if trace else None
        return [np.asarray(f["data"], dtype=f["dtype"]).reshape(f["shape"])
                for f in result["fetches"]]

    def generate(self, tokens, max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 trace=False, attempt: int = 0,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: Optional[int] = None,
                 logprobs: bool = False) -> Dict[str, Any]:
        """Autoregressive generation on a decode-enabled server. Returns
        ``{"tokens": [...], "ttft_ms": float, "finish_reason":
        "eos"|"budget"|"pool-edge"|"deadline", "weights_version": int}``
        (plus ``"logprobs"`` when requested). ``temperature=0`` is greedy
        (bit-identical to the argmax path); ``temperature>0`` samples
        under the per-request top-k/top-p policy, deterministic per
        ``(tokens, seed)`` whatever else the server is running. Same
        deadline/retry semantics as ``predict`` (a failed generation is
        retryable: no state outlives the request's KV slot)."""
        params: Dict[str, Any] = {
            "tokens": [int(t) for t in np.asarray(tokens).reshape(-1)]}
        if max_new_tokens is not None:
            params["max_new_tokens"] = int(max_new_tokens)
        if eos_id is not None:
            params["eos_id"] = int(eos_id)
        if temperature:
            params["temperature"] = float(temperature)
        if top_k:
            params["top_k"] = int(top_k)
        if top_p != 1.0:
            params["top_p"] = float(top_p)
        if seed is not None:
            params["seed"] = int(seed)
        if logprobs:
            params["logprobs"] = True
        if trace:
            from ..obs import new_trace_id

            params["trace"] = trace if isinstance(trace, str) \
                else new_trace_id()
        deadline = (time.monotonic() + timeout_ms / 1e3
                    if timeout_ms is not None else None)
        result = self.call_with_retries("generate", params,
                                        deadline=deadline, attempt=attempt)
        self.last_trace = result.get("trace") if trace else None
        return result

    def healthz(self) -> Dict[str, Any]:
        return self.call("healthz")

    def stats(self) -> Dict[str, Any]:
        return self.call("stats")

    def metrics(self) -> str:
        """Prometheus text exposition over the line-JSON protocol (the
        HTTP-speaking sibling is ``GET /metrics`` on the same port)."""
        return self.call("metrics")["text"]

    def reload(self, dirname: str) -> Dict[str, Any]:
        """Hot-swap the server's weights from a re-exported inference dir."""
        return self.call("reload", {"dirname": dirname})

    def close(self):
        f, s = self._file, self._sock
        self._file = None
        self._sock = None
        for obj in (f, s):
            if obj is None:
                continue
            try:
                obj.close()
            except OSError:
                # the transport is already dead; a close failure carries no
                # further signal — counted, never silently swallowed
                self.close_errors += 1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

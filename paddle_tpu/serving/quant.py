"""Weight-only quantized serving + the CPU tuning lane (docs/design.md §20).

Every published CPU number before this module ran an untuned f32 backend.
This module gives serving an opt-in quantized weight store and makes the
CPU lane's configuration a *measured* choice, the PR-4 autotune discipline
applied to serving:

* **Weight-only quantization** — ``quantize_export(dirname, mode)`` walks
  a frozen ``transformer_lm`` inference export (``decode_roles``, the one
  IR walk the decode/sharded/placement tiers already share) and quantizes
  every fc/matmul/fused-QKV weight (``QUANT_ROLES``): per-output-channel
  symmetric int8 (one f32 scale per column, ``{"q", "s"}`` leaves) or bf16
  storage. Activations, layer norms, biases, the position table, and the
  decode KV pools stay f32. The matmul kernel (``ops/quant.dequant_matmul``)
  dequantizes on the fly with f32 accumulation; the per-channel scale
  folds into the convert pass the dot operand materializes anyway
  (weight-side — an output-epilogue scale FMA-fuses into following adds
  in layout-dependent ways and breaks cross-layout bit-equality, see the
  kernel's docstring).
* **Accuracy contract** — ``calibrate_error`` reports the max-abs logit
  error and the greedy-token (top-1) agreement of the quantized forward
  against the f32 reference on calibration feeds; ``quantize_export``
  refuses with a typed ``QuantizationError`` when agreement falls below
  the floor, so the lane is opt-in-safe: a model whose greedy streams the
  int8 grid would change cannot be quantized by accident.
* **Engines** — ``QuantizedServingEngine`` / ``QuantizedDecodeEngine``
  drop into the unchanged MicroBatcher / GenerationBatcher /
  ServingServer stack. Hot reload stages the NEW export through the same
  quantizer, so scales and quantized ints validate and swap together in
  the ONE reference store every dispatch snapshots — wholly-old-or-
  wholly-new now includes the scales. The sharded engines
  (serving/sharded.py ``quantize=``) shard ``q`` by the same column
  blocks as the f32 layout and the scale vector by the matching output
  blocks, so the bit-safety argument is preserved *within* the quantized
  lane: no contraction ever splits, dp2×tp2 int8 equals single-device
  int8 bit-for-bit.
* **CPU tuning** — ``apply_cpu_flags`` shapes the XLA CPU thread pool /
  process affinity (must run pre-jax-init; ``flags.cpu_threads`` /
  ``flags.cpu_pin``), and ``tools/perf_lab.py cpu`` sweeps threads ×
  quant mode × bucket ladder in subprocesses, writing ``cpu_tuned.json``
  next to the export ONLY on a measured >5% closed-loop win
  (``ADOPTION_MIN_WIN``). ``ServingServer(quantize="auto")`` adopts what
  the sweep proved (``resolve_quantize``) and serves f32 otherwise —
  measurement decides, never hope. On hosts whose XLA build has no int8
  GEMM (dequant runs through convert + the f32 dot), the sweep typically
  adopts f32; the quantized lane still buys 4x smaller resident weights,
  which is what flips must-shard models to single-chip in the placement
  searcher (serving/placement.py ``ModelProfile.quantize``).
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .engine import InFlightBatch, ServingEngine, _flat_items
from .decode import DecodeEngine

QUANT_MODES = ("int8", "bf16")

#: decode-pytree roles that quantize: every fc/matmul/fused-QKV weight of
#: the transformer (plus the embedding table — its gathered rows dequant
#: per lookup). Layer norms, biases, and the position table stay f32: they
#: are O(D) where the weights are O(D^2), and their error would ride every
#: activation.
QUANT_ROLES = frozenset({"emb", "wq", "wk", "wv", "wqkv", "wo",
                         "wup", "wdown", "out_w"})

#: default greedy-token agreement floor quantize_export refuses below
DEFAULT_AGREEMENT_FLOOR = 0.999

#: a tuned CPU config is adopted only when its closed-loop QPS beats the
#: untuned f32 baseline by at least this much (the PR-4 >5% autotune bar)
ADOPTION_MIN_WIN = 0.05

#: filename of the tuned-config sidecar perf_lab writes next to an export
TUNED_CONFIG_NAME = "cpu_tuned.json"

#: pt_serving_quant_mode gauge encoding (fleet table / scraped_gauges)
QUANT_MODE_GAUGE = {None: 0.0, "": 0.0, "f32": 0.0, "int8": 1.0, "bf16": 2.0}
QUANT_MODE_NAMES = {0: "f32", 1: "int8", 2: "bf16"}


class QuantizationError(ValueError):
    """Typed refusal of the accuracy contract: the quantized forward's
    greedy-token agreement against the f32 reference fell below the floor.
    Carries the measured numbers so the operator sees how far off the
    grid landed."""

    def __init__(self, mode: str, agreement: float, floor: float,
                 max_abs_err: float):
        self.mode = mode
        self.agreement = float(agreement)
        self.floor = float(floor)
        self.max_abs_err = float(max_abs_err)
        super().__init__(
            f"weight-only {mode} quantization refused: greedy-token "
            f"agreement {agreement:.4f} below the {floor:.4f} floor "
            f"(max abs logit error {max_abs_err:.3e}) — the quantized "
            f"lane would change served tokens")


def _check_mode(mode: str) -> str:
    if mode not in QUANT_MODES:
        raise ValueError(f"unknown quantization mode {mode!r}; "
                         f"known: {QUANT_MODES}")
    return mode


# ---------------------------------------------------------------------------
# quantization of host weights
# ---------------------------------------------------------------------------


def quantize_weight(w, mode: str):
    """One weight -> its quantized leaf.

    ``int8``: per-OUTPUT-channel symmetric — scale[j] = max|w[:, j]| / 127,
    q = clip(rint(w / scale), ±127) int8; returns ``{"q": int8, "s": f32}``.
    The round-trip error is bounded elementwise by ``scale/2`` (tested).
    ``bf16``: plain bf16 storage (the convert is the dequant; no scale).
    """
    import ml_dtypes

    _check_mode(mode)
    w = np.asarray(w)
    if mode == "bf16":
        return w.astype(ml_dtypes.bfloat16)
    reduce_axes = tuple(range(w.ndim - 1))  # all but the output channel
    scale = np.abs(w).max(axis=reduce_axes) / 127.0
    scale = np.where(scale == 0.0, 1.0, scale).astype(np.float32)
    q = np.clip(np.rint(w.astype(np.float32) / scale), -127, 127) \
        .astype(np.int8)
    return {"q": q, "s": scale}


def dequantize_weight(leaf) -> np.ndarray:
    """Quantized leaf -> its f32 reconstruction (tests/error analysis —
    the serving path never materializes this)."""
    if isinstance(leaf, dict):
        return leaf["q"].astype(np.float32) * leaf["s"]
    return np.asarray(leaf).astype(np.float32)


def is_quantized_leaf(leaf) -> bool:
    import ml_dtypes

    return isinstance(leaf, dict) or (
        hasattr(leaf, "dtype")
        and leaf.dtype in (np.dtype(ml_dtypes.bfloat16), np.int8))


def quantize_params(host_params: Dict[str, Any], mode: str) -> Dict[str, Any]:
    """Decode-roles host pytree -> the same tree with QUANT_ROLES leaves
    quantized (idempotent: an already-quantized tree passes through)."""
    _check_mode(mode)

    def leaf(role, v):
        if role in QUANT_ROLES and not is_quantized_leaf(v):
            return quantize_weight(v, mode)
        return v if is_quantized_leaf(v) else np.asarray(v)

    out = {k: leaf(k, v) for k, v in host_params.items() if k != "layers"}
    out["layers"] = [{k: leaf(k, v) for k, v in lp.items()}
                     for lp in host_params["layers"]]
    return out


def is_quantized_params(params: Dict[str, Any]) -> bool:
    return any(is_quantized_leaf(leaf) for _p, leaf in _flat_items(params))


def param_bytes(params: Dict[str, Any]) -> int:
    """Total leaf bytes of a (possibly quantized) params pytree."""
    return int(sum(int(getattr(leaf, "nbytes", 0))
                   for _p, leaf in _flat_items(params)))


# ---------------------------------------------------------------------------
# export loading + the accuracy contract
# ---------------------------------------------------------------------------


def _load_host(dirname: str):
    """(roles, cfg, host_params, feed_len) of a transformer_lm export."""
    from .. import io as model_io
    from ..core.executor import Scope
    from ..models.transformer import decode_params_from_scope, decode_roles

    scope = Scope()
    program, feed_names, _fetch = model_io.load_inference_model(
        dirname, None, scope=scope)
    roles, cfg = decode_roles(program)
    host = decode_params_from_scope(roles, scope)
    feed_len = None
    var = program.global_block().find_var_recursive(feed_names[0])
    if var is not None and var.shape is not None and len(var.shape) > 1 \
            and var.shape[1] not in (None, -1):
        feed_len = int(var.shape[1])
    return roles, cfg, host, feed_len


def _calibration_ids(cfg: Dict[str, Any], feeds, feed_len: Optional[int],
                     sample_rows: int, seed: int) -> np.ndarray:
    if feeds is not None:
        if isinstance(feeds, dict):
            if len(feeds) != 1:
                raise ValueError(f"calibration feeds want the one ids "
                                 f"feed, got {sorted(feeds)}")
            feeds = next(iter(feeds.values()))
        ids = np.asarray(feeds)
        if ids.ndim != 2:
            raise ValueError(f"calibration ids must be [rows, T], got "
                             f"shape {ids.shape}")
        return ids.astype(np.int32)
    rng = np.random.RandomState(seed)
    t = feed_len or cfg["max_len"]
    return rng.randint(0, cfg["vocab"], (sample_rows, t)).astype(np.int32)


def _compare_forwards(cfg, host, qparams, ids) -> Dict[str, Any]:
    """f32 vs quantized whole-sequence logits on the SAME pure-jax forward
    (models/transformer.predict_forward — bit-identical to the exported IR
    program on f32 leaves, tested in tests/test_serving_sharded.py)."""
    import jax

    from ..models.transformer import predict_forward

    fwd = jax.jit(functools.partial(predict_forward, cfg=cfg))
    ref = np.asarray(fwd(host, ids))
    qlog = np.asarray(fwd(qparams, ids))
    agree = float(np.mean(
        np.argmax(ref, axis=-1) == np.argmax(qlog, axis=-1)))
    err = np.abs(qlog - ref)
    return {
        "positions": int(ref.shape[0] * ref.shape[1]),
        "max_abs_logit_err": float(err.max()),
        "mean_abs_logit_err": float(err.mean()),
        "token_agreement": agree,
        "top1_agreement": agree,  # greedy token IS the top-1 logit
    }


def calibrate_error(dirname: str, feeds=None, mode: str = "int8",
                    sample_rows: int = 8, seed: int = 0) -> Dict[str, Any]:
    """The accuracy contract's measurement: quantize ``dirname``'s weights
    at ``mode`` and report max-abs/mean-abs logit error plus greedy-token
    (top-1) agreement against the f32 forward on ``feeds`` (a ``[rows,
    T]`` ids array / one-entry feed dict; synthesized from the export's
    declared shape when omitted)."""
    _check_mode(mode)
    _roles, cfg, host, feed_len = _load_host(dirname)
    ids = _calibration_ids(cfg, feeds, feed_len, sample_rows, seed)
    rep = _compare_forwards(cfg, host, quantize_params(host, mode), ids)
    rep["mode"] = mode
    return rep


class QuantizedStore:
    """What ``quantize_export`` hands back: the quantized host pytree plus
    everything the engines and the placement accountant need — roles, cfg,
    per-mode byte sizes, and the calibration report (when run)."""

    __slots__ = ("dirname", "mode", "roles", "cfg", "params",
                 "weights_bytes", "f32_bytes", "calibration")

    def __init__(self, dirname, mode, roles, cfg, params, weights_bytes,
                 f32_bytes, calibration=None):
        self.dirname = dirname
        self.mode = mode
        self.roles = roles
        self.cfg = cfg
        self.params = params
        self.weights_bytes = int(weights_bytes)
        self.f32_bytes = int(f32_bytes)
        self.calibration = calibration


def quantize_export(dirname: str, mode: str = "int8",
                    calibration_feeds=None,
                    agreement_floor: float = DEFAULT_AGREEMENT_FLOOR,
                    calibrate: bool = True,
                    sample_rows: int = 8, seed: int = 0) -> QuantizedStore:
    """Quantize a frozen inference export's weights for serving.

    With ``calibrate`` (the default), the quantized forward is judged
    against the f32 reference on ``calibration_feeds`` (synthesized when
    omitted) and the export is REFUSED with a typed ``QuantizationError``
    when greedy-token agreement falls below ``agreement_floor`` — the
    opt-in-safe contract: served tokens must not change. ``calibrate=
    False`` skips the forward passes (the engines use it after the
    operator's export has already passed the gate once)."""
    _check_mode(mode)
    _roles, cfg, host, feed_len = _load_host(dirname)
    qparams = quantize_params(host, mode)
    store = QuantizedStore(dirname, mode, _roles, cfg, qparams,
                           weights_bytes=param_bytes(qparams),
                           f32_bytes=param_bytes(host))
    if calibrate:
        ids = _calibration_ids(cfg, calibration_feeds, feed_len,
                               sample_rows, seed)
        rep = _compare_forwards(cfg, host, qparams, ids)
        rep["mode"] = mode
        store.calibration = rep
        if rep["token_agreement"] < agreement_floor:
            raise QuantizationError(mode, rep["token_agreement"],
                                    agreement_floor,
                                    rep["max_abs_logit_err"])
    return store


# ---------------------------------------------------------------------------
# engines
# ---------------------------------------------------------------------------


def quantized_mem_detail(params) -> Dict[str, int]:
    """q/s/f32 byte split of a quantized param store — the memory
    ledger's lazy ``detail`` callback for quantized weight entries
    (obs/mem.py): the int store and its per-channel scales are
    accounted separately in snapshots and OOM bundles."""
    from .engine import _flat_items

    out = {"q_bytes": 0, "s_bytes": 0, "f32_bytes": 0}
    for path, leaf in _flat_items(params):
        nb = int(getattr(leaf, "nbytes", 0))
        if path.endswith(".q"):
            out["q_bytes"] += nb
        elif path.endswith(".s"):
            out["s_bytes"] += nb
        else:
            out["f32_bytes"] += nb
    return out


class QuantizedServingEngine(ServingEngine):
    """One-shot predict over a weight-only quantized param store — a
    drop-in ``ServingEngine`` whose compiled step is
    ``models/transformer.predict_forward`` over quantized leaves (the same
    pure-jax forward the sharded engines run; its f32 branch is
    bit-identical to the exported IR program, so the ONLY difference
    f32-vs-quantized A/Bs measure is the quantization itself).

    The export must be a ``transformer_lm`` logits export — quantization
    recovers the weight roles from the IR (``decode_roles``) and will not
    guess at an arbitrary program. The bucket ladder, LRU compile cache,
    warmup, and chaos hooks are inherited unchanged; ``reload_params``
    re-quantizes the new export at the frozen mode, so every dispatch
    snapshots a wholly-old-or-wholly-new (weights AND scales) store."""

    def __init__(self, dirname: str, mode: str = "int8", place=None, **kw):
        self.quant_mode = _check_mode(mode)
        super().__init__(dirname, place=place, **kw)
        if len(self.feed_names) != 1 or len(self.fetch_names) != 1:
            raise ValueError(
                f"quantized serving wants the transformer_lm logits export "
                f"(one ids feed, one logits fetch), got feeds="
                f"{list(self.feed_names)} fetches={list(self.fetch_names)}")
        if not self.fetch_per_row[self.fetch_names[0]]:
            raise ValueError("quantized serving: the fetch must be per-row "
                             "(the [N, T, V] logits)")

    # -- load: roles walk + quantize + device placement --
    def _load_params(self):
        import jax

        from ..models.transformer import decode_params_from_scope, \
            decode_roles

        self.roles, self.cfg = decode_roles(self.program)
        host = decode_params_from_scope(self.roles, self.scope)
        qhost = quantize_params(host, self.quant_mode)
        with jax.default_device(self._device):
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(a, self._device), qhost)

    def _mem_weights_detail(self):
        with self._lock:
            params = self._params
        return quantized_mem_detail(params)

    # -- compile cache: predict_forward over the quantized store --
    def _make_fn(self, sig: Tuple):
        import jax

        from ..models.transformer import predict_forward

        return jax.jit(functools.partial(predict_forward, cfg=self.cfg))

    def _annotate_cost(self, fn, sig: Tuple):
        from ..flags import get_flag

        if not get_flag("obs_cost_analysis"):
            return None, None
        try:
            from ..obs import cost as obs_cost

            with self._lock:
                params = self._params
            avals = obs_cost.abstractify(params)
            feed_aval = obs_cost.abstractify(
                np.zeros(sig[0][1], np.dtype(sig[0][2])))
            res = obs_cost.analyze_jit(fn, avals, feed_aval)
            return res["flops"], res["bytes"]
        except Exception:
            return None, None

    def dispatch_prepared(self, feeds: Dict[str, np.ndarray], rows: int):
        import jax

        bucket = self.bucket_batch(rows)
        if bucket != rows:
            feeds = {n: np.concatenate(
                [a, np.zeros((bucket - rows,) + a.shape[1:], a.dtype)])
                for n, a in feeds.items()}
        sig = tuple((n, feeds[n].shape, str(feeds[n].dtype))
                    for n in self.feed_names)
        entry = self._get_fn(sig)
        if self.chaos is not None:
            self.chaos.on_dispatch()
        with self._lock:  # one consistent (params, version) snapshot:
            params = self._params  # ints and scales swap as ONE reference
            version = self.params_version
        cold = entry.cold
        t_call = time.monotonic() if cold else 0.0
        with jax.default_device(self._device):
            ids = jax.device_put(feeds[self.feed_names[0]], self._device)
            logits = entry.fn(params, ids)
        if cold:
            entry.compile_s = time.monotonic() - t_call
            entry.cold = False
            from ..obs import get_tracer

            tr = get_tracer()
            if tr.enabled:
                tr.add_span("serving/compile", t_call, entry.compile_s,
                            cat="compile",
                            args={"bucket": bucket,
                                  "quantize": self.quant_mode,
                                  "flops": entry.flops})
        return InFlightBatch([logits], rows, bucket, version,
                             flops=entry.flops)

    # -- hot reload: re-quantize the new export at the frozen mode --
    def stage_params(self, dirname: str) -> Dict[str, Any]:
        """Reload staging through the quantizer (decode.stage_decode_params
        — the one shared validator): the staged set re-quantizes at the
        frozen mode BEFORE the flat validation, so the comparison covers
        the ``.q``/``.s`` paths alike and a reload can never swap ints
        without their scales (or vice versa)."""
        import jax

        from .decode import stage_decode_params

        staged = stage_decode_params(
            self, dirname, lambda host: quantize_params(host,
                                                        self.quant_mode))
        with jax.default_device(self._device):
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(a, self._device), staged)


class QuantizedDecodeEngine(DecodeEngine):
    """Decode serving over a quantized param store: the slot-pooled KV
    cache stays f32 and UNTOUCHED (quantizing the pool would change the
    attention math mid-stream); only the weight contractions dequantize on
    the fly. ``GenerationBatcher`` — continuous batching, deadlines,
    drain, the token-boundary reload barrier — runs on top unchanged, and
    steady-state decode still compiles nothing (the same cache-counter
    contract, tested)."""

    def __init__(self, dirname: str, mode: str = "int8", **kw):
        self.quant_mode = _check_mode(mode)
        super().__init__(dirname, **kw)

    def _device_put_params(self, host_params):
        if not is_quantized_params(host_params):
            host_params = quantize_params(host_params, self.quant_mode)
        return super()._device_put_params(host_params)

    def _mem_weights_detail(self):
        with self._lock:
            params = self._params
        return quantized_mem_detail(params)

    def _stage_transform(self, staged: Dict[str, Any]) -> Dict[str, Any]:
        # reload staging through the quantizer: the staged set quantizes
        # at the frozen mode BEFORE the flat validation, so the comparison
        # covers scales and ints alike, and the commit (one reference
        # store at the batcher's token boundary) swaps them together
        return quantize_params(staged, self.quant_mode)


# ---------------------------------------------------------------------------
# CPU lane: thread-pool shaping + the measured tuned config
# ---------------------------------------------------------------------------


def apply_cpu_flags(threads: Optional[int] = None,
                    pin: Optional[bool] = None) -> bool:
    """Best-effort XLA CPU thread/affinity shaping from ``flags.cpu_threads``
    / ``flags.cpu_pin`` (or explicit arguments). Two mechanisms with
    different windows:

    * **process CPU affinity** (``threads >= 1`` or ``pin``): applies
      IMMEDIATELY and caps the cores every thread pool — Eigen included —
      can actually run on, so it works even after jax is up;
    * **XLA_FLAGS** ``--xla_cpu_multi_thread_eigen=false`` (``threads ==
      1``): read once at CPU backend creation, so it only lands while no
      jax computation has run yet (importing paddle_tpu imports jax, but
      the backend initializes lazily at first use). The perf_lab sweep
      runs each config in a fresh subprocess for exactly this reason.

    Returns True when the XLA_FLAGS path could still take effect (no
    backend initialized yet), False when only the affinity applied."""
    from ..flags import get_flag

    threads = int(get_flag("cpu_threads")) if threads is None else int(threads)
    pin = bool(get_flag("cpu_pin")) if pin is None else bool(pin)
    xb = sys.modules.get("jax._src.xla_bridge")
    pre_init = not (xb is not None and getattr(xb, "_backends", None))
    if threads == 1 and pre_init:
        xf = os.environ.get("XLA_FLAGS", "")
        if "xla_cpu_multi_thread_eigen" not in xf:
            os.environ["XLA_FLAGS"] = \
                (xf + " --xla_cpu_multi_thread_eigen=false").strip()
    if (pin or threads >= 1) and hasattr(os, "sched_setaffinity"):
        want = threads if threads > 0 else (os.cpu_count() or 1)
        try:
            have = sorted(os.sched_getaffinity(0))
            os.sched_setaffinity(0, set(have[:max(1, want)]))
        except OSError:
            pass  # containers may forbid affinity changes; best effort
    return pre_init


def tuned_config_path(dirname: str) -> str:
    return os.path.join(dirname, TUNED_CONFIG_NAME)


def write_tuned_config(dirname: str, config: Dict[str, Any]) -> str:
    """Persist a measured CPU serving config next to the export (the
    perf_lab cpu sweep's output — only written on a >5% closed-loop win,
    so the file's existence IS the adoption decision)."""
    cfg = dict(config)
    cfg.setdefault("schema", 1)
    cfg.setdefault("written_by", "tools/perf_lab.py cpu")
    path = tuned_config_path(dirname)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(cfg, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_tuned_config(dirname: str) -> Optional[Dict[str, Any]]:
    path = tuned_config_path(dirname)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def resolve_quantize(dirname: Optional[str], spec) -> Optional[str]:
    """Normalize a ``quantize=`` spelling to a mode or None.

    ``None``/``""``/``"f32"`` = off; ``"int8"``/``"bf16"`` = forced;
    ``"auto"`` = adopt the export's measured ``cpu_tuned.json`` when one
    exists (the perf_lab sweep only writes it on a >5% win) and f32
    otherwise."""
    if spec in (None, "", "f32", False):
        return None
    if spec == "auto":
        cfg = load_tuned_config(dirname) if dirname else None
        mode = (cfg or {}).get("quantize")
        return _check_mode(mode) if mode else None
    return _check_mode(spec)


def adopt_tuned(dirname: str) -> Optional[Dict[str, Any]]:
    """The FULL ``quantize="auto"`` adoption: load the export's measured
    ``cpu_tuned.json`` and apply its thread shaping (``apply_cpu_flags``
    — the affinity half works even post-init). Returns the config dict
    (the server applies its ``max_batch_size``/``quantize`` itself) or
    None when nothing was measured. The process-global affinity change is
    deliberate and opt-in twice over: the operator both ran the sweep
    (the file only exists after a >5% win) and asked for "auto"."""
    cfg = load_tuned_config(dirname)
    if cfg and cfg.get("threads"):
        apply_cpu_flags(threads=int(cfg["threads"]))
    return cfg

"""Typed serving errors + their wire encoding.

The fault-tolerance contract of the serving plane (the TPU re-expression
of the reference's Go master/pserver recovery loops) is that every failure
a client can observe is TYPED and carries a machine-readable ``info()``
dict the server returns verbatim as the RPC ``error`` payload. Wire codes:

* ``rejected`` — the request was NOT executed and MAY be retried
  (``reason``: ``queue_full`` backpressure, ``shedding`` probabilistic
  load shed while degraded, ``draining`` graceful shutdown in progress).
* ``unavailable`` — the request failed mid-flight for a transient server
  reason (injected fault, step-fn error); retryable, the work was not
  partially applied (inference is stateless).
* ``deadline_exceeded`` — the caller's deadline passed before dispatch;
  terminal (retrying what the client already gave up on wastes a device
  call — the exact failure the deadline exists to prevent).

``ServingClient`` maps the codes back to these classes, retries the
retryable ones with exponential backoff + jitter, and wraps an exhausted
budget in the terminal ``RetryBudgetExceeded`` (``last_error`` preserved).
"""
from __future__ import annotations

from typing import Any, Dict, Optional


class ServingError(RuntimeError):
    """Base of all typed serving errors. ``code`` is the wire error code;
    ``retryable`` is the client-side retry eligibility."""

    code = "error"
    retryable = False
    # True when this instance was decoded from an RPC error payload: the
    # server ANSWERED (proof of liveness for circuit breakers) as opposed
    # to the same type raised locally before any bytes moved
    remote = False

    def info(self) -> Dict[str, Any]:
        return {"code": self.code, "message": str(self)}


class QueueFullError(ServingError):
    """Structured backpressure rejection: the request was NOT enqueued."""

    code = "rejected"
    retryable = True

    def __init__(self, queue_depth: int, capacity: int):
        self.queue_depth = queue_depth
        self.capacity = capacity
        super().__init__(
            f"serving queue full ({queue_depth}/{capacity}); request rejected")

    def info(self) -> Dict[str, Any]:
        return {"code": "rejected", "reason": "queue_full",
                "queue_depth": self.queue_depth, "capacity": self.capacity}


class KVPoolExhausted(QueueFullError):
    """Paged-KV backpressure (serving/kvcache.py): the page pool has no
    free page and nothing cached is evictable (every cached page is
    pinned by an in-flight generation). QueueFullError lineage — the
    request was NOT admitted and MAY be retried once generations retire;
    the wire shape adds the pool numbers so the operator can tell pool
    pressure from queue pressure."""

    def __init__(self, needed: int, free_pages: int, total_pages: int):
        self.needed = needed
        self.free_pages = free_pages
        self.total_pages = total_pages
        ServingError.__init__(
            self, f"KV page pool exhausted: need {needed} page(s), "
            f"{free_pages}/{total_pages} free and nothing evictable")

    def info(self) -> Dict[str, Any]:
        return {"code": "rejected", "reason": "kv_pool_exhausted",
                "needed": self.needed, "free_pages": self.free_pages,
                "total_pages": self.total_pages}


class ShuttingDown(ServingError):
    """The server (or batcher) is draining/closed: not enqueued, retryable
    against a replica — this instance will not take new work."""

    code = "rejected"
    retryable = True

    def __init__(self, message: str = "serving shutting down"):
        super().__init__(message)

    def info(self) -> Dict[str, Any]:
        return {"code": "rejected", "reason": "draining",
                "message": str(self)}


class LoadShedError(ServingError):
    """Probabilistic shed while the server is degraded: not enqueued."""

    code = "rejected"
    retryable = True

    def __init__(self, state: str, queue_depth: int, capacity: int):
        self.state = state
        self.queue_depth = queue_depth
        self.capacity = capacity
        super().__init__(f"load shed while {state} "
                         f"(queue {queue_depth}/{capacity})")

    def info(self) -> Dict[str, Any]:
        return {"code": "rejected", "reason": "shedding", "state": self.state,
                "queue_depth": self.queue_depth, "capacity": self.capacity}


class DeadlineExceeded(ServingError):
    """The request's deadline passed before it was dispatched (shed at
    coalesce time) or before the client's retry loop could re-send it.
    Terminal: the caller has already given up on the answer."""

    code = "deadline_exceeded"
    retryable = False

    def __init__(self, overshoot_s: float = 0.0, where: str = "coalesce"):
        self.overshoot_ms = overshoot_s * 1e3
        self.where = where
        super().__init__(
            f"deadline exceeded {self.overshoot_ms:.1f}ms before {where}")

    def info(self) -> Dict[str, Any]:
        return {"code": "deadline_exceeded", "where": self.where,
                "overshoot_ms": self.overshoot_ms}


class ServingUnavailable(ServingError):
    """Transient mid-flight server fault (step-fn error, injected fault).
    Retryable: inference is stateless, nothing was partially applied."""

    code = "unavailable"
    retryable = True

    def info(self) -> Dict[str, Any]:
        return {"code": "unavailable", "message": str(self)}


class InjectedFault(ServingUnavailable):
    """A chaos-harness fault (serving/chaos.py) — distinguishable in logs
    from organic faults, identical on the wire (code ``unavailable``)."""


class ServingRejected(ServingError):
    """Client-side view of a structured ``rejected`` answer (queue_full /
    shedding / draining). Retryable — ideally against a replica.

    NOTE: the one deliberate asymmetry in the hierarchy — ``info`` here is
    a DICT property (the wire payload as received), not the ``info()``
    method the server-side classes use to build that payload; the original
    serving API shipped ``err.info["reason"]`` and that shape is kept.
    Generic code should use :func:`error_info` to read either kind."""

    code = "rejected"
    retryable = True

    def __init__(self, info: Dict[str, Any]):
        self._info = dict(info)
        super().__init__(f"request rejected: {info.get('reason', info)}")

    @property
    def info(self) -> Dict[str, Any]:
        return self._info


class NoHealthyReplicas(ServingError):
    """Fleet-level: the router found no routable replica (every replica is
    dead, partitioned, circuit-open, or draining). Retryable — replicas
    restart and circuits half-open, so the fleet may recover; the request
    itself was never dispatched anywhere."""

    code = "unavailable"
    retryable = True

    def __init__(self, replicas: int = 0,
                 last_error: Optional[BaseException] = None):
        self.replicas = replicas
        self.last_error = last_error
        tail = (f"; last replica error: {type(last_error).__name__}: "
                f"{last_error}" if last_error is not None else "")
        super().__init__(
            f"no healthy replica among {replicas} registered{tail}")

    def info(self) -> Dict[str, Any]:
        return {"code": "unavailable", "reason": "no_healthy_replicas",
                "replicas": self.replicas, "message": str(self)}


class TenantQuotaExceeded(ServingError):
    """Fleet-level: the tenant's token bucket is empty. Retryable (the
    bucket refills at ``rate`` tokens/s) but the polite client backs off
    at least ``retry_after_s`` first — hammering a dry bucket is exactly
    the traffic the quota exists to absorb."""

    code = "rejected"
    retryable = True

    def __init__(self, tenant: str, rate: float, retry_after_s: float = 0.0):
        self.tenant = tenant
        self.rate = rate
        self.retry_after_s = retry_after_s
        super().__init__(
            f"tenant {tenant!r} over quota ({rate:g} req/s); "
            f"retry after {retry_after_s:.3f}s")

    def info(self) -> Dict[str, Any]:
        return {"code": "rejected", "reason": "quota", "tenant": self.tenant,
                "rate": self.rate, "retry_after_s": self.retry_after_s}


class FleetOverloaded(ServingError):
    """Fleet-level load shed: aggregate pressure across replicas crossed
    this tenant's priority bar (low-priority tenants shed first as
    pressure rises — the PR-2 health machine lifted to the fleet).
    Retryable; not enqueued anywhere."""

    code = "rejected"
    retryable = True

    def __init__(self, tenant: str, priority: int, pressure: float,
                 bar: float):
        self.tenant = tenant
        self.priority = priority
        self.pressure = pressure
        self.bar = bar
        super().__init__(
            f"fleet shedding priority<={priority} tenants "
            f"(pressure {pressure:.2f} >= bar {bar:.2f}); "
            f"tenant {tenant!r} shed")

    def info(self) -> Dict[str, Any]:
        return {"code": "rejected", "reason": "shedding", "scope": "fleet",
                "tenant": self.tenant, "priority": self.priority,
                "pressure": self.pressure, "bar": self.bar}


class RetryBudgetExceeded(ServingError):
    """Terminal client error: the retry budget ran out. ``last_error`` is
    the final retryable error observed; nothing was silently swallowed."""

    code = "retry_budget_exceeded"
    retryable = False

    def __init__(self, attempts: int, last_error: Optional[BaseException]):
        self.attempts = attempts
        self.last_error = last_error
        super().__init__(
            f"retry budget exhausted after {attempts} attempt(s); "
            f"last error: {type(last_error).__name__}: {last_error}")


def error_info(e: ServingError) -> Dict[str, Any]:
    """The wire payload of any ServingError, whether its ``info`` is the
    server-side method or ``ServingRejected``'s received-payload dict."""
    info = e.info
    return info() if callable(info) else info


def error_from_wire(err: Dict[str, Any]) -> ServingError:
    """Map a structured RPC ``error`` dict back to its typed class.
    Decoded instances carry ``remote=True``: the server answered."""
    code = err.get("code")
    if code == "rejected":
        e: ServingError = ServingRejected(err)
    elif code == "deadline_exceeded":
        e = DeadlineExceeded(where=err.get("where", "server"))
        e.overshoot_ms = err.get("overshoot_ms", 0.0)
    elif code == "unavailable":
        e = ServingUnavailable(err.get("message", "serving unavailable"))
    else:
        e = ServingError(f"serving error: {err}")
    e.remote = True
    return e

"""Micro-batcher: coalesce concurrent requests into one padded device call.

A TPU (or any XLA device) wants few LARGE dispatches, not many small ones;
individual user requests arrive as batch-1..k tensors. The batcher sits
between them:

* ``submit()`` validates and enqueues a request onto a **bounded** queue and
  returns a future. A full queue REJECTS immediately (``QueueFullError``)
  instead of blocking — load shedding at the edge keeps tail latency bounded
  and lets the caller retry against a replica (the reference's pserver-side
  send buffers blocked, which is exactly the failure mode this avoids).
* a request may carry an absolute **deadline** (monotonic seconds): one
  whose deadline has already passed when the worker would coalesce it is
  shed with a typed ``DeadlineExceeded`` instead of wasting space in a
  device dispatch the client has stopped waiting for.
* a background thread pulls requests, coalescing until ``max_batch_size``
  rows are gathered or ``batch_timeout_ms`` has elapsed since the first
  request — whichever comes first — then dispatches ONE
  ``engine.run_batch`` call and scatters per-row results back to each
  request's future.
* requests only coalesce when their trailing-shape signature matches (same
  compiled bucket); a mismatched request is carried over to start the next
  batch rather than reordered behind later traffic.
* dispatch is a bounded two-stage **pipeline** (``pipeline_depth``, default
  2): the worker host-prepares and *asynchronously* dispatches a batch,
  then hands the in-flight handle to a completion thread that blocks on
  the device and scatters results — so padding/coalescing of batch N+1
  overlaps the device executing batch N. A slot semaphore (returned only
  once a batch fully completes) hard-caps dispatched-not-completed
  batches at ``pipeline_depth``. ``flush()`` is the pipeline barrier the
  hot-reload path uses; ``pipeline_depth=1`` restores the fully
  synchronous dispatch.
* ``close()`` drains: the worker keeps serving until the queue is empty,
  then exits; anything it cannot serve resolves with a typed
  ``ShuttingDown`` — a submitted future ALWAYS resolves, it never hangs.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs.events import get_event_log
from ..obs.goodput import get_accountant
from .engine import ServingEngine
from .errors import DeadlineExceeded, QueueFullError, ShuttingDown  # noqa: F401 (QueueFullError re-exported: PR-1 import site)
from .stats import PREDICT_STAGES, ServingStats


class _Request:
    __slots__ = ("feeds", "sig", "rows", "future", "t_submit", "deadline",
                 "trace_id", "t_enqueue", "t_dequeue", "t_dispatched",
                 "timings", "weights_version")

    def __init__(self, feeds, sig, rows, deadline=None, trace_id=None,
                 t_submit=None):
        self.feeds = feeds
        self.sig = sig
        self.rows = rows
        self.deadline = deadline  # absolute monotonic seconds, or None
        self.trace_id = trace_id  # wire-propagated correlation id, or None
        self.weights_version = None  # params version the batch ran on
        self.future: Future = Future()
        # t_submit is the START of submit() (so the pad stage is inside the
        # measured latency and the per-stage spans sum to it)
        self.t_submit = time.monotonic() if t_submit is None else t_submit
        self.t_enqueue = self.t_submit  # set after the queue put
        self.t_dequeue = None  # first worker pull (queue_wait ends here)
        self.t_dispatched = None  # dispatch_prepared returned
        self.timings: Dict[str, float] = {}  # stage -> seconds


class MicroBatcher:
    """Background request coalescer over a ``ServingEngine``.

    ``start=False`` builds the batcher without its worker thread — requests
    then pile up in the queue until ``start()`` (deterministic coalescing
    in tests, pre-fill before opening traffic).
    """

    def __init__(self, engine: ServingEngine,
                 max_batch_size: Optional[int] = None,
                 batch_timeout_ms: float = 5.0,
                 queue_capacity: int = 64,
                 stats: Optional[ServingStats] = None,
                 pipeline_depth: int = 2,
                 start: bool = True):
        self.engine = engine
        self.max_batch_size = int(max_batch_size or engine.max_batch_size)
        if self.max_batch_size > engine.max_batch_size:
            raise ValueError("batcher max_batch_size exceeds the engine's")
        self.batch_timeout_s = batch_timeout_ms / 1e3
        self.queue_capacity = int(queue_capacity)
        self.stats = stats
        self.chaos = None  # optional ChaosInjector (queue-stall hook)
        # goodput accounting (docs §23): per-request stage seconds flow
        # into the accountant at completion. Defaults to the process
        # accountant (zero-cost while disabled — one attribute read); a
        # ServingServer rebinds this to its registry-scoped accountant.
        self.accountant = get_accountant()
        # depth-2 dispatch pipeline (docs/design.md §13): the worker splits
        # each batch into host-prepare + async device dispatch, then hands
        # the in-flight handle to a completion thread for the host sync and
        # per-row scatter. While the completion thread blocks on batch N,
        # the worker pads/coalesces and dispatches batch N+1 — the slot
        # semaphore (released only when a batch fully completes) caps how
        # far the host runs ahead at pipeline_depth outstanding batches.
        # pipeline_depth=1 restores the fully synchronous dispatch.
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._inflight_q: Optional["queue.Queue"] = None
        self._in_flight = 0  # dispatched-not-completed batches (gauge)
        self._in_flight_lock = threading.Lock()
        # the HARD cap on dispatched-not-completed batches: the worker takes
        # a slot before launching, the completion stage returns it only
        # AFTER the batch fully finishes — the device queue can never hold
        # more than pipeline_depth outstanding batches
        self._slots = threading.Semaphore(self.pipeline_depth)
        self._pause = threading.Event()  # flush() barrier gate
        self._flush_lock = threading.Lock()  # one barrier at a time
        self._completion_thread: Optional[threading.Thread] = None
        if stats is not None:
            stats.set_pipeline_depth(self.pipeline_depth)
        self._queue: "queue.Queue[_Request]" = queue.Queue(self.queue_capacity)
        self._carry: Optional[_Request] = None  # held-over (mismatch/overflow)
        self._pending = 0  # accepted futures not yet resolved (drain gauge)
        self._pending_lock = threading.Lock()
        self._stop = threading.Event()
        self._closed = False
        self._close_lock = threading.Lock()  # orders submit's put vs close
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- producer side --
    def submit(self, feeds: Dict[str, Any],
               deadline: Optional[float] = None,
               trace_id: Optional[str] = None) -> Future:
        """Enqueue one request (leading dim = rows). Never blocks: raises
        ``QueueFullError`` when the bounded queue is full, ``ShuttingDown``
        after ``close()``. ``deadline`` is absolute ``time.monotonic()``
        seconds; an already-expired request is refused up front.
        ``trace_id`` tags the request's spans/timings (wire-propagated by
        the server); the returned future carries the request as
        ``fut.request`` so the caller can read ``request.timings`` after
        the result resolves."""
        t0 = time.monotonic()
        if self._closed:
            # a drained queue would accept the put but no worker will ever
            # serve it — fail now, not at the caller's result() timeout
            raise ShuttingDown("batcher closed")
        if deadline is not None and t0 >= deadline:
            if self.stats:
                self.stats.record_deadline()
            ev = get_event_log()
            if ev.enabled:
                ev.emit("deadline_shed", severity="warn", trace_id=trace_id,
                        where="submit", overshoot_ms=(t0 - deadline) * 1e3)
            raise DeadlineExceeded(t0 - deadline, "submit")
        padded, sig, rows = self.engine.prepare_request(feeds)
        if rows > self.max_batch_size:
            raise ValueError(
                f"request of {rows} rows exceeds max_batch_size "
                f"{self.max_batch_size}; split it client-side")
        req = _Request(padded, sig, rows, deadline=deadline,
                       trace_id=trace_id, t_submit=t0)
        req.timings["pad"] = time.monotonic() - t0
        if self.stats:
            self.stats.record_stage("pad", req.timings["pad"])
        with self._close_lock:
            # re-check under the lock: a close() racing this submit either
            # sees our put (and drains/fails it) or we see its _closed
            if self._closed:
                raise ShuttingDown("batcher closed")
            # count BEFORE the put: the worker may resolve the request the
            # instant it lands, and `pending` must never transiently read
            # 0 while an accepted request is unresolved (drain correctness)
            with self._pending_lock:
                self._pending += 1
            try:
                self._queue.put_nowait(req)
            except queue.Full:
                with self._pending_lock:
                    self._pending -= 1
                if self.stats:
                    self.stats.record_reject()
                ev = get_event_log()
                if ev.enabled:
                    ev.emit("queue_full", severity="warn",
                            trace_id=trace_id, depth=self.queue_depth,
                            capacity=self.queue_capacity)
                raise QueueFullError(self.queue_depth,
                                     self.queue_capacity) from None
        req.t_enqueue = time.monotonic()
        if self.stats:
            self.stats.record_submit()
        req.future.request = req  # timings/trace ride back with the future
        return req.future

    @property
    def queue_depth(self) -> int:
        return self._queue.qsize() + (1 if self._carry is not None else 0)

    @property
    def pending(self) -> int:
        """Accepted requests whose future has not resolved yet (queued,
        mid-dispatch OR in the completion pipeline) — the server's drain
        loop waits on this."""
        with self._pending_lock:
            return self._pending

    @property
    def in_flight(self) -> int:
        """Batches dispatched to the device but not yet completed — the
        device-queue occupancy gauge (0..pipeline_depth)."""
        with self._in_flight_lock:
            return self._in_flight

    def flush(self, timeout: float = 30.0, then=None) -> bool:
        """Pipeline barrier: pause new dispatches, wait until no batch is
        mid-dispatch or awaiting completion, run ``then()`` (if given) at
        the quiesced point, then resume. The hot-reload path passes the
        weight swap as ``then`` so it happens at a clean pipeline boundary
        — every batch dispatched before the barrier has fully completed on
        the old weights, every batch after it snapshots the new. Queued
        requests are unaffected (they dispatch after, on the new weights).
        Returns False (and does NOT run ``then``) if the pipeline failed
        to quiesce within ``timeout`` — under sustained traffic the pause
        gate guarantees it normally drains within ~one batch time.
        Concurrent flushes serialize (the gate must stay closed for the
        whole quiesce+then of each caller); a close() racing a barrier
        that is still WAITING aborts it with False (shutdown wins), while
        one already at its quiesced point completes its ``then`` with the
        gate still closed — either way no dispatch overlaps ``then``."""
        with self._flush_lock:
            self._pause.set()
            try:
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    if self._stop.is_set():
                        return False  # shutting down: the gate is void
                    if self.in_flight == 0:
                        if then is not None:
                            then()
                        return True
                    time.sleep(0.001)
                return False
            finally:
                self._pause.clear()

    # -- worker side --
    def start(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._closed = False
            if self.pipeline_depth > 1:
                # unbounded hand-off: the slot semaphore is the backpressure
                # (a bounded queue would free its slot at get(), letting the
                # host run depth+1 batches ahead while one is mid-finish)
                self._inflight_q = queue.Queue()
                self._completion_thread = threading.Thread(
                    target=self._completion_loop, daemon=True,
                    name="paddle-tpu-microbatcher-complete")
                self._completion_thread.start()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="paddle-tpu-microbatcher")
            self._thread.start()

    def _next(self, timeout: float) -> Optional[_Request]:
        if self._carry is not None:
            r, self._carry = self._carry, None
            return r  # t_dequeue kept from its FIRST pull (carry != queue)
        try:
            r = self._queue.get(timeout=timeout)
        except queue.Empty:
            return None
        r.t_dequeue = time.monotonic()
        r.timings["queue_wait"] = r.t_dequeue - r.t_enqueue
        if self.stats:
            self.stats.record_stage("queue_wait", r.timings["queue_wait"])
        return r

    def _shed_expired(self, req: _Request) -> bool:
        """Coalesce-time deadline check: a request whose deadline has
        passed is resolved with ``DeadlineExceeded`` and never occupies a
        slot in a device dispatch. Returns True when shed."""
        if req.deadline is None:
            return False
        now = time.monotonic()
        if now < req.deadline:
            return False
        if self._complete(req, exc=DeadlineExceeded(now - req.deadline,
                                                    "coalesce")):
            if self.stats:
                self.stats.record_deadline()
            if self.accountant.enabled:
                # the whole wall this request spent before the shed
                # decision is the `shed` category (docs §23)
                self.accountant.account_shed(now - req.t_submit)
            ev = get_event_log()
            if ev.enabled:
                ev.emit("deadline_shed", severity="warn",
                        trace_id=req.trace_id, where="coalesce",
                        overshoot_ms=(now - req.deadline) * 1e3)
        return True

    def _loop(self) -> None:
        try:
            while True:
                first = self._next(0.05)
                if first is None:
                    if self._stop.is_set():
                        return
                    continue
                if self.chaos is not None:
                    # injected queue stall, per batch (an idle poll must not
                    # roll the dice — it would drain the fault budget with no
                    # traffic to observe the fault); stalling with `first` in
                    # hand lets the queue build behind it, and may expire it
                    self.chaos.on_coalesce()
                if self._shed_expired(first):
                    continue
                batch = [first]
                rows = first.rows
                deadline = time.monotonic() + self.batch_timeout_s
                while rows < self.max_batch_size:
                    nxt = self._next(max(0.0, deadline - time.monotonic()))
                    if nxt is None:  # timed out — ship what we have
                        break
                    if self._shed_expired(nxt):
                        continue
                    if nxt.sig != first.sig or rows + nxt.rows > self.max_batch_size:
                        self._carry = nxt  # starts the next batch, keeps order
                        break
                    batch.append(nxt)
                    rows += nxt.rows
                self._dispatch(batch, rows)
        finally:
            # the completion thread exits only on this sentinel, AFTER
            # finishing every in-flight batch the worker handed it — so a
            # drain still resolves everything dispatched
            if self._inflight_q is not None:
                self._inflight_q.put(None)

    def _complete(self, req: _Request, result=None, exc=None) -> bool:
        """Resolve a future exactly once (cancelled/raced ones are done)."""
        if req.future.done():
            return False
        try:
            if exc is not None:
                req.future.set_exception(exc)
            else:
                req.future.set_result(result)
        except Exception:  # lost a set race — the other side owns it
            return False
        with self._pending_lock:
            self._pending -= 1
        return True

    def _fail_batch(self, batch: List[_Request], e: Exception) -> None:
        if self.stats:
            self.stats.record_failure(len(batch))
        ev = get_event_log()
        if ev.enabled:
            ev.emit("batch_failed", severity="error",
                    trace_id=next((r.trace_id for r in batch
                                   if r.trace_id), None),
                    requests=len(batch),
                    error=f"{type(e).__name__}: {e}"[:200])
        for r in batch:
            self._complete(r, exc=e)

    def _dispatch(self, batch: List[_Request], rows: int) -> None:
        """Host-prepare + async device dispatch. With the pipeline enabled
        the host sync happens on the completion thread (``_finish``); this
        thread immediately returns to coalescing the next batch."""
        t_d = time.monotonic()
        for r in batch:
            # coalesce = first dequeue -> dispatch start (the batch window)
            r.timings["coalesce"] = t_d - (r.t_dequeue or t_d)
            if self.stats:
                self.stats.record_stage("coalesce", r.timings["coalesce"])
        if len(batch) > 1 and not all(self.engine.fetch_per_row.values()):
            # a fetch without a per-row batch dim (a batch reduction) would
            # mix the coalesced clients' rows — refuse to scatter it
            self._fail_batch(batch, ValueError(
                "a fetch does not lead with the batch dim; it cannot be "
                "scattered across coalesced requests — serve such models "
                "with max_batch_size=1 or per-row fetch targets"))
            return
        if len(batch) == 1:
            # fast path: a single-request batch reuses the buffer
            # prepare_request already padded at submit — no per-name
            # re-stack (counted as single_request_batches in stats)
            feeds = batch[0].feeds
        else:
            feeds = {n: np.concatenate([r.feeds[n] for r in batch], axis=0)
                     for n in self.engine.feed_names}
        # take a pipeline slot (hard cap: pipeline_depth dispatched-not-
        # completed), then clear the flush() barrier gate; the pause check
        # shares the in_flight lock so flush can never observe a quiesced
        # pipeline while a dispatch is slipping past the gate
        # the gate honors the pause unconditionally — no shutdown escape, so
        # a barrier that reached its quiesced point runs then() with NO
        # dispatch slipping in (even a racing close()); the wait is bounded
        # because flush() always clears the pause in its finally
        self._slots.acquire()
        while True:
            with self._in_flight_lock:
                if not self._pause.is_set():
                    self._in_flight += 1
                    occ = self._in_flight
                    break
            time.sleep(0.0005)
        if self.stats:
            self.stats.record_pipeline(occ)
        try:
            # requests were prepared (validated/coerced/padded) at submit;
            # don't re-run that work per dispatched batch
            inflight = self.engine.dispatch_prepared(feeds, rows)
        except Exception as e:
            with self._in_flight_lock:
                self._in_flight -= 1
            self._slots.release()
            self._fail_batch(batch, e)
            return
        t_done = time.monotonic()
        dispatch_s = t_done - t_d  # concat + slot wait + H2D + launch
        for r in batch:
            r.timings["dispatch"] = dispatch_s
            r.t_dispatched = t_done
            if self.stats:
                # per REQUEST, not per batch: the stage histograms then
                # decompose request latency (their means sum to ~it)
                self.stats.record_stage("dispatch", dispatch_s)
        from ..obs import get_tracer

        tr = get_tracer()
        if tr.enabled:
            tr.add_span("serve/dispatch", t_d, dispatch_s, cat="serving",
                        args={"rows": rows, "bucket": inflight.bucket,
                              "requests": len(batch), "occupancy": occ})
        if self._inflight_q is not None:
            self._inflight_q.put((batch, inflight))
        else:
            self._finish(batch, inflight)

    def _finish(self, batch: List[_Request], inflight) -> None:
        """Device-complete stage: host sync, per-row scatter, resolve.
        The pipeline slot is returned only HERE, after the batch fully
        finished — the worker cannot run further ahead in the meantime."""
        t_f = time.monotonic()
        try:
            outs = self.engine.complete(inflight)
        except Exception as e:
            self._fail_batch(batch, e)
            return
        finally:
            with self._in_flight_lock:
                self._in_flight -= 1
            self._slots.release()
        t_synced = time.monotonic()
        sync_s = t_synced - t_f
        # counted only once the device call actually completed (failure
        # paths land in record_failure, matching the pre-pipeline stats)
        if self.stats:
            self.stats.record_batch(inflight.rows, inflight.bucket,
                                    requests=len(batch),
                                    flops=inflight.flops)
        off = 0
        results = []
        for r in batch:
            res = [o[off:off + r.rows] if self.engine.fetch_per_row[n] else o
                   for n, o in zip(self.engine.fetch_names, outs)]
            off += r.rows
            results.append(res)
        now = time.monotonic()
        scatter_s = now - t_synced
        for r, res in zip(batch, results):
            # the params snapshot this batch ran on: the capture/flight
            # plane reads it off the resolved future (fut.request)
            r.weights_version = inflight.weights_version
            # ALL timings land BEFORE the future resolves: set_result wakes
            # the server handler, which reads r.timings — a write after it
            # would race the handler's dict iteration (and "total" must not
            # depend on a stats object being attached: tracing uses it too)
            # pipeline_wait: launched -> completion thread picked it up
            # (the depth-2 hand-off queue + the device call ahead of it)
            r.timings["pipeline_wait"] = t_f - (r.t_dispatched or t_f)
            r.timings["device_sync"] = sync_s
            r.timings["scatter"] = scatter_s
            r.timings["total"] = now - r.t_submit
            if self.stats:
                self.stats.record_stage("pipeline_wait",
                                        r.timings["pipeline_wait"])
                self.stats.record_stage("device_sync", sync_s)
                self.stats.record_stage("scatter", scatter_s)
            if self._complete(r, result=res) and self.stats:
                self.stats.record_done(r.timings["total"])
        if self.accountant.enabled:
            # classify each completed request's stage seconds into the
            # serving taxonomy (the t_submit anchor lets the accountant
            # keep timeline-drawable intervals too)
            for r in batch:
                self.accountant.account_request(r.timings, t0=r.t_submit)
        self._trace_batch(batch, inflight, t_f, sync_s, scatter_s, now)

    def _trace_batch(self, batch, inflight, t_f, sync_s, scatter_s,
                     now) -> None:
        """Emit per-batch + per-request spans and offer p99 exemplars —
        only when the tracer is live (zero work otherwise)."""
        from ..obs import get_tracer

        tr = get_tracer()
        if not tr.enabled:
            return
        tr.add_span("serve/complete", t_f, (now - t_f), cat="serving",
                    args={"rows": inflight.rows, "bucket": inflight.bucket,
                          "device_sync_ms": sync_s * 1e3,
                          "scatter_ms": scatter_s * 1e3})
        for r in batch:
            if not r.timings.get("total"):
                continue
            sid = tr.add_span("serve/request", r.t_submit,
                              r.timings["total"], cat="serving",
                              trace_id=r.trace_id,
                              args={"rows": r.rows})
            # reconstruct stage child spans from the recorded timestamps
            # (they were measured on three different threads; the request
            # row in the trace shows them as one contiguous lane)
            t = r.t_submit
            for stage in PREDICT_STAGES:  # the one stage list (stats.py)
                dur = r.timings.get(stage)
                if dur is None:
                    continue
                tr.add_span(f"serve/{stage}", t, dur, cat="serving",
                            trace_id=r.trace_id, parent=sid)
                t += dur
            tr.exemplars.offer(
                r.trace_id or f"req-{sid}", r.timings["total"],
                [{"name": s, "dur_ms": d * 1e3}
                 for s, d in r.timings.items()])

    def _completion_loop(self) -> None:
        q = self._inflight_q
        while True:
            item = q.get()
            if item is None:  # worker exited; pipeline fully drained
                return
            self._finish(*item)

    def close(self, timeout: float = 10.0) -> None:
        """Graceful drain: no new submits land, the worker serves what is
        already queued, then exits; whatever cannot be served resolves with
        a typed ``ShuttingDown`` (a submitted future never hangs)."""
        with self._close_lock:  # no submit can land a put after this
            self._closed = True
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
            if t.is_alive():
                # worker still mid-dispatch (a long compile): it owns the
                # queue and will drain it on its way out — draining here
                # too would race it into double-completing requests
                return
        # the worker's exit pushed the pipeline sentinel; the completion
        # thread finishes every dispatched batch, then exits
        ct = self._completion_thread
        if ct is not None and ct.is_alive():
            ct.join(timeout)
        # worker gone (or never started): fail anything still pending
        leftover, self._carry = ([self._carry] if self._carry else []), None
        while True:
            try:
                leftover.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for r in leftover:
            self._complete(r, exc=ShuttingDown("batcher closed"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

"""Micro-batcher: coalesce concurrent requests into one padded device call.

A TPU (or any XLA device) wants few LARGE dispatches, not many small ones;
individual user requests arrive as batch-1..k tensors. The batcher sits
between them:

* ``submit()`` validates and enqueues a request onto a **bounded** queue and
  returns a future. A full queue REJECTS immediately (``QueueFullError``)
  instead of blocking — load shedding at the edge keeps tail latency bounded
  and lets the caller retry against a replica (the reference's pserver-side
  send buffers blocked, which is exactly the failure mode this avoids).
* a request may carry an absolute **deadline** (monotonic seconds): one
  whose deadline has already passed when the worker would coalesce it is
  shed with a typed ``DeadlineExceeded`` instead of wasting space in a
  device dispatch the client has stopped waiting for.
* a background thread pulls requests, coalescing until ``max_batch_size``
  rows are gathered or ``batch_timeout_ms`` has elapsed since the first
  request — whichever comes first — then dispatches ONE
  ``engine.run_batch`` call and scatters per-row results back to each
  request's future.
* requests only coalesce when their trailing-shape signature matches (same
  compiled bucket); a mismatched request is carried over to start the next
  batch rather than reordered behind later traffic.
* ``close()`` drains: the worker keeps serving until the queue is empty,
  then exits; anything it cannot serve resolves with a typed
  ``ShuttingDown`` — a submitted future ALWAYS resolves, it never hangs.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from .engine import ServingEngine
from .errors import DeadlineExceeded, QueueFullError, ShuttingDown  # noqa: F401 (QueueFullError re-exported: PR-1 import site)
from .stats import ServingStats


class _Request:
    __slots__ = ("feeds", "sig", "rows", "future", "t_submit", "deadline")

    def __init__(self, feeds, sig, rows, deadline=None):
        self.feeds = feeds
        self.sig = sig
        self.rows = rows
        self.deadline = deadline  # absolute monotonic seconds, or None
        self.future: Future = Future()
        self.t_submit = time.monotonic()


class MicroBatcher:
    """Background request coalescer over a ``ServingEngine``.

    ``start=False`` builds the batcher without its worker thread — requests
    then pile up in the queue until ``start()`` (deterministic coalescing
    in tests, pre-fill before opening traffic).
    """

    def __init__(self, engine: ServingEngine,
                 max_batch_size: Optional[int] = None,
                 batch_timeout_ms: float = 5.0,
                 queue_capacity: int = 64,
                 stats: Optional[ServingStats] = None,
                 start: bool = True):
        self.engine = engine
        self.max_batch_size = int(max_batch_size or engine.max_batch_size)
        if self.max_batch_size > engine.max_batch_size:
            raise ValueError("batcher max_batch_size exceeds the engine's")
        self.batch_timeout_s = batch_timeout_ms / 1e3
        self.queue_capacity = int(queue_capacity)
        self.stats = stats
        self.chaos = None  # optional ChaosInjector (queue-stall hook)
        self._queue: "queue.Queue[_Request]" = queue.Queue(self.queue_capacity)
        self._carry: Optional[_Request] = None  # held-over (mismatch/overflow)
        self._pending = 0  # accepted futures not yet resolved (drain gauge)
        self._pending_lock = threading.Lock()
        self._stop = threading.Event()
        self._closed = False
        self._close_lock = threading.Lock()  # orders submit's put vs close
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- producer side --
    def submit(self, feeds: Dict[str, Any],
               deadline: Optional[float] = None) -> Future:
        """Enqueue one request (leading dim = rows). Never blocks: raises
        ``QueueFullError`` when the bounded queue is full, ``ShuttingDown``
        after ``close()``. ``deadline`` is absolute ``time.monotonic()``
        seconds; an already-expired request is refused up front."""
        if self._closed:
            # a drained queue would accept the put but no worker will ever
            # serve it — fail now, not at the caller's result() timeout
            raise ShuttingDown("batcher closed")
        if deadline is not None and time.monotonic() >= deadline:
            if self.stats:
                self.stats.record_deadline()
            raise DeadlineExceeded(time.monotonic() - deadline, "submit")
        padded, sig, rows = self.engine.prepare_request(feeds)
        if rows > self.max_batch_size:
            raise ValueError(
                f"request of {rows} rows exceeds max_batch_size "
                f"{self.max_batch_size}; split it client-side")
        req = _Request(padded, sig, rows, deadline=deadline)
        with self._close_lock:
            # re-check under the lock: a close() racing this submit either
            # sees our put (and drains/fails it) or we see its _closed
            if self._closed:
                raise ShuttingDown("batcher closed")
            # count BEFORE the put: the worker may resolve the request the
            # instant it lands, and `pending` must never transiently read
            # 0 while an accepted request is unresolved (drain correctness)
            with self._pending_lock:
                self._pending += 1
            try:
                self._queue.put_nowait(req)
            except queue.Full:
                with self._pending_lock:
                    self._pending -= 1
                if self.stats:
                    self.stats.record_reject()
                raise QueueFullError(self.queue_depth,
                                     self.queue_capacity) from None
        if self.stats:
            self.stats.record_submit()
        return req.future

    @property
    def queue_depth(self) -> int:
        return self._queue.qsize() + (1 if self._carry is not None else 0)

    @property
    def pending(self) -> int:
        """Accepted requests whose future has not resolved yet (queued OR
        mid-dispatch) — the server's drain loop waits on this."""
        with self._pending_lock:
            return self._pending

    # -- worker side --
    def start(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._closed = False
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="paddle-tpu-microbatcher")
            self._thread.start()

    def _next(self, timeout: float) -> Optional[_Request]:
        if self._carry is not None:
            r, self._carry = self._carry, None
            return r
        try:
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def _shed_expired(self, req: _Request) -> bool:
        """Coalesce-time deadline check: a request whose deadline has
        passed is resolved with ``DeadlineExceeded`` and never occupies a
        slot in a device dispatch. Returns True when shed."""
        if req.deadline is None:
            return False
        now = time.monotonic()
        if now < req.deadline:
            return False
        if self._complete(req, exc=DeadlineExceeded(now - req.deadline,
                                                    "coalesce")):
            if self.stats:
                self.stats.record_deadline()
        return True

    def _loop(self) -> None:
        while True:
            first = self._next(0.05)
            if first is None:
                if self._stop.is_set():
                    return
                continue
            if self.chaos is not None:
                # injected queue stall, per batch (an idle poll must not
                # roll the dice — it would drain the fault budget with no
                # traffic to observe the fault); stalling with `first` in
                # hand lets the queue build behind it, and may expire it
                self.chaos.on_coalesce()
            if self._shed_expired(first):
                continue
            batch = [first]
            rows = first.rows
            deadline = time.monotonic() + self.batch_timeout_s
            while rows < self.max_batch_size:
                nxt = self._next(max(0.0, deadline - time.monotonic()))
                if nxt is None:  # timed out — ship what we have
                    break
                if self._shed_expired(nxt):
                    continue
                if nxt.sig != first.sig or rows + nxt.rows > self.max_batch_size:
                    self._carry = nxt  # starts the next batch, keeps order
                    break
                batch.append(nxt)
                rows += nxt.rows
            self._dispatch(batch, rows)

    def _complete(self, req: _Request, result=None, exc=None) -> bool:
        """Resolve a future exactly once (cancelled/raced ones are done)."""
        if req.future.done():
            return False
        try:
            if exc is not None:
                req.future.set_exception(exc)
            else:
                req.future.set_result(result)
        except Exception:  # lost a set race — the other side owns it
            return False
        with self._pending_lock:
            self._pending -= 1
        return True

    def _fail_batch(self, batch: List[_Request], e: Exception) -> None:
        if self.stats:
            self.stats.record_failure(len(batch))
        for r in batch:
            self._complete(r, exc=e)

    def _dispatch(self, batch: List[_Request], rows: int) -> None:
        if len(batch) > 1 and not all(self.engine.fetch_per_row.values()):
            # a fetch without a per-row batch dim (a batch reduction) would
            # mix the coalesced clients' rows — refuse to scatter it
            self._fail_batch(batch, ValueError(
                "a fetch does not lead with the batch dim; it cannot be "
                "scattered across coalesced requests — serve such models "
                "with max_batch_size=1 or per-row fetch targets"))
            return
        feeds = {n: np.concatenate([r.feeds[n] for r in batch], axis=0)
                 for n in self.engine.feed_names}
        try:
            # requests were prepared (validated/coerced/padded) at submit;
            # don't re-run that work per dispatched batch
            outs = self.engine.run_prepared(feeds, rows)
        except Exception as e:
            self._fail_batch(batch, e)
            return
        if self.stats:
            self.stats.record_batch(rows, self.engine.bucket_batch(rows))
        now = time.monotonic()
        off = 0
        for r in batch:
            res = [o[off:off + r.rows] if self.engine.fetch_per_row[n] else o
                   for n, o in zip(self.engine.fetch_names, outs)]
            off += r.rows
            if self._complete(r, result=res) and self.stats:
                self.stats.record_done(now - r.t_submit)

    def close(self, timeout: float = 10.0) -> None:
        """Graceful drain: no new submits land, the worker serves what is
        already queued, then exits; whatever cannot be served resolves with
        a typed ``ShuttingDown`` (a submitted future never hangs)."""
        with self._close_lock:  # no submit can land a put after this
            self._closed = True
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
            if t.is_alive():
                # worker still mid-dispatch (a long compile): it owns the
                # queue and will drain it on its way out — draining here
                # too would race it into double-completing requests
                return
        # worker gone (or never started): fail anything still pending
        leftover, self._carry = ([self._carry] if self._carry else []), None
        while True:
            try:
                leftover.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for r in leftover:
            self._complete(r, exc=ShuttingDown("batcher closed"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

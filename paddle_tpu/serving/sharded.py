"""Mesh-sharded serving: one model spanning N devices (docs/design.md §18).

``ShardedServingEngine`` serves a ``transformer_lm`` inference export over
a (dp, tp) device mesh; ``ShardedDecodeEngine`` shards the decode path's
slot-pooled KV cache along heads so continuous batching survives sharding.
Both are drop-in engines: the ``MicroBatcher`` / ``GenerationBatcher`` /
``ServingServer`` stack above them is unchanged.

Execution layout — the **bit-safe column layout**:

* The architecture is RECOVERED from the exported IR program
  (``models/transformer.decode_roles`` — the same walk the decode export
  uses), never re-described by the caller; a non-transformer export is
  refused loudly.
* Every matmul weight is a COLUMN shard over the ``tp`` mesh axis — each
  rank computes its slice of the output features with the FULL
  contraction — and activations all-gather back to replicated at each
  boundary. q/k/v columns are HEAD blocks, so attention (and the decode
  KV pool) shards along heads and the flash kernel runs unchanged per
  rank. Because no contraction dim is ever split and an all-gather is a
  concatenation, per-element float math is IDENTICAL to the
  single-device engine: predict logits and greedy decode streams are
  bit-equal (tested at the lane-aligned shapes tier-1 pins; a fused
  [D,3D] qkv weight is column-permuted at load so each rank's contiguous
  slice is its own [q_r | k_r | v_r]).
* ``dp`` splits batch rows via ``shard_map`` — no collectives at all on
  the data axis (inference rows are independent). Batch buckets round up
  to multiples of dp.
* The collective schedule is therefore STATIC per compiled signature:
  ``4 * n_layers + 2`` all-gathers when tp > 1, zero otherwise
  (``expected_collectives``); ``measured_collectives`` counts all-gather
  instructions in the compiled HLO, and bench.py bars on the two
  agreeing — a regression that sneaks a reduce-scatter/psum into this
  program (breaking bit-exactness) fails the round.

The per-signature compile cache, warmup ladder, hot-reload
stage/commit atomicity (ONE pytree reference swap — every dispatch runs
wholly on one weights version across ALL shards), chaos hooks, and
cache counters are inherited from the single-device engines.
"""
from __future__ import annotations

import functools
import math
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .decode import DecodeEngine, _flat_items
from .engine import ServingEngine

#: decode-pytree leaves that column-shard over tp: {role: column axis}
_COL_AXIS = {"emb": 1, "out_w": 1, "out_b": 0, "wq": 1, "wk": 1, "wv": 1,
             "wqkv": 1, "wo": 1, "wup": 1, "bup": 0, "wdown": 1,
             "bdown": 0}


def expected_collectives(cfg: Dict[str, Any], tp: int) -> int:
    """The column layout's static all-gather count per dispatch: emb +
    (ctx, attn out, FFN hidden, FFN out) per layer + head."""
    return 0 if tp <= 1 else 4 * int(cfg["n_layers"]) + 2


def count_hlo_collectives(compiled_text: str) -> int:
    """all-gather instructions in a compiled HLO dump (start/done pairs
    count once). The deterministic per-dispatch collective contract is
    judged against this."""
    n = 0
    for line in compiled_text.splitlines():
        s = line.strip()
        if "= " not in s:
            continue
        op = s.split("= ", 1)[1]
        # strip the result type annotation: "f32[...] all-gather(...)"
        if (" all-gather(" in op or op.startswith("all-gather(")
                or " all-gather-start(" in op
                or op.startswith("all-gather-start(")):
            n += 1
    return n


def _qkv_col_perm(three_d: int, tp: int) -> np.ndarray:
    """Column permutation making each tp rank's contiguous 3D/tp slice of
    a fused [D, 3D] qkv weight its own [q_r | k_r | v_r] head blocks."""
    D = three_d // 3
    per = D // tp
    return np.concatenate([
        np.arange(j * D + r * per, j * D + (r + 1) * per)
        for r in range(tp) for j in range(3)])


def _permute_qkv_cols(w: np.ndarray, tp: int) -> np.ndarray:
    """Reorder a fused [D, 3D] qkv weight's columns so each tp rank's
    contiguous 3D/tp slice is [q_r | k_r | v_r] (its own head blocks)."""
    if tp <= 1:
        return w
    return np.asarray(w)[:, _qkv_col_perm(w.shape[1], tp)]


def _shard_mesh(dp: int, tp: int, devices=None, platform: Optional[str] = None):
    from ..parallel.mesh import serving_mesh

    return serving_mesh(dp, tp, devices=devices, platform=platform)


class _ShardedParamStore:
    """Shared plumbing of both sharded engines: role->spec mapping,
    sharded device placement, the per-shard HBM account, and the
    cost-model comm attribution (plan term per dispatch)."""

    def _mem_shard_label(self):
        """Ledger mesh annotation (obs/mem.py): which axes this engine's
        stores are split over — "dp2xtp4" — so per-shard entries in OOM
        bundles name their layout."""
        return f"dp{self.dp}xtp{self.tp}"

    def _comm_profile(self):
        """The analytic profile the comm attribution prices gathers with
        — built ONCE (the cfg is frozen; this sits on the hot path)."""
        prof = getattr(self, "_comm_profile_cache", None)
        if prof is None:
            from .placement import ModelProfile

            prof = ModelProfile.synthetic(
                self.cfg["n_layers"], self.cfg["n_heads"],
                self.cfg["d_model"], self.cfg["d_ff"], self.cfg["vocab"],
                self.cfg["max_len"])
            self._comm_profile_cache = prof
        return prof

    def _predicted_comm_s(self, rows: int, seq: Optional[int] = None) -> float:
        """Cost-model-attributed collective seconds of one dispatch (the
        plan's comm term at this shape; 0 when the engine was built
        without a plan) — feeds pt_serving_shard_collective_seconds."""
        plan = self.plan
        if plan is None or self.tp <= 1 or plan.inventory is None:
            return 0.0
        inv = plan.inventory
        b_loc = math.ceil(rows / self.dp)
        n_coll = expected_collectives(self.cfg, self.tp)
        return (n_coll * inv.alpha_s
                + self._comm_profile().gather_bytes(b_loc, seq)
                * (self.tp - 1) / self.tp / inv.link_bw)

    def _record_collectives(self, rows: int, seq: Optional[int] = None) -> None:
        """One sharded dispatch ran the static gather schedule: count it
        (and its plan-modeled seconds) into the attached stats."""
        if self.stats is None or self.tp <= 1:
            return
        self.stats.record_collectives(
            expected_collectives(self.cfg, self.tp),
            self._predicted_comm_s(rows, seq))

    def _param_spec(self, role: str):
        from jax.sharding import PartitionSpec

        ax = _COL_AXIS.get(role)
        if ax is None or self.tp <= 1:
            return PartitionSpec()
        ndim = 2 if role not in ("out_b", "bup", "bdown") else 1
        spec = [None] * ndim
        spec[ax if ndim > 1 else 0] = "tp"
        return PartitionSpec(*spec)

    def _leaf_spec(self, role: str, leaf):
        """Per-leaf partition spec. A quantized int8 leaf ({"q", "s"},
        serving/quant.py) shards ``q`` by the SAME column blocks as the
        f32 weight and the per-output-channel scale vector by the
        matching output blocks — each rank's epilogue multiplies its own
        columns by its own scales, so the bit-safety argument (no split
        contraction, gather = concatenation) holds inside the quantized
        lane. bf16 leaves shard like their f32 siblings."""
        if isinstance(leaf, dict):
            from jax.sharding import PartitionSpec

            ax = _COL_AXIS.get(role)
            if ax is None or self.tp <= 1:
                return {"q": PartitionSpec(), "s": PartitionSpec()}
            qspec = [None] * leaf["q"].ndim
            qspec[ax] = "tp"
            return {"q": PartitionSpec(*qspec), "s": PartitionSpec("tp")}
        return self._param_spec(role)

    def _param_specs_pytree(self, params):
        specs = {k: self._leaf_spec(k, v)
                 for k, v in params.items() if k != "layers"}
        specs["layers"] = [{k: self._leaf_spec(k, v) for k, v in lp.items()}
                           for lp in params["layers"]]
        return specs

    def _shard_put(self, host_params):
        """Host pytree -> mesh-sharded pytree (wqkv columns permuted so a
        rank's slice is its own head blocks; a quantized wqkv permutes q
        columns AND scales by the same index, keeping each rank's scale
        aligned with its columns)."""
        import jax
        from jax.sharding import NamedSharding

        def put(role, leaf):
            spec = self._leaf_spec(role, leaf)
            if isinstance(leaf, dict):
                q, s = np.asarray(leaf["q"]), np.asarray(leaf["s"])
                if role == "wqkv" and self.tp > 1:
                    cols = _qkv_col_perm(q.shape[1], self.tp)
                    q, s = q[:, cols], s[cols]
                return {
                    "q": jax.device_put(
                        q, NamedSharding(self.mesh, spec["q"])),
                    "s": jax.device_put(
                        s, NamedSharding(self.mesh, spec["s"]))}
            arr = np.asarray(leaf)
            if role == "wqkv":
                arr = _permute_qkv_cols(arr, self.tp)
            return jax.device_put(arr, NamedSharding(self.mesh, spec))

        out = {k: put(k, v) for k, v in host_params.items() if k != "layers"}
        out["layers"] = [{k: put(k, v) for k, v in lp.items()}
                        for lp in host_params["layers"]]
        return out

    def shard_hbm_bytes(self) -> Dict[int, int]:
        """Resident param bytes per mesh device — the per-device
        occupancy gauge's numerator (pools/activations are accounted by
        the placement cost model, not measured here)."""
        out = {i: 0 for i in range(len(self.mesh.devices.flat))}
        dev_index = {d: i for i, d in enumerate(self.mesh.devices.flat)}
        with self._lock:
            params = self._params
        for _path, leaf in _flat_items(params):
            for s in getattr(leaf, "addressable_shards", []):
                i = dev_index.get(s.device)
                if i is not None:
                    out[i] += int(np.prod(s.data.shape)
                                  * s.data.dtype.itemsize)
        return out


class ShardedServingEngine(_ShardedParamStore, ServingEngine):
    """One-shot predict over a (dp, tp) mesh — a drop-in ``ServingEngine``
    whose compiled step is ``models/transformer.predict_forward`` under
    ``shard_map``: batch rows split over dp, every weight column-sharded
    over tp, activations gathered at the static §18 boundaries.

    The export must be a ``transformer_lm`` logits export (one int-ids
    feed, one per-row [N, T, V] fetch); anything else raises — sharding
    recovers the architecture from the IR and will not guess.
    """

    def __init__(self, dirname: str, dp: int = 1, tp: int = 1,
                 place=None, devices=None, stats=None, plan=None,
                 quantize=None, **kw):
        self.dp = int(dp)
        self.tp = int(tp)
        if self.dp < 1 or self.dp & (self.dp - 1):
            raise ValueError(f"dp must be a power of two (batch buckets "
                             f"are), got {dp}")
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        self._ctor_devices = devices
        self.plan = plan
        self.stats = stats  # optional: collective-time attribution
        if quantize is not None:
            from .quant import _check_mode

            self.quant_mode = _check_mode(quantize)
        super().__init__(dirname, place=place, **kw)
        if len(self.feed_names) != 1 or len(self.fetch_names) != 1:
            raise ValueError(
                f"sharded serving wants the transformer_lm logits export "
                f"(one ids feed, one logits fetch), got feeds="
                f"{list(self.feed_names)} fetches={list(self.fetch_names)}")
        if not self.fetch_per_row[self.fetch_names[0]]:
            raise ValueError("sharded serving: the fetch must be per-row "
                             "(the [N, T, V] logits)")
        # dp splits the batch dim of every bucket: round the ladder up to
        # multiples of dp (pow2 ladder + pow2 dp -> only entries < dp move)
        if self.dp > 1:
            self.batch_buckets = tuple(sorted(
                {int(math.ceil(b / self.dp) * self.dp)
                 for b in self.batch_buckets}))
            self.max_batch_size = self.batch_buckets[-1]

    # -- load: roles walk + mesh + column shards (no single-device stage) --
    def _load_params(self):
        from jax.sharding import NamedSharding, PartitionSpec

        from ..models.transformer import decode_params_from_scope, \
            decode_roles

        self.roles, self.cfg = decode_roles(self.program)
        if self.cfg["n_heads"] % self.tp or self.cfg["d_model"] % self.tp \
                or self.cfg["d_ff"] % self.tp \
                or self.cfg["vocab"] % self.tp:
            raise ValueError(
                f"tp={self.tp} does not divide the column extents "
                f"(heads={self.cfg['n_heads']} d_model="
                f"{self.cfg['d_model']} d_ff={self.cfg['d_ff']} "
                f"vocab={self.cfg['vocab']}) — the placement searcher "
                f"only emits divisor splits")
        self.mesh = _shard_mesh(self.dp, self.tp,
                                devices=self._ctor_devices,
                                platform=self._place.jax_device().platform)
        self._feed_sharding = NamedSharding(self.mesh,
                                            PartitionSpec("dp", None))
        host = decode_params_from_scope(self.roles, self.scope)
        if self.quant_mode is not None:
            from .quant import quantize_params

            host = quantize_params(host, self.quant_mode)
        return self._shard_put(host)

    # -- compile cache: shard_map-wrapped predict_forward per signature --
    def _make_fn(self, sig: Tuple):
        import jax
        from jax.sharding import PartitionSpec as P

        from ..models.transformer import predict_forward
        from ..parallel._compat import shard_map

        with self._lock:
            specs = self._param_specs_pytree(self._params)
        body = functools.partial(predict_forward, cfg=self.cfg,
                                 tp=self.tp,
                                 tp_axis="tp" if self.tp > 1 else None)
        fn = shard_map(lambda p, ids: body(p, ids), mesh=self.mesh,
                       in_specs=(specs, P("dp", None)),
                       out_specs=P("dp", None, None), check_vma=False)
        return jax.jit(fn)

    def _annotate_cost(self, fn, sig: Tuple):
        from ..flags import get_flag

        if not get_flag("obs_cost_analysis"):
            return None, None
        try:
            from ..obs import cost as obs_cost

            with self._lock:
                params = self._params
            avals = obs_cost.abstractify(params)
            feed_aval = obs_cost.abstractify(
                np.zeros(sig[0][1], np.dtype(sig[0][2])))
            res = obs_cost.analyze_jit(fn, avals, feed_aval)
            return res["flops"], res["bytes"]
        except Exception:
            return None, None

    def measured_collectives(self, rows: int) -> int:
        """Compile (or reuse) the bucket serving ``rows`` and count the
        all-gather instructions in its HLO — the contract check."""
        bucket = self.bucket_batch(rows)
        var = self._feed_vars[self.feed_names[0]]
        t = tuple(var.shape)[1:]
        dt = var.dtype.np_dtype if var.dtype is not None else np.int64
        feeds, sig, _ = self.prepare_request(
            {self.feed_names[0]: np.zeros((bucket,) + t, dt)})
        entry = self._get_fn(tuple(
            (n, feeds[n].shape, str(feeds[n].dtype))
            for n in self.feed_names))
        with self._lock:
            params = self._params
        ids = feeds[self.feed_names[0]]
        txt = entry.fn.lower(params, ids).compile().as_text()
        return count_hlo_collectives(txt)

    @property
    def expected_collectives_per_dispatch(self) -> int:
        return expected_collectives(self.cfg, self.tp)

    # -- dispatch: params pytree + dp-sharded ids --
    def dispatch_prepared(self, feeds: Dict[str, np.ndarray],
                          rows: int):
        import jax

        from .engine import InFlightBatch

        bucket = self.bucket_batch(rows)
        if bucket != rows:
            feeds = {n: np.concatenate(
                [a, np.zeros((bucket - rows,) + a.shape[1:], a.dtype)])
                for n, a in feeds.items()}
        sig = tuple((n, feeds[n].shape, str(feeds[n].dtype))
                    for n in self.feed_names)
        entry = self._get_fn(sig)
        if self.chaos is not None:
            self.chaos.on_dispatch()
        with self._lock:  # one consistent (params, version) snapshot —
            params = self._params  # the pytree swap covers EVERY shard
            version = self.params_version
        cold = entry.cold
        t_call = time.monotonic() if cold else 0.0
        ids = jax.device_put(feeds[self.feed_names[0]], self._feed_sharding)
        logits = entry.fn(params, ids)
        if cold:
            entry.compile_s = time.monotonic() - t_call
            entry.cold = False
            from ..obs import get_tracer

            tr = get_tracer()
            if tr.enabled:
                tr.add_span("serving/compile", t_call, entry.compile_s,
                            cat="compile",
                            args={"bucket": bucket, "dp": self.dp,
                                  "tp": self.tp, "flops": entry.flops})
        self._record_collectives(bucket)
        return InFlightBatch([logits], rows, bucket, version,
                             flops=entry.flops)

    # -- hot reload: decode-style pytree validation, sharded staging --
    def _stage_transform(self, staged: Dict[str, Any]) -> Dict[str, Any]:
        """Quantized sharding re-quantizes the staged set at the frozen
        mode BEFORE validation: the .q/.s paths flat-compare together,
        so quantized ints and their scales stage — and later commit —
        as one set."""
        if self.quant_mode is not None:
            from .quant import quantize_params

            return quantize_params(staged, self.quant_mode)
        return staged

    def stage_params(self, dirname: str) -> Dict[str, Any]:
        """Load + validate a re-exported dir against the frozen roles
        (decode.stage_decode_params — the one shared validator), then
        place the column shards — all WITHOUT touching the live set.
        ``commit_params`` (inherited) is ONE pytree reference store, so
        every dispatch snapshots a wholly-old or wholly-new set across
        ALL shards (PR-2's guarantee, mesh-wide)."""
        from .decode import stage_decode_params

        return self._shard_put(
            stage_decode_params(self, dirname, self._stage_transform))


class ShardedDecodeEngine(_ShardedParamStore, DecodeEngine):
    """Decode serving over a tp mesh: the slot-pooled KV cache sharded
    along HEADS (``[L, slots+1, max_len, H/tp, Dh]`` per rank), params
    column-sharded, one shard_map-compiled chunk fn per (lanes, chunk,
    window) signature. ``GenerationBatcher`` — continuous batching, the
    slot scheduler, deadlines, drain, reload barrier — runs on top
    UNCHANGED, and steady-state decode still compiles nothing (the same
    cache-counter contract, tested).

    dp is meaningless inside one decode engine (the slot pool IS the
    batch); data-parallel decode is replica groups, the fleet tier's
    business."""

    def __init__(self, dirname: str, tp: int = 1, place=None, devices=None,
                 plan=None, stats=None, quantize=None, **kw):
        self.tp = int(tp)
        self.dp = 1
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        self._ctor_devices = devices
        self.plan = plan
        self.stats = stats  # optional: collective attribution
        self.mesh = None  # built on first _device_put_params
        if quantize is not None:
            from .quant import _check_mode

            self.quant_mode = _check_mode(quantize)
        super().__init__(dirname, place=place, **kw)

    @property
    def expected_collectives_per_dispatch(self) -> int:
        return expected_collectives(self.cfg, self.tp)

    def _device_put_params(self, host_params):
        c = self.cfg
        if c["n_heads"] % self.tp or c["d_model"] % self.tp \
                or c["d_ff"] % self.tp or c["vocab"] % self.tp:
            raise ValueError(
                f"tp={self.tp} does not divide the column extents "
                f"(heads={c['n_heads']} d_model={c['d_model']} "
                f"d_ff={c['d_ff']} vocab={c['vocab']})")
        if self.mesh is None:
            self.mesh = _shard_mesh(1, self.tp,
                                    devices=self._ctor_devices,
                                    platform=self._place.jax_device()
                                    .platform)
        if self.quant_mode is not None:
            from .quant import is_quantized_params, quantize_params

            if not is_quantized_params(host_params):
                host_params = quantize_params(host_params, self.quant_mode)
        return self._shard_put(host_params)

    def _stage_transform(self, staged):
        # quantized reload: re-quantize BEFORE the flat validation (ints
        # and scales compare — and swap — together); the base
        # stage_params then routes through _device_put_params -> shards
        if self.quant_mode is not None:
            from .quant import quantize_params

            return quantize_params(staged, self.quant_mode)
        return staged

    def _pool_spec(self):
        from jax.sharding import PartitionSpec

        # [L, slots+1, max_len, H, Dh]: heads axis over tp
        return PartitionSpec(None, None, None,
                             "tp" if self.tp > 1 else None, None)

    def _alloc_pools(self):
        import jax
        from jax.sharding import NamedSharding

        sharding = NamedSharding(self.mesh, self._pool_spec())
        z = np.zeros(self._pool_shape, np.float32)
        return (jax.device_put(z, sharding), jax.device_put(z, sharding))

    def _make_chunk_fn(self, lanes: int, chunk: int, window: int,
                       full: bool = False):
        import jax
        from jax.sharding import PartitionSpec as P

        from ..models.transformer import decode_forward_chunk
        from ..parallel._compat import shard_map

        with self._lock:
            specs = self._param_specs_pytree(self._params)
        body = functools.partial(decode_forward_chunk, cfg=self.cfg,
                                 window=window, full_logits=full,
                                 tp=self.tp,
                                 tp_axis="tp" if self.tp > 1 else None)
        pool = self._pool_spec()
        # the per-lane sample policy vectors replicate, like positions
        samp = {"temp": P(), "topk": P(), "topp": P(), "key": P(),
                "plen": P()}
        fn = shard_map(
            lambda p, pk, pv, tok, pos, val, slot, smp:
                body(p, pk, pv, tok, pos, val, slot, smp),
            mesh=self.mesh,
            in_specs=(specs, pool, pool, P(), P(), P(), P(), samp),
            out_specs=(P(), P(), P(), pool, pool), check_vma=False)
        return jax.jit(fn, donate_argnums=(1, 2))

    def dispatch_chunk(self, tokens, positions, valids, slots, window: int,
                       sample=None, full: bool = False):
        out = super().dispatch_chunk(tokens, positions, valids, slots,
                                     window, sample=sample, full=full)
        # each chunk runs the same static gather schedule as predict —
        # count it so a decode-only sharded replica's collective
        # instruments move too (.shape only: tokens may be the pipelined
        # device carry, and materializing it here would sync the pipeline)
        lanes, chunk = tokens.shape
        self._record_collectives(lanes, seq=chunk)
        return out

    def measured_collectives(self, window: Optional[int] = None) -> int:
        """all-gather count in the compiled steady-state decode step."""
        import jax

        window = window or self.kv_buckets[0]
        entry = self._get_fn(self.max_slots, 1, window)
        toks = np.zeros((self.max_slots, 1), np.int32)
        zeros = np.zeros(self.max_slots, np.int32)
        slots = np.full(self.max_slots, self.trash_slot, np.int32)
        with self._lock:
            params = self._params
        txt = entry.fn.lower(
            params, self.pool_k, self.pool_v,
            jax.numpy.asarray(toks), zeros, zeros, slots,
            self.default_sample(self.max_slots)).compile().as_text()
        return count_hlo_collectives(txt)

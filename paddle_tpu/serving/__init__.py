"""paddle_tpu.serving — dynamic-batching inference serving.

The deployment half of the roadmap: the training side exports a frozen
program (``io.save_inference_model``) and the synchronous ``Predictor``
runs it one request at a time; this package turns that artifact into a
traffic-serving engine. Four pieces, composable or used together via
``ServingServer``:

* ``ServingEngine`` (engine.py) — frozen program + device-resident params,
  bucket-ladder padding, LRU compile cache with hit/miss accounting,
  ``warmup()`` to pre-compile the ladder.
* ``MicroBatcher`` (batcher.py) — bounded-queue request coalescing into one
  padded device call per batch window; rejects (never blocks) when full.
* ``ServingServer`` / ``ServingClient`` (server.py) — dependency-free
  threaded TCP line-JSON front: ``predict`` / ``healthz`` / ``stats``.
* ``ServingStats`` (stats.py) — QPS, latency percentiles, batch fill,
  queue depth, compile hits/misses, rejects.

Quickstart::

    import paddle_tpu as fluid
    from paddle_tpu.serving import ServingServer, ServingClient

    with ServingServer("exported_model_dir", max_batch_size=16,
                       batch_timeout_ms=2.0, warmup=True) as srv:
        with ServingClient(srv.endpoint) as c:
            outs = c.predict({"x": x_batch})   # list of np arrays
            print(c.stats()["latency_ms"])
"""
from .batcher import MicroBatcher, QueueFullError  # noqa: F401
from .engine import ServingEngine  # noqa: F401
from .server import ServingClient, ServingRejected, ServingServer  # noqa: F401
from .stats import ServingStats  # noqa: F401

__all__ = [
    "MicroBatcher", "QueueFullError", "ServingEngine", "ServingClient",
    "ServingRejected", "ServingServer", "ServingStats",
]

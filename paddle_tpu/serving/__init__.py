"""paddle_tpu.serving — dynamic-batching inference serving, fault-tolerant.

The deployment half of the roadmap: the training side exports a frozen
program (``io.save_inference_model``) and the synchronous ``Predictor``
runs it one request at a time; this package turns that artifact into a
traffic-serving engine with a full resilience layer (docs/design.md §12 —
the serving-side re-expression of the reference's Go fault-tolerance
plane). Pieces, composable or used together via ``ServingServer``:

* ``ServingEngine`` (engine.py) — frozen program + device-resident params,
  bucket-ladder padding, LRU compile cache with hit/miss accounting,
  ``warmup()`` to pre-compile the ladder, ``reload_params()`` zero-downtime
  atomic hot weight reload.
* ``MicroBatcher`` (batcher.py) — bounded-queue request coalescing into one
  padded device call per batch window; rejects (never blocks) when full;
  sheds deadline-expired requests at coalesce time; drains on close (a
  submitted future always resolves, with a result or a typed error);
  depth-2 dispatch pipeline (host-prepare of batch N+1 overlaps the
  in-flight device call, docs/design.md §13) with ``flush()`` as the
  reload barrier.
* ``ServingServer`` / ``ServingClient`` (server.py) — dependency-free
  threaded TCP line-JSON front: ``predict`` / ``healthz`` / ``stats`` /
  ``reload``; health state machine (healthy/degraded/draining) with
  probabilistic load shedding; graceful SIGTERM drain. The client retries
  retryable errors with exponential backoff + jitter under a budget and
  reconnects automatically.
* ``ServingStats`` (stats.py) — QPS, latency percentiles, batch fill,
  queue depth, compile hits/misses, rejects/sheds/deadline misses,
  weights version — cumulative and sliding-window.
* ``ChaosInjector`` (chaos.py) — seeded fault injection (slow device
  calls, step faults, connection drops, queue stalls) proving all of the
  above recovers; wired into ``tools/serve_bench.py --chaos``.
* ``FleetRouter`` / ``LocalFleet`` (fleet.py, docs/design.md §17) — the
  fleet tier over N replicas: least-loaded routing off scraped
  ``/metrics`` gauges, per-tenant token-bucket quotas with priority
  shedding, hedged predicts, circuit breaking with half-open probing,
  replica failover under one shared retry budget, rolling reload, and
  autoscale hooks; ``FleetChaos`` (chaos.py) storms it with replica
  kills/restarts, partitions, and slow replicas.
* ``QuantizedServingEngine`` / ``QuantizedDecodeEngine`` (quant.py,
  docs/design.md §20) — weight-only int8/bf16 serving: per-output-channel
  symmetric stores (~26% of the f32 resident bytes at int8) dequantized
  on the fly with f32 accumulation, a typed accuracy contract
  (``quantize_export`` refuses below the greedy-token-agreement floor),
  quantized hot reload (ints and scales swap as one store), bit-safe
  column sharding (``quantize=`` on the sharded engines), and the
  measured CPU lane: ``tools/perf_lab.py cpu`` writes ``cpu_tuned.json``
  only on a >5% closed-loop win and ``ServingServer(quantize="auto")``
  adopts it.
* ``PagedDecodeEngine`` / ``ShardedPagedDecodeEngine`` /
  ``QuantizedPagedDecodeEngine`` (kvcache.py, docs/design.md §22) —
  decode serving over a paged KV pool (fixed-size page blocks + per-slot
  page tables as a static-shape gather index; ~half the dense HBM
  reservation at the default overcommit) with a radix-tree prefix cache:
  shared prompt prefixes prefill ONCE, ref-counted and LRU-evicted,
  invalidated by hot reload, bit-identical greedy streams vs the unpaged
  engine; cache-aware slot-scheduler admission, typed
  ``KVPoolExhausted`` backpressure, ``pt_serving_kv_pages`` /
  ``pt_serving_prefix_*`` gauges.
* ``sampling`` / ``SpecDecoder`` (sampling.py, spec.py, docs/design.md
  §25) — the token-policy subsystem: per-lane temperature/top-k/top-p
  sampling rides the ONE compiled decode step as runtime data (greedy
  lanes stay bit-identical to argmax; sampled lanes are deterministic
  per (request, seed) under any co-tenancy), and speculative decoding
  verifies k draft proposals per lane in one batched target step with
  exact-distribution rejection sampling
  (``GenerationBatcher(spec=SpecDecoder(...))``).
* ``errors`` (errors.py) — the typed error hierarchy + wire codes.

Since PR 9 the whole stack is black-boxed (docs/design.md §19): faults,
health transitions, circuit trips, failovers, reloads, sheds, and chaos
injections emit typed events (``paddle_tpu.obs.events`` — zero-cost when
off, ``log_json=True`` bridges them to stdlib logging as one-line JSON),
``ServingServer(capture_every=N)`` samples requests for bit-identical
replay, and the flight recorder (``paddle_tpu.obs.flight``) freezes
everything into postmortem bundles that ``tools/paddle_cli.py doctor``
reconstructs.

Quickstart::

    import paddle_tpu as fluid
    from paddle_tpu.serving import ServingServer, ServingClient

    with ServingServer("exported_model_dir", max_batch_size=16,
                       batch_timeout_ms=2.0, warmup=True) as srv:
        with ServingClient(srv.endpoint, retries=4) as c:
            outs = c.predict({"x": x_batch}, timeout_ms=200)
            c.reload("exported_model_dir_v2")   # hot weight swap
            print(c.stats()["latency_ms"], c.healthz()["state"])
"""
from .batcher import MicroBatcher, QueueFullError  # noqa: F401
from .chaos import ChaosInjector, FleetChaos  # noqa: F401
from .decode import (DecodeEngine, GenerationBatcher,  # noqa: F401
                     GenerationResult, SlotScheduler)
from .engine import ServingEngine  # noqa: F401
from .errors import (DeadlineExceeded, FleetOverloaded,  # noqa: F401
                     InjectedFault, KVPoolExhausted, LoadShedError,
                     NoHealthyReplicas, RetryBudgetExceeded, ServingError,
                     ServingRejected, ServingUnavailable, ShuttingDown,
                     TenantQuotaExceeded)
from .kvcache import (PagedDecodeEngine,  # noqa: F401
                      QuantizedPagedDecodeEngine, RadixPrefixCache,
                      ShardedPagedDecodeEngine)
from .fleet import FleetRouter, LocalFleet, TokenBucket  # noqa: F401
from .placement import (DeviceInventory, ModelProfile,  # noqa: F401
                        NoFeasiblePlacement, PlacementPlan,
                        PlacementSearcher, TrafficProfile, profile_export)
from .quant import (QuantizationError, QuantizedDecodeEngine,  # noqa: F401
                    QuantizedServingEngine, QuantizedStore, calibrate_error,
                    quantize_export)
from .server import ServingClient, ServingServer  # noqa: F401
from .sharded import (ShardedDecodeEngine,  # noqa: F401
                      ShardedServingEngine, expected_collectives)
from .spec import SpecDecoder  # noqa: F401
from .stats import FleetStats, ServingStats  # noqa: F401

__all__ = [
    "ChaosInjector", "DeadlineExceeded", "DecodeEngine", "DeviceInventory",
    "FleetChaos", "FleetOverloaded", "FleetRouter", "FleetStats",
    "GenerationBatcher", "GenerationResult", "InjectedFault",
    "KVPoolExhausted", "LoadShedError", "LocalFleet", "MicroBatcher",
    "ModelProfile", "NoFeasiblePlacement", "NoHealthyReplicas",
    "PagedDecodeEngine", "PlacementPlan",
    "PlacementSearcher", "QuantizationError", "QuantizedDecodeEngine",
    "QuantizedPagedDecodeEngine",
    "QuantizedServingEngine", "QuantizedStore", "QueueFullError",
    "RadixPrefixCache", "RetryBudgetExceeded", "ServingClient",
    "ServingEngine", "ServingError", "ServingRejected",
    "ServingServer", "ServingStats", "ServingUnavailable",
    "ShardedDecodeEngine", "ShardedPagedDecodeEngine",
    "ShardedServingEngine", "ShuttingDown",
    "SlotScheduler", "SpecDecoder", "TenantQuotaExceeded", "TokenBucket",
    "TrafficProfile", "calibrate_error", "expected_collectives",
    "profile_export", "quantize_export",
]

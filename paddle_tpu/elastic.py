"""Elastic training: worker-loss detection + automatic restart from the
latest sharded checkpoint.

<- the reference's Go fault-tolerance plane: the master re-queues work from
dead workers (go/master/service.go:313-356 checkTimeoutFunc) and pserver
clients re-resolve membership from etcd on change
(go/pserver/client/etcd_client.go:35-110). A jax.distributed world is a
FIXED topology — a lost process breaks every in-flight collective — so the
TPU-native re-expression of elastic membership is supervisor-driven
restart: detect the loss (process exit OR missed heartbeats, which also
catches hangs), tear the incarnation down, re-form the cluster, and resume
from the newest complete per-shard checkpoint (io.save_checkpoint's
_SUCCESS-marked serials, which multi-host barriers keep consistent).

Roles:
  ElasticSupervisor — owns the heartbeat master (master/rpc.py), spawns the
      worker processes with fresh coordinator endpoints per incarnation,
      monitors exit codes + heartbeat TTL, restarts up to ``max_restarts``.
  ElasticWorker — worker-side helper: per-step heartbeat to the master and
      checkpoint-resume (returns the step to continue from).

Driven end-to-end by tests/test_distributed.py::
test_elastic_recovery_restarts_from_checkpoint (2-process localhost
cluster, one worker hangs mid-run, the job resumes and converges).
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional, Sequence

from .master.rpc import MasterRPCClient, MasterServer


def _reserve_ports(n: int) -> List[socket.socket]:
    """Bind n ephemeral ports and KEEP the sockets open; the caller closes
    them immediately before spawning the workers that re-bind them. The
    bound window shrinks the bind-then-reuse race to the spawn instant
    (it cannot be eliminated without workers binding port 0 themselves and
    reporting back); a residual collision surfaces as a worker exit and is
    named as a possibility in the supervisor's failure event."""
    socks = []
    try:
        for _ in range(n):
            # no SO_REUSEADDR: the reservation socket never listens (no
            # TIME_WAIT to bypass), and REUSEADDR on the holder would let
            # any other REUSEADDR binder take the port DURING the hold —
            # defeating the exclusion. Workers re-bind after close()
            # without needing it.
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
    except Exception:
        for s in socks:
            s.close()
        raise
    return socks


class ElasticSupervisor:
    """Spawn-and-watch loop for an n-worker localhost training job.

    worker_argv: the command each worker runs (the supervisor adds the
    PADDLE_* cluster env, PADDLE_MASTER_ENDPOINT and PADDLE_ELASTIC_GEN).
    A worker is declared lost when its process exits nonzero OR when its
    last heartbeat is older than ``heartbeat_ttl`` (after an initial
    ``startup_grace`` for cluster formation). On loss: every survivor is
    killed (their collectives are wedged anyway) and the job restarts —
    workers are expected to resume via ElasticWorker.resume_step.
    """

    def __init__(self, worker_argv: Sequence[str], n_workers: int,
                 heartbeat_ttl: float = 15.0, startup_grace: float = 120.0,
                 max_restarts: int = 3, poll_interval: float = 0.5,
                 restart_backoff: float = 1.0,
                 restart_backoff_max: float = 30.0,
                 env: Optional[Dict[str, str]] = None, cwd: Optional[str] = None,
                 on_event: Optional[Callable[[str], None]] = None):
        self.worker_argv = list(worker_argv)
        self.n_workers = n_workers
        self.heartbeat_ttl = heartbeat_ttl
        self.startup_grace = startup_grace
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        # exponential backoff between incarnations: an immediate respawn
        # of a persistently-failing job (bad image, poisoned checkpoint,
        # flapping host) hammers the machine and floods the logs; doubling
        # the pause per restart gives transient faults time to clear.
        # restart_backoff=0 disables (tests that count restarts quickly).
        self.restart_backoff = restart_backoff
        self.restart_backoff_max = restart_backoff_max
        self.env = dict(env or {})
        self.cwd = cwd
        self.on_event = on_event or (lambda msg: None)
        self.restarts = 0
        self.outputs: List[List[str]] = []  # per incarnation, per rank
        self._logs: List = []  # open per-rank log files, current incarnation

    def restart_delay(self, restarts: Optional[int] = None) -> float:
        """Backoff before incarnation ``restarts + 1``: base * 2^restarts,
        capped at ``restart_backoff_max``."""
        n = self.restarts if restarts is None else restarts
        if self.restart_backoff <= 0:
            return 0.0
        # cap the exponent before the pow: 2.0**1024 overflows float, and
        # any sane cap is hit long before 2**63 anyway
        return min(self.restart_backoff_max,
                   self.restart_backoff * (2.0 ** min(n, 63)))

    def _spawn(self, server: MasterServer) -> List[subprocess.Popen]:
        gen = server.service.new_generation()
        socks = _reserve_ports(self.n_workers)
        endpoints = ",".join(
            f"127.0.0.1:{s.getsockname()[1]}" for s in socks)
        envs = []
        for i in range(self.n_workers):
            e = dict(os.environ)
            for k, v in self.env.items():
                if v is None:
                    e.pop(k, None)  # None = unset (e.g. strip PYTHONPATH)
                else:
                    e[k] = v
            e["PADDLE_TRAINER_ENDPOINTS"] = endpoints
            e["PADDLE_TRAINERS_NUM"] = str(self.n_workers)
            e["PADDLE_TRAINER_ID"] = str(i)
            e["PADDLE_MASTER_ENDPOINT"] = server.endpoint
            e["PADDLE_ELASTIC_GEN"] = str(gen)
            envs.append(e)
        # Workers log to temp files, not pipes: a PIPE nobody drains blocks
        # the worker inside print after ~64KB, stops its heartbeats, and the
        # supervisor would kill a healthy job as hung (advisor r3, medium).
        for s in socks:
            s.close()  # released at the last instant before the re-bind
        procs = []
        self._logs = []
        for e in envs:
            logf = tempfile.TemporaryFile(mode="w+", encoding="utf-8",
                                          errors="replace")
            self._logs.append(logf)
            procs.append(subprocess.Popen(
                self.worker_argv, stdout=logf,
                stderr=subprocess.STDOUT, text=True, cwd=self.cwd, env=e))
        self.on_event(f"spawned generation {gen} ({self.n_workers} workers)")
        return procs

    def _kill_all(self, procs):
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=30)
            except Exception:
                pass
        outs = []
        for logf in self._logs:
            try:
                logf.seek(0)
                outs.append(logf.read())
            except Exception:
                outs.append("")
            finally:
                logf.close()
        self._logs = []
        self.outputs.append(outs)

    def run(self) -> int:
        """Run to completion; returns the number of restarts performed.
        Raises RuntimeError when max_restarts is exhausted."""
        with MasterServer() as server:
            for _attempt in range(self.max_restarts + 1):
                procs = self._spawn(server)
                t0 = time.monotonic()
                failed = None
                while True:
                    time.sleep(self.poll_interval)
                    codes = [p.poll() for p in procs]
                    if any(c not in (None, 0) for c in codes):
                        failed = (f"worker exit codes {codes} (early exits "
                                  f"can also mean an endpoint port was "
                                  f"grabbed by another process between "
                                  f"reservation and worker bind)")
                        break
                    if all(c == 0 for c in codes):
                        self._kill_all(procs)
                        return self.restarts
                    hb = server.service.live_workers(self.heartbeat_ttl)
                    # the first beat precedes the (compile-heavy) first
                    # step, so the grace window holds until every worker
                    # has COMPLETED a step (reported step >= 1) — a slow
                    # first compile must not read as a wedged worker
                    steps = hb["steps"]
                    all_progressed = (
                        len(steps) == self.n_workers
                        and all(s >= 1 for s in steps.values()))
                    waited = time.monotonic() - t0
                    if all_progressed or waited > self.startup_grace:
                        missing = [i for i in range(self.n_workers)
                                   if i not in hb["live"]
                                   and codes[i] is None]
                        if missing:
                            failed = (f"heartbeat lost for workers {missing} "
                                      f"(steps {hb['steps']})")
                            break
                self._kill_all(procs)
                if _attempt == self.max_restarts:
                    self.on_event(f"incarnation failed: {failed}")
                    break
                delay = self.restart_delay()
                self.on_event(
                    f"incarnation failed: {failed}; restarting in "
                    f"{delay:.1f}s (restart {self.restarts + 1}/"
                    f"{self.max_restarts})")
                if delay > 0:
                    time.sleep(delay)
                self.restarts += 1
            raise RuntimeError(
                f"elastic job failed: {failed}; gave up after "
                f"{self.restarts} restarts (max_restarts="
                f"{self.max_restarts})")


class ElasticWorker:
    """Worker-side elastic plumbing: heartbeats + checkpoint resume."""

    def __init__(self, master_endpoint: Optional[str] = None,
                 worker_id: Optional[int] = None):
        self.endpoint = master_endpoint or os.environ.get(
            "PADDLE_MASTER_ENDPOINT")
        self.worker_id = (int(os.environ.get("PADDLE_TRAINER_ID", 0))
                          if worker_id is None else worker_id)
        self._client = (MasterRPCClient(self.endpoint)
                        if self.endpoint else None)

    def heartbeat(self, step: int):
        """Report liveness + progress; call once per training step. A hung
        step therefore reads as a lost worker after the TTL — that is the
        point (background-thread beats would mask wedged collectives). The
        beat carries this incarnation's generation so a stale pre-restart
        worker cannot pollute the successor's registry."""
        if self._client is not None:
            gen = os.environ.get("PADDLE_ELASTIC_GEN")
            self._client.call("heartbeat", self.worker_id, int(step),
                              None if gen is None else int(gen))

    def resume_step(self, executor, checkpoint_dir, main_program=None,
                    scope=None, host_tables=None) -> int:
        """Load the newest complete checkpoint into ``scope`` and return
        the step to continue FROM (serial + 1); 0 when none exists.
        ``host_tables``: HostEmbeddingTable instances restored alongside
        the device persistables — the pserver-resident parameter class the
        reference's elastic plane recovered via its shard checkpoints
        (go/pserver/service.go LoadCheckpoint)."""
        from . import io as fio

        try:
            serial = fio.load_checkpoint(executor, checkpoint_dir,
                                         main_program=main_program,
                                         scope=scope,
                                         host_tables=host_tables)
            return serial + 1
        except FileNotFoundError:
            return 0

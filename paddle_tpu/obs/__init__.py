"""paddle_tpu.obs — unified tracing + metrics (docs/design.md §15).

The observability plane for the hot paths built in PRs 1-4: when a p99
regresses or occupancy drops, the spans and metrics here say WHICH stage
(queue wait / pad / H2D / device / sync / scatter; host prep / H2D /
device window / fetch sync) ate the time — you cannot tune what you
cannot attribute.

* ``trace``   — ``Tracer``: thread-safe nested spans on a monotonic clock
  in a bounded ring, zero-cost when disabled, Chrome trace-event export,
  p99 exemplar retention (``ExemplarStore``). Request/step correlation
  via ``new_trace_id()`` riding the serving wire protocol.
* ``metrics`` — ``MetricsRegistry``: counters/gauges/histograms with
  Prometheus text exposition. ``ServingStats`` publishes through one of
  these (one source of truth); training instruments use the process
  default (``get_registry()``).
* ``cost``    — XLA cost-analysis FLOPs annotation at compile time (the
  executor and serving compile caches), powering the live MFU gauges.
* ``http``    — ``MetricsServer``: a standalone ``GET /metrics`` endpoint
  for training jobs (ServingServer answers /metrics on its own port).
* ``events``  — ``EventLog``: typed, bounded, thread-safe structured
  events (health transitions, circuit trips, failovers, reloads, sheds,
  chaos injections, NaN sentinels) with pluggable sinks incl. a stdlib-
  ``logging`` one-line-JSON bridge; zero-cost when disabled (docs §19).
* ``flight``  — ``FlightRecorder``: postmortem bundles (events + span
  exemplars + metrics + flags + provider snapshots), sampled request
  capture and a bit-identical replay harness, triggered by worker-thread
  crashes / SLO breaches / NaN sentinels / signals / ``dump()``.
* ``slo``     — ``SLOWatchdog``: declarative multi-window burn-rate SLOs
  (p95 ceiling, error-rate budget, MFU / decode-tokens floors) evaluated
  off the existing registry; breaches export ``pt_slo_*``, emit events,
  and trip flight-recorder dumps.

Turn tracing on with ``flags.set_flag("obs_trace", True)`` (or
``PT_FLAG_OBS_TRACE=1``), or programmatically ``obs.enable()``; the
event log with ``obs_events`` / ``events.get_event_log().enable()``.
"""
from .trace import (ExemplarStore, Span, Tracer, disable, enable,  # noqa: F401
                    get_tracer, init_from_flags, new_trace_id)
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      RateWindow, get_registry)
from .cost import abstractify, analyze_jit, flops_of_lowered, peak_flops  # noqa: F401
from .http import MetricsServer  # noqa: F401
from .events import (DISCARDED, Event, EventLog,  # noqa: F401
                     LoggingJSONSink, enable_json_logging, get_event_log)
from .flight import (FlightRecorder, get_recorder, load_bundle,  # noqa: F401
                     replay_bundle, validate_bundle)
from .mem import (MemoryLedger, NOOP_ALLOCATION, get_ledger)  # noqa: F401
from .slo import SLO, SLOWatchdog, judge_bench, parse_slo_spec  # noqa: F401
from .goodput import (GOOD_CATEGORIES, TRAIN_CATEGORIES,  # noqa: F401
                      GoodputAccountant, get_accountant,
                      serving_categories)
from .profile import (ProfileError, attribute_regression,  # noqa: F401
                      build_profile, diff_profiles, format_diff,
                      goodput_report, load_profile, profile_from_window,
                      save_profile)

__all__ = [
    "Counter", "DISCARDED", "Event", "EventLog", "ExemplarStore",
    "FlightRecorder", "GOOD_CATEGORIES", "Gauge", "GoodputAccountant",
    "Histogram", "LoggingJSONSink",
    "MemoryLedger", "MetricsRegistry", "MetricsServer", "NOOP_ALLOCATION",
    "ProfileError", "RateWindow",
    "SLO", "SLOWatchdog",
    "Span", "TRAIN_CATEGORIES", "Tracer", "abstractify", "analyze_jit",
    "attribute_regression", "build_profile", "diff_profiles",
    "disable", "enable", "enable_json_logging", "flops_of_lowered",
    "format_diff", "get_accountant", "get_event_log", "get_ledger",
    "get_recorder",
    "get_registry", "get_tracer", "goodput_report",
    "init_from_flags", "judge_bench", "load_bundle", "load_profile",
    "new_trace_id", "parse_slo_spec", "peak_flops", "profile_from_window",
    "replay_bundle", "save_profile", "serving_categories",
    "validate_bundle",
]

"""paddle_tpu.obs — unified tracing + metrics (docs/design.md §15).

The observability plane for the hot paths built in PRs 1-4: when a p99
regresses or occupancy drops, the spans and metrics here say WHICH stage
(queue wait / pad / H2D / device / sync / scatter; host prep / H2D /
device window / fetch sync) ate the time — you cannot tune what you
cannot attribute.

* ``trace``   — ``Tracer``: thread-safe nested spans on a monotonic clock
  in a bounded ring, zero-cost when disabled, Chrome trace-event export,
  p99 exemplar retention (``ExemplarStore``). Request/step correlation
  via ``new_trace_id()`` riding the serving wire protocol.
* ``metrics`` — ``MetricsRegistry``: counters/gauges/histograms with
  Prometheus text exposition. ``ServingStats`` publishes through one of
  these (one source of truth); training instruments use the process
  default (``get_registry()``).
* ``cost``    — XLA cost-analysis FLOPs annotation at compile time (the
  executor and serving compile caches), powering the live MFU gauges.
* ``http``    — ``MetricsServer``: a standalone ``GET /metrics`` endpoint
  for training jobs (ServingServer answers /metrics on its own port).

Turn tracing on with ``flags.set_flag("obs_trace", True)`` (or
``PT_FLAG_OBS_TRACE=1``), or programmatically ``obs.enable()``.
"""
from .trace import (ExemplarStore, Span, Tracer, disable, enable,  # noqa: F401
                    get_tracer, init_from_flags, new_trace_id)
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      RateWindow, get_registry)
from .cost import abstractify, analyze_jit, flops_of_lowered, peak_flops  # noqa: F401
from .http import MetricsServer  # noqa: F401

__all__ = [
    "Counter", "ExemplarStore", "Gauge", "Histogram", "MetricsRegistry",
    "MetricsServer", "RateWindow", "Span", "Tracer", "abstractify",
    "analyze_jit",
    "disable", "enable", "flops_of_lowered", "get_registry", "get_tracer",
    "init_from_flags", "new_trace_id", "peak_flops",
]

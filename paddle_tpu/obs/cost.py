"""XLA cost-analysis FLOPs annotation — the MFU attribution source.

bench.py computes MFU from *analytic* model FLOPs; that only works when a
human sat down with the architecture. Live attribution needs the number
for WHATEVER program is currently compiled, so the executor and serving
compile caches annotate each cache entry with the FLOPs XLA's own cost
analysis assigns to the lowered computation
(``jax.stages.Lowered.cost_analysis()`` — no XLA compile needed; the
pre-optimization HLO walk is milliseconds and runs ONCE per cache entry,
i.e. per unique program signature).

MFU then falls out per dispatch: ``flops_per_call x calls_per_sec /
(peak_tflops x 1e12)``, with the peak from ``flags.obs_peak_tflops``
(default: bench.py's chip nominal). Pre-optimization FLOPs slightly
overcount what a fused executable really retires (CSE/DCE land later) —
good enough for attribution, and the bias is stable across rounds, so
trends are trustworthy.
"""
from __future__ import annotations

from typing import Any, Dict, Optional


def _cost_dict(lowered) -> Optional[dict]:
    """The cost-analysis dict of a ``jax.stages.Lowered``, or None (never
    raises — telemetry must not take down the hot path it measures)."""
    try:
        ca = lowered.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):  # per-device list on some backends
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) else None


def _positive(v) -> Optional[float]:
    try:
        v = float(v)
    except (TypeError, ValueError):
        return None
    # some backends report -1/0 for "unknown"
    return v if v > 0 else None


def flops_of_lowered(lowered) -> Optional[float]:
    """FLOPs from a ``jax.stages.Lowered``; None when unavailable."""
    ca = _cost_dict(lowered)
    return _positive(ca.get("flops")) if ca else None


def analyze_jit(fn, *abstract_args, static=None) -> Dict[str, Any]:
    """Lower ``fn`` (a plain function or jax.jit wrapper) against
    ``jax.ShapeDtypeStruct`` args and return {'flops': float|None,
    'bytes': float|None}. Shared by the serving engine and the executor so
    both caches annotate the same way."""
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    try:
        lowered = jitted.lower(*abstract_args)
    except Exception:
        return {"flops": None, "bytes": None}
    # ONE cost-analysis walk (it re-traverses the whole HLO) for both stats
    ca = _cost_dict(lowered)
    if not ca:
        return {"flops": None, "bytes": None}
    return {"flops": _positive(ca.get("flops")),
            "bytes": _positive(ca.get("bytes accessed"))}


def abstractify(v) -> "Any":
    """Value -> ShapeDtypeStruct (arrays pass structurally, pytrees map)."""
    import jax

    def one(x):
        import numpy as np
        a = x if hasattr(x, "shape") and hasattr(x, "dtype") else np.asarray(x)
        return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)

    return jax.tree_util.tree_map(one, v)


def peak_flops() -> float:
    """Chip peak in FLOP/s from ``flags.obs_peak_tflops``."""
    from ..flags import get_flag

    return float(get_flag("obs_peak_tflops")) * 1e12

"""FlightRecorder: postmortem bundles + sampled request capture + replay.

The black-box flight recorder of the serving/training stack (docs §19).
It holds *references* to the live telemetry — the event log, the tracer's
p99 exemplars, metrics registries, flags, and any registered providers
(each ``ServingServer`` / ``FleetRouter`` / ``SLOWatchdog`` contributes a
snapshot callable) — and, on a trigger, freezes everything into ONE
schema-versioned JSON bundle an operator can carry away from the incident:

* **triggers** — an unhandled exception on a paddle-tpu worker thread
  (``arm()`` chains ``threading.excepthook``), an SLO breach (the
  watchdog calls ``maybe_dump``), the first training NaN (executor
  sentinel), a signal (``install_signal_handler``), or an explicit
  ``dump()``. Automatic triggers are rate-limited per reason so a breach
  storm cannot write a thousand bundles.
* **zero-cost when off** — the recorder does nothing until triggered;
  the only hot-path touch is the *sampled* request capture, guarded by
  one counter compare at the serving handler.
* **request capture + replay** — 1-in-N successful predict/generate
  requests are captured (inputs, bucket signature, seed, weights
  version, output digest) into a bounded ring; ``replay_bundle()``
  re-runs each capture against a FRESH engine built from the recorded
  export dir and verifies bit-identical outputs (serving is
  deterministic: frozen weights, fixed PRNG key, greedy decode).

Bundle schema v1 (validated by ``validate_bundle``)::

    {schema_version, created_unix, created_monotonic, trigger,
     events: [...], events_dropped, event_counts,
     exemplars: [...], metrics: {name: prometheus_text},
     flags: {...}, providers: {name: {...}}, captures: [...],
     process: {python, jax, pid}}
"""
from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .events import get_event_log
from .metrics import get_registry
from .trace import get_tracer

SCHEMA_VERSION = 1

#: keys every v1 bundle must carry (validate_bundle enforces)
REQUIRED_KEYS = ("schema_version", "created_unix", "created_monotonic",
                 "trigger", "events", "events_dropped", "event_counts",
                 "exemplars", "metrics", "flags", "providers", "captures",
                 "process")

#: encoded arrays above this many bytes keep only their digest (bundles
#: must stay carry-able; the digest alone still proves bit-identity)
MAX_CAPTURE_BYTES = 1 << 20


def encode_array(arr: np.ndarray) -> Dict[str, Any]:
    arr = np.ascontiguousarray(arr)
    out: Dict[str, Any] = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
    if arr.nbytes <= MAX_CAPTURE_BYTES:
        out["data"] = arr.tolist()
    return out


def decode_array(spec: Dict[str, Any]) -> np.ndarray:
    return np.asarray(spec["data"], dtype=spec["dtype"]).reshape(
        spec["shape"])


def output_digest(arrays) -> str:
    """Canonical sha256 over (dtype, shape, raw bytes) of every output —
    the bit-identity witness replay compares against."""
    h = hashlib.sha256()
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a))
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class FlightRecorder:
    """Snapshot the live telemetry into postmortem bundles on triggers."""

    def __init__(self, events=None, tracer=None, registry=None,
                 dir: Optional[str] = None, capture_limit: int = 64,
                 min_dump_interval_s: float = 2.0):
        self.events = events or get_event_log()
        self.tracer = tracer or get_tracer()
        self.registry = registry or get_registry()
        self.dir = dir  # None -> flags.obs_flight_dir -> tempdir
        self.capture_limit = int(capture_limit)
        self.min_dump_interval_s = float(min_dump_interval_s)
        self._lock = threading.Lock()
        self._providers: Dict[str, Callable[[], Any]] = {}
        self._captures: deque = deque(maxlen=self.capture_limit)
        self._capture_seq = 0
        self._last_dump: Dict[str, float] = {}  # trigger type -> monotonic
        self.dumps: List[str] = []  # bundle paths written
        self.dump_errors = 0
        self._armed = False
        self._prev_excepthook = None

    # -- providers ---------------------------------------------------------
    def register_provider(self, name: str, fn: Callable[[], Any]) -> str:
        """Register a snapshot callable whose result lands under
        ``providers[name]`` in every bundle (a server's weights version +
        placement, a router's replica table, the watchdog's summary).
        Returns the name as an unregister token."""
        with self._lock:
            self._providers[name] = fn
        return name

    def unregister_provider(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    # -- request capture ---------------------------------------------------
    def capture_predict(self, model_dir: str, feeds: Dict[str, Any],
                        outputs, weights_version=None,
                        trace_id: Optional[str] = None,
                        seed: int = 0) -> None:
        """Record one successful predict: inputs, bucket signature, seed,
        weights version, and the outputs' digest (+ data when small).
        Never raises — capture is telemetry, not the data path."""
        try:
            enc = {n: encode_array(np.asarray(a)) for n, a in feeds.items()}
            rows = next(iter(enc.values()))["shape"][0] if enc else 0
            sig = sorted((n, s["shape"][1:], s["dtype"])
                         for n, s in enc.items())
            with self._lock:
                self._capture_seq += 1
                self._captures.append({
                    "id": self._capture_seq, "kind": "predict",
                    "model_dir": model_dir, "feeds": enc, "rows": rows,
                    "bucket_sig": sig, "seed": int(seed),
                    "weights_version": weights_version,
                    "trace_id": trace_id, "wall": time.time(),
                    "outputs": [encode_array(np.asarray(o))
                                for o in outputs],
                    "digest": output_digest(outputs)})
        except Exception:
            pass

    def capture_generate(self, model_dir: str, prompt,
                         max_new_tokens: Optional[int], eos_id,
                         tokens, weights_version=None,
                         trace_id: Optional[str] = None) -> None:
        """Record one successful generation (prompt, budget, eos, weights
        version, produced token ids). Never raises."""
        try:
            with self._lock:
                self._capture_seq += 1
                self._captures.append({
                    "id": self._capture_seq, "kind": "generate",
                    "model_dir": model_dir,
                    "prompt": [int(t) for t in
                               np.asarray(prompt).reshape(-1)],
                    "max_new_tokens": (int(max_new_tokens)
                                       if max_new_tokens is not None
                                       else None),
                    "eos_id": int(eos_id) if eos_id is not None else None,
                    "weights_version": weights_version,
                    "trace_id": trace_id, "wall": time.time(),
                    "tokens": [int(t) for t in tokens]})
        except Exception:
            pass

    @property
    def captures(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._captures)

    # -- bundles -----------------------------------------------------------
    def _resolve_dir(self) -> str:
        if self.dir:
            return self.dir
        from ..flags import get_flag

        d = get_flag("obs_flight_dir")
        return d or os.path.join(tempfile.gettempdir(), "paddle_tpu_flight")

    def snapshot(self, trigger: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
        """Freeze the telemetry into one schema-v1 bundle dict."""
        with self._lock:
            providers = dict(self._providers)
            captures = list(self._captures)
        prov_out: Dict[str, Any] = {}
        for name, fn in providers.items():
            try:
                prov_out[name] = fn()
            except Exception as e:  # a dead provider must not kill the dump
                prov_out[name] = {"error": f"{type(e).__name__}: {e}"}
        try:
            metrics = {"default": self.registry.expose()}
        except Exception:
            metrics = {}
        try:
            from ..flags import flags as _flags

            flag_snap = _flags()
        except Exception:
            flag_snap = {}
        try:
            import jax

            jax_ver = jax.__version__
        except Exception:
            jax_ver = None
        return {
            "schema_version": SCHEMA_VERSION,
            "created_unix": time.time(),
            "created_monotonic": time.monotonic(),
            "trigger": dict(trigger or {"type": "manual"}),
            "events": self.events.snapshot(),
            "events_dropped": self.events.dropped,
            "event_counts": self.events.counts(),
            "exemplars": self.tracer.exemplars.snapshot(),
            "metrics": metrics,
            "flags": flag_snap,
            "providers": prov_out,
            "captures": captures,
            "process": {"python": sys.version.split()[0], "jax": jax_ver,
                        "pid": os.getpid()},
        }

    def dump(self, path: Optional[str] = None,
             trigger: Optional[Dict[str, Any]] = None) -> str:
        """Write one bundle; returns its path. An explicit dump is never
        rate-limited (the operator asked)."""
        bundle = self.snapshot(trigger)
        if path is None:
            d = self._resolve_dir()
            os.makedirs(d, exist_ok=True)
            ttype = bundle["trigger"].get("type", "manual")
            path = os.path.join(
                d, f"flight_{ttype}_{int(time.time() * 1e3)}_"
                   f"{os.getpid()}.json")
        with open(path, "w") as f:
            json.dump(bundle, f, default=str)
        with self._lock:
            self.dumps.append(path)
        ev = self.events
        if ev.enabled:
            ev.emit("bundle_dumped", path=path,
                    trigger=bundle["trigger"].get("type"))
        return path

    def maybe_dump(self, trigger: Dict[str, Any]) -> Optional[str]:
        """Rate-limited automatic dump (one per trigger type per
        ``min_dump_interval_s``); returns the path or None. Never raises
        — an automatic trigger fires from hot/exception paths."""
        ttype = trigger.get("type", "auto")
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(ttype, -1e18)
            if now - last < self.min_dump_interval_s:
                return None
            self._last_dump[ttype] = now
        try:
            return self.dump(trigger=trigger)
        except Exception:
            self.dump_errors += 1
            return None

    def clear(self) -> None:
        """Reset captures/dump history/rate limits (tests)."""
        with self._lock:
            self._captures.clear()
            self._last_dump.clear()
            self.dumps = []

    # -- automatic triggers ------------------------------------------------
    _WORKER_PREFIXES = ("paddle-tpu", "pt-fleet")

    def arm(self, dir: Optional[str] = None) -> "FlightRecorder":
        """Install the worker-thread crash trigger: an unhandled exception
        on any ``paddle-tpu-*`` / ``pt-fleet-*`` thread (engine, batcher,
        decode loop, fleet scraper/hedger, chaos) emits a
        ``worker_exception`` event and dumps a bundle. Chains the previous
        ``threading.excepthook``. Idempotent."""
        if dir is not None:
            self.dir = dir
        if self._armed:
            return self
        self._armed = True
        prev = threading.excepthook
        self._prev_excepthook = prev
        rec = self

        def hook(args):
            try:
                name = getattr(args.thread, "name", "") or ""
                if name.startswith(rec._WORKER_PREFIXES):
                    ev = rec.events
                    if ev.enabled:
                        ev.emit("worker_exception", severity="error",
                                thread=name,
                                exc=f"{getattr(args.exc_type, '__name__', args.exc_type)}: "
                                    f"{args.exc_value}")
                    rec.maybe_dump({"type": "worker_exception",
                                    "thread": name,
                                    "exc": str(args.exc_value)})
            except Exception:
                pass
            prev(args)

        threading.excepthook = hook
        return self

    def disarm(self) -> None:
        if self._armed and self._prev_excepthook is not None:
            threading.excepthook = self._prev_excepthook
        self._armed = False
        self._prev_excepthook = None

    def install_signal_handler(self, signum=None) -> None:
        """SIGUSR2 (default) -> dump a bundle. Main thread only (a CPython
        ``signal.signal`` constraint)."""
        import signal as _signal

        signum = _signal.SIGUSR2 if signum is None else signum

        def _on(sig, frame):
            threading.Thread(
                target=lambda: self.maybe_dump({"type": "signal",
                                                "signum": int(sig)}),
                daemon=True, name="paddle-tpu-flight-dump").start()

        _signal.signal(signum, _on)


_default_recorder = FlightRecorder()


def get_recorder() -> FlightRecorder:
    """The process-wide default recorder (servers, routers, the executor
    sentinel, and the SLO watchdog all feed/trip this one)."""
    return _default_recorder


# -- bundle validation -----------------------------------------------------

def validate_bundle(bundle: Dict[str, Any]) -> List[str]:
    """Schema-v1 check; returns a list of problems (empty = valid)."""
    problems: List[str] = []
    if not isinstance(bundle, dict):
        return ["bundle is not a JSON object"]
    for k in REQUIRED_KEYS:
        if k not in bundle:
            problems.append(f"missing key {k!r}")
    if bundle.get("schema_version") != SCHEMA_VERSION:
        problems.append(
            f"schema_version {bundle.get('schema_version')!r} != "
            f"{SCHEMA_VERSION}")
    if not isinstance(bundle.get("trigger"), dict) or \
            "type" not in (bundle.get("trigger") or {}):
        problems.append("trigger must be a dict with a 'type'")
    for i, ev in enumerate(bundle.get("events") or []):
        for k in ("eid", "type", "severity", "t", "wall"):
            if k not in ev:
                problems.append(f"events[{i}] missing {k!r}")
                break
    for i, cap in enumerate(bundle.get("captures") or []):
        kind = cap.get("kind")
        if kind not in ("predict", "generate"):
            problems.append(f"captures[{i}] bad kind {kind!r}")
        elif kind == "predict" and ("feeds" not in cap
                                    or "digest" not in cap):
            problems.append(f"captures[{i}] predict missing feeds/digest")
        elif kind == "generate" and ("prompt" not in cap
                                     or "tokens" not in cap):
            problems.append(f"captures[{i}] generate missing prompt/tokens")
    return problems


def load_bundle(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


# -- replay harness --------------------------------------------------------

def replay_bundle(bundle, model_dir: Optional[str] = None
                  ) -> List[Dict[str, Any]]:
    """Re-run every captured request against a FRESH engine built from
    the capture's recorded export dir (``model_dir`` overrides, e.g. the
    bundle traveled to another machine) and verify bit-identical outputs.

    Predicts re-run through ``ServingEngine.run_batch`` (same bucket
    ladder, same fixed PRNG key) and compare output digests; generations
    re-run through ``generate_sequential`` (the same compiled signatures
    the continuous batcher used — lane-independent math) and compare
    exact token ids. Returns one ``{id, kind, ok, detail}`` per capture
    (``ok=None`` = skipped: a digest-only capture whose inputs were too
    large to travel — not a bit-identity failure).
    """
    if isinstance(bundle, str):
        bundle = load_bundle(bundle)
    # lazy: obs must stay importable without the serving tree
    from ..serving.decode import DecodeEngine, generate_sequential
    from ..serving.engine import ServingEngine

    results: List[Dict[str, Any]] = []
    engines: Dict[str, ServingEngine] = {}
    dengines: Dict[str, DecodeEngine] = {}
    for cap in bundle.get("captures") or []:
        d = model_dir or cap.get("model_dir")
        entry = {"id": cap.get("id"), "kind": cap.get("kind"),
                 "weights_version": cap.get("weights_version")}
        try:
            if cap["kind"] == "predict":
                if any("data" not in s for s in cap["feeds"].values()):
                    # digest-only capture (a feed exceeded
                    # MAX_CAPTURE_BYTES): the inputs did not travel, so
                    # bit-identity cannot be re-verified — skipped, not
                    # failed
                    entry["ok"] = None
                    entry["detail"] = ("skipped: feeds captured "
                                       "digest-only (over the capture "
                                       "size limit)")
                    results.append(entry)
                    continue
                eng = engines.get(d)
                if eng is None:
                    eng = engines[d] = ServingEngine(
                        d, max_batch_size=max(32, int(cap.get("rows") or 1)))
                feeds = {n: decode_array(s)
                         for n, s in cap["feeds"].items()}
                outs = eng.run_batch(feeds)
                got = output_digest(outs)
                entry["ok"] = got == cap["digest"]
                entry["detail"] = ("bit-identical" if entry["ok"] else
                                   f"digest {got[:12]} != "
                                   f"{cap['digest'][:12]}")
            else:
                deng = dengines.get(d)
                if deng is None:
                    deng = dengines[d] = DecodeEngine(d, max_slots=1)
                budget = cap.get("max_new_tokens") or len(cap["tokens"])
                toks = generate_sequential(
                    deng, [np.asarray(cap["prompt"], np.int64)], budget,
                    eos_id=cap.get("eos_id"))[0]
                entry["ok"] = toks == list(cap["tokens"])
                entry["detail"] = ("bit-identical" if entry["ok"] else
                                   f"tokens {toks} != {cap['tokens']}")
        except Exception as e:
            entry["ok"] = False
            entry["detail"] = f"replay error: {type(e).__name__}: {e}"
        results.append(entry)
    return results

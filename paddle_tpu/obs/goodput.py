"""Goodput accountant: classify every second of wall time, name its owner.

PRs 5/9 gave the stack signals (spans, stage histograms, typed events);
nothing *accounts* for time — when a bench round slips or a replica's p95
drifts, a human greps spans by hand. This module is the attribution tier
(docs/design.md §23): an exhaustive, non-overlapping taxonomy over the
existing instrumentation, with a closure invariant (categories sum to the
measured wall within tolerance) so "where did the time go" is a framework
answer, not an investigation.

Two planes, one accountant:

* **training** — instrumented code (the executor's ``run``/``run_steps``
  paths, the prefetcher) feeds raw intervals via ``account(category, t0,
  dur)``; a *window* (``window()`` context manager, one ``run_steps``
  bench loop, a trainer epoch) classifies them with a priority sweep into
  ``device_compute / host_input / h2d / compile / fetch_sync / idle``.
  The sweep attributes every instant of the window to exactly ONE
  category (overlaps resolve by priority: a device-bound instant is
  device_compute even while the prefetcher stages the next batch — time
  hidden behind the device is not badput), so the closure invariant
  ``sum(categories) == wall`` holds exactly by construction; ``idle`` is
  the uncovered remainder and *attributed* time (non-idle) is the
  coverage witness the ``goodput_accounting_closure`` bench bar judges.
* **serving** — per-request accounting off the stage timings the batcher
  already records: the ONE stage list in ``serving/stats.py`` (``STAGES``)
  plus the accountant's non-stage request categories (``retry_backoff``,
  ``shed``) and the per-request ``idle`` residual. Categories sum to the
  request's measured wall (``timings["total"]``) within tolerance because
  the stage timestamps are contiguous by construction (batcher.py).

Design constraints (the PR-5 discipline, verbatim):

* **zero-cost when disabled** — ``window()`` returns one shared no-op
  singleton (identity-tested), ``account*()`` is one attribute read and
  an early return; every instrumentation site guards on ``enabled``.
* **bounded** — raw intervals land in an overwrite ring with a dropped
  counter; a week of accounting cannot leak memory.
* **one source of truth** — the windowed ``pt_goodput_ratio`` gauge and
  the ``pt_badput_seconds_total{category}`` counters are ``obs.metrics``
  instruments on the accountant's registry (a server binds its stats
  registry, so ``GET /metrics`` carries them and ``scraped_gauges()``
  rolls them up fleet-wide); ``summary()`` reads the same state.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .metrics import MetricsRegistry, RateWindow, get_registry

#: training-plane taxonomy (docs §23; ``collective`` added by the sharded
#: trainer, docs §24). ``idle`` is the sweep residual.
TRAIN_CATEGORIES = ("device_compute", "collective", "collective_hidden",
                    "host_input", "h2d", "compile", "fetch_sync",
                    "checkpoint", "idle")

#: sweep priorities: at any instant the highest-priority *active* interval
#: owns it (device beats everything — host work overlapped with the device
#: is hidden, not badput; an h2d transfer nested inside host_prep carves
#: its own category out of the parent instead of double counting).
#: ``collective`` sits ABOVE device_compute: the sharded trainer feeds its
#: reduce-scatter/all-gather intervals nested inside the device window
#: (parallel/ddp.py), and the sweep carves them out of device time — the
#: closure invariant stays exact by construction.
#: ``checkpoint`` sits BELOW everything: an async snapshot copied out
#: while the device window runs is attributed to device_compute (the
#: snapshot is provably free); only checkpoint seconds the run is
#: actually *exposed* to — a sync save blocking the step loop, or the
#: publish tail spilling past the window — surface as checkpoint badput.
TRAIN_PRIORITY = {"collective": 7, "device_compute": 6, "compile": 5,
                  "fetch_sync": 4, "h2d": 3, "host_input": 2,
                  "checkpoint": 1,
                  # the hidden slice of the collective model (docs §27):
                  # lowest priority so any concurrent interval — above
                  # all, device_compute — owns the wall-clock; the
                  # category records that the seconds existed and were
                  # overlapped, without ever carving time out of compute
                  "collective_hidden": 0}

#: categories whose seconds count as GOODPUT (the device doing, or the
#: host blocked on, useful model math); everything else — queueing,
#: padding, compiles, backoff sleeps, sheds, idle — is badput
GOOD_CATEGORIES = frozenset({
    "device_compute", "fetch_sync",            # train plane
    "dispatch", "device_sync", "prefill", "decode_step",  # serving plane
})

#: per-request closure tolerance: stage timestamps are contiguous by
#: construction, so 5% absorbs only scheduler jitter between stamps
CLOSURE_TOL = 0.05


def serving_categories() -> Tuple[str, ...]:
    """The serving request taxonomy: the ONE stage list owned by
    ``serving/stats.py`` (shared with the batcher and the stage
    histograms — ISSUE 14 dedup) plus the accountant's non-stage request
    categories and the residual. Lazy import: obs must stay importable
    without the serving tree."""
    from ..serving.stats import EXTRA_REQUEST_CATEGORIES, STAGES

    return STAGES + EXTRA_REQUEST_CATEGORIES + ("idle",)


class _NoopWindow:
    """Shared do-nothing window: the disabled-accountant fast path
    allocates NOTHING per call (tests assert identity)."""

    __slots__ = ()
    result = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_WINDOW = _NoopWindow()


class _Window:
    """An open accounting window; closing sweeps the raw intervals into
    the train taxonomy and snapshots the serving request accounting that
    landed while it was open."""

    __slots__ = ("_acct", "label", "result")

    def __init__(self, acct, label):
        self._acct = acct
        self.label = label
        self.result = None

    def __enter__(self):
        self._acct.begin_window(self.label)
        return self

    def __exit__(self, *exc):
        self.result = self._acct.end_window()
        return False


def _sweep(intervals: Sequence[Tuple[str, float, float]], t0: float,
           t1: float) -> Tuple[Dict[str, float], float]:
    """Priority-classify raw (category, start, dur) intervals over
    [t0, t1]: every instant goes to the highest-priority active category;
    uncovered instants are the returned idle. Exhaustive and
    non-overlapping by construction: sum(out) + idle == t1 - t0."""
    out = {c: 0.0 for c in TRAIN_PRIORITY}
    events: List[Tuple[float, int, str]] = []
    for cat, s, d in intervals:
        a, b = max(s, t0), min(s + d, t1)
        if b <= a or cat not in TRAIN_PRIORITY:
            continue
        events.append((a, 1, cat))
        events.append((b, 0, cat))
    if not events:
        return out, max(0.0, t1 - t0)
    events.sort(key=lambda e: (e[0], e[1]))
    by_prio = sorted(TRAIN_PRIORITY, key=lambda c: -TRAIN_PRIORITY[c])
    active = {c: 0 for c in TRAIN_PRIORITY}
    cur, idle = t0, 0.0
    for t, kind, cat in events:
        if t > cur:
            top = next((c for c in by_prio if active[c] > 0), None)
            if top is None:
                idle += t - cur
            else:
                out[top] += t - cur
            cur = t
        active[cat] += 1 if kind else -1
    if t1 > cur:
        idle += t1 - cur
    return out, idle


class GoodputAccountant:
    """Thread-safe time accountant over both planes (docs §23)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 window_s: float = 10.0, max_intervals: int = 65536):
        self._lock = threading.Lock()
        self._enabled = False
        self.registry = registry
        self.max_intervals = max(16, int(max_intervals))
        self._intervals: deque = deque(maxlen=self.max_intervals)
        self.intervals_dropped = 0
        # cumulative per-category seconds (profiles read these)
        self._train_cum: Dict[str, float] = {}
        self._serve_cum: Dict[str, float] = {}
        self._serve_wall = 0.0       # sum of request walls accounted
        self._serve_attributed = 0.0
        self._serve_requests = 0
        self._closure_violations = 0  # requests outside CLOSURE_TOL
        # current window state (begin_window/end_window)
        self._win_t0: Optional[float] = None
        self._win_label = ""
        self._win_serve: Dict[str, float] = {}
        self._win_serve_wall = 0.0
        self._win_serve_attr = 0.0
        self._win_serve_requests = 0
        self.last_window: Optional[Dict[str, Any]] = None
        # windowed good/bad rates -> the live ratio gauge
        self._good_rate = RateWindow(window_s)
        self._bad_rate = RateWindow(window_s)
        self._badput_counter = None
        if registry is not None:
            self._ensure_instruments()

    # -- switches ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, registry: Optional[MetricsRegistry] = None
               ) -> "GoodputAccountant":
        if registry is not None:
            self.registry = registry
        self._ensure_instruments()
        self._enabled = True
        return self

    def disable(self) -> None:
        self._enabled = False

    def reset(self) -> None:
        """Drop all accounted state (tests, round boundaries)."""
        with self._lock:
            self._intervals.clear()
            self.intervals_dropped = 0
            self._train_cum = {}
            self._serve_cum = {}
            self._serve_wall = self._serve_attributed = 0.0
            self._serve_requests = 0
            self._closure_violations = 0
            self._win_t0 = None
            self.last_window = None

    def _ensure_instruments(self) -> None:
        r = self.registry or get_registry()
        self.registry = r
        r.gauge("pt_goodput_ratio",
                "Windowed goodput seconds / accounted seconds "
                "(1.0 when nothing was accounted in the window)",
                callback=self.goodput_ratio)
        self._badput_counter = r.counter(
            "pt_badput_seconds_total",
            "Accounted non-productive seconds by category",
            labelnames=("category",))

    # -- recording ---------------------------------------------------------
    def account(self, category: str, t0: float, dur: float) -> None:
        """Record one raw training-plane interval (``t0`` monotonic
        seconds). Classification happens at window close — instrumented
        sites just report what they measured."""
        if not self._enabled or dur <= 0:
            return
        with self._lock:
            if len(self._intervals) == self._intervals.maxlen:
                self.intervals_dropped += 1
            self._intervals.append((category, t0, dur))

    def account_request(self, timings: Dict[str, float],
                        t0: Optional[float] = None) -> None:
        """Classify one completed serving request's stage timings
        (``serving/stats.STAGES`` names + ``total``). The residual
        (wall minus attributed stages) is the request's ``idle``;
        requests whose attributed time misses the wall by more than
        ``CLOSURE_TOL`` are counted as closure violations. ``t0`` (the
        request's submit monotonic time) additionally records the stage
        intervals into the ring so the timeline export can draw them."""
        if not self._enabled or not timings:
            return
        cats = serving_categories()
        wall = float(timings.get("total") or 0.0)
        attributed = 0.0
        good = bad = 0.0
        with self._lock:
            t = t0
            for stage in cats:
                dur = timings.get(stage)
                if not dur or dur <= 0:
                    continue
                attributed += dur
                self._serve_cum[stage] = self._serve_cum.get(stage, 0.0) + dur
                if self._win_t0 is not None:
                    self._win_serve[stage] = \
                        self._win_serve.get(stage, 0.0) + dur
                if stage in GOOD_CATEGORIES:
                    good += dur
                else:
                    bad += dur
                    if self._badput_counter is not None:
                        self._badput_counter.labels(category=stage).inc(dur)
                if t is not None:
                    if len(self._intervals) == self._intervals.maxlen:
                        self.intervals_dropped += 1
                    self._intervals.append((stage, t, dur))
                    t += dur
            if wall <= 0:
                wall = attributed
            idle = max(0.0, wall - attributed)
            if idle > 0:
                self._serve_cum["idle"] = \
                    self._serve_cum.get("idle", 0.0) + idle
                bad += idle
                if self._badput_counter is not None:
                    self._badput_counter.labels(category="idle").inc(idle)
                if self._win_t0 is not None:
                    self._win_serve["idle"] = \
                        self._win_serve.get("idle", 0.0) + idle
            self._serve_wall += wall
            self._serve_attributed += attributed
            self._serve_requests += 1
            if wall > 0 and abs(wall - attributed) > CLOSURE_TOL * wall:
                self._closure_violations += 1
            if self._win_t0 is not None:
                self._win_serve_wall += wall
                self._win_serve_attr += attributed
                self._win_serve_requests += 1
        if good:
            self._good_rate.add(good)
        if bad:
            self._bad_rate.add(bad)

    def account_shed(self, seconds: float) -> None:
        """A request shed after spending ``seconds`` in the system
        (deadline shed at coalesce time, mid-generation shed): its whole
        wall is the ``shed`` category."""
        if not self._enabled or seconds <= 0:
            return
        self.account_request({"total": seconds, "shed": seconds})

    def account_retry_backoff(self, seconds: float) -> None:
        """Client-side retry backoff sleep: request-seconds the caller
        spent waiting to try again."""
        if not self._enabled or seconds <= 0:
            return
        self.account_request({"total": seconds, "retry_backoff": seconds})

    # -- windows -----------------------------------------------------------
    def window(self, label: str = ""):
        """Context manager over one accounting window (a bench workload,
        a trainer epoch). Disabled: the shared no-op singleton."""
        if not self._enabled:
            return _NOOP_WINDOW
        return _Window(self, label)

    def begin_window(self, label: str = "") -> None:
        with self._lock:
            self._win_t0 = time.monotonic()
            self._win_label = label
            self._win_serve = {}
            self._win_serve_wall = self._win_serve_attr = 0.0
            self._win_serve_requests = 0

    def end_window(self) -> Optional[Dict[str, Any]]:
        """Close the current window: sweep the train intervals that
        intersect it, snapshot the serving accounting that landed in it,
        and return the summary (also kept as ``last_window``)."""
        t1 = time.monotonic()
        with self._lock:
            if self._win_t0 is None:
                return None
            t0, label = self._win_t0, self._win_label
            self._win_t0 = None
            intervals = [iv for iv in self._intervals if iv[1] + iv[2] > t0
                         and iv[1] < t1 and iv[0] in TRAIN_PRIORITY]
            serve = dict(self._win_serve)
            serve_wall = self._win_serve_wall
            serve_attr = self._win_serve_attr
            serve_n = self._win_serve_requests
        cats, idle = _sweep(intervals, t0, t1)
        wall = t1 - t0
        attributed = sum(cats.values())
        train_good = sum(s for c, s in cats.items() if c in GOOD_CATEGORIES)
        train_bad = attributed - train_good + idle
        if train_good:
            self._good_rate.add(train_good)
        if train_bad:
            self._bad_rate.add(train_bad)
        if self._badput_counter is not None:
            for c, s in cats.items():
                if s > 0 and c not in GOOD_CATEGORIES:
                    self._badput_counter.labels(category=c).inc(s)
            if idle > 0:
                self._badput_counter.labels(category="idle").inc(idle)
        with self._lock:
            for c, s in cats.items():
                if s > 0:
                    self._train_cum[c] = self._train_cum.get(c, 0.0) + s
            if idle > 0:
                self._train_cum["idle"] = \
                    self._train_cum.get("idle", 0.0) + idle
        train_cats = {c: s for c, s in cats.items() if s > 0}
        train_cats["idle"] = idle
        good = train_good + sum(s for c, s in serve.items()
                                if c in GOOD_CATEGORIES)
        accounted = attributed + idle + sum(serve.values())
        self.last_window = {
            "label": label,
            "wall_s": wall,
            "t0_monotonic": t0,
            "train": {
                "categories": train_cats,
                "attributed_s": attributed,
                # closure witness: fraction of the window explained by
                # real (non-idle) categories
                "closure": attributed / wall if wall > 0 else 1.0,
            },
            "serving": {
                "categories": serve,
                "wall_s": serve_wall,
                "attributed_s": serve_attr,
                "closure": serve_attr / serve_wall if serve_wall > 0 else 1.0,
                "requests": serve_n,
            },
            "goodput_ratio": good / accounted if accounted > 0 else 1.0,
        }
        return self.last_window

    def classify_range(self, t0: float, t1: float) -> Dict[str, Any]:
        """Ad-hoc train-plane attribution over an arbitrary monotonic
        range WITHOUT touching the window state — for callers (the bench
        closure workload) measuring inside an already-open window."""
        cats, idle = _sweep(self.intervals(), t0, t1)
        wall = max(0.0, t1 - t0)
        attributed = sum(cats.values())
        out = {c: s for c, s in cats.items() if s > 0}
        out["idle"] = idle
        return {
            "categories": out,
            "wall_s": wall,
            "attributed_s": attributed,
            "closure": attributed / wall if wall > 0 else 1.0,
        }

    # -- reading -----------------------------------------------------------
    def goodput_ratio(self) -> float:
        """Windowed good / (good + bad) accounted seconds; 1.0 when the
        window saw nothing (idleness is not a verdict)."""
        g, b = self._good_rate.rate(), self._bad_rate.rate()
        return g / (g + b) if (g + b) > 0 else 1.0

    def summary(self) -> Dict[str, Any]:
        """Rollup for stats RPCs / flight providers: cumulative category
        seconds per plane, closure witnesses, the live ratio."""
        with self._lock:
            train = dict(self._train_cum)
            serve = dict(self._serve_cum)
            wall, attr = self._serve_wall, self._serve_attributed
            n, viol = self._serve_requests, self._closure_violations
        return {
            "goodput_ratio": self.goodput_ratio(),
            "train": {"categories": train},
            "serving": {
                "categories": serve,
                "wall_s": wall,
                "attributed_s": attr,
                "closure": attr / wall if wall > 0 else 1.0,
                "requests": n,
                "closure_violations": viol,
            },
        }

    def intervals(self) -> List[Tuple[str, float, float]]:
        """Snapshot of the raw interval ring (category, t0, dur) —
        monotonic-clock absolute, oldest first."""
        with self._lock:
            return list(self._intervals)

    def dump_intervals(self, path: str) -> int:
        """Write the per-category interval lanes for the timeline export
        (``tools/timeline.py --goodput_path``); returns the count."""
        ivs = self.intervals()
        t0 = min((s for _, s, _ in ivs), default=time.monotonic())
        doc = {"schema": 1, "t0_monotonic": t0,
               "intervals": [{"category": c, "t0": s, "dur": d,
                              "good": c in GOOD_CATEGORIES}
                             for c, s, d in ivs]}
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(ivs)


_default = GoodputAccountant()


def get_accountant() -> GoodputAccountant:
    """The process-wide default accountant every instrumentation site
    feeds (the attribution-plane sibling of ``get_tracer()``)."""
    return _default


def init_from_flags() -> GoodputAccountant:
    """Honor ``flags.obs_goodput`` (an env var alone turns accounting
    on) — called lazily by the instrumented entry points."""
    from ..flags import get_flag

    if get_flag("obs_goodput") and not _default.enabled:
        _default.enable()
    return _default

"""Span tracer: low-overhead, thread-safe, bounded — the attribution layer.

The hot paths built in PRs 1-4 (the fused ``run_steps`` window, the depth-2
serving dispatch pipeline, the Pallas dW route) are visible only as
aggregate counters; when a p99 regresses nothing says WHICH stage ate the
time. The tracer records *per-stage spans* — named intervals on a
monotonic clock, nested per thread, tagged with a request trace-id or a
training step-id — into a bounded ring buffer, and exports them as Chrome
trace-event JSON (the same format ``tools/timeline.py`` emits, so host
profiler events and obs spans merge into one timeline).

Design constraints (docs/design.md §15):

* **zero-cost when disabled** — ``span()`` returns a shared no-op context
  manager (no allocation, one attribute read); every instrumentation site
  is guarded by the same check. Enabling is a runtime switch
  (``enable()`` / the ``obs_trace`` flag), not a rebuild.
* **bounded** — finished spans land in a ``deque(maxlen=capacity)``; a
  week-long serving process cannot leak memory through its own telemetry.
* **thread-safe** — one lock around the ring; the per-thread span stack
  (for nesting/depth) lives in ``threading.local`` and needs none.
* **monotonic** — span timestamps are ``time.monotonic()``; wall-clock
  jumps (NTP) cannot produce negative durations.

Exemplar sampling (``ExemplarStore``): percentiles say *that* the tail is
slow, exemplars say *why* — the store retains the complete span list of
the K slowest requests/steps, evicting faster ones, so the p99's trace is
still inspectable hours later even though the ring has long rotated.
"""
from __future__ import annotations

import heapq
import itertools
import json
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


def new_trace_id() -> str:
    """16-hex-char request/step correlation id (rides the wire protocol)."""
    return uuid.uuid4().hex[:16]


class Span:
    """One finished interval. ``t0`` is monotonic seconds; ``dur`` seconds.
    ``parent`` is the enclosing span's ``sid`` on the same thread (0 = root)
    — the CLI's self-time report subtracts children via this link."""

    __slots__ = ("sid", "name", "cat", "t0", "dur", "tid", "trace_id",
                 "parent", "args")

    def __init__(self, sid, name, cat, t0, dur, tid, trace_id, parent, args):
        self.sid = sid
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.dur = dur
        self.tid = tid
        self.trace_id = trace_id
        self.parent = parent
        self.args = args

    def to_dict(self) -> Dict[str, Any]:
        d = {"sid": self.sid, "name": self.name, "cat": self.cat,
             "t0": self.t0, "dur": self.dur, "tid": self.tid,
             "parent": self.parent}
        if self.trace_id:
            d["trace_id"] = self.trace_id
        if self.args:
            d["args"] = self.args
        return d


class _NoopSpan:
    """Shared do-nothing context manager: the disabled-tracer fast path
    allocates NOTHING per call (tests assert identity)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _LiveSpan:
    """An open span; closing records it into the tracer's ring. The span's
    id is assigned at OPEN so children started while it is live can link
    their ``parent`` to it (the per-thread stack carries open sids)."""

    __slots__ = ("_tracer", "name", "cat", "trace_id", "args", "_t0",
                 "_parent", "sid")

    def __init__(self, tracer, name, cat, trace_id, args):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.args = args

    def __enter__(self):
        tl = self._tracer._tls
        stack = getattr(tl, "stack", None)
        if stack is None:
            stack = tl.stack = []
        self._parent = stack[-1] if stack else 0
        self.sid = next(self._tracer._sid)
        # push BEFORE reading the clock so nesting bookkeeping isn't counted
        stack.append(self.sid)
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        dur = time.monotonic() - self._t0
        tl = self._tracer._tls
        if tl.stack and tl.stack[-1] == self.sid:
            tl.stack.pop()
        self._tracer._record(self.name, self.cat, self._t0, dur,
                             self.trace_id, self._parent, self.args,
                             sid=self.sid)
        return False


class ExemplarStore:
    """Keep the complete span lists of the K slowest keys (min-heap by
    duration: a new trace evicts the fastest retained one)."""

    def __init__(self, k: int = 8):
        self.k = int(k)
        self._lock = threading.Lock()
        self._heap: List[Any] = []  # (duration, seq, key, spans)
        self._seq = itertools.count()

    def would_retain(self, duration: float) -> bool:
        """Cheap pre-check so callers skip assembling the span list for
        traces that would be rejected anyway (the common case)."""
        if self.k <= 0:
            return False
        with self._lock:
            return len(self._heap) < self.k or duration > self._heap[0][0]

    def offer(self, key: str, duration: float,
              spans: List[Dict[str, Any]]) -> bool:
        """Returns True when the trace was retained."""
        if self.k <= 0:
            return False
        with self._lock:
            item = (duration, next(self._seq), key, spans)
            if len(self._heap) < self.k:
                heapq.heappush(self._heap, item)
                return True
            if duration > self._heap[0][0]:
                heapq.heapreplace(self._heap, item)
                return True
            return False

    def snapshot(self) -> List[Dict[str, Any]]:
        """Slowest-first list of {key, duration_s, spans}."""
        with self._lock:
            items = sorted(self._heap, reverse=True)
        return [{"key": k, "duration_s": d, "spans": s}
                for d, _, k, s in items]

    def clear(self) -> None:
        with self._lock:
            self._heap.clear()


class Tracer:
    """Bounded ring of finished spans + per-thread nesting state."""

    def __init__(self, capacity: int = 65536, exemplars: int = 8):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._enabled = False
        # >= 1: _record indexes the ring, a 0-capacity ring would crash the
        # instrumented hot path telemetry must never take down
        self.capacity = max(1, int(capacity))
        self._ring: List[Span] = []
        self._next = 0  # ring write cursor
        self._sid = itertools.count(1)
        self.dropped = 0  # spans overwritten since enable()
        self.exemplars = ExemplarStore(exemplars)
        self._t_epoch = time.monotonic()  # export time base

    # -- switches --
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, capacity: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None and max(1, int(capacity)) != self.capacity:
                self.capacity = max(1, int(capacity))
                self._ring = []
                self._next = 0
            self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def clear(self) -> None:
        with self._lock:
            self._ring = []
            self._next = 0
            self.dropped = 0
        self.exemplars.clear()

    # -- recording --
    def span(self, name: str, cat: str = "host",
             trace_id: Optional[str] = None, **args):
        """Context manager measuring one interval. Disabled: returns the
        shared no-op singleton — no allocation on the hot path."""
        if not self._enabled:
            return _NOOP
        return _LiveSpan(self, name, cat, trace_id, args or None)

    def add_span(self, name: str, t0: float, dur: float, cat: str = "host",
                 trace_id: Optional[str] = None, tid: Optional[int] = None,
                 parent: int = 0, args: Optional[Dict] = None) -> int:
        """Record an externally-measured interval (``t0`` monotonic
        seconds). Used by code that already took its own timestamps — the
        batcher's stage timings, profiler.RecordEvent re-emission."""
        if not self._enabled:
            return 0
        return self._record(name, cat, t0, dur, trace_id, parent, args,
                            tid=tid)

    def _record(self, name, cat, t0, dur, trace_id, parent, args,
                tid=None, sid=None) -> int:
        if sid is None:
            sid = next(self._sid)
        sp = Span(sid, name, cat, t0, dur,
                  threading.get_ident() & 0xFFFFFF if tid is None else tid,
                  trace_id, parent, args)
        with self._lock:
            if len(self._ring) < self.capacity:
                self._ring.append(sp)
            else:
                self._ring[self._next] = sp
                self.dropped += 1
            self._next = (self._next + 1) % max(self.capacity, 1)
        return sid

    # -- reading --
    def spans(self, trace_id: Optional[str] = None) -> List[Span]:
        """Finished spans in recording order (oldest first); optionally
        only those tagged with ``trace_id``."""
        with self._lock:
            if len(self._ring) < self.capacity:
                out = list(self._ring)
            else:
                out = self._ring[self._next:] + self._ring[:self._next]
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- export --
    def to_chrome_trace(self, extra_events: Optional[List[Dict]] = None) -> Dict:
        """Chrome trace-event JSON dict (``{"traceEvents": [...]}``) —
        loadable in chrome://tracing / ui.perfetto.dev and mergeable with
        ``tools/timeline.py`` output (same schema, 'X' complete events).
        ``extra_events`` (pre-formatted event dicts, e.g. the profiler's
        host events converted by timeline.py) are appended verbatim."""
        spans = self.spans()
        t0 = min((s.t0 for s in spans), default=self._t_epoch)
        events: List[Dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
             "args": {"name": "paddle_tpu obs"}}]
        trace: Dict[str, Any] = {"traceEvents": events,
                                 # absolute monotonic base of ts=0: lets
                                 # timeline.py re-align this dump against
                                 # profiler events rebased to a different
                                 # zero (chrome ignores unknown keys)
                                 "t0_monotonic": t0}
        for s in spans:
            args = dict(s.args or {})
            if s.trace_id:
                args["trace_id"] = s.trace_id
            events.append({
                "ph": "X", "cat": s.cat, "name": s.name, "pid": 0,
                "tid": s.tid, "ts": (s.t0 - t0) * 1e6, "dur": s.dur * 1e6,
                "args": args})
        if extra_events:
            events.extend(extra_events)
        return trace

    def dump(self, path: str) -> int:
        """Write the Chrome trace JSON; returns the span count written."""
        trace = self.to_chrome_trace()
        with open(path, "w") as f:
            json.dump(trace, f)
        return sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")


_default = Tracer()


def get_tracer() -> Tracer:
    """The process-wide default tracer every instrumentation site uses."""
    return _default


def enable(capacity: Optional[int] = None) -> Tracer:
    _default.enable(capacity)
    return _default


def disable() -> None:
    _default.disable()


def init_from_flags() -> Tracer:
    """Honor ``flags.obs_trace`` / ``obs_trace_capacity`` /
    ``obs_exemplars`` (called lazily by the instrumented entry points so
    an env var alone turns tracing on)."""
    from ..flags import get_flag

    if get_flag("obs_trace") and not _default.enabled:
        _default.exemplars.k = int(get_flag("obs_exemplars"))
        _default.enable(int(get_flag("obs_trace_capacity")))
    return _default

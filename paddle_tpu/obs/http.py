"""MetricsServer: a standalone Prometheus scrape endpoint for training jobs.

``ServingServer`` answers ``GET /metrics`` on its own port (server.py); a
training job has no listener at all, so this one-file HTTP server gives it
one::

    from paddle_tpu.obs import MetricsServer, get_registry
    ms = MetricsServer(port=9184)          # port=0 picks a free one
    ...train...                            # instruments publish to the
    ms.close()                             # default registry

Dependency-free (stdlib ``http.server``), threaded, exposes:

* ``GET /metrics``  — Prometheus text exposition of the registry
* ``GET /healthz``  — liveness (``ok``)

Scrape-pull only; nothing here ever blocks a training step.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .metrics import MetricsRegistry, get_registry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):
        srv: "MetricsServer" = self.server  # type: ignore[assignment]
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = srv.registry.expose().encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
        elif path == "/healthz" and srv.healthz_cb is not None:
            # a caller-supplied liveness dict (the fleet router serves its
            # state/pressure here) — JSON, like ServingServer's healthz
            try:
                body = (json.dumps(srv.healthz_cb()) + "\n").encode()
                self.send_response(200)
            except Exception:
                body = b"{\"ok\": false}\n"
                self.send_response(500)
            self.send_header("Content-Type", "application/json")
        elif path == "/healthz":
            body = b"ok\n"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
        else:
            body = b"not found\n"
            self.send_response(404)
            self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes are not stdout events
        pass


class MetricsServer(ThreadingHTTPServer):
    """Threaded scrape endpoint over a ``MetricsRegistry`` (default: the
    process registry). ``with MetricsServer(port=0) as ms: ms.endpoint``."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry: Optional[MetricsRegistry] = None,
                 healthz: Optional[Callable[[], dict]] = None):
        super().__init__((host, port), _Handler)
        self.registry = registry or get_registry()
        self.healthz_cb = healthz
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True,
                                        name="paddle-tpu-metrics")
        self._thread.start()

    @property
    def endpoint(self) -> str:
        host, port = self.server_address[:2]
        return f"{host}:{port}"

    def close(self) -> None:
        self.shutdown()
        self.server_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

"""Live device-memory ledger: measured HBM attribution (docs §28).

The obs tier measures *time* exhaustively (PR-5 tracing, PR-9 flight
bundles, PR-13 goodput closure) but until now measured *memory* nowhere
— yet every feasibility decision in the system (serving placement §18,
quantization flips §20, paged-KV admission §22, the train searcher's
HBM gate §27) rides an **analytic** byte account that was never checked
against what actually lives on the device.

``MemoryLedger`` is that check. Every framework-owned device allocation
registers here with ``{component, shard, dtype, bytes, label}``:

* engine weight stores (f32 and quantized ``.q``/``.s``),
* dense and paged KV pools (pages broken out free/active/prefix-cached
  via a lazy ``detail`` callback),
* decode slot carries and prefetch buffers,
* ZeRO/3D param+optimizer shards per mesh axis,
* compile-cache retained executables (XLA cost-analysis bytes where
  available),
* resilience snapshot host buffers (``device="host"`` — excluded from
  the device reconciliation).

Three closure surfaces keep the ledger honest:

1. **Reconciliation** — ``reconcile()`` diffs ledger totals against a
   bounded ``jax.live_arrays()`` walk → ``pt_mem_unattributed_bytes`` /
   ``pt_mem_attributed_ratio`` (the goodput ``sum == wall`` discipline
   applied to bytes). An allocation the ledger does not know about shows
   up as unattributed — the negative test injects one and watches the
   gauge catch it.
2. **Model-vs-measured drift** — ``reconcile_model(account)`` compares
   per-component measured bytes against the analytic
   ``ModelProfile``/``TrainProfile`` account; drift beyond
   ``obs_mem_drift_tolerance`` produces a typed finding and a
   ``mem_drift`` event — the first measured audit of the byte math that
   gates every placement decision.
3. **High-water marks + residency intervals** — exported to the Chrome
   timeline as a per-component memory lane (``tools/timeline.py
   --mem_path``, pid 3).

OOM becomes a first-class postmortem: RESOURCE_EXHAUSTED caught at
dispatch/compile calls ``handle_oom()`` which emits an ``oom`` event and
trips a PR-9 flight bundle carrying the full ledger snapshot + top-N
allocations + high-water history; ``paddle_cli doctor`` ranks the
suspect component ("kv_pool 61% of HBM at failure, 2.3 GiB above plan").

Design constraints (the PR-5 discipline, verbatim):

* **zero-cost when disabled** — every instrumentation site is guarded by
  one ``led.enabled`` attribute read; a disabled ``track()`` records
  nothing and returns one shared ``NOOP_ALLOCATION`` sentinel
  (identity-tested like the tracer's no-op span and the event log's
  ``DISCARDED``).
* **bounded** — residency intervals land in an overwrite ring; the
  high-water history is a bounded ring; ``reconcile()`` caps its
  ``live_arrays`` walk (``max_arrays``) and counts its own cost in
  ``pt_mem_reconcile_seconds_total`` so it is cheap enough to run per
  bench round on CPU.
* **never on the math path** — the ledger only *observes* bytes; with
  the flag off the serving/training numerics are bit-identical.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

#: the component taxonomy (docs/design.md §28). ``track()`` accepts any
#: string, but these are what the instrumented tree produces and what
#: ``paddle_cli doctor`` knows how to rank.
COMPONENTS = (
    "weights",        # engine weight stores (f32 / quantized .q+.s)
    "kv_pool",        # dense or paged KV cache pools
    "decode_carry",   # decode-loop carry state held across steps
    "prefetch",       # reader DevicePrefetcher staged batches
    "train_state",    # ZeRO/3D placed params + optimizer shards
    "compile_cache",  # retained executables (cost-analysis bytes)
    "snapshot_host",  # resilience snapshot host buffers (host-side)
    "other",
)

_INTERVAL_RING = 4096   # completed residency intervals kept for timeline
_HIGHWATER_RING = 512   # (t, total_bytes) samples kept for postmortems


def _nbytes(value: Any) -> int:
    """Best-effort byte count of an array / pytree / int. Walks dicts,
    lists and tuples; leaves must expose ``.nbytes`` or be numbers.
    Never imports jax — host-only processes can run the ledger."""
    if value is None:
        return 0
    if isinstance(value, (int, float)):
        return int(value)
    nb = getattr(value, "nbytes", None)
    if nb is not None:
        return int(nb)
    if isinstance(value, dict):
        return sum(_nbytes(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return sum(_nbytes(v) for v in value)
    return 0


class _NoopAllocation:
    """Shared sentinel a disabled ``track()`` returns — the identity test
    asserts no per-call allocation on the disabled path (the PR-5
    ``_NOOP`` span / PR-9 ``DISCARDED`` pattern)."""

    __slots__ = ()

    def resize(self, value: Any) -> None:
        pass

    def release(self) -> None:
        pass

    def __repr__(self):  # pragma: no cover - debugging nicety
        return "<allocation discarded: ledger disabled>"


NOOP_ALLOCATION = _NoopAllocation()


class Allocation:
    """One live tracked allocation. ``resize()`` when the underlying
    store changes size (e.g. compile cache grows), ``release()`` when the
    device memory is dropped. Safe to release twice."""

    __slots__ = ("_ledger", "aid", "component", "label", "shard", "dtype",
                 "device", "bytes", "detail", "t0", "released")

    def __init__(self, ledger, aid, component, label, shard, dtype, device,
                 nbytes, detail):
        self._ledger = ledger
        self.aid = aid
        self.component = component
        self.label = label
        self.shard = shard
        self.dtype = dtype
        self.device = device
        self.bytes = int(nbytes)
        self.detail = detail
        self.t0 = time.monotonic()
        self.released = False

    def resize(self, value: Any) -> None:
        self._ledger._resize(self, _nbytes(value))

    def release(self) -> None:
        self._ledger._release(self)

    def to_dict(self) -> Dict[str, Any]:
        d = {"component": self.component, "label": self.label,
             "bytes": self.bytes, "device": self.device, "t0": self.t0}
        if self.shard is not None:
            d["shard"] = self.shard
        if self.dtype is not None:
            d["dtype"] = str(self.dtype)
        if self.detail is not None:
            try:
                detail = self.detail()
                if detail is not None:
                    d["detail"] = detail
            except Exception:
                pass
        return d


class MemoryLedger:
    """Thread-safe registry of framework-owned device (and host)
    allocations, with reconciliation against ``jax.live_arrays()``,
    model-vs-measured drift findings, high-water tracking, OOM
    postmortems and admission watermark hooks."""

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._enabled = False
        self._registry = registry
        self._allocs: Dict[int, Allocation] = {}
        self._aid = 0
        self._capacity = 0          # HBM bytes for occupancy/headroom
        self._totals: Dict[str, int] = {}       # device bytes/component
        self._host_totals: Dict[str, int] = {}  # host bytes/component
        self._high_water: Dict[str, int] = {}   # per-component device HW
        self._hw_total = 0
        self._hw_ring: List[Any] = []           # (t, total) bounded ring
        self._intervals: List[Dict[str, Any]] = []  # completed residencies
        self._next_iv = 0
        self._last_reconcile: Dict[str, Any] = {}
        self._last_drift: List[Dict[str, Any]] = []
        self._counters = None   # lazy (reconcile_seconds_total, oom_total)
        self._oom_count = 0
        self._exported: List[Any] = []  # registries already carrying gauges

    # -- switches --
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, capacity_bytes: Optional[int] = None) -> "MemoryLedger":
        with self._lock:
            if capacity_bytes:
                self._capacity = int(capacity_bytes)
            self._enabled = True
        self._register_flight_provider()
        self.export_gauges()
        return self

    def disable(self) -> None:
        self._enabled = False

    def clear(self) -> None:
        """Drop all tracked state (tests); gauges read zeros after."""
        with self._lock:
            self._allocs = {}
            self._totals = {}
            self._host_totals = {}
            self._high_water = {}
            self._hw_total = 0
            self._hw_ring = []
            self._intervals = []
            self._next_iv = 0
            self._last_reconcile = {}
            self._last_drift = []

    def _register_flight_provider(self) -> None:
        try:
            from .flight import get_recorder

            get_recorder().register_provider("mem_ledger", self.snapshot)
        except Exception:
            pass

    # -- capacity / watermark hooks --
    def set_capacity(self, nbytes: int) -> None:
        self._capacity = int(nbytes)

    @property
    def capacity(self) -> int:
        return self._capacity

    def device_bytes(self) -> int:
        with self._lock:
            return sum(self._totals.values())

    def occupancy(self) -> float:
        """Measured fraction of declared HBM capacity in use; 0.0 when no
        capacity is declared (gauges stay meaningful without config)."""
        cap = self._capacity
        if cap <= 0:
            return 0.0
        return self.device_bytes() / float(cap)

    def headroom(self) -> Optional[int]:
        """Bytes of declared capacity not yet attributed, or None when no
        capacity is declared — admission hooks treat None as 'no opinion'."""
        cap = self._capacity
        if cap <= 0:
            return None
        return cap - self.device_bytes()

    def above_watermark(self, watermark: float) -> bool:
        """Admission hook: is measured occupancy above ``watermark``
        (fraction of capacity)? False when disabled or capacity unknown —
        modeled-only admission keeps working unchanged."""
        if not self._enabled or watermark <= 0.0 or self._capacity <= 0:
            return False
        return self.occupancy() > watermark

    # -- recording --
    def track(self, component: str, label: str, value: Any = None,
              shard: Optional[str] = None, dtype: Any = None,
              device: str = "device",
              detail: Optional[Callable[[], Any]] = None):
        """Register one framework-owned allocation; returns a live
        ``Allocation`` handle (or the shared ``NOOP_ALLOCATION`` when
        disabled). ``value`` may be an array, a pytree of arrays, or a
        byte count; ``device="host"`` allocations are tracked but
        excluded from device totals and reconciliation. ``detail`` is a
        lazy callback evaluated only at snapshot/dump time (e.g. paged-KV
        free/active/cached byte split)."""
        if not self._enabled:
            return NOOP_ALLOCATION
        nb = _nbytes(value)
        with self._lock:
            self._aid += 1
            a = Allocation(self, self._aid, component, label, shard, dtype,
                           device, nb, detail)
            self._allocs[a.aid] = a
            self._bump(component, nb, device)
        return a

    def _bump(self, component: str, delta: int, device: str) -> None:
        # caller holds the lock
        tot = self._host_totals if device == "host" else self._totals
        tot[component] = tot.get(component, 0) + delta
        if device != "host":
            cur = self._totals.get(component, 0)
            if cur > self._high_water.get(component, 0):
                self._high_water[component] = cur
            total = sum(self._totals.values())
            if total > self._hw_total:
                self._hw_total = total
            ring = self._hw_ring
            ring.append((time.monotonic(), total))
            if len(ring) > _HIGHWATER_RING:
                del ring[: len(ring) - _HIGHWATER_RING]

    def _record_interval(self, a: Allocation, nbytes: int, now: float) -> None:
        # caller holds the lock; one completed residency for the timeline
        iv = {"t0": a.t0, "dur": max(0.0, now - a.t0),
              "component": a.component, "label": a.label,
              "bytes": int(nbytes), "device": a.device}
        if len(self._intervals) < _INTERVAL_RING:
            self._intervals.append(iv)
        else:
            self._intervals[self._next_iv] = iv
        self._next_iv = (self._next_iv + 1) % _INTERVAL_RING

    def _resize(self, a: Allocation, nbytes: int) -> None:
        if not self._enabled or a.released:
            return
        with self._lock:
            delta = int(nbytes) - a.bytes
            if delta == 0:
                return
            now = time.monotonic()
            self._record_interval(a, a.bytes, now)
            a.bytes = int(nbytes)
            a.t0 = now
            self._bump(a.component, delta, a.device)

    def _release(self, a: Allocation) -> None:
        if a.released:
            return
        with self._lock:
            if a.released:
                return
            a.released = True
            self._allocs.pop(a.aid, None)
            self._record_interval(a, a.bytes, time.monotonic())
            self._bump(a.component, -a.bytes, a.device)

    # -- reading --
    def totals(self, device: str = "device") -> Dict[str, int]:
        with self._lock:
            src = self._host_totals if device == "host" else self._totals
            return {k: v for k, v in src.items() if v}

    def high_water(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self._high_water)
            out["total"] = self._hw_total
            return out

    def high_water_history(self) -> List[Any]:
        with self._lock:
            return list(self._hw_ring)

    def allocations(self) -> List[Allocation]:
        with self._lock:
            return list(self._allocs.values())

    def top_allocations(self, n: int = 10) -> List[Dict[str, Any]]:
        allocs = sorted(self.allocations(), key=lambda a: -a.bytes)[:n]
        return [a.to_dict() for a in allocs]

    def dump_intervals(self) -> Dict[str, Any]:
        """Residency intervals (completed + live) for the Chrome-timeline
        memory lane (``tools/timeline.py --mem_path``, pid 3)."""
        now = time.monotonic()
        with self._lock:
            if len(self._intervals) < _INTERVAL_RING:
                ivs = list(self._intervals)
            else:
                ivs = (self._intervals[self._next_iv:]
                       + self._intervals[: self._next_iv])
            for a in self._allocs.values():
                ivs.append({"t0": a.t0, "dur": max(0.0, now - a.t0),
                            "component": a.component, "label": a.label,
                            "bytes": a.bytes, "device": a.device,
                            "live": True})
        return {"intervals": ivs, "high_water": self.high_water(),
                "high_water_history": self.high_water_history()}

    def snapshot(self) -> Dict[str, Any]:
        """Full ledger state for the flight-recorder ``mem_ledger``
        provider — what the OOM bundle carries and doctor ranks."""
        return {
            "enabled": self._enabled,
            "capacity_bytes": self._capacity,
            "device_bytes": self.device_bytes(),
            "occupancy": self.occupancy(),
            "totals": self.totals(),
            "host_totals": self.totals(device="host"),
            "high_water": self.high_water(),
            "high_water_history": self.high_water_history()[-64:],
            "top_allocations": self.top_allocations(10),
            "reconcile": dict(self._last_reconcile),
            "drift": list(self._last_drift),
            "oom_count": self._oom_count,
        }

    # -- closure surface 1: reconciliation vs jax.live_arrays() --
    def reconcile(self, baseline_bytes: int = 0,
                  max_arrays: Optional[int] = None) -> Dict[str, Any]:
        """Diff ledger device totals against measured ``jax.live_arrays()``
        bytes — the closure gauge. ``baseline_bytes`` subtracts arrays
        that predate the workload (in-process tests); ``max_arrays``
        bounds the walk (CI hygiene; the truncation is reported, never
        silent). Updates ``pt_mem_unattributed_bytes`` /
        ``pt_mem_attributed_ratio`` and counts its own wall cost in
        ``pt_mem_reconcile_seconds_total``."""
        t_start = time.monotonic()
        if max_arrays is None:
            try:
                from ..flags import get_flag

                max_arrays = int(get_flag("obs_mem_reconcile_max_arrays"))
            except Exception:
                max_arrays = 4096
        live = 0
        n = 0
        truncated = False
        try:
            import jax

            for arr in jax.live_arrays():
                if n >= max_arrays:
                    truncated = True
                    break
                n += 1
                try:
                    live += int(arr.nbytes)
                except Exception:
                    pass
        except Exception:
            pass
        live = max(0, live - int(baseline_bytes))
        attributed = self.device_bytes()
        unattributed = max(0, live - attributed)
        ratio = (attributed / float(live)) if live > 0 else 1.0
        seconds = time.monotonic() - t_start
        res = {"live_bytes": live, "attributed_bytes": attributed,
               "unattributed_bytes": unattributed, "ratio": ratio,
               "arrays": n, "truncated": truncated, "seconds": seconds,
               "baseline_bytes": int(baseline_bytes)}
        with self._lock:
            self._last_reconcile = res
        c = self._get_counters()
        if c is not None:
            try:
                c["reconcile_seconds"].inc(seconds)
                c["reconcile_total"].inc()
            except Exception:
                pass
        return res

    def last_reconcile(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._last_reconcile)

    # -- closure surface 2: model-vs-measured drift --
    def reconcile_model(self, account: Dict[str, int],
                        tolerance: Optional[float] = None
                        ) -> List[Dict[str, Any]]:
        """Compare measured per-component device bytes against the
        analytic ``account`` ({component: planned_bytes}, e.g. from
        ``ModelProfile``). Components drifting beyond ``tolerance``
        (relative, default flag ``obs_mem_drift_tolerance``) produce a
        typed finding and a ``mem_drift`` event. Returns ALL per-component
        findings; each carries ``within_tolerance``."""
        if tolerance is None:
            try:
                from ..flags import get_flag

                tolerance = float(get_flag("obs_mem_drift_tolerance"))
            except Exception:
                tolerance = 0.1
        measured = self.totals()
        findings: List[Dict[str, Any]] = []
        for comp in sorted(set(account) | set(measured)):
            plan = int(account.get(comp, 0))
            got = int(measured.get(comp, 0))
            if plan <= 0 and got <= 0:
                continue
            denom = float(max(plan, 1))
            drift = (got - plan) / denom
            ok = abs(drift) <= tolerance if plan > 0 else False
            findings.append({"component": comp, "planned_bytes": plan,
                             "measured_bytes": got, "drift": drift,
                             "within_tolerance": ok})
        with self._lock:
            self._last_drift = findings
        try:
            from .events import get_event_log

            log = get_event_log()
            if log.enabled:
                for f in findings:
                    if not f["within_tolerance"]:
                        log.emit("mem_drift", severity="warn",
                                 component=f["component"],
                                 planned_bytes=f["planned_bytes"],
                                 measured_bytes=f["measured_bytes"],
                                 drift=round(f["drift"], 4))
        except Exception:
            pass
        return findings

    # -- OOM postmortem --
    @staticmethod
    def is_oom(exc: BaseException) -> bool:
        """Classify an exception as XLA device-memory exhaustion.
        RESOURCE_EXHAUSTED is how XLA spells OOM across backends."""
        text = "%s: %s" % (type(exc).__name__, exc)
        low = text.lower()
        return ("resource_exhausted" in low or "resource exhausted" in low
                or "out of memory" in low)

    def handle_oom(self, exc: BaseException, component: str = "unknown",
                   **ctx) -> Optional[str]:
        """OOM postmortem: emit an ``oom`` event and trip a flight bundle
        carrying the full ledger snapshot (the ``mem_ledger`` provider) +
        top-N allocations + high-water history. Returns the bundle path
        (None when the recorder declines/rate-limits). Never raises —
        the original exception is what propagates."""
        self._oom_count += 1
        c = self._get_counters()
        if c is not None:
            try:
                c["oom_total"].inc()
            except Exception:
                pass
        info = {"component": component, "error": str(exc)[:500]}
        info.update({k: v for k, v in ctx.items() if v is not None})
        try:
            from .events import get_event_log

            log = get_event_log()
            if log.enabled:
                top = self.top_allocations(3)
                log.emit("oom", severity="error",
                         device_bytes=self.device_bytes(),
                         occupancy=round(self.occupancy(), 4),
                         top=[{"component": t["component"],
                               "bytes": t["bytes"]} for t in top],
                         **info)
        except Exception:
            pass
        try:
            from .flight import get_recorder

            self._register_flight_provider()
            trigger = {"type": "oom"}
            trigger.update(info)
            return get_recorder().maybe_dump(trigger)
        except Exception:
            return None

    # -- gauges --
    def _get_counters(self):
        if self._counters is None:
            try:
                from .metrics import get_registry

                r = self._registry or get_registry()
                self._counters = {
                    "reconcile_seconds": r.counter(
                        "pt_mem_reconcile_seconds_total",
                        "Wall seconds spent in ledger reconciliation "
                        "passes (CI-hygiene budget)"),
                    "reconcile_total": r.counter(
                        "pt_mem_reconcile_total",
                        "Ledger reconciliation passes run"),
                    "oom_total": r.counter(
                        "pt_mem_oom_total",
                        "RESOURCE_EXHAUSTED postmortems handled"),
                }
            except Exception:
                return None
        return self._counters

    def export_gauges(self, registry=None) -> None:
        """Register the ``pt_mem_*`` pull gauges into ``registry`` (the
        process default when omitted). Callback-style — scraping reads
        live ledger state; callable any number of times on any number of
        registries (each server exports on its own /metrics page)."""
        if registry is None:
            try:
                from .metrics import get_registry

                registry = self._registry or get_registry()
            except Exception:
                return
        if any(r is registry for r in self._exported):
            return
        try:
            registry.gauge(
                "pt_mem_tracked_bytes",
                "Ledger-attributed device bytes across all components",
                callback=self.device_bytes)
            registry.gauge(
                "pt_mem_hbm_capacity_bytes",
                "Declared device HBM capacity (flag obs_mem_hbm_bytes)",
                callback=lambda: self._capacity)
            registry.gauge(
                "pt_mem_hbm_occupancy",
                "Measured fraction of declared HBM capacity in use",
                callback=self.occupancy)
            registry.gauge(
                "pt_mem_unattributed_bytes",
                "live_arrays bytes the ledger cannot attribute "
                "(closure gauge; last reconcile pass)",
                callback=lambda: self._last_reconcile.get(
                    "unattributed_bytes", 0))
            registry.gauge(
                "pt_mem_attributed_ratio",
                "attributed/live byte ratio of the last reconcile pass "
                "(1.0 = full closure)",
                callback=lambda: self._last_reconcile.get("ratio", 1.0))
            registry.gauge(
                "pt_mem_high_water_bytes",
                "High-water mark of total tracked device bytes",
                callback=lambda: self._hw_total)
            registry.gauge(
                "pt_mem_kv_pool_share",
                "kv_pool fraction of all tracked device bytes",
                callback=self._kv_share)
            comp = registry.gauge(
                "pt_mem_component_bytes",
                "Ledger-attributed device bytes by component",
                labelnames=("component",))
            for name in COMPONENTS:
                comp.labels(component=name).set_callback(
                    lambda n=name: self._totals.get(n, 0))
            drift = registry.gauge(
                "pt_mem_drift_ratio",
                "Relative model-vs-measured byte drift by component "
                "(last reconcile_model pass)",
                labelnames=("component",))
            for name in COMPONENTS:
                drift.labels(component=name).set_callback(
                    lambda n=name: self._drift_of(n))
            self._exported.append(registry)
        except Exception:
            pass

    def _kv_share(self) -> float:
        with self._lock:
            total = sum(self._totals.values())
            kv = self._totals.get("kv_pool", 0)
        return (kv / float(total)) if total > 0 else 0.0

    def _drift_of(self, component: str) -> float:
        with self._lock:
            for f in self._last_drift:
                if f["component"] == component:
                    return f["drift"]
        return 0.0


_default = MemoryLedger()


def get_ledger() -> MemoryLedger:
    """The process-wide default memory ledger every registration site
    writes into (the memory-plane sibling of ``get_tracer()``)."""
    return _default


def init_from_flags() -> MemoryLedger:
    """Honor ``flags.obs_mem`` / ``obs_mem_hbm_bytes`` — an env var alone
    (``PT_FLAG_OBS_MEM=1``) turns the ledger on."""
    from ..flags import get_flag

    if not _default.enabled and get_flag("obs_mem"):
        cap = int(get_flag("obs_mem_hbm_bytes"))
        _default.enable(capacity_bytes=cap or None)
    return _default

"""Structured event log: the black-box half of the obs plane (docs §19).

PR 5 answers "how fast is it *right now*" — gauges, histograms, spans.
Nothing records *what happened*: health transitions, circuit trips,
failovers, reload commits, shed decisions, chaos injections and NaN
sentinels exist only as counters, and the evidence dies with the process.
This module is the typed, bounded, thread-safe event log every subsystem
emits into; the flight recorder (obs/flight.py) snapshots it into
postmortem bundles and ``paddle_cli doctor`` reconstructs incident
timelines from it.

Design constraints (the PR-5 discipline, verbatim):

* **zero-cost when disabled** — every instrumentation site is guarded by
  one ``log.enabled`` attribute read; a disabled ``emit()`` records
  nothing and returns one shared ``DISCARDED`` sentinel (identity-tested
  like the tracer's no-op span).
* **bounded** — events land in an overwrite ring with a ``dropped``
  counter; a week of chaos cannot leak memory through its own black box.
* **typed** — ``type`` comes from the taxonomy below (unknown types are
  allowed but counted under their own label); each event carries
  monotonic time, wall time, severity, and trace/step id links so the
  doctor can join events against spans and SLO breaches.
* **counted** — every recorded event increments
  ``pt_events_total{type,severity}`` in the log's registry, so even a
  rotated-out event leaves a scrape-able trace.
* **pluggable sinks** — ``add_sink(fn)`` fans each event out (e.g. the
  stdlib-``logging`` one-line-JSON bridge, ``LoggingJSONSink``); a sink
  that raises is counted (``sink_errors``), never allowed to take down
  the hot path.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

#: the event taxonomy (docs/design.md §19). Emitting an unlisted type is
#: legal — the list documents what the instrumented tree produces and what
#: ``paddle_cli doctor`` knows how to rank.
EVENT_TYPES = (
    # serving / fleet plane
    "health_transition",     # healthy/degraded/draining (+ fleet scope)
    "circuit_open", "circuit_half_open", "circuit_close",
    "failover", "hedge", "hedge_win",
    "reload_stage", "reload_commit",
    "scale_event",
    "deadline_shed", "load_shed", "quota_reject", "queue_full",
    "batch_failed", "decode_step_failed",
    "no_healthy_replicas",
    "replica_unreachable", "replica_reachable",
    # chaos plane
    "chaos_inject",
    # training numerics sentinels
    "nan_detected", "loss_spike", "grad_norm_spike",
    # training resilience plane (parallel/resilience.py, docs §26)
    "checkpoint_saved",      # snapshot published (_SUCCESS written)
    "rollback",              # sentinel escalation -> restore last-good
    "preemption",            # SIGTERM caught -> grace snapshot + typed exit
    "elastic_resize",        # resume re-planned for a new device count
    # memory plane (obs/mem.py, docs §28): RESOURCE_EXHAUSTED postmortem
    # (attrs name the suspect component + ledger state at failure) and a
    # model-vs-measured byte drift beyond obs_mem_drift_tolerance
    "oom", "mem_drift",
    # watchdog / recorder
    "slo_breach", "worker_exception", "bundle_dumped",
    # differential attribution (obs/profile.py, docs §23): a profile pair
    # regressed beyond tolerance — attrs name the owning category
    "perf_regression",
)

SEVERITIES = ("debug", "info", "warn", "error")


class Event:
    """One recorded occurrence. ``t`` is monotonic seconds (joinable with
    span timestamps), ``wall`` unix seconds (human timelines), ``step``
    a training step id, ``trace_id`` the request correlation id."""

    __slots__ = ("eid", "type", "severity", "t", "wall", "trace_id",
                 "step", "attrs")

    def __init__(self, eid, type, severity, t, wall, trace_id, step, attrs):
        self.eid = eid
        self.type = type
        self.severity = severity
        self.t = t
        self.wall = wall
        self.trace_id = trace_id
        self.step = step
        self.attrs = attrs

    def to_dict(self) -> Dict[str, Any]:
        d = {"eid": self.eid, "type": self.type, "severity": self.severity,
             "t": self.t, "wall": self.wall}
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
        if self.step is not None:
            d["step"] = self.step
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class _Discarded:
    """Shared sentinel a disabled ``emit()`` returns — the identity test
    asserts no per-call allocation on the disabled path."""

    __slots__ = ()

    def __repr__(self):  # pragma: no cover - debugging nicety
        return "<event discarded: log disabled>"


DISCARDED = _Discarded()


class EventLog:
    """Bounded, thread-safe ring of typed events + sink fan-out."""

    def __init__(self, capacity: int = 4096, registry=None):
        self._lock = threading.Lock()
        self._enabled = False
        self.capacity = max(1, int(capacity))
        self._ring: List[Event] = []
        self._next = 0
        self._eid = 0
        self.dropped = 0
        self.sink_errors = 0
        self._sinks: List[Callable[[Event], None]] = []
        self._registry = registry
        self._counter = None  # lazy: pt_events_total{type,severity}

    # -- switches --
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, capacity: Optional[int] = None) -> "EventLog":
        with self._lock:
            if capacity is not None and max(1, int(capacity)) != self.capacity:
                self.capacity = max(1, int(capacity))
                self._ring = []
                self._next = 0
            self._enabled = True
        return self

    def disable(self) -> None:
        self._enabled = False

    def clear(self) -> None:
        with self._lock:
            self._ring = []
            self._next = 0
            self.dropped = 0

    # -- sinks --
    def add_sink(self, fn: Callable[[Event], None]) -> None:
        with self._lock:
            if fn not in self._sinks:
                self._sinks.append(fn)

    def remove_sink(self, fn: Callable[[Event], None]) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    def _count(self, type: str, severity: str) -> None:
        c = self._counter
        if c is None:
            from .metrics import get_registry

            r = self._registry or get_registry()
            c = self._counter = r.counter(
                "pt_events_total", "Structured events by type and severity",
                labelnames=("type", "severity"))
        try:
            c.labels(type=type, severity=severity).inc()
        except Exception:
            pass  # a broken registry must not take down the emitter

    # -- recording --
    def emit(self, type: str, severity: str = "info",
             trace_id: Optional[str] = None, step: Optional[int] = None,
             **attrs):
        """Record one event; returns it (or ``DISCARDED`` when disabled).
        Hot-path sites guard with ``if log.enabled:`` so a disabled log
        costs one attribute read and zero allocation."""
        if not self._enabled:
            return DISCARDED
        if severity not in SEVERITIES:
            severity = "info"
        now = time.monotonic()
        with self._lock:
            self._eid += 1
            ev = Event(self._eid, type, severity, now, time.time(),
                       trace_id, step, attrs or None)
            if len(self._ring) < self.capacity:
                self._ring.append(ev)
            else:
                self._ring[self._next] = ev
                self.dropped += 1
            self._next = (self._next + 1) % self.capacity
            sinks = list(self._sinks)
        self._count(type, severity)
        for s in sinks:
            try:
                s(ev)
            except Exception:
                self.sink_errors += 1
        return ev

    # -- reading --
    def events(self, type: Optional[str] = None,
               trace_id: Optional[str] = None,
               min_severity: Optional[str] = None) -> List[Event]:
        """Recorded events oldest-first, optionally filtered."""
        with self._lock:
            if len(self._ring) < self.capacity:
                out = list(self._ring)
            else:
                out = self._ring[self._next:] + self._ring[:self._next]
        if type is not None:
            out = [e for e in out if e.type == type]
        if trace_id is not None:
            out = [e for e in out if e.trace_id == trace_id]
        if min_severity is not None:
            floor = SEVERITIES.index(min_severity)
            out = [e for e in out
                   if SEVERITIES.index(e.severity) >= floor]
        return out

    def counts(self) -> Dict[str, int]:
        """{type: count} over the RETAINED ring (rotated-out events live
        on only in ``pt_events_total``)."""
        out: Dict[str, int] = {}
        for e in self.events():
            out[e.type] = out.get(e.type, 0) + 1
        return out

    def snapshot(self) -> List[Dict[str, Any]]:
        return [e.to_dict() for e in self.events()]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


class LoggingJSONSink:
    """Bridge events into stdlib ``logging`` as one-line JSON — the
    structured-logging satellite: faults were silently counted, now every
    one is a grep-able log line. Severity maps onto logging levels."""

    LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
              "warn": logging.WARNING, "error": logging.ERROR}

    def __init__(self, logger: str = "paddle_tpu.events"):
        self._log = logging.getLogger(logger)

    def __call__(self, ev: Event) -> None:
        self._log.log(self.LEVELS.get(ev.severity, logging.INFO),
                      json.dumps(ev.to_dict(), sort_keys=True, default=str))


_default = EventLog()
_json_sink: Optional[LoggingJSONSink] = None
_json_lock = threading.Lock()


def get_event_log() -> EventLog:
    """The process-wide default event log every instrumentation site
    emits into (the event-plane sibling of ``get_tracer()``)."""
    return _default


def enable_json_logging(logger: str = "paddle_tpu.events") -> EventLog:
    """Enable the default log (if off) and attach ONE shared stdlib-
    ``logging`` JSON sink — the ``log_json=`` / ``--log-json`` wiring.
    Idempotent."""
    global _json_sink
    with _json_lock:
        if _json_sink is None:
            _json_sink = LoggingJSONSink(logger)
            _default.add_sink(_json_sink)
    if not _default.enabled:
        _default.enable()
    return _default


def init_from_flags() -> EventLog:
    """Honor ``flags.obs_events`` / ``obs_events_capacity`` (an env var
    alone turns the black box on); ``obs_sentinel`` implies events — a
    sentinel with nowhere to record would be a silent sentinel."""
    from ..flags import get_flag

    if not _default.enabled and (get_flag("obs_events")
                                 or get_flag("obs_sentinel")):
        _default.enable(int(get_flag("obs_events_capacity")))
    return _default

"""Persisted profiles + the differential attributor (docs §23).

A *profile* is one schema-versioned JSON artifact freezing a goodput
accounting window: the taxonomy breakdown of one bench workload or one
serving run. The point of persisting it is the DIFF — two rounds of the
same workload, subtracted per category, name the owner of a regression
("step +8%; 91% of the delta in fetch_sync") instead of leaving a human
to grep spans. The differential attributor:

* normalizes per unit (steps / requests) when both profiles carry units,
  so a longer run is not read as a slower one;
* exploits the closure invariant — category deltas sum to the wall delta,
  so shares are exact attribution, not correlation;
* emits a ``perf_regression`` event and (rate-limited) trips the PR-9
  flight recorder when the wall regresses beyond tolerance, and registers
  a ``goodput`` provider so postmortem bundles carry the latest profile
  pair + diff for ``paddle_cli doctor`` to rank.

Durability matches the TuningDB discipline (tune/db.py): atomic
tmp+replace publish, and a corrupt / field-less / future-schema file is a
typed ``ProfileError`` (an ``IOError``) — attributing a regression off
garbage is the one thing this must never do.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from .goodput import GOOD_CATEGORIES, GoodputAccountant

#: bump when the profile layout changes; loaders refuse the future
SCHEMA_VERSION = 1

#: fields every profile must carry to be trusted (corrupt-file refusal)
_REQUIRED_FIELDS = ("schema", "kind", "workload", "wall_s", "categories")

_KINDS = ("train", "serving")

#: default wall-regression tolerance for the attributor (flag-overridable)
DEFAULT_TOLERANCE = 0.03


class ProfileError(IOError):
    """Typed refusal: unreadable, corrupt, or alien-schema profile (the
    checkpoint-manifest / TuningDB IOError discipline)."""


def build_profile(kind: str, workload: str, categories: Dict[str, float],
                  wall_s: float, units: Optional[int] = None,
                  goodput_ratio: Optional[float] = None,
                  meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Assemble one schema-v1 profile dict. ``categories`` must be the
    exhaustive taxonomy breakdown (incl. ``idle``); closure is derived."""
    if kind not in _KINDS:
        raise ValueError(f"profile kind must be one of {_KINDS}, got {kind!r}")
    cats = {c: float(s) for c, s in categories.items() if s > 0}
    attributed = sum(s for c, s in cats.items() if c != "idle")
    wall = float(wall_s)
    good = sum(s for c, s in cats.items() if c in GOOD_CATEGORIES)
    total = sum(cats.values())
    return {
        "schema": SCHEMA_VERSION,
        "kind": kind,
        "workload": str(workload),
        "created_unix": time.time(),
        "wall_s": wall,
        "units": int(units) if units else None,
        "categories": cats,
        "attributed_s": attributed,
        "closure": attributed / wall if wall > 0 else 1.0,
        "goodput_ratio": (goodput_ratio if goodput_ratio is not None
                          else (good / total if total > 0 else 1.0)),
        "meta": dict(meta or {}),
    }


def profile_from_window(window: Dict[str, Any], workload: str,
                        units: Optional[int] = None,
                        meta: Optional[Dict[str, Any]] = None
                        ) -> Dict[str, Any]:
    """Freeze one closed accountant window (``end_window()`` /
    ``window().result``) into a profile. The plane with the accounted
    time decides the kind: a workload that completed serving requests is
    a serving profile (units = requests), otherwise a train profile over
    the window wall."""
    serving = window.get("serving") or {}
    train = window.get("train") or {}
    if serving.get("requests") and serving.get("wall_s", 0.0) >= \
            train.get("attributed_s", 0.0):
        return build_profile(
            "serving", workload, serving.get("categories") or {},
            serving.get("wall_s", 0.0),
            units=units if units is not None else serving.get("requests"),
            goodput_ratio=window.get("goodput_ratio"), meta=meta)
    return build_profile(
        "train", workload, train.get("categories") or {},
        window.get("wall_s", 0.0), units=units,
        goodput_ratio=window.get("goodput_ratio"), meta=meta)


def capture_profile(acct: GoodputAccountant, kind: str, workload: str,
                    units: Optional[int] = None,
                    meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Profile the accountant's CUMULATIVE state for one plane (a serving
    run's lifetime breakdown; bench windows use ``profile_from_window``)."""
    s = acct.summary()
    if kind == "serving":
        sv = s["serving"]
        return build_profile("serving", workload, sv["categories"],
                             sv["wall_s"], units=units or sv["requests"],
                             goodput_ratio=s["goodput_ratio"], meta=meta)
    cats = s["train"]["categories"]
    return build_profile("train", workload, cats, sum(cats.values()),
                         units=units, goodput_ratio=s["goodput_ratio"],
                         meta=meta)


# -- persistence (TuningDB discipline) --------------------------------------

def validate_profile(p: Any) -> List[str]:
    problems: List[str] = []
    if not isinstance(p, dict):
        return ["profile is not a JSON object"]
    for k in _REQUIRED_FIELDS:
        if k not in p:
            problems.append(f"missing field {k!r}")
    schema = p.get("schema")
    if isinstance(schema, int) and schema > SCHEMA_VERSION:
        problems.append(f"schema {schema} is from the future "
                        f"(this build reads <= {SCHEMA_VERSION})")
    elif "schema" in p and not isinstance(schema, int):
        problems.append(f"schema must be an int, got {type(schema).__name__}")
    if "kind" in p and p.get("kind") not in _KINDS:
        problems.append(f"kind must be one of {_KINDS}, got {p.get('kind')!r}")
    if "categories" in p and not isinstance(p.get("categories"), dict):
        problems.append("categories must be a mapping")
    return problems


def save_profile(profile: Dict[str, Any], path: str) -> str:
    """Atomic publish: tmp in the target dir + ``os.replace`` — a reader
    (or a crashed writer) can never observe a torn profile."""
    problems = validate_profile(profile)
    if problems:
        raise ProfileError(f"refusing to save an invalid profile: "
                           f"{'; '.join(problems)}")
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".profile_", suffix=".tmp", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(profile, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load_profile(path: str) -> Dict[str, Any]:
    """Load + validate; typed ``ProfileError`` on unreadable / corrupt /
    future-schema files (never attribute off garbage)."""
    try:
        with open(path) as f:
            p = json.load(f)
    except OSError as e:
        raise ProfileError(f"cannot read profile {path!r}: {e}") from e
    except ValueError as e:
        raise ProfileError(f"corrupt profile {path!r}: {e}") from e
    problems = validate_profile(p)
    if problems:
        raise ProfileError(f"invalid profile {path!r}: "
                           f"{'; '.join(problems)}")
    return p


# -- the differential attributor --------------------------------------------

def _per_unit(p: Dict[str, Any]) -> float:
    u = p.get("units")
    return 1.0 / u if u else 1.0


def diff_profiles(base: Dict[str, Any], cur: Dict[str, Any],
                  tolerance: Optional[float] = None) -> Dict[str, Any]:
    """Attribute ``cur`` minus ``base``: per-category wall deltas
    (normalized per unit when both profiles carry units) and the owners
    of the change, shares exact because category deltas sum to the wall
    delta (closure). ``regressed`` is a wall ratio beyond ``tolerance``
    (default ``flags.obs_profile_diff_tolerance``)."""
    for name, p in (("base", base), ("cur", cur)):
        problems = validate_profile(p)
        if problems:
            raise ProfileError(f"diff {name} profile invalid: "
                               f"{'; '.join(problems)}")
    if tolerance is None:
        try:
            from ..flags import get_flag

            tolerance = float(get_flag("obs_profile_diff_tolerance"))
        except Exception:
            tolerance = DEFAULT_TOLERANCE
    norm_a, norm_b = _per_unit(base), _per_unit(cur)
    normalized = bool(base.get("units")) and bool(cur.get("units"))
    if not normalized:
        norm_a = norm_b = 1.0
    wall_a = base["wall_s"] * norm_a
    wall_b = cur["wall_s"] * norm_b
    wall_delta = wall_b - wall_a
    wall_ratio = wall_b / wall_a if wall_a > 0 else float("inf")
    cats = sorted(set(base["categories"]) | set(cur["categories"]))
    owners = []
    for c in cats:
        a = base["categories"].get(c, 0.0) * norm_a
        b = cur["categories"].get(c, 0.0) * norm_b
        d = b - a
        owners.append({
            "category": c, "base_s": a, "cur_s": b, "delta_s": d,
            # share of the wall delta this category owns (signed; only
            # meaningful when the wall actually moved)
            "share": d / wall_delta if abs(wall_delta) > 1e-12 else 0.0,
        })
    owners.sort(key=lambda o: -abs(o["delta_s"]))
    regressed = wall_a > 0 and wall_ratio > 1.0 + tolerance
    unit = "unit" if normalized else "run"
    if owners and abs(wall_delta) > 1e-12:
        top = owners[0]
        summary = (f"{cur.get('workload')}: wall/{unit} "
                   f"{wall_ratio - 1.0:+.1%}; {abs(top['share']):.0%} of "
                   f"the delta in {top['category']} "
                   f"({top['delta_s'] * 1e3:+.3f} ms/{unit})")
    else:
        summary = f"{cur.get('workload')}: wall/{unit} unchanged"
    return {
        "workload": cur.get("workload"),
        "kind": cur.get("kind"),
        "normalized_per_unit": normalized,
        "wall_base_s": wall_a,
        "wall_cur_s": wall_b,
        "wall_delta_s": wall_delta,
        "wall_ratio": wall_ratio,
        "tolerance": tolerance,
        "regressed": regressed,
        "owners": owners,
        "summary": summary,
    }


def format_diff(diff: Dict[str, Any], top: int = 8) -> str:
    """Human-readable attribution table for the CLI / bench stderr."""
    unit = "unit" if diff.get("normalized_per_unit") else "run"
    lines = [diff["summary"]]
    lines.append(f"  wall/{unit}: {diff['wall_base_s'] * 1e3:.3f} -> "
                 f"{diff['wall_cur_s'] * 1e3:.3f} ms "
                 f"({diff['wall_ratio']:.4f}x, tolerance "
                 f"{diff['tolerance']:.0%})"
                 + ("  REGRESSED" if diff["regressed"] else ""))
    lines.append(f"  {'category':<16} {'base ms':>10} {'cur ms':>10} "
                 f"{'delta ms':>10} {'share':>7}")
    for o in diff["owners"][:top]:
        if abs(o["delta_s"]) < 1e-12 and o["base_s"] == 0 and o["cur_s"] == 0:
            continue
        lines.append(f"  {o['category']:<16} {o['base_s'] * 1e3:>10.3f} "
                     f"{o['cur_s'] * 1e3:>10.3f} "
                     f"{o['delta_s'] * 1e3:>+10.3f} "
                     f"{o['share']:>6.0%}")
    return "\n".join(lines)


# -- regression alerting + flight-recorder join -----------------------------

_last_lock = threading.Lock()
_last_profiles: List[Dict[str, Any]] = []  # bounded pair ring per provider
_last_diff: Optional[Dict[str, Any]] = None
_provider_registered = False


def _goodput_provider() -> Dict[str, Any]:
    with _last_lock:
        return {"profiles": list(_last_profiles), "diff": _last_diff}


def _register_provider() -> None:
    global _provider_registered
    with _last_lock:
        if _provider_registered:
            return
        _provider_registered = True
    from .flight import get_recorder

    get_recorder().register_provider("goodput", _goodput_provider)


def record_profile(profile: Dict[str, Any]) -> None:
    """Remember a captured profile (last two per process) and register
    the ``goodput`` flight provider, so postmortem bundles carry the
    profile pair for doctor's attribution join."""
    with _last_lock:
        _last_profiles.append(profile)
        del _last_profiles[:-2]
    _register_provider()


def attribute_regression(base: Dict[str, Any], cur: Dict[str, Any],
                         tolerance: Optional[float] = None,
                         trip_recorder: bool = True) -> Dict[str, Any]:
    """Diff two profiles and ALERT: on a wall regression beyond
    tolerance, emit a ``perf_regression`` event naming the owning
    category and (rate-limited) dump a flight-recorder bundle. The diff
    is also remembered for the ``goodput`` bundle provider. Returns the
    diff either way."""
    global _last_diff
    diff = diff_profiles(base, cur, tolerance=tolerance)
    with _last_lock:
        _last_diff = diff
    _register_provider()
    if diff["regressed"]:
        from .events import get_event_log

        top = diff["owners"][0] if diff["owners"] else {}
        ev = get_event_log()
        if ev.enabled:
            ev.emit("perf_regression", severity="warn",
                    workload=diff.get("workload"),
                    wall_ratio=round(diff["wall_ratio"], 4),
                    owner=top.get("category"),
                    owner_share=round(top.get("share", 0.0), 4),
                    summary=diff["summary"])
        if trip_recorder:
            from .flight import get_recorder

            get_recorder().maybe_dump({
                "type": "perf_regression",
                "workload": diff.get("workload"),
                "wall_ratio": round(diff["wall_ratio"], 4),
                "owner": top.get("category")})
    return diff


def goodput_report(profile: Dict[str, Any]) -> str:
    """Render one profile as the breakdown table ``paddle_cli goodput``
    prints."""
    wall = profile.get("wall_s", 0.0)
    units = profile.get("units")
    lines = [f"{profile.get('kind')} profile '{profile.get('workload')}' "
             f"(schema v{profile.get('schema')}): wall {wall:.3f}s"
             + (f", {units} units ({wall / units * 1e3:.3f} ms/unit)"
                if units else ""),
             f"goodput ratio {profile.get('goodput_ratio', 0.0):.3f}, "
             f"closure {profile.get('closure', 0.0):.3f} "
             f"(attributed {profile.get('attributed_s', 0.0):.3f}s)"]
    lines.append(f"  {'category':<16} {'seconds':>10} {'share':>7}  class")
    cats = profile.get("categories") or {}
    total = sum(cats.values()) or 1.0
    for c, s in sorted(cats.items(), key=lambda kv: -kv[1]):
        klass = "goodput" if c in GOOD_CATEGORIES else "badput"
        lines.append(f"  {c:<16} {s:>10.4f} {s / total:>6.1%}  {klass}")
    return "\n".join(lines)

"""Metrics registry: counters / gauges / histograms + Prometheus text.

One registry is ONE scrape surface: ``ServingServer`` exposes its stats
object's registry on ``GET /metrics``; training jobs register into the
process default registry and serve it via ``MetricsServer``. Instruments
are get-or-create by (name, labelnames) so independent subsystems can
share a metric family without coordination; registering the same name
with a DIFFERENT type or label set raises (silent divergence is how two
sources of truth come back).

Naming scheme (docs/design.md §15): ``pt_<plane>_<what>_<unit>`` —
``pt_serving_requests_total{event="submitted"}``,
``pt_serving_stage_seconds{stage="queue_wait"}``,
``pt_train_step_flops_total``, ``pt_serving_mfu``. Counters end in
``_total``; durations are seconds; gauges are instantaneous.

Exposition follows the Prometheus text format 0.0.4: ``# HELP`` /
``# TYPE`` headers, one sample per line, histograms as cumulative
``_bucket{le=...}`` series plus ``_sum``/``_count``. ``Gauge`` accepts a
zero-arg callback so queue depths / occupancy are read at scrape time
rather than pushed on every change.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# serving latencies (ms to s scale) through training steps (seconds)
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if v != v:  # NaN
        return "NaN"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _labelstr(labelnames: Sequence[str], labelvalues: Sequence[str]) -> str:
    if not labelnames:
        return ""
    inner = ",".join(f'{n}="{_escape(v)}"'
                     for n, v in zip(labelnames, labelvalues))
    return "{" + inner + "}"


class _Instrument:
    """One metric family; label children share the family's lock."""

    typ = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], "_Instrument"] = {}
        if not self.labelnames:
            self._init_value()

    def _init_value(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def labels(self, *labelvalues, **labelkv) -> "_Instrument":
        if labelkv:
            if labelvalues:
                raise ValueError("pass labels positionally OR by name")
            labelvalues = tuple(labelkv[n] for n in self.labelnames)
        key = tuple(str(v) for v in labelvalues)
        if len(key) != len(self.labelnames):
            raise ValueError(f"{self.name}: expected labels "
                             f"{self.labelnames}, got {key}")
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = type(self).__new__(type(self))
                child.name = self.name
                child.help = self.help
                child.labelnames = ()
                child._lock = self._lock
                child._children = {}
                child._init_value()
                self._children[key] = child
            return child

    def children(self) -> Dict[Tuple[str, ...], "_Instrument"]:
        """Snapshot of the label children, keyed by label-value tuple —
        lets callers derive per-label views from the one true counter."""
        with self._lock:
            return dict(self._children)

    def remove(self, *labelvalues, **labelkv) -> None:
        """Drop one label child (e.g. a decommissioned replica's series)."""
        if labelkv:
            if labelvalues:
                raise ValueError("pass labels positionally OR by name")
            labelvalues = tuple(labelkv[n] for n in self.labelnames)
        key = tuple(str(v) for v in labelvalues)
        with self._lock:
            self._children.pop(key, None)

    def prune(self, keep) -> None:
        """Drop every label child whose key is not in ``keep`` (an
        iterable of label-value tuples, or bare values for one-label
        families)."""
        keys = set()
        for k in keep:
            if not isinstance(k, tuple):
                k = (k,)
            keys.add(tuple(str(v) for v in k))
        with self._lock:
            for key in [k for k in self._children if k not in keys]:
                del self._children[key]

    def _samples(self) -> List[Tuple[str, str, float]]:
        """[(suffix, labelstr, value)] — flat family expansion."""
        out: List[Tuple[str, str, float]] = []
        if self.labelnames:
            with self._lock:
                items = sorted(self._children.items())
            for key, child in items:
                ls = _labelstr(self.labelnames, key)
                out.extend((suf, _merge_labels(ls, extra), v)
                           for suf, extra, v in child._sample_values())
        else:
            out.extend((suf, _merge_labels("", extra), v)
                       for suf, extra, v in self._sample_values())
        return out

    def _sample_values(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {_escape(self.help)}",
                 f"# TYPE {self.name} {self.typ}"]
        for suffix, labelstr, value in self._samples():
            lines.append(f"{self.name}{suffix}{labelstr} {_fmt(value)}")
        return "\n".join(lines)


def _merge_labels(base: str, extra: str) -> str:
    """Merge two ``{...}`` label strings (either may be empty)."""
    if not extra:
        return base
    if not base:
        return extra
    return base[:-1] + "," + extra[1:]


class Counter(_Instrument):
    """Monotonic float counter (``_total`` naming is the caller's job)."""

    typ = "counter"

    def _init_value(self):
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _sample_values(self):
        return [("", "", self.value)]


class Gauge(_Instrument):
    """Set/inc/dec, or a zero-arg ``callback`` read at scrape time."""

    typ = "gauge"

    def __init__(self, name, help, labelnames=(),
                 callback: Optional[Callable[[], float]] = None):
        self._callback = callback
        super().__init__(name, help, labelnames)

    def _init_value(self):
        self._value = 0.0
        if not hasattr(self, "_callback"):
            self._callback = None  # label children have no callback

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_callback(self, fn: Callable[[], float]) -> None:
        self._callback = fn

    @property
    def value(self) -> float:
        cb = getattr(self, "_callback", None)
        if cb is not None:
            try:
                return float(cb())
            except Exception:
                return float("nan")  # a broken callback must not kill scrape
        with self._lock:
            return self._value

    def _sample_values(self):
        return [("", "", self.value)]


class Histogram(_Instrument):
    """Fixed-bucket cumulative histogram (Prometheus semantics)."""

    typ = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        super().__init__(name, help, labelnames)

    def _init_value(self):
        if not hasattr(self, "buckets"):
            self.buckets = DEFAULT_BUCKETS
        self._counts = [0] * (len(self.buckets) + 1)  # + +Inf
        self._sum = 0.0
        self._n = 0

    def labels(self, *labelvalues, **labelkv):
        child = super().labels(*labelvalues, **labelkv)
        child.buckets = self.buckets
        if len(child._counts) != len(self.buckets) + 1:
            child._counts = [0] * (len(self.buckets) + 1)
        return child

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._n += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def _sample_values(self):
        with self._lock:
            counts = list(self._counts)
            total, s = self._n, self._sum
        out = []
        cum = 0
        for b, c in zip(self.buckets, counts[:-1]):
            cum += c
            out.append(("_bucket", f'{{le="{_fmt(b)}"}}', cum))
        out.append(("_bucket", '{le="+Inf"}', total))
        out.append(("_sum", "", s))
        out.append(("_count", "", total))
        return out


class RateWindow:
    """Per-second ring summing amounts over a sliding window — the
    denominator-free half of a rate gauge (``rate()`` divides by the
    window actually covered). Thread-safe; used for the live FLOP/s and
    MFU gauges on both the serving and training planes."""

    def __init__(self, window_s: float = 10.0):
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._ring: List[List[float]] = []  # [whole_second, amount]
        self._t0 = time.monotonic()

    def add(self, amount: float) -> None:
        now = time.monotonic()
        sec = int(now)
        with self._lock:
            if self._ring and self._ring[-1][0] == sec:
                self._ring[-1][1] += amount
            else:
                self._ring.append([sec, amount])
            horizon = int(now - self.window_s) - 1
            while self._ring and self._ring[0][0] < horizon:
                self._ring.pop(0)

    def rate(self) -> float:
        now = time.monotonic()
        with self._lock:
            total = sum(a for sec, a in self._ring
                        if now - sec <= self.window_s)
        horizon = min(self.window_s, max(now - self._t0, 1e-9))
        return total / horizon


class MetricsRegistry:
    """Get-or-create instrument store + one-call text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: "Dict[str, _Instrument]" = {}
        self._t0 = time.monotonic()

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if type(inst) is not cls or \
                        inst.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(inst).__name__}{inst.labelnames}; cannot "
                        f"re-register as {cls.__name__}{tuple(labelnames)}")
                return inst
            inst = cls(name, help, labelnames, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = (),
              callback: Optional[Callable[[], float]] = None) -> Gauge:
        g = self._get_or_create(Gauge, name, help, labelnames,
                                callback=callback)
        if callback is not None and g._callback is None:
            g._callback = callback
        return g

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._instruments.get(name)

    def instruments(self) -> Dict[str, _Instrument]:
        """Snapshot of every registered family by name — the metrics-doc
        generator (obs/metrics_doc.py) walks this."""
        with self._lock:
            return dict(self._instruments)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._instruments.pop(name, None)

    def expose(self) -> str:
        """The Prometheus text page (0.0.4): every family, HELP/TYPE +
        samples, newline-terminated."""
        with self._lock:
            insts = [self._instruments[k] for k in sorted(self._instruments)]
        return "\n".join(i.expose() for i in insts) + "\n" if insts else "\n"


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process default registry (training-side instruments land here;
    each ``ServingStats`` scopes its own)."""
    return _default_registry

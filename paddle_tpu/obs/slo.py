"""Declarative multi-window burn-rate SLO watchdog (docs §19).

PR 5's gauges say what the system is doing; nothing says whether that is
*acceptable* or rings when it stops being so. This module evaluates
declared objectives off the EXISTING telemetry (no new instrumentation on
the hot paths):

* **ratio SLOs** (error rate): the classic SRE shape. Budget = the
  allowed bad fraction (``target``); burn rate = observed_fraction /
  target over a window. Evaluated over TWO windows (fast + slow, e.g.
  5 s / 60 s in-process): a breach requires BOTH above
  ``burn_threshold``, so a single bad second cannot page while a
  sustained burn cannot hide in a long average.
* **gauge SLOs** (p95 latency ceiling, MFU floor, decode tokens/s
  floor): burn = value / target (ceilings) or target / value (floors);
  a breach requires ``consecutive`` evaluations over threshold — the
  gauge analogue of the two-window rule.

The watchdog exports ``pt_slo_burn_rate{slo}`` and
``pt_slo_breach_total{slo}``, emits a typed ``slo_breach`` event per
breach, and trips the flight recorder (``maybe_dump`` — rate-limited) so
every breach leaves a postmortem bundle behind. ``judge_bench`` is the
offline twin: it judges a finished serve_bench run against declared SLOs
(the serving counterpart of bench.py's per-class bars) with nonzero exit
on breach.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

_EPS = 1e-12


class SLO:
    """One declared objective.

    ``kind='ratio'``: ``read(window_s) -> (bad, total)``; burn =
    (bad/total) / target per window; breach when every window burns past
    ``burn_threshold``.

    ``kind='gauge'``: ``read() -> value``; burn = value/target (ceiling)
    or target/value (``floor=True``); breach after ``consecutive``
    evaluations over threshold.
    """

    def __init__(self, name: str, target: float, read: Callable,
                 kind: str = "gauge", floor: bool = False,
                 windows: Sequence[float] = (5.0, 60.0),
                 burn_threshold: float = 1.0, consecutive: int = 2):
        if kind not in ("ratio", "gauge"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        self.name = name
        self.target = float(target)
        self.read = read
        self.kind = kind
        self.floor = bool(floor)
        self.windows = tuple(float(w) for w in windows)
        self.burn_threshold = float(burn_threshold)
        self.consecutive = max(1, int(consecutive))
        self._over_streak = 0

    def burns(self) -> List[float]:
        """Current burn rate per window (gauge SLOs report one value)."""
        if self.kind == "ratio":
            out = []
            for w in self.windows:
                bad, total = self.read(w)
                frac = bad / total if total else 0.0
                out.append(frac / max(self.target, _EPS))
            return out
        v = float(self.read())
        if self.floor:
            return [self.target / max(v, _EPS)]
        return [v / max(self.target, _EPS)]

    def evaluate(self) -> Dict[str, Any]:
        """One evaluation: burn rates + the (streak-aware) breach bit."""
        burns = self.burns()
        over = all(b >= self.burn_threshold for b in burns)
        if self.kind == "gauge":
            self._over_streak = self._over_streak + 1 if over else 0
            breached = self._over_streak >= self.consecutive
        else:
            breached = over
        return {"slo": self.name, "kind": self.kind, "target": self.target,
                "burns": [round(b, 4) for b in burns],
                "burn": round(max(burns), 4), "breached": breached}


class SLOWatchdog:
    """Evaluate a set of SLOs on an interval; export burn gauges, count
    breaches, emit events, and trip flight-recorder dumps."""

    def __init__(self, slos: Sequence[SLO] = (), registry=None,
                 recorder=None, events=None, interval_s: float = 1.0,
                 start: bool = False):
        from .events import get_event_log
        from .metrics import get_registry

        self.slos: List[SLO] = list(slos)
        self.registry = registry or get_registry()
        self.events = events or get_event_log()
        self._recorder = recorder  # None -> lazy default (flight.py)
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._burn_gauge = self.registry.gauge(
            "pt_slo_burn_rate", "Current SLO burn rate (worst window)",
            labelnames=("slo",))
        self._breach_counter = self.registry.counter(
            "pt_slo_breach_total", "SLO breach evaluations",
            labelnames=("slo",))
        for s in self.slos:  # zeros visible before the first breach
            self._breach_counter.labels(slo=s.name)
        self.evals = 0
        self._last: Dict[str, Dict[str, Any]] = {}
        self._breaches: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    @property
    def recorder(self):
        if self._recorder is None:
            from .flight import get_recorder

            self._recorder = get_recorder()
        return self._recorder

    def add(self, slo: SLO) -> "SLOWatchdog":
        with self._lock:
            self.slos.append(slo)
        self._breach_counter.labels(slo=slo.name)
        return self

    def evaluate_now(self) -> Dict[str, Dict[str, Any]]:
        """One synchronous sweep (the loop does this on ``interval_s``).
        Returns {slo: evaluation}."""
        with self._lock:
            slos = list(self.slos)
        out: Dict[str, Dict[str, Any]] = {}
        for s in slos:
            try:
                res = s.evaluate()
            except Exception as e:  # a broken reader must not kill the dog
                res = {"slo": s.name, "error": f"{type(e).__name__}: {e}",
                       "burn": 0.0, "breached": False}
            out[s.name] = res
            self._burn_gauge.labels(slo=s.name).set(res["burn"])
            if res["breached"]:
                self._breach_counter.labels(slo=s.name).inc()
                with self._lock:
                    self._breaches[s.name] = \
                        self._breaches.get(s.name, 0) + 1
                if self.events.enabled:
                    self.events.emit("slo_breach", severity="error",
                                     slo=s.name, burn=res["burn"],
                                     target=s.target, kind=s.kind)
                self.recorder.maybe_dump(
                    {"type": "slo_breach", "slo": s.name,
                     "burn": res["burn"], "target": s.target})
        with self._lock:
            self.evals += 1
            self._last = out
        return out

    def summary(self) -> Dict[str, Any]:
        """Last evaluation + cumulative breach counts (rides postmortem
        bundles as the ``slo`` provider and bench records)."""
        with self._lock:
            return {"evals": self.evals, "breaches": dict(self._breaches),
                    "last": dict(self._last),
                    "slos": [{"slo": s.name, "kind": s.kind,
                              "target": s.target, "floor": s.floor}
                             for s in self.slos]}

    # -- lifecycle --
    def start(self) -> "SLOWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self.recorder.register_provider("slo", self.summary)
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="paddle-tpu-slo-watchdog")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.evaluate_now()

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        if self._recorder is not None:
            self._recorder.unregister_provider("slo")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- declarative constructors -----------------------------------------
    @staticmethod
    def serving_slos(stats, p95_ms: Optional[float] = None,
                     err_rate: Optional[float] = None,
                     mfu_floor: Optional[float] = None,
                     decode_tps_floor: Optional[float] = None,
                     windows: Sequence[float] = (5.0, 60.0),
                     consecutive: int = 2) -> List[SLO]:
        """SLOs over one ``ServingStats``: p95 latency ceiling, error
        rate (failed + deadline_exceeded over completed+bad), MFU floor,
        decode tokens/s floor. Pass only the bars you declare."""
        out: List[SLO] = []
        if p95_ms is not None:
            def _p95():
                return stats.snapshot()["latency_ms"]["p95"]

            out.append(SLO("p95_ms", p95_ms, _p95, kind="gauge",
                           consecutive=consecutive))
        if err_rate is not None:
            def _ratio(w):
                bad = (stats.recent("failed", w)
                       + stats.recent("deadline_exceeded", w))
                good = stats.recent("completed", w)
                return bad, bad + good

            out.append(SLO("err_rate", err_rate, _ratio, kind="ratio",
                           windows=windows))
        if mfu_floor is not None:
            out.append(SLO("mfu", mfu_floor, stats.mfu, kind="gauge",
                           floor=True, consecutive=consecutive))
        if decode_tps_floor is not None:
            out.append(SLO("decode_tokens_per_s", decode_tps_floor,
                           stats.decode_tokens_rate, kind="gauge",
                           floor=True, consecutive=consecutive))
        return out

    @staticmethod
    def fleet_slos(fleet_stats, p95_ms: Optional[float] = None,
                   err_rate_per_s: Optional[float] = None,
                   consecutive: int = 2) -> List[SLO]:
        """SLOs over a ``FleetStats`` (router plane): router p95 ceiling
        and failed-requests/s ceiling."""
        out: List[SLO] = []
        if p95_ms is not None:
            def _p95():
                return fleet_stats.snapshot()["latency_ms"]["p95"]

            out.append(SLO("fleet_p95_ms", p95_ms, _p95, kind="gauge",
                           consecutive=consecutive))
        if err_rate_per_s is not None:
            state = {"last": (time.monotonic(), fleet_stats.failed)}

            def _rate():
                now, cur = time.monotonic(), fleet_stats.failed
                t0, prev = state["last"]
                state["last"] = (now, cur)
                return (cur - prev) / max(now - t0, _EPS)

            out.append(SLO("fleet_err_per_s", err_rate_per_s, _rate,
                           kind="gauge", consecutive=consecutive))
        return out


# -- offline judgment (tools/serve_bench.py --slo) -------------------------

#: spec key -> (result keys to try, ceiling/floor). err_rate is derived.
_BENCH_KEYS = {
    "p50_ms": (("p50_ms", "gen_p50_ms"), False),
    "p95_ms": (("p95_ms", "gen_p95_ms"), False),
    "p99_ms": (("p99_ms",), False),
    "ttft_p95_ms": (("ttft_p95_ms",), False),
    "qps_min": (("qps",), True),
    "tokens_per_s_min": (("tokens_per_s",), True),
    "err_rate": ((), False),
}


def parse_slo_spec(spec: str) -> Dict[str, float]:
    """"p95_ms=50,err_rate=0.01" -> {"p95_ms": 50.0, ...}; unknown keys
    raise (a typo'd bar that silently never judges is worse than none)."""
    out: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        k = k.strip()
        if k not in _BENCH_KEYS:
            raise ValueError(f"unknown SLO key {k!r}; known: "
                             f"{sorted(_BENCH_KEYS)}")
        out[k] = float(v)
    if not out:
        raise ValueError("empty SLO spec")
    return out


def _bench_err_rate(result: Dict[str, Any]) -> Tuple[float, str]:
    bad = (result.get("errors", 0) + result.get("retry_exhausted", 0)
           + result.get("deadline_missed", 0))
    ok = result.get("requests", result.get("generations", 0))
    total = ok + bad
    return (bad / total if total else 0.0,
            f"{bad}/{total} failed|exhausted|deadline")


def judge_bench(result: Dict[str, Any],
                specs: Dict[str, float]) -> Tuple[bool, List[str]]:
    """Judge one serve_bench result dict against declared SLOs; returns
    (ok, report lines). A missing metric is a breach — a bar that cannot
    be measured must fail loudly, not pass silently."""
    ok = True
    lines: List[str] = []
    for key, target in specs.items():
        if key == "err_rate":
            value, detail = _bench_err_rate(result)
            passed = value <= target
            lines.append(
                f"{'SLO ok    ' if passed else 'SLO BREACH'} "
                f"err_rate={value:.4f} (target <= {target:g}; {detail})")
            ok &= passed
            continue
        keys, is_floor = _BENCH_KEYS[key]
        value = next((result[k] for k in keys if k in result), None)
        if value is None:
            lines.append(f"SLO BREACH {key}: metric "
                         f"{'/'.join(keys)} missing from the run")
            ok = False
            continue
        passed = value >= target if is_floor else value <= target
        op = ">=" if is_floor else "<="
        lines.append(f"{'SLO ok    ' if passed else 'SLO BREACH'} "
                     f"{key}={value:.3f} (target {op} {target:g})")
        ok &= passed
    return ok, lines

"""Generate ``docs/metrics.md`` from the live registries (docs §23).

The ``pt_*`` metric namespace grew across nine PRs with no single
contract: every subsystem registers instruments where it runs, and the
only census was grepping. This module makes the doc a DERIVED artifact:

* ``live_instruments()`` instantiates the registry-bearing subsystems
  against throwaway registries (``ServingStats``, ``FleetStats``, the
  goodput accountant, the event log's counter, the SLO watchdog, the
  train/tune instrument families) and walks what they registered — name,
  type, labels, and the HELP text straight from the source of truth;
* ``scan_source_names()`` regex-scans the package for ``pt_*`` string
  literals — the completeness backstop that catches instruments created
  lazily on paths too heavy to instantiate here (server pull-gauges,
  paged-KV gauges);
* ``render_doc()`` merges both into one markdown table. Names found only
  by the scan are still listed (with their source files), so the doc is
  exhaustive by construction.

The drift test (tests/test_obs_goodput.py) asserts every scanned name
appears in the checked-in ``docs/metrics.md``: adding an instrument
without regenerating (``paddle_cli metrics-doc``) fails CI.
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Tuple

from .metrics import MetricsRegistry

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: string literals that LOOK like metric names but are not one concrete
#: instrument (prefix matches, format templates)
_SCAN_EXCLUDE = re.compile(r"(_$|^pt_$)")

_NAME_RE = re.compile(r"""["'](pt_[a-z0-9_]+)["']""")


def _collect(reg: MetricsRegistry, source: str,
             out: Dict[str, Dict[str, object]]) -> None:
    for name, inst in reg.instruments().items():
        if not name.startswith("pt_"):
            continue
        out.setdefault(name, {
            "type": inst.typ,
            "labels": tuple(inst.labelnames),
            "help": inst.help,
            "source": source,
        })


def live_instruments() -> Dict[str, Dict[str, object]]:
    """{name: {type, labels, help, source}} from instantiating the
    registry-bearing subsystems against throwaway registries."""
    out: Dict[str, Dict[str, object]] = {}
    # serving + fleet planes: the stats objects register everything in
    # their constructors
    from ..serving.stats import FleetStats, ServingStats

    _collect(ServingStats(registry=MetricsRegistry()).registry,
             "serving/stats.py ServingStats", out)
    _collect(FleetStats(registry=MetricsRegistry()).registry,
             "serving/stats.py FleetStats", out)
    # attribution plane (docs §23)
    from .goodput import GoodputAccountant

    _collect(GoodputAccountant(registry=MetricsRegistry()).registry,
             "obs/goodput.py GoodputAccountant", out)
    # black box + watchdog
    r = MetricsRegistry()
    from .events import EventLog

    log = EventLog(registry=r)
    log.enable()
    log.emit("health_transition")  # forces the lazy counter
    _collect(r, "obs/events.py EventLog", out)
    r = MetricsRegistry()
    from .slo import SLOWatchdog

    SLOWatchdog([], registry=r)
    _collect(r, "obs/slo.py SLOWatchdog", out)
    # memory plane (docs §28): the ledger's gauges are all scrape-time
    # callbacks, registered by export_gauges against any registry
    r = MetricsRegistry()
    from .mem import MemoryLedger

    MemoryLedger().export_gauges(r)
    _collect(r, "obs/mem.py MemoryLedger", out)
    # training + tuner planes register into the PROCESS default registry
    # lazily; poke them, then read only their families off it
    from ..core.executor import _train_metrics
    from .metrics import get_registry

    _train_metrics()
    _collect_prefixed(get_registry(), "pt_train_",
                      "core/executor.py _train_metrics", out)
    # the ledger's counters (reconcile walltime/count, OOM count) are
    # process-wide like pt_events_total — poke the lazy family
    from .mem import get_ledger

    get_ledger()._get_counters()
    _collect_prefixed(get_registry(), "pt_mem_",
                      "obs/mem.py MemoryLedger counters", out)
    try:
        from ..tune import service as tune_service

        tune_service._metrics()
        _collect_prefixed(get_registry(), "pt_tune_",
                          "tune/service.py", out)
    except Exception:
        pass
    return out


def _collect_prefixed(reg: MetricsRegistry, prefix: str, source: str,
                      out: Dict[str, Dict[str, object]]) -> None:
    for name, inst in reg.instruments().items():
        if name.startswith(prefix):
            out.setdefault(name, {
                "type": inst.typ,
                "labels": tuple(inst.labelnames),
                "help": inst.help,
                "source": source,
            })


def scan_source_names(root: str = _PKG_ROOT) -> Dict[str, List[str]]:
    """{pt_* literal: [files]} across the package source — the
    completeness backstop for instruments registered on paths too heavy
    to instantiate (server pull-gauges, paged-KV engines)."""
    found: Dict[str, List[str]] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                continue
            for m in _NAME_RE.finditer(text):
                name = m.group(1)
                if _SCAN_EXCLUDE.search(name):
                    continue
                files = found.setdefault(name, [])
                if rel not in files:
                    files.append(rel)
    return found


def render_doc() -> str:
    """The full ``docs/metrics.md`` markdown text."""
    live = live_instruments()
    scanned = scan_source_names()
    names = sorted(set(live) | set(scanned))
    lines = [
        "# Metric namespace contract (`pt_*`)",
        "",
        "GENERATED by `tools/paddle_cli.py metrics-doc` — do not edit by "
        "hand; regenerate after adding or renaming an instrument (the "
        "drift test in tests/test_obs_goodput.py fails on a `pt_*` name "
        "missing from this file).",
        "",
        "Conventions (docs/design.md §15): `pt_<plane>_<what>_<unit>`; "
        "counters end in `_total`, durations are seconds, gauges are "
        "instantaneous (some are scrape-time callbacks).",
        "",
        "| metric | type | labels | description |",
        "|---|---|---|---|",
    ]
    for name in names:
        info = live.get(name)
        if info:
            labels = ", ".join(info["labels"]) or "-"
            help_ = str(info["help"]).replace("|", "\\|")
            typ = info["type"]
        else:
            labels = "-"
            typ = "(runtime)"
            files = ", ".join(sorted(scanned.get(name, []))[:3])
            help_ = f"registered lazily at runtime; see {files}"
        lines.append(f"| `{name}` | {typ} | {labels} | {help_} |")
    lines.append("")
    lines.append(f"{len(names)} instruments "
                 f"({len(live)} described from live registries, "
                 f"{len(set(scanned) - set(live))} source-scanned).")
    lines.append("")
    return "\n".join(lines)

"""Weight decay regularizers (<- python/paddle/fluid/regularizer.py)."""
from __future__ import annotations


class WeightDecayRegularizer:
    def append_regularization_op(self, block, param, grad):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self._coeff = regularization_coeff

    def append_regularization_op(self, block, param, grad):
        from . import unique_name

        decay = block.create_var(
            unique_name.generate(f"{param.name}.l2decay"),
            dtype=param.dtype, shape=param.shape)
        block.append_op("scale", {"X": [param]}, {"Out": [decay]}, {"scale": self._coeff})
        block.append_op("sum", {"X": [grad, decay]}, {"Out": [grad]})


class L1Decay(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self._coeff = regularization_coeff

    def append_regularization_op(self, block, param, grad):
        from . import unique_name

        sign = block.create_var(
            unique_name.generate(f"{param.name}.sign"),
            dtype=param.dtype, shape=param.shape)
        decay = block.create_var(
            unique_name.generate(f"{param.name}.l1decay"),
            dtype=param.dtype, shape=param.shape)
        block.append_op("sign", {"X": [param]}, {"Out": [sign]})
        block.append_op("scale", {"X": [sign]}, {"Out": [decay]}, {"scale": self._coeff})
        block.append_op("sum", {"X": [grad, decay]}, {"Out": [grad]})


L2DecayRegularizer = L2Decay
L1DecayRegularizer = L1Decay


def append_regularization_ops(block, params_grads, global_regularization=None):
    """<- regularizer.py append_regularization_ops: per-param regularizer wins
    over the optimizer-level one."""
    for param, grad in params_grads:
        attr = getattr(param, "_param_attr", None)
        reg = (attr.regularizer if attr is not None and attr.regularizer is not None
               else global_regularization)
        if reg is None:
            continue
        reg.append_regularization_op(block, param, grad)
    return params_grads

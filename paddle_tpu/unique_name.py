"""Unique name generator (<- python/paddle/fluid/unique_name.py)."""
from __future__ import annotations

import contextlib
from collections import defaultdict


class NameGenerator:
    def __init__(self):
        self.ids = defaultdict(int)

    def generate(self, prefix: str) -> str:
        self.ids[prefix] += 1
        return f"{prefix}_{self.ids[prefix] - 1}"


_generator = NameGenerator()


def generate(prefix: str) -> str:
    return _generator.generate(prefix)


@contextlib.contextmanager
def guard(new_generator=None):
    global _generator
    prev = _generator
    _generator = new_generator or NameGenerator()
    try:
        yield
    finally:
        _generator = prev

"""Pipeline parallelism over the 'pp' mesh axis (GPipe-style microbatching).

The reference has no pipeline engine (its model-parallel story is layer-wise
placement, gserver/gradientmachines/ParallelNeuralNetwork.h); this is the
TPU-native implementation the 'pp' axis in ``mesh.MESH_AXES`` promises:
stage parameters are stacked on a leading axis and sharded ``P('pp')`` so
each device owns one stage, and microbatches flow stage-to-stage over ICI
via ``lax.ppermute`` inside ``shard_map``. The schedule is the classic
GPipe fill-drain: M microbatches over S stages take M + S - 1 ticks, every
device running the SAME stage function on its own weights each tick (SPMD —
one compiled program, no per-stage executables).

The tick loop is a ``lax.scan`` (compile time and HLO size are O(1) in the
tick count; round 2's Python unroll scaled linearly). Each tick emits the
last stage's output as a scan OUTPUT (not a carry), so reverse-mode AD
saves O(1) per tick rather than re-saving the whole output buffer.

``jax.grad`` through the schedule IS the pipeline backward: ppermute
transposes to the reverse rotation and the scan transposes to a reverse
scan, so backward microbatches drain in the opposite direction — exactly
GPipe's backward pass.

Memory: reverse-mode over the ``gpipe`` scan keeps, per tick, the carry
activation plus ``fn``'s internal residuals — O((M+S-1) * (mb activation
+ fn residuals)) per device. With ``remat=True`` each tick's ``fn`` is
``jax.checkpoint``-ed, cutting the per-tick cost to the carry alone: peak
activation residency is then the textbook GPipe O(M) microbatch buffer.
``one_f_one_b`` below is the true 1F1B schedule bounding residency at
O(S): it interleaves forward and backward microbatches in ONE loop, which
is only possible when the engine owns the loss and gradients (see its
docstring for why a custom_vjp cannot do this). Rule of thumb: embed a
pipeline inside a larger differentiated program -> ``gpipe``; own the
whole training step and care about M >> S memory -> ``one_f_one_b``.

Restrictions (deliberate, minimal-but-real):
  * stages are structurally homogeneous (same ``fn``, different weights) —
    the transformer-stack case; embed/head layers run outside the pipeline;
  * ``fn`` keeps the microbatch shape (stage i feeds stage i+1);
  * the microbatch count must divide the batch.
"""
from __future__ import annotations

import warnings
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def gpipe(fn: Callable[[Any, Any], Any], stage_params: Any, x, mesh: Mesh,
          axis: str = "pp", microbatches: int = 4, remat: bool = False,
          batch_axes: tuple = ("dp",), param_specs: Any = None):
    """Run ``x`` through S pipeline stages of ``fn`` with GPipe scheduling.

    fn(params_one_stage, x_mb) -> y_mb  must keep the microbatch shape.
    stage_params: pytree whose leaves have leading dim S == mesh.shape[axis]
    (stacked per-stage weights; sharded ``P('pp')`` by this call).
    x: [B, ...]. remat: checkpoint each tick's ``fn`` (see module
    docstring). ``batch_axes``: mesh axes (those present) the batch dim is
    sharded over — under a dp x pp mesh each dp replica pipelines only its
    own batch shard instead of redundantly recomputing the global batch.
    ``param_specs``: optional pytree of PartitionSpecs overriding the
    default ``P(axis)`` per leaf — this is how tensor parallelism composes
    with the pipeline (Megatron-sharded stage weights over a 'tp' axis; the
    stage ``fn`` is then responsible for the matching ``lax.psum``s).
    Returns y: [B, ...], batch-sharded the same way and replicated over pp.
    """
    n_stages = mesh.shape[axis]
    data_axes = tuple(a for a in batch_axes
                      if a in mesh.axis_names and a != axis)
    dp_total = 1
    for a in data_axes:
        dp_total *= mesh.shape[a]
    batch = x.shape[0]
    if batch % (microbatches * dp_total):
        raise ValueError(f"batch {batch} not divisible by microbatches "
                         f"{microbatches} x data shards {dp_total}")
    mb = batch // dp_total // microbatches
    stage_fn = jax.checkpoint(fn) if remat else fn

    def local(params, x):
        # params leaves: [1, ...] (this device's stage); x: this data
        # shard's batch (the full batch when no data axis is present)
        w = jax.tree.map(lambda p: p[0], params)
        stage = lax.axis_index(axis)
        local_batch = x.shape[0]
        xs = x.reshape((microbatches, mb) + x.shape[1:])
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        ticks = microbatches + n_stages - 1

        def tick(carry, t):
            # stage 0 injects microbatch t while filling; other stages (and
            # stage 0 after the fill) consume what rotated in last tick
            inject = lax.dynamic_index_in_dim(
                xs, jnp.minimum(t, microbatches - 1), 0, keepdims=False)
            state = jnp.where(stage == 0, inject, carry)
            y = stage_fn(w, state)
            emit = jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y))
            return lax.ppermute(y, axis, perm), emit

        carry0 = jnp.zeros((mb,) + x.shape[1:], x.dtype)
        _, emits = lax.scan(tick, carry0, jnp.arange(ticks))
        # the last stage emits microbatch t-(S-1) at tick t; psum replicates
        outs = emits[n_stages - 1:]
        out = lax.psum(outs, axis)
        return out.reshape((local_batch,) + out.shape[2:])

    pspec = (param_specs if param_specs is not None
             else jax.tree.map(lambda _: P(axis), stage_params))
    xspec = P(data_axes if data_axes else None)
    fn_sharded = shard_map(
        local, mesh=mesh,
        in_specs=(pspec, xspec), out_specs=xspec, check_vma=False)
    return fn_sharded(stage_params, x)


def one_f_one_b_preferred(microbatches: int, n_stages: int) -> bool:
    """The 1F1B-vs-GPipe crossover as a DECISION, not a warning: True when
    the 1F1B schedule is the measured-faster choice (M > 2S — below that
    the per-tick vjp replay loses to GPipe-remat; docs/perf.md '1F1B head
    gating' has the measured bracket, 1.16x slower at M=2S, 0.80x at
    M=8S). ``ShardedTrainStep`` picks its pipeline schedule with this and
    ``TrainPlacementSearcher`` prices plans with it — the same rule that
    used to only warn on stderr now feeds the searcher (docs §27)."""
    return n_stages > 1 and microbatches > 2 * n_stages


def one_f_one_b(stage_fn, loss_grad_fn, stage_params, head_params, x, labels,
                mesh: Mesh, axis: str = "pp", microbatches: int = 4,
                batch_axes: tuple = ("dp",), param_specs: Any = None,
                warn: bool = True):
    """1F1B pipeline TRAINING step: loss + grads in ONE interleaved schedule.

    Why this is a separate engine and not a grad rule on ``gpipe``: inside
    a jitted program the backward only starts after the whole forward (the
    loss is a global barrier), so any fwd/bwd-split formulation — including
    a custom_vjp — must stash one activation per microbatch: O(M) per
    device, GPipe's residency. True 1F1B interleaves forward and backward
    microbatches in one loop, which means the engine must OWN the loss and
    the gradients. This function is that loop; ``gpipe`` remains the
    composable fallback for pipelines embedded in larger differentiated
    programs (the pipelined_transformer_stack op uses it for exactly that
    reason — IR autodiff splits fwd/grad ops).

    Schedule (S stages, M microbatches, one F slot + one B slot per tick):
      F(s, m) at tick s + m;  B(s, m) at tick 2(S-1) - s + m
    so device s holds at most 2(S-1-s)+1 stashed stage INPUTS — O(S),
    independent of M (GPipe-with-remat saves O(M+S) per-tick carries).
    Total ticks: 2(S-1) + M. Backward recomputes each stage forward from
    the stashed input via ``jax.vjp`` (the same replay remat pays).

    stage_fn(w_stage, x_mb) -> y_mb                     (shape-preserving)
    loss_grad_fn(head_params, y_mb, label_mb)
        -> (loss_mb_scalar, dy_mb, dhead_mb)            (caller builds it
        with jax.value_and_grad over the head+loss; it runs ONLY on the
        last stage, at the tick its microbatch exits the stack)
    ``labels`` may be any pytree of [B, ...] arrays (a dict of label
    feeds); each leaf is microbatched along dim 0 and the per-microbatch
    tree is handed to ``loss_grad_fn``. ``warn=False`` silences the
    M <= 2S stderr warning — callers that already consulted
    ``one_f_one_b_preferred`` (the ddp schedule pick, the placement
    searcher) made the decision upstream.
    Returns (mean_loss, stage_param_grads, head_param_grads, dx).
    """
    n_stages = mesh.shape[axis]
    data_axes = tuple(a for a in batch_axes
                      if a in mesh.axis_names and a != axis)
    dp_total = 1
    for a in data_axes:
        dp_total *= mesh.shape[a]
    batch = x.shape[0]
    if batch % (microbatches * dp_total):
        raise ValueError(f"batch {batch} not divisible by microbatches "
                         f"{microbatches} x data shards {dp_total}")
    mb = batch // dp_total // microbatches
    M = microbatches
    S = n_stages
    if warn and S > 1 and M <= 2 * S:
        # Selection rule (measured, docs/perf.md "1F1B head gating"): 1F1B
        # pays a per-tick vjp forward replay that only amortizes when
        # M >> S. At S=4 the measured points bracket the crossover: M=8
        # (= 2S) was 1.16x SLOWER than GPipe-remat and M=32 (= 8S) was
        # 0.80x (20% faster) — so M == 2S is still on the losing side and
        # warns too; the crossover lies somewhere in (2S, 8S). Below it,
        # GPipe-remat wins on time and 1F1B's O(S) residency buys little
        # (GPipe's O(M) stash is small when M is).
        warnings.warn(
            f"one_f_one_b with M={M} microbatches over S={S} stages: "
            f"M <= 2S is a regime where GPipe-remat measured FASTER "
            f"(1F1B 1.16x slower at M=8/S=4; first measured-faster point "
            f"M=32/S=4 at 0.80x; docs/perf.md '1F1B head gating'). Prefer "
            f"gpipe(remat=True) here unless the O(S) activation residency "
            f"is the point, or raise microbatches toward >= {8 * S} (the "
            f"measured-faster regime, M >> S).",
            RuntimeWarning, stacklevel=2)
    stash_len = 2 * S  # >= max in-flight 2(S-1)+1

    def local(params, head_p, x, labels):
        w = jax.tree.map(lambda p: p[0], params)
        stage = lax.axis_index(axis)
        local_batch = x.shape[0]
        xs = x.reshape((M, mb) + x.shape[1:])
        lbls = jax.tree.map(
            lambda a: a.reshape((M, mb) + a.shape[1:]), labels)
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]
        ticks = 2 * (S - 1) + M

        def tick(carry, t):
            (act_in, grad_in, stash, dw, dhead, loss_sum) = carry
            # ---- F phase -------------------------------------------------
            mf = t - stage                       # this device's F microbatch
            f_valid = (mf >= 0) & (mf < M)
            mf_c = jnp.clip(mf, 0, M - 1)
            inject = lax.dynamic_index_in_dim(xs, mf_c, 0, keepdims=False)
            x_in = jnp.where(stage == 0, inject, act_in)
            y = stage_fn(w, x_in)
            stash = lax.dynamic_update_index_in_dim(
                stash, x_in, mf_c % stash_len, 0)
            # last stage: head loss + dy for the microbatch that just
            # exited. GATED under lax.cond, not computed-then-masked: for a
            # real LM head (d x V matmul + its vjp) an ungated call would
            # execute on every stage every tick — S-1 redundant head
            # passes per tick whose masked results are discarded (VERDICT
            # r4 item 8). The cond's predicate is stage-local, so only the
            # last-stage device takes the head branch; the others take the
            # zero branch. Wall-clock per tick is set by the last stage
            # either way (the masked work overlapped it), so this is a
            # per-device FLOP/energy fix — measured numbers in
            # docs/perf.md "1F1B head gating".
            is_last = stage == S - 1
            fmask = f_valid & is_last
            lbl_mb = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, mf_c, 0,
                                                   keepdims=False), lbls)

            def run_head(args):
                hp, y_mb, lbl = args
                loss_mb, dy, dh = loss_grad_fn(hp, y_mb, lbl)
                return loss_mb.astype(jnp.float32), dy, dh

            def skip_head(args):
                hp, y_mb, lbl = args
                return (jnp.zeros((), jnp.float32), jnp.zeros_like(y_mb),
                        jax.tree.map(jnp.zeros_like, hp))

            loss_mb, dy, dh = lax.cond(fmask, run_head, skip_head,
                                       (head_p, y, lbl_mb))
            loss_sum = loss_sum + loss_mb
            dhead = jax.tree.map(lambda a, g: a + g, dhead, dh)
            # ---- B phase -------------------------------------------------
            mbk = t - 2 * (S - 1) + stage        # this device's B microbatch
            b_valid = (mbk >= 0) & (mbk < M)
            mb_c = jnp.clip(mbk, 0, M - 1)
            g_in = jnp.where(is_last, dy, grad_in)
            x_saved = lax.dynamic_index_in_dim(stash, mb_c % stash_len, 0,
                                               keepdims=False)
            _, vjp = jax.vjp(stage_fn, w, x_saved)
            dw_mb, dx_mb = vjp(g_in)
            dw = jax.tree.map(
                lambda a, g: a + jnp.where(b_valid, g, jnp.zeros_like(g)),
                dw, dw_mb)
            emit_dx = jnp.where((stage == 0) & b_valid, dx_mb,
                                jnp.zeros_like(dx_mb))
            # ---- rotate --------------------------------------------------
            act_out = lax.ppermute(y, axis, fwd_perm)
            grad_out = lax.ppermute(dx_mb, axis, bwd_perm)
            return ((act_out, grad_out, stash, dw, dhead, loss_sum),
                    emit_dx)

        zeros_mb = jnp.zeros((mb,) + x.shape[1:], x.dtype)
        stash0 = jnp.zeros((stash_len, mb) + x.shape[1:], x.dtype)
        dw0 = jax.tree.map(jnp.zeros_like, w)
        dhead0 = jax.tree.map(jnp.zeros_like, head_p)
        carry0 = (zeros_mb, zeros_mb, stash0, dw0, dhead0,
                  jnp.zeros((), jnp.float32))
        (_, _, _, dw, dhead, loss_sum), emits = lax.scan(
            tick, carry0, jnp.arange(ticks))
        # B(0, m) lands at tick 2(S-1)+m; emits are zero elsewhere. psum
        # replicates device 0's dx rows (and sums the per-stage zero rows)
        dx_rows = lax.psum(emits[2 * (S - 1):], axis)
        # every grad is scaled so the outputs are d(mean loss)/d(...): the
        # per-microbatch seeds were d loss_mb/dy, and loss = mean_m loss_mb
        # (pmean'd over dp below; each shard's dx carries the 1/dp factor
        # of the global mean)
        dx = dx_rows.reshape((local_batch,) + x.shape[1:]) / (M * dp_total)
        # stage grads live per device (their stage); re-stack [1, ...]
        dw = jax.tree.map(lambda g: g[None] / M, dw)
        # head grads + loss were accumulated on the last stage only; share
        dhead = jax.tree.map(lambda g: lax.psum(g, axis) / M, dhead)
        loss = lax.psum(loss_sum, axis) / M
        if data_axes:
            loss = lax.pmean(loss, data_axes)
            dhead = jax.tree.map(lambda g: lax.pmean(g, data_axes), dhead)
            dw = jax.tree.map(lambda g: lax.pmean(g, data_axes), dw)
        return loss, dw, dhead, dx

    pspec = (param_specs if param_specs is not None
             else jax.tree.map(lambda _: P(axis), stage_params))
    xspec = P(data_axes if data_axes else None)
    hspec = jax.tree.map(lambda _: P(), head_params)
    lspec = jax.tree.map(lambda _: xspec, labels)
    fn_sharded = shard_map(
        local, mesh=mesh,
        in_specs=(pspec, hspec, xspec, lspec),
        out_specs=(P(), pspec, hspec, xspec), check_vma=False)
    return fn_sharded(stage_params, head_params, x, labels)

"""Pipeline parallelism over the 'pp' mesh axis (GPipe-style microbatching).

The reference has no pipeline engine (its model-parallel story is layer-wise
placement); this is the TPU-native implementation the 'pp' axis in
``mesh.MESH_AXES`` promises: stage parameters are stacked on a leading axis
and sharded ``P('pp')`` so each device owns one stage, and microbatches flow
stage-to-stage over ICI via ``lax.ppermute`` inside ``shard_map``. The
schedule is the classic GPipe fill-drain: M microbatches over S stages take
M + S - 1 ticks, every device running the SAME stage function on its own
weights each tick (SPMD — no per-stage programs to compile).

``jax.grad`` through the schedule IS the pipeline backward: ppermute
transposes to the reverse rotation, so backward microbatches drain in the
opposite direction, exactly GPipe's backward pass.

Restrictions (deliberate, minimal-but-real):
  * stages are structurally homogeneous (same ``fn``, different weights) —
    the transformer-stack case; embed/head layers run outside the pipeline;
  * the microbatch count must divide the batch.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def gpipe(fn: Callable[[Any, Any], Any], stage_params: Any, x, mesh: Mesh,
          axis: str = "pp", microbatches: int = 4):
    """Run ``x`` through S pipeline stages of ``fn`` with GPipe scheduling.

    fn(params_one_stage, x_mb) -> y_mb  must keep the microbatch shape
    (stage i's output feeds stage i+1's input).
    stage_params: pytree whose leaves have leading dim S == mesh.shape[axis]
    (stacked per-stage weights; the caller shards or this call shards them
    ``P('pp')``). x: [B, ...] with B % microbatches == 0.
    Returns y: [B, ...] replicated over the pp axis.
    """
    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    if batch % microbatches:
        raise ValueError(f"batch {batch} not divisible by microbatches "
                         f"{microbatches}")
    mb = batch // microbatches

    def local(params, x):
        # params leaves: [1, ...] (this device's stage); x: full batch
        w = jax.tree.map(lambda p: p[0], params)
        stage = lax.axis_index(axis)
        xs = x.reshape((microbatches, mb) + x.shape[1:])
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        carry = jnp.zeros((mb,) + x.shape[1:], x.dtype)
        outs = []
        for t in range(microbatches + n_stages - 1):
            # stage 0 injects microbatch t while filling; other stages (and
            # stage 0 after the fill) consume what rotated in last tick
            inject = xs[min(t, microbatches - 1)]
            state = jnp.where(stage == 0, inject, carry)
            y = fn(w, state)
            if t >= n_stages - 1:
                # the last stage emits microbatch t-(S-1)
                outs.append(jnp.where(stage == n_stages - 1, y,
                                      jnp.zeros_like(y)))
            carry = lax.ppermute(y, axis, perm)
        # only the last stage holds real outputs; psum replicates them
        out = lax.psum(jnp.stack(outs), axis)
        return out.reshape((batch,) + out.shape[2:])

    pspec = jax.tree.map(lambda _: P(axis), stage_params)
    fn_sharded = shard_map(
        local, mesh=mesh,
        in_specs=(pspec, P()), out_specs=P(), check_vma=False)
    return fn_sharded(stage_params, x)

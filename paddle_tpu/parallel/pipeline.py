"""Pipeline parallelism over the 'pp' mesh axis (GPipe-style microbatching).

The reference has no pipeline engine (its model-parallel story is layer-wise
placement, gserver/gradientmachines/ParallelNeuralNetwork.h); this is the
TPU-native implementation the 'pp' axis in ``mesh.MESH_AXES`` promises:
stage parameters are stacked on a leading axis and sharded ``P('pp')`` so
each device owns one stage, and microbatches flow stage-to-stage over ICI
via ``lax.ppermute`` inside ``shard_map``. The schedule is the classic
GPipe fill-drain: M microbatches over S stages take M + S - 1 ticks, every
device running the SAME stage function on its own weights each tick (SPMD —
one compiled program, no per-stage executables).

The tick loop is a ``lax.scan`` (compile time and HLO size are O(1) in the
tick count; round 2's Python unroll scaled linearly). Each tick emits the
last stage's output as a scan OUTPUT (not a carry), so reverse-mode AD
saves O(1) per tick rather than re-saving the whole output buffer.

``jax.grad`` through the schedule IS the pipeline backward: ppermute
transposes to the reverse rotation and the scan transposes to a reverse
scan, so backward microbatches drain in the opposite direction — exactly
GPipe's backward pass.

Memory (documented in lieu of a 1F1B scheduler): reverse-mode over the
scan keeps, per tick, the carry activation plus ``fn``'s internal
residuals — O((M+S-1) * (mb activation + fn residuals)) per device. With
``remat=True`` each tick's ``fn`` is ``jax.checkpoint``-ed, cutting the
per-tick cost to the carry alone: peak activation residency is then the
textbook GPipe O(M) microbatch buffer. A true 1F1B schedule would bound
residency at O(S) by interleaving forward and backward ticks, but that
requires a hand-scheduled backward (custom_vjp over the whole pipeline)
that no longer composes with ``jax.grad`` of the surrounding program; the
remat knob plus GPipe residency is the deliberate trade until a 1F1B
custom_vjp is worth that loss of composability.

Restrictions (deliberate, minimal-but-real):
  * stages are structurally homogeneous (same ``fn``, different weights) —
    the transformer-stack case; embed/head layers run outside the pipeline;
  * ``fn`` keeps the microbatch shape (stage i feeds stage i+1);
  * the microbatch count must divide the batch.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def gpipe(fn: Callable[[Any, Any], Any], stage_params: Any, x, mesh: Mesh,
          axis: str = "pp", microbatches: int = 4, remat: bool = False,
          batch_axes: tuple = ("dp",), param_specs: Any = None):
    """Run ``x`` through S pipeline stages of ``fn`` with GPipe scheduling.

    fn(params_one_stage, x_mb) -> y_mb  must keep the microbatch shape.
    stage_params: pytree whose leaves have leading dim S == mesh.shape[axis]
    (stacked per-stage weights; sharded ``P('pp')`` by this call).
    x: [B, ...]. remat: checkpoint each tick's ``fn`` (see module
    docstring). ``batch_axes``: mesh axes (those present) the batch dim is
    sharded over — under a dp x pp mesh each dp replica pipelines only its
    own batch shard instead of redundantly recomputing the global batch.
    ``param_specs``: optional pytree of PartitionSpecs overriding the
    default ``P(axis)`` per leaf — this is how tensor parallelism composes
    with the pipeline (Megatron-sharded stage weights over a 'tp' axis; the
    stage ``fn`` is then responsible for the matching ``lax.psum``s).
    Returns y: [B, ...], batch-sharded the same way and replicated over pp.
    """
    n_stages = mesh.shape[axis]
    data_axes = tuple(a for a in batch_axes
                      if a in mesh.axis_names and a != axis)
    dp_total = 1
    for a in data_axes:
        dp_total *= mesh.shape[a]
    batch = x.shape[0]
    if batch % (microbatches * dp_total):
        raise ValueError(f"batch {batch} not divisible by microbatches "
                         f"{microbatches} x data shards {dp_total}")
    mb = batch // dp_total // microbatches
    stage_fn = jax.checkpoint(fn) if remat else fn

    def local(params, x):
        # params leaves: [1, ...] (this device's stage); x: this data
        # shard's batch (the full batch when no data axis is present)
        w = jax.tree.map(lambda p: p[0], params)
        stage = lax.axis_index(axis)
        local_batch = x.shape[0]
        xs = x.reshape((microbatches, mb) + x.shape[1:])
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        ticks = microbatches + n_stages - 1

        def tick(carry, t):
            # stage 0 injects microbatch t while filling; other stages (and
            # stage 0 after the fill) consume what rotated in last tick
            inject = lax.dynamic_index_in_dim(
                xs, jnp.minimum(t, microbatches - 1), 0, keepdims=False)
            state = jnp.where(stage == 0, inject, carry)
            y = stage_fn(w, state)
            emit = jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y))
            return lax.ppermute(y, axis, perm), emit

        carry0 = jnp.zeros((mb,) + x.shape[1:], x.dtype)
        _, emits = lax.scan(tick, carry0, jnp.arange(ticks))
        # the last stage emits microbatch t-(S-1) at tick t; psum replicates
        outs = emits[n_stages - 1:]
        out = lax.psum(outs, axis)
        return out.reshape((local_batch,) + out.shape[2:])

    pspec = (param_specs if param_specs is not None
             else jax.tree.map(lambda _: P(axis), stage_params))
    xspec = P(data_axes if data_axes else None)
    fn_sharded = shard_map(
        local, mesh=mesh,
        in_specs=(pspec, xspec), out_specs=xspec, check_vma=False)
    return fn_sharded(stage_params, x)

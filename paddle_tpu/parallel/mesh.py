"""Device mesh + sharding helpers.

TPU-native replacement for the reference's device/communicator plumbing
(platform/nccl_helper.h NCCLContextMap, details/multi_devices_graph_builder.cc):
parallelism is declared as a named ``jax.sharding.Mesh`` with axes

    dp — data parallel (batch dim)
    tp — tensor parallel (hidden dims)
    pp — pipeline stages
    sp — sequence/context parallel
    ep — expert parallel

plus ``PartitionSpec``s per tensor. XLA GSPMD then *inserts* the all-reduce/
all-gather/reduce-scatter collectives over ICI that the reference inserted by
hand as AllReduceOpHandle/BroadcastOpHandle SSA nodes.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES = ("dp", "tp", "pp", "sp", "ep")


def make_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    platform: Optional[str] = None,
) -> Mesh:
    """Build a Mesh from {axis_name: size}. Defaults to pure data parallel
    over every addressable device.

    >>> mesh = make_mesh({"dp": 4, "tp": 2})
    """
    if devices is None:
        devices = jax.devices(platform) if platform else jax.devices()
    if axes is None:
        axes = {"dp": len(devices)}
    sizes = list(axes.values())
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, only {len(devices)} available")
    dev_array = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(dev_array, tuple(axes))


def serving_mesh(dp: int, tp: int,
                 devices: Optional[Sequence[jax.Device]] = None,
                 platform: Optional[str] = None) -> Mesh:
    """The serving tier's flat ('dp', 'tp') mesh over the first dp*tp
    addressable devices (serving/sharded.py builds its engines on this;
    tier-1 runs it on the conftest-forced virtual CPU devices). Raises
    with the XLA_FLAGS hint when the host exposes too few devices —
    the one setup mistake everyone makes once."""
    if devices is None:
        devices = jax.devices(platform) if platform else jax.devices()
    n = int(dp) * int(tp)
    if n > len(devices):
        raise ValueError(
            f"serving mesh needs dp*tp = {n} devices, only "
            f"{len(devices)} available (host meshes: set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N before "
            f"jax initializes)")
    return make_mesh({"dp": int(dp), "tp": int(tp)}, devices=devices[:n])


def train_mesh(dp: int, tp: int = 1, pp: int = 1,
               devices: Optional[Sequence[jax.Device]] = None,
               platform: Optional[str] = None) -> Mesh:
    """The training tier's 3-axis ('dp', 'tp', 'pp') mesh over the first
    dp*tp*pp addressable devices (parallel/ddp.py builds its windows on
    this — docs/design.md §27). Size-1 axes stay in the mesh so one set
    of PartitionSpecs covers every (dp, tp, pp) combination; the same
    XLA_FLAGS hint as ``serving_mesh`` when the host is short."""
    if devices is None:
        devices = jax.devices(platform) if platform else jax.devices()
    n = int(dp) * int(tp) * int(pp)
    if n > len(devices):
        raise ValueError(
            f"train mesh needs dp*tp*pp = {n} devices, only "
            f"{len(devices)} available (host meshes: set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N before "
            f"jax initializes)")
    return make_mesh({"dp": int(dp), "tp": int(tp), "pp": int(pp)},
                     devices=devices[:n])


def sharding_for(mesh: Mesh, *spec) -> NamedSharding:
    """NamedSharding helper: sharding_for(mesh, 'dp', None) etc."""
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def param_sharding(mesh: Mesh, var) -> NamedSharding:
    """Sharding for a parameter Variable.

    ParamAttr.sharding (a tuple naming a mesh axis per dim, e.g.
    (None, 'tp')) is the TPU-native generalisation of the reference's
    BuildStrategy.kReduce parameter placement; unset -> replicated.
    Axes absent from the mesh are ignored so the same model code runs on
    dp-only and dp×tp meshes.
    """
    attr = getattr(var, "_param_attr", None)
    spec = getattr(attr, "sharding", None) if attr is not None else None
    if spec is None:
        return replicated(mesh)
    cleaned = tuple(s if (s in mesh.axis_names) else None for s in spec)
    return NamedSharding(mesh, PartitionSpec(*cleaned))

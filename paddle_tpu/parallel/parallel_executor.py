"""ParallelExecutor: multi-device training as one GSPMD-sharded XLA program.

<- paddle/fluid/framework/parallel_executor.cc + details/ (SSA graph,
AllReduceOpHandle, ThreadedSSAGraphExecutor). The entire ~5k-LoC machinery
collapses: the traced block is jitted with NamedShardings over a Mesh —
batch split over 'dp', params replicated (all_reduce strategy) or sharded
('tp'/'reduce' strategy) — and XLA GSPMD inserts the gradient all-reduces
over ICI *inside* the compiled program, overlapped with backward compute.

BuildStrategy/ExecutionStrategy are kept as API-compatible knobs:
reduce_strategy selects replicated vs sharded parameter placement.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.executor import Scope, build_step_fn, coerce_int64_feed, global_scope
from ..core.ir import Program, default_main_program
from .mesh import make_mesh, param_sharding, replicated


class BuildStrategy:
    """<- details/build_strategy.h:24 {kAllReduce, kReduce}.

    ``async_mode`` is the TPU re-expression of the reference's async pserver
    training (listen_and_serv_op.cc RunAsyncLoop): LOCAL SGD. Each dp worker
    takes ``local_sgd_steps`` fully-local optimizer steps (no gradient
    collective at all — the analogue of workers pushing/pulling a stale
    pserver param copy at their own pace), then the workers' parameters are
    averaged over ICI. Staleness is bounded by the period instead of being
    unbounded like the pserver queue, which is the sound collective version
    of the same throughput-over-consistency trade.
    """

    class ReduceStrategy:
        AllReduce = 0  # replicated params, gradient all-reduce (default)
        Reduce = 1  # params sharded over dp (ZeRO-style reduce+scatter)

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.debug_graphviz_path = ""
        self.async_mode = False
        self.local_sgd_steps = 4  # sync period when async_mode is on


class ExecutionStrategy:
    """<- details/execution_strategy.h."""

    def __init__(self):
        self.num_threads = 0  # meaningless on XLA; kept for API parity
        self.num_iteration_per_drop_scope = 1


class ParallelExecutor:
    """Data/tensor-parallel executor over a device mesh.

    fluid-compatible surface::

        pe = ParallelExecutor(use_tpu=True, loss_name=loss.name,
                              main_program=main, scope=scope)
        loss_vals = pe.run(fetch_list=[loss.name], feed={...})

    ``feed`` carries the GLOBAL batch; it is split over the mesh's 'dp' axis
    (<- the reference splitting feed across per-device scopes,
    parallel_executor.py:234). Parameters must already exist in ``scope``
    (run the startup program through a plain Executor first — the analogue of
    BCastParamsToGPUs is the device_put with a replicated sharding here).
    """

    def __init__(
        self,
        use_tpu: bool = True,
        loss_name: Optional[str] = None,
        main_program: Optional[Program] = None,
        build_strategy: Optional[BuildStrategy] = None,
        exec_strategy: Optional[ExecutionStrategy] = None,
        scope: Optional[Scope] = None,
        mesh: Optional[Mesh] = None,
        num_trainers: int = 1,
        trainer_id: int = 0,
        amp: bool = False,
    ):
        self.program = main_program or default_main_program()
        self.scope = scope or global_scope()
        self.build_strategy = build_strategy or BuildStrategy()
        self.mesh = mesh if mesh is not None else make_mesh(
            platform="tpu" if use_tpu else None
        )
        if "dp" not in self.mesh.axis_names:
            raise ValueError("ParallelExecutor mesh must have a 'dp' axis")
        self.loss_name = loss_name
        self.amp = amp
        self.async_mode = bool(getattr(self.build_strategy, "async_mode", False)
                               or getattr(self.program, "_async_mode", False))
        self.local_sgd_steps = int(getattr(self.build_strategy,
                                           "local_sgd_steps", 4))
        self._runs_since_sync = 0
        self._avg_fn = None
        # multi-host SPMD (jax.distributed initialized, mesh spans hosts):
        # feeds are PROCESS-LOCAL batch shards assembled into global arrays,
        # fetches return the replicated value (or this host's shard of a
        # batch output) — the reference's per-trainer data reading
        self._multiprocess = jax.process_count() > 1
        self._cache: Dict[Any, Any] = {}
        self._step_seed = 0
        self._placed = False
        # every array this executor creates must live on the mesh's backend:
        # the axon TPU plugin registers itself as the default jax backend, so
        # an unpinned PRNGKey/device_put would land on the TPU even when the
        # mesh is the virtual CPU mesh, and resharding a TPU-committed array
        # onto a CPU mesh forces _multi_slice on the TPU backend. Multi-host:
        # pin to this PROCESS's first mesh device (a remote device cannot be
        # a default_device)
        pid = jax.process_index()
        mine = [d for d in self.mesh.devices.flat if d.process_index == pid]
        self._device0 = mine[0] if mine else self.mesh.devices.flat[0]

    def _to_mesh_host(self, v):
        """Pull a cross-backend device array through host memory.

        jax.device_put from (e.g.) a TPU array to a CPU-mesh sharding slices
        on the *source* backend; going via numpy keeps placement entirely on
        the mesh's own backend.
        """
        if isinstance(v, jax.Array):
            if self._multiprocess:
                # multi-host: a locally-committed array cannot device_put
                # onto a global sharding (cross-host reshard); go via host —
                # every process holds the same startup value
                return np.asarray(v)
            try:
                src_platform = next(iter(v.devices())).platform
            except Exception:
                return v
            if src_platform != self._device0.platform:
                return np.asarray(v)
        return v

    # -- local SGD (async_mode) ---------------------------------------------
    def _place_state_stacked(self, names: Sequence[str]):
        """async_mode placement: every state var becomes [dp, *shape] sharded
        P('dp') — each worker owns a full, independently-evolving copy.
        make_array_from_callback places only addressable shards, so this
        works identically single- and multi-controller."""
        dp = self.mesh.shape["dp"]
        sh = NamedSharding(self.mesh, PartitionSpec("dp"))
        for n in names:
            v = self.scope.get(n)
            if v is None:
                raise RuntimeError(
                    f"variable {n!r} missing from scope; run the startup program first"
                )
            arr = np.asarray(self._to_mesh_host(v))
            # a value restored from an async-mode checkpoint is ALREADY
            # stacked [dp, *var.shape]; broadcasting it again would produce
            # [dp, dp, ...] and a confusing trace-time shape error on resume
            var = self.program.global_block().find_var_recursive(n)
            vshape = None
            if var is not None and var.shape is not None:
                vs = tuple(int(s) for s in var.shape)
                if all(s >= 0 for s in vs):
                    vshape = vs
            already_stacked = (
                vshape is not None and arr.ndim >= 1 and arr.shape[0] == dp
                and tuple(arr.shape[1:]) == vshape
                and tuple(arr.shape) != vshape)
            stacked = arr if already_stacked else np.broadcast_to(
                arr, (dp,) + arr.shape)
            self.scope.set(n, jax.make_array_from_callback(
                stacked.shape, sh, lambda idx, a=stacked: a[idx]))

    def _build_local_sgd_step(self, step, feed_sig_names):
        """Wrap the traced step in shard_map: per-worker params (leading dp
        dim), per-worker batch shard, NO collectives inside — local SGD."""
        from ._compat import shard_map
        from jax import lax

        mesh = self.mesh

        def local_fn(feed_vals, readonly, donated, key):
            readonly = {k: v[0] for k, v in readonly.items()}
            donated = {k: v[0] for k, v in donated.items()}
            key = jax.random.fold_in(key, lax.axis_index("dp"))
            fetches, new_state = step(feed_vals, readonly, donated, key)
            # float scalar fetches (losses) pmean over ALL workers inside
            # the step — every host then reports the global mean even though
            # no gradient collective runs; batch-shaped and non-float
            # fetches stay per-worker (matching _merge_fetch's contract)
            fetches = [lax.pmean(f, "dp")
                       if jnp.ndim(f) == 0 and jnp.issubdtype(f.dtype, jnp.floating)
                       else f
                       for f in fetches]
            return ([f[None] for f in fetches],
                    {k: v[None] for k, v in new_state.items()})

        def feed_spec(ndim):
            return PartitionSpec(*(("dp",) + (None,) * (ndim - 1))) if ndim \
                else PartitionSpec()

        def wrapped(feed_vals, readonly, donated, key):
            in_specs = (
                {k: feed_spec(v.ndim) for k, v in feed_vals.items()},
                {k: PartitionSpec("dp") for k in readonly},
                {k: PartitionSpec("dp") for k in donated},
                PartitionSpec(),
            )
            fn = shard_map(
                local_fn, mesh=mesh, in_specs=in_specs,
                out_specs=(PartitionSpec("dp"), PartitionSpec("dp")),
                check_vma=False)
            return fn(feed_vals, readonly, donated, key)

        return wrapped

    def _sync_workers(self, state_names: Sequence[str]):
        """Average the workers' float state over dp (the local-SGD sync)."""
        # barrier: the step executable carries its own collective (the loss
        # pmean) — launching the averaging executable (all-reduce) while
        # some device threads are still inside the step interleaves two
        # collectives' rendezvous across executables and deadlocks XLA:CPU
        # ("cross_module ... expected 8, got 6"). Wait for the step's
        # outputs before enqueueing the sync.
        jax.block_until_ready([self.scope.get(n) for n in state_names
                               if isinstance(self.scope.get(n), jax.Array)])
        avg = self._avg_fn
        if avg is None:
            sh = NamedSharding(self.mesh, PartitionSpec("dp"))

            @functools.partial(jax.jit, out_shardings=sh)
            def avg(x):
                return jnp.broadcast_to(jnp.mean(x, axis=0), x.shape)

            # cache: a fresh closure per sync would defeat jit's cache and
            # recompile the average at every period
            self._avg_fn = avg

        for n in state_names:
            v = self.scope.get(n)
            if (isinstance(v, jax.Array) and v.ndim >= 1
                    and jnp.issubdtype(v.dtype, jnp.floating)):
                self.scope.set(n, avg(v))

    # -- parameter placement (<- BCastParamsToGPUs, parallel_executor.cc:134) --
    def _place_state(self, names: Sequence[str]):
        zero_shard = (
            self.build_strategy.reduce_strategy == BuildStrategy.ReduceStrategy.Reduce
        )
        for n in names:
            v = self.scope.get(n)
            if v is None:
                raise RuntimeError(
                    f"variable {n!r} missing from scope; run the startup program first"
                )
            var = self.program.global_block().find_var_recursive(n)
            sh = param_sharding(self.mesh, var) if var is not None else replicated(self.mesh)
            if zero_shard and sh.spec == PartitionSpec() and var is not None:
                # kReduce strategy: shard the largest dim over dp if divisible
                shape = np.shape(v)
                for d, size in enumerate(shape):
                    if size % self.mesh.shape["dp"] == 0 and size >= self.mesh.shape["dp"]:
                        spec = [None] * len(shape)
                        spec[d] = "dp"
                        sh = NamedSharding(self.mesh, PartitionSpec(*spec))
                        break
            val = self._to_mesh_host(v)
            if self._multiprocess:
                # build the global array from this host's copy of the value
                # (identical on every host — startup ran with one seed);
                # make_array_from_callback places only addressable shards
                # and avoids device_put's cross-host verification collective
                arr = np.asarray(val)
                self.scope.set(n, jax.make_array_from_callback(
                    arr.shape, sh, lambda idx, a=arr: a[idx]))
            else:
                self.scope.set(n, jax.device_put(val, sh))

    def _feed_sharding(self, arr):
        spec = [None] * np.ndim(arr)
        if spec:
            spec[0] = "dp"
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def _check_batch_divisible(self, name, arr):
        if arr.ndim and arr.shape[0] % self.mesh.shape["dp"] != 0:
            raise ValueError(
                f"feed {name!r}: global batch {arr.shape[0]} not divisible "
                f"by dp={self.mesh.shape['dp']}"
            )

    def place_feed(self, feed: Dict[str, Any]) -> Dict[str, Any]:
        """Pre-place a feed dict on the mesh (dp-sharded batch dim) so a
        REUSED batch is transferred once instead of per run() call —
        device-resident values are passed through by run() untouched."""
        with jax.default_device(self._device0):
            out = {}
            for k, v in feed.items():
                arr = np.asarray(v)
                var = self.program.global_block().find_var_recursive(k)
                if var is not None and var.dtype is not None:
                    arr = arr.astype(var.dtype.np_dtype, copy=False)
                arr = coerce_int64_feed(arr, k)
                sh = self._feed_sharding(arr)
                if self._multiprocess:
                    out[k] = jax.make_array_from_process_local_data(sh, arr)
                else:
                    # same validation as run(): fail with the framework's
                    # error, not an opaque JAX sharding error
                    self._check_batch_divisible(k, arr)
                    out[k] = jax.device_put(arr, sh)
            return out

    def run(
        self,
        fetch_list: Sequence[Union[str, Any]],
        feed: Optional[Dict[str, Any]] = None,
        return_numpy: bool = True,
        seed: Optional[int] = None,
    ) -> List[np.ndarray]:
        # pin ALL placement (feed device_puts, the PRNG key, parameter
        # placement on first run) to the mesh's device pool — see _device0
        with jax.default_device(self._device0):
            return self._run_pinned(fetch_list, feed, return_numpy, seed)

    def _run_pinned(self, fetch_list, feed, return_numpy, seed):
        feed = feed or {}
        fetch_names = [f if isinstance(f, str) else f.name for f in fetch_list]
        feed_names = tuple(sorted(feed))
        feed_vals = {}
        for k in feed_names:
            v = feed[k]
            if (isinstance(v, jax.Array)
                    and v.sharding == self._feed_sharding(v)):
                # already placed with this mesh's feed sharding (place_feed,
                # or a reused batch) — re-placement would force a host round
                # trip per step
                feed_vals[k] = v
                continue
            arr = np.asarray(v)
            var = self.program.global_block().find_var_recursive(k)
            if var is not None and var.dtype is not None:
                arr = arr.astype(var.dtype.np_dtype, copy=False)
            arr = coerce_int64_feed(arr, k)
            sh = self._feed_sharding(arr)
            if self._multiprocess:
                # each host feeds its own slice of the global batch
                feed_vals[k] = jax.make_array_from_process_local_data(sh, arr)
                continue
            self._check_batch_divisible(k, arr)
            feed_vals[k] = jax.device_put(arr, sh)

        sig = tuple((k, feed_vals[k].shape, str(feed_vals[k].dtype)) for k in feed_names)
        key_cache = (self.program.uid, self.program.version, sig,
                     tuple(fetch_names), self.amp)
        entry = self._cache.get(key_cache)
        if entry is None:
            step, readonly_names, donated_names, state_out = build_step_fn(
                self.program, 0, feed_names, fetch_names, amp=self.amp,
                mesh=self.mesh
            )
            if self.async_mode:
                step = self._build_local_sgd_step(step, feed_names)
            if not self._placed:
                if self.async_mode:
                    self._place_state_stacked(readonly_names + donated_names)
                else:
                    self._place_state(readonly_names + donated_names)
                self._placed = True
            jitted = jax.jit(step, donate_argnums=(2,))
            entry = (jitted, readonly_names, donated_names, state_out)
            self._cache[key_cache] = entry
        fn, readonly_names, donated_names, state_out = entry

        readonly = {n: self.scope.get(n) for n in readonly_names}
        donated = {n: self.scope.get(n) for n in donated_names}
        if seed is None:
            self._step_seed += 1
            seed = self._step_seed
        key = jax.random.PRNGKey(np.uint32(seed))
        if self._multiprocess:
            # the key must be a global (replicated) array: a locally-committed
            # input cannot enter a multi-host jit
            karr = np.asarray(key)
            key = jax.make_array_from_callback(
                karr.shape, NamedSharding(self.mesh, PartitionSpec()),
                lambda idx: karr[idx])
        with self.mesh:
            fetches, new_state = fn(feed_vals, readonly, donated, key)
        for n in state_out:
            self.scope.set(n, new_state[n])
        if self.async_mode:
            self._runs_since_sync += 1
            if self._runs_since_sync >= self.local_sgd_steps:
                self._sync_workers(state_out)
                self._runs_since_sync = 0
        if return_numpy:
            fetches = [self._merge_fetch(self._fetch_np(v)) if self.async_mode
                       else self._fetch_np(v) for v in fetches]
        return fetches

    def _fetch_np(self, v) -> np.ndarray:
        """Fetch -> numpy. Multi-host: a replicated value reads this host's
        copy; a sharded value yields THIS HOST's portion (e.g. the local
        batch this process fed), stitched from its non-replica shards along
        whatever dims are actually sharded."""
        if isinstance(v, jax.Array) and not v.is_fully_addressable:
            if v.sharding.is_fully_replicated or v.ndim == 0:
                return np.asarray(v.addressable_shards[0].data)
            shards = [s for s in v.addressable_shards if s.replica_id == 0]
            if not shards:
                # this host holds only replica copies (e.g. a P('tp') value
                # with 'dp' spanning hosts): shard data is identical per
                # index, so dedupe by index and stitch from any replica
                by_index = {}
                for s in v.addressable_shards:
                    by_index.setdefault(tuple(map(str, s.index)), s)
                shards = list(by_index.values())
            starts = [min((s.index[d].start or 0) for s in shards)
                      for d in range(v.ndim)]
            stops = [max((s.index[d].stop if s.index[d].stop is not None
                          else v.shape[d]) for s in shards)
                     for d in range(v.ndim)]
            out = np.empty([b - a for a, b in zip(starts, stops)], v.dtype)
            for s in shards:
                sl = tuple(slice((i.start or 0) - a,
                                 (i.stop if i.stop is not None else dim) - a)
                           for i, a, dim in zip(s.index, starts, v.shape))
                out[sl] = np.asarray(s.data)
            return out
        return np.asarray(v)

    @staticmethod
    def _merge_fetch(arr: np.ndarray) -> np.ndarray:
        """async_mode fetches arrive stacked [dp, ...] — per-worker scalars
        (losses, stacked to rank 1) merge to their mean; everything of rank
        >= 2 is a per-worker batch shard and concatenates back to the global
        batch (the reference PE's fetch merge semantics)."""
        if arr.ndim <= 1:
            return arr.mean() if np.issubdtype(arr.dtype, np.floating) else arr[0]
        return arr.reshape((-1,) + arr.shape[2:])

"""ParallelExecutor: multi-device training as one GSPMD-sharded XLA program.

<- paddle/fluid/framework/parallel_executor.cc + details/ (SSA graph,
AllReduceOpHandle, ThreadedSSAGraphExecutor). The entire ~5k-LoC machinery
collapses: the traced block is jitted with NamedShardings over a Mesh —
batch split over 'dp', params replicated (all_reduce strategy) or sharded
('tp'/'reduce' strategy) — and XLA GSPMD inserts the gradient all-reduces
over ICI *inside* the compiled program, overlapped with backward compute.

BuildStrategy/ExecutionStrategy are kept as API-compatible knobs:
reduce_strategy selects replicated vs sharded parameter placement.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.executor import Scope, build_step_fn, global_scope
from ..core.ir import Program, default_main_program
from .mesh import make_mesh, param_sharding, replicated


class BuildStrategy:
    """<- details/build_strategy.h:24 {kAllReduce, kReduce}.

    ``async_mode`` is the TPU re-expression of the reference's async pserver
    training (listen_and_serv_op.cc RunAsyncLoop): LOCAL SGD. Each dp worker
    takes ``local_sgd_steps`` fully-local optimizer steps (no gradient
    collective at all — the analogue of workers pushing/pulling a stale
    pserver param copy at their own pace), then the workers' parameters are
    averaged over ICI. Staleness is bounded by the period instead of being
    unbounded like the pserver queue, which is the sound collective version
    of the same throughput-over-consistency trade.
    """

    class ReduceStrategy:
        AllReduce = 0  # replicated params, gradient all-reduce (default)
        Reduce = 1  # params sharded over dp (ZeRO-style reduce+scatter)

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.debug_graphviz_path = ""
        self.async_mode = False
        self.local_sgd_steps = 4  # sync period when async_mode is on


class ExecutionStrategy:
    """<- details/execution_strategy.h."""

    def __init__(self):
        self.num_threads = 0  # meaningless on XLA; kept for API parity
        self.num_iteration_per_drop_scope = 1


class ParallelExecutor:
    """Data/tensor-parallel executor over a device mesh.

    fluid-compatible surface::

        pe = ParallelExecutor(use_tpu=True, loss_name=loss.name,
                              main_program=main, scope=scope)
        loss_vals = pe.run(fetch_list=[loss.name], feed={...})

    ``feed`` carries the GLOBAL batch; it is split over the mesh's 'dp' axis
    (<- the reference splitting feed across per-device scopes,
    parallel_executor.py:234). Parameters must already exist in ``scope``
    (run the startup program through a plain Executor first — the analogue of
    BCastParamsToGPUs is the device_put with a replicated sharding here).
    """

    def __init__(
        self,
        use_tpu: bool = True,
        loss_name: Optional[str] = None,
        main_program: Optional[Program] = None,
        build_strategy: Optional[BuildStrategy] = None,
        exec_strategy: Optional[ExecutionStrategy] = None,
        scope: Optional[Scope] = None,
        mesh: Optional[Mesh] = None,
        num_trainers: int = 1,
        trainer_id: int = 0,
        amp: bool = False,
    ):
        self.program = main_program or default_main_program()
        self.scope = scope or global_scope()
        self.build_strategy = build_strategy or BuildStrategy()
        self.mesh = mesh if mesh is not None else make_mesh(
            platform="tpu" if use_tpu else None
        )
        if "dp" not in self.mesh.axis_names:
            raise ValueError("ParallelExecutor mesh must have a 'dp' axis")
        self.loss_name = loss_name
        self.amp = amp
        self.async_mode = bool(getattr(self.build_strategy, "async_mode", False)
                               or getattr(self.program, "_async_mode", False))
        self.local_sgd_steps = int(getattr(self.build_strategy,
                                           "local_sgd_steps", 4))
        self._runs_since_sync = 0
        self._avg_fn = None
        self._cache: Dict[Any, Any] = {}
        self._step_seed = 0
        self._placed = False
        # every array this executor creates must live on the mesh's backend:
        # the axon TPU plugin registers itself as the default jax backend, so
        # an unpinned PRNGKey/device_put would land on the TPU even when the
        # mesh is the virtual CPU mesh, and resharding a TPU-committed array
        # onto a CPU mesh forces _multi_slice on the TPU backend
        self._device0 = self.mesh.devices.flat[0]

    def _to_mesh_host(self, v):
        """Pull a cross-backend device array through host memory.

        jax.device_put from (e.g.) a TPU array to a CPU-mesh sharding slices
        on the *source* backend; going via numpy keeps placement entirely on
        the mesh's own backend.
        """
        if isinstance(v, jax.Array):
            try:
                src_platform = next(iter(v.devices())).platform
            except Exception:
                return v
            if src_platform != self._device0.platform:
                return np.asarray(v)
        return v

    # -- local SGD (async_mode) ---------------------------------------------
    def _place_state_stacked(self, names: Sequence[str]):
        """async_mode placement: every state var becomes [dp, *shape] sharded
        P('dp') — each worker owns a full, independently-evolving copy."""
        dp = self.mesh.shape["dp"]
        sh = NamedSharding(self.mesh, PartitionSpec("dp"))
        for n in names:
            v = self.scope.get(n)
            if v is None:
                raise RuntimeError(
                    f"variable {n!r} missing from scope; run the startup program first"
                )
            arr = np.asarray(self._to_mesh_host(v))
            self.scope.set(
                n, jax.device_put(np.broadcast_to(arr, (dp,) + arr.shape), sh))

    def _build_local_sgd_step(self, step, feed_sig_names):
        """Wrap the traced step in shard_map: per-worker params (leading dp
        dim), per-worker batch shard, NO collectives inside — local SGD."""
        from jax import shard_map
        from jax import lax

        mesh = self.mesh

        def local_fn(feed_vals, readonly, donated, key):
            readonly = {k: v[0] for k, v in readonly.items()}
            donated = {k: v[0] for k, v in donated.items()}
            key = jax.random.fold_in(key, lax.axis_index("dp"))
            fetches, new_state = step(feed_vals, readonly, donated, key)
            return ([f[None] for f in fetches],
                    {k: v[None] for k, v in new_state.items()})

        def feed_spec(ndim):
            return PartitionSpec(*(("dp",) + (None,) * (ndim - 1))) if ndim \
                else PartitionSpec()

        def wrapped(feed_vals, readonly, donated, key):
            in_specs = (
                {k: feed_spec(v.ndim) for k, v in feed_vals.items()},
                {k: PartitionSpec("dp") for k in readonly},
                {k: PartitionSpec("dp") for k in donated},
                PartitionSpec(),
            )
            fn = shard_map(
                local_fn, mesh=mesh, in_specs=in_specs,
                out_specs=(PartitionSpec("dp"), PartitionSpec("dp")),
                check_vma=False)
            return fn(feed_vals, readonly, donated, key)

        return wrapped

    def _sync_workers(self, state_names: Sequence[str]):
        """Average the workers' float state over dp (the local-SGD sync)."""
        avg = self._avg_fn
        if avg is None:
            sh = NamedSharding(self.mesh, PartitionSpec("dp"))

            @functools.partial(jax.jit, out_shardings=sh)
            def avg(x):
                return jnp.broadcast_to(jnp.mean(x, axis=0), x.shape)

            # cache: a fresh closure per sync would defeat jit's cache and
            # recompile the average at every period
            self._avg_fn = avg

        for n in state_names:
            v = self.scope.get(n)
            if (isinstance(v, jax.Array) and v.ndim >= 1
                    and jnp.issubdtype(v.dtype, jnp.floating)):
                self.scope.set(n, avg(v))

    # -- parameter placement (<- BCastParamsToGPUs, parallel_executor.cc:134) --
    def _place_state(self, names: Sequence[str]):
        zero_shard = (
            self.build_strategy.reduce_strategy == BuildStrategy.ReduceStrategy.Reduce
        )
        for n in names:
            v = self.scope.get(n)
            if v is None:
                raise RuntimeError(
                    f"variable {n!r} missing from scope; run the startup program first"
                )
            var = self.program.global_block().find_var_recursive(n)
            sh = param_sharding(self.mesh, var) if var is not None else replicated(self.mesh)
            if zero_shard and sh.spec == PartitionSpec() and var is not None:
                # kReduce strategy: shard the largest dim over dp if divisible
                shape = np.shape(v)
                for d, size in enumerate(shape):
                    if size % self.mesh.shape["dp"] == 0 and size >= self.mesh.shape["dp"]:
                        spec = [None] * len(shape)
                        spec[d] = "dp"
                        sh = NamedSharding(self.mesh, PartitionSpec(*spec))
                        break
            self.scope.set(n, jax.device_put(self._to_mesh_host(v), sh))

    def _feed_sharding(self, arr):
        spec = [None] * np.ndim(arr)
        if spec:
            spec[0] = "dp"
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def run(
        self,
        fetch_list: Sequence[Union[str, Any]],
        feed: Optional[Dict[str, Any]] = None,
        return_numpy: bool = True,
        seed: Optional[int] = None,
    ) -> List[np.ndarray]:
        # pin ALL placement (feed device_puts, the PRNG key, parameter
        # placement on first run) to the mesh's device pool — see _device0
        with jax.default_device(self._device0):
            return self._run_pinned(fetch_list, feed, return_numpy, seed)

    def _run_pinned(self, fetch_list, feed, return_numpy, seed):
        feed = feed or {}
        fetch_names = [f if isinstance(f, str) else f.name for f in fetch_list]
        feed_names = tuple(sorted(feed))
        feed_vals = {}
        for k in feed_names:
            arr = np.asarray(feed[k])
            var = self.program.global_block().find_var_recursive(k)
            if var is not None and var.dtype is not None:
                arr = arr.astype(var.dtype.np_dtype, copy=False)
            if arr.ndim and arr.shape[0] % self.mesh.shape["dp"] != 0:
                raise ValueError(
                    f"feed {k!r}: global batch {arr.shape[0]} not divisible by "
                    f"dp={self.mesh.shape['dp']}"
                )
            feed_vals[k] = jax.device_put(arr, self._feed_sharding(arr))

        sig = tuple((k, feed_vals[k].shape, str(feed_vals[k].dtype)) for k in feed_names)
        key_cache = (id(self.program), self.program.version, sig,
                     tuple(fetch_names), self.amp)
        entry = self._cache.get(key_cache)
        if entry is None:
            step, readonly_names, donated_names, state_out = build_step_fn(
                self.program, 0, feed_names, fetch_names, amp=self.amp
            )
            if self.async_mode:
                step = self._build_local_sgd_step(step, feed_names)
            if not self._placed:
                if self.async_mode:
                    self._place_state_stacked(readonly_names + donated_names)
                else:
                    self._place_state(readonly_names + donated_names)
                self._placed = True
            jitted = jax.jit(step, donate_argnums=(2,))
            entry = (jitted, readonly_names, donated_names, state_out)
            self._cache[key_cache] = entry
        fn, readonly_names, donated_names, state_out = entry

        readonly = {n: self.scope.get(n) for n in readonly_names}
        donated = {n: self.scope.get(n) for n in donated_names}
        if seed is None:
            self._step_seed += 1
            seed = self._step_seed
        key = jax.random.PRNGKey(np.uint32(seed))
        with self.mesh:
            fetches, new_state = fn(feed_vals, readonly, donated, key)
        for n in state_out:
            self.scope.set(n, new_state[n])
        if self.async_mode:
            self._runs_since_sync += 1
            if self._runs_since_sync >= self.local_sgd_steps:
                self._sync_workers(state_out)
                self._runs_since_sync = 0
        if return_numpy:
            fetches = [self._merge_fetch(np.asarray(v)) if self.async_mode
                       else np.asarray(v) for v in fetches]
        return fetches

    @staticmethod
    def _merge_fetch(arr: np.ndarray) -> np.ndarray:
        """async_mode fetches arrive stacked [dp, ...] — per-worker scalars
        (losses, stacked to rank 1) merge to their mean; everything of rank
        >= 2 is a per-worker batch shard and concatenates back to the global
        batch (the reference PE's fetch merge semantics)."""
        if arr.ndim <= 1:
            return arr.mean() if np.issubdtype(arr.dtype, np.floating) else arr[0]
        return arr.reshape((-1,) + arr.shape[2:])

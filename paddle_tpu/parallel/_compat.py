"""jax version-compat shims for the parallel package.

``shard_map`` graduated from ``jax.experimental.shard_map`` to a top-level
``jax.shard_map`` export, and its replication-check kwarg was renamed
``check_rep`` -> ``check_vma`` (after graduation, so there is a version
window with the top-level export and the OLD kwarg). One import site so
every user of manual sharding in this package resolves the same callable
on any of the three generations; call sites use the new ``check_vma``
spelling and the shim downgrades it when the resolved function predates it.
"""
import inspect

try:  # jax >= 0.5: top-level export
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

try:
    _HAS_CHECK_VMA = "check_vma" in inspect.signature(_shard_map).parameters
except (ValueError, TypeError):  # unintrospectable: assume current spelling
    _HAS_CHECK_VMA = True

if _HAS_CHECK_VMA:
    shard_map = _shard_map
else:
    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, *args, **kwargs)

__all__ = ["shard_map"]

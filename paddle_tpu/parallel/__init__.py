from .mesh import make_mesh, sharding_for  # noqa: F401
from .parallel_executor import ParallelExecutor, BuildStrategy, ExecutionStrategy  # noqa: F401
from .pipeline import gpipe  # noqa: F401
from .ddp import ShardedTrainError, ShardedTrainStep, split_train_block  # noqa: F401
from .resilience import (CheckpointPolicy, PreemptedError,  # noqa: F401
                         ResilientTrainer, RollbackExhausted, TrainChaos,
                         WorkerKilled)

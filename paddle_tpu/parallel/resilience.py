"""Fault-tolerant elastic training: the training-side twin of the serving
resilience plane (docs/design.md §26).

The reference's production story was the etcd-backed master/pserver tier
that survived worker death mid-job; our serving stack rebuilt that
discipline end to end (typed errors + retries + drain, fleet chaos), but a
training run that lost a host, caught a preemption SIGTERM, or hit a NaN
simply died and restarted from whatever checkpoint someone last wrote by
hand. ``ResilientTrainer`` closes that gap around the windowed step loop:

* **async snapshot checkpoints** — at a window boundary the persistable
  state is copied device→host (the only exposed cost), then a background
  publisher thread writes it through io.py's manifest+``_SUCCESS``
  discipline while the NEXT window computes. The write overlaps device
  time, so the goodput sweep attributes it to ``device_compute`` — the
  snapshot is provably ~free; only the boundary copy (and a ``sync=True``
  publish) surfaces as ``checkpoint`` badput. Double-buffered: one
  snapshot writing + one queued; a third is SKIPPED (counted), never
  allowed to stall the step loop.
* **bit-deterministic resume** — every checkpoint stamps the cursor
  (next window, global step, skipped windows) and the executor's PRNG
  seed counter via io.py's ``_TRAIN_STATE.json``. A killed-and-resumed
  run replays the exact seed stream and consumes the exact batches the
  uninterrupted run would have — the repo's signature bitwise gate,
  applied to training.
* **preemption + failure handling** — SIGTERM (or
  ``request_preemption()``) triggers a grace final snapshot then a typed
  ``PreemptedError``; a non-finite loss window rolls back to the last
  good snapshot with bounded exponential backoff, and a window that
  faults twice in a row is SKIPPED (a deterministic poison would
  otherwise NaN forever). Every transition emits an event and lands in
  flight-recorder bundles.
* **elastic dp resize** — ``elastic=True`` re-plans (dp, accum, zero)
  for the CURRENT device inventory with ``TrainPlacementSearcher``,
  preserving the global batch; ``_ZERO.json`` reshard-on-load makes a
  dp4→dp2 resume exact.
* **training chaos** — ``TrainChaos`` drives seeded kills, SIGTERM
  storms, checkpoint corruption, NaN injection and host stalls through
  the same hook discipline as ``serving/chaos.py``: off means one
  ``is None`` check, and a failing storm replays from its seed.
"""
from __future__ import annotations

import glob
import os
import queue
import random
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import io as model_io
from ..core.executor import Executor, Scope
from ..obs.events import get_event_log
from ..obs.flight import get_recorder
from ..obs.goodput import get_accountant

#: injector counter -> the fault name its chaos_inject event carries
#: (same join discipline as serving/chaos.py FAULT_NAMES)
FAULT_NAMES = {"kills": "kill", "sigterms": "sigterm",
               "corruptions": "corrupt_ckpt", "nans": "nan",
               "stalls": "stall"}


class PreemptedError(RuntimeError):
    """Typed preemption exit: the grace snapshot is on disk. ``serial``
    is the final checkpoint, ``window`` the next window to execute —
    a supervisor restarts the job and resumes bit-exactly."""

    def __init__(self, serial: int, window: int):
        super().__init__(
            f"preempted: final snapshot serial={serial}, resume at "
            f"window {window}")
        self.serial = serial
        self.window = window


class WorkerKilled(RuntimeError):
    """``TrainChaos``'s in-process stand-in for ``kill -9`` mid-window:
    un-published progress is lost exactly as a real kill would lose it
    (queued snapshots are dropped; only completed publishes survive)."""

    def __init__(self, window: int):
        super().__init__(f"chaos: worker killed at window {window}")
        self.window = window


class RollbackExhausted(RuntimeError):
    """More consecutive rollbacks than the budget allows — the run is
    diverging faster than it recovers; a human (or the supervisor's
    page) owns the next move."""

    def __init__(self, window: int, rollbacks: int):
        super().__init__(
            f"rollback budget exhausted at window {window} after "
            f"{rollbacks} consecutive rollbacks")
        self.window = window
        self.rollbacks = rollbacks


class CheckpointPolicy:
    """Snapshot cadence + retention. ``every_windows``/``every_seconds``
    are OR'd (either due triggers a snapshot); ``max_keep`` is io.py's
    retention budget (the newest complete serial is never deleted);
    ``sync=True`` publishes inline on the step thread — the control arm
    of the async-overhead bench, not a production setting."""

    def __init__(self, every_windows: Optional[int] = 1,
                 every_seconds: Optional[float] = None, max_keep: int = 3,
                 sync: bool = False, grace_seconds: float = 5.0):
        self.every_windows = (max(1, int(every_windows))
                              if every_windows is not None else None)
        self.every_seconds = (float(every_seconds)
                              if every_seconds is not None else None)
        self.max_keep = int(max_keep)
        self.sync = bool(sync)
        self.grace_seconds = float(grace_seconds)

    def due(self, windows_since: int, seconds_since: float) -> bool:
        if self.every_windows is not None \
                and windows_since >= self.every_windows:
            return True
        return (self.every_seconds is not None
                and seconds_since >= self.every_seconds)


# -- resilience-plane obs instruments (process default registry) ----------
_resil_obs = None
_resil_obs_lock = threading.Lock()


def _resilience_metrics():
    """Lazy get-or-create of the checkpoint/rollback instruments, one set
    per process (the ``_train_metrics`` discipline)."""
    global _resil_obs
    if _resil_obs is not None:
        return _resil_obs
    with _resil_obs_lock:
        if _resil_obs is not None:
            return _resil_obs
        from ..obs import get_registry

        r = get_registry()
        _resil_obs = {
            "saves": r.counter("pt_train_ckpt_saves_total",
                               "Snapshot checkpoints published (_SUCCESS)"),
            "skipped": r.counter(
                "pt_train_ckpt_skipped_total",
                "Snapshots skipped because both buffers were in flight"),
            "seconds": r.counter(
                "pt_train_ckpt_seconds_total",
                "Seconds spent copying + publishing snapshots"),
            "last_serial": r.gauge("pt_train_ckpt_last_serial",
                                   "Serial of the newest published snapshot"),
            "rollbacks": r.counter(
                "pt_train_rollbacks_total",
                "Rollbacks to the last good snapshot (sentinel escalation)"),
            "preemptions": r.counter(
                "pt_train_preemptions_total",
                "Preemptions handled with a grace snapshot + typed exit"),
        }
    return _resil_obs


class TrainChaos:
    """Seeded fault injector for the training plane (the PR-7 FleetChaos
    discipline pointed at a trainer): every injection is one coin flip
    from one seeded RNG, counted and event-logged, so a failing storm
    replays exactly. Hooks:

    * ``on_window(trainer, w)`` — window start: may stall the host,
      flag a preemption, raise ``WorkerKilled``, or return ``"nan"`` to
      poison this window's batch (the numerics-sentinel drill).
    * ``on_window_end(trainer, w)`` — after compute, before the
      snapshot publishes: the worst-case crash point (a kill here loses
      the whole window).
    * ``on_published(dir, serial)`` — after ``_SUCCESS``: may tear an
      array file so the NEXT load must fall back through the manifest.
    """

    def __init__(self, seed: int = 0, kill_prob: float = 0.0,
                 sigterm_prob: float = 0.0, corrupt_prob: float = 0.0,
                 nan_prob: float = 0.0, stall_prob: float = 0.0,
                 stall_ms: float = 10.0, max_faults: Optional[int] = None):
        self.seed = int(seed)
        self.kill_prob = kill_prob
        self.sigterm_prob = sigterm_prob
        self.corrupt_prob = corrupt_prob
        self.nan_prob = nan_prob
        self.stall_prob = stall_prob
        self.stall_ms = stall_ms
        self.max_faults = max_faults
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected = {"kills": 0, "sigterms": 0, "corruptions": 0,
                         "nans": 0, "stalls": 0}

    @classmethod
    def default_storm(cls, seed: int = 0) -> "TrainChaos":
        """The bench storm: every fault class armed, bounded count."""
        return cls(seed=seed, kill_prob=0.08, sigterm_prob=0.08,
                   corrupt_prob=0.15, nan_prob=0.10, stall_prob=0.10,
                   stall_ms=5.0, max_faults=12)

    def _roll(self, prob: float, counter: str, **attrs) -> bool:
        if prob <= 0.0:
            return False
        with self._lock:
            if self.max_faults is not None \
                    and sum(self.injected.values()) >= self.max_faults:
                return False
            if self._rng.random() >= prob:
                return False
            self.injected[counter] += 1
        ev = get_event_log()
        if ev.enabled:
            ev.emit("chaos_inject", severity="warn",
                    fault=FAULT_NAMES[counter], seed=self.seed, **attrs)
        return True

    def on_window(self, trainer: "ResilientTrainer",
                  window: int) -> Optional[str]:
        if self._roll(self.stall_prob, "stalls", window=window):
            time.sleep(self.stall_ms / 1e3)
        if self._roll(self.sigterm_prob, "sigterms", window=window):
            trainer.request_preemption()
        if self._roll(self.kill_prob, "kills", window=window):
            trainer._abandon_pending()
            raise WorkerKilled(window)
        if self._roll(self.nan_prob, "nans", window=window):
            return "nan"
        return None

    def on_window_end(self, trainer: "ResilientTrainer",
                      window: int) -> None:
        if self._roll(self.kill_prob, "kills", window=window,
                      at="window_end"):
            trainer._abandon_pending()
            raise WorkerKilled(window)

    def on_published(self, checkpoint_dir: str, serial: int) -> None:
        if not self._roll(self.corrupt_prob, "corruptions", serial=serial):
            return
        files = sorted(glob.glob(os.path.join(
            model_io.checkpoint_serial_dir(checkpoint_dir, serial),
            "*.npy")))
        if not files:
            return
        data = open(files[0], "rb").read()
        with open(files[0], "wb") as f:
            f.write(data[:max(1, len(data) // 2)])

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.injected)


class ResilientTrainer:
    """Supervisor around the windowed step loop. ``feed_fn(w)`` must be a
    pure function of the window index returning one global-batch feed
    dict — that purity is what makes kill-and-resume bit-identical: the
    resumed run asks for the same windows and draws the same seeds.

    ``parallel={"dp":..,"accum_steps":..,"zero_stage":..}`` wraps the
    program in a ``ShardedTrainStep``; ``elastic=True`` instead asks
    ``TrainPlacementSearcher`` to plan those three axes for the CURRENT
    device inventory, preserving ``global_batch`` — resuming a dp4
    checkpoint on 2 devices re-plans and reshard-on-load does the rest.
    """

    def __init__(self, program, *, checkpoint_dir: str,
                 feed_fn: Callable[[int], Dict[str, Any]],
                 loss_name: str, executor: Optional[Executor] = None,
                 scope: Optional[Scope] = None,
                 startup_program=None, seed: Optional[int] = None,
                 window_steps: int = 4, parallel: Optional[dict] = None,
                 elastic: bool = False, inventory=None,
                 global_batch: Optional[int] = None, max_accum: int = 64,
                 policy: Optional[CheckpointPolicy] = None,
                 max_rollbacks: int = 4, rollback_backoff: float = 0.0,
                 rollback_backoff_max: float = 1.0,
                 chaos: Optional[TrainChaos] = None):
        self.program = program
        self.checkpoint_dir = checkpoint_dir
        self.feed_fn = feed_fn
        self.loss_name = loss_name
        self.window_steps = max(1, int(window_steps))
        self.policy = policy or CheckpointPolicy()
        self.max_rollbacks = max(0, int(max_rollbacks))
        self.rollback_backoff = float(rollback_backoff)
        self.rollback_backoff_max = float(rollback_backoff_max)
        self.chaos = chaos
        self.exe = executor or Executor(None)
        self.scope = scope if scope is not None else Scope()
        if startup_program is not None:
            self.exe.run(startup_program, scope=self.scope, seed=seed)

        self.plan = None
        if elastic:
            import jax

            from ..placement import (DeviceInventory, TrainPlacementSearcher,
                                     TrainProfile)

            if global_batch is None:
                raise ValueError("elastic=True needs global_batch")
            n = (int(inventory.n_devices) if inventory is not None
                 else len(jax.devices()))
            inventory = inventory or DeviceInventory.host(n)
            profile = TrainProfile.from_program(program, self.scope,
                                                feed=feed_fn(0))
            self.plan = TrainPlacementSearcher(
                profile, inventory, global_batch,
                max_accum=max_accum).search(n)
            parallel = {"dp": self.plan.dp,
                        "accum_steps": self.plan.accum_steps,
                        "zero_stage": self.plan.zero_stage}
        self.ddp = None
        if parallel:
            from .ddp import ShardedTrainStep

            self.ddp = ShardedTrainStep(program, executor=self.exe,
                                        **parallel)

        # background publisher: double buffer = one writing + one queued
        self._pub_q: "queue.Queue" = queue.Queue(maxsize=2)
        self._pub_cv = threading.Condition()
        self._pub_pending = 0
        self._pub_err: Optional[BaseException] = None
        self._pub_thread: Optional[threading.Thread] = None
        self._closed = False

        self._preempt = threading.Event()
        self._old_sigterm = None
        self.last_serial = -1
        self.window = 0          # next window to execute
        self.global_step = 0
        self.skipped_windows: List[int] = []
        self.rollbacks = 0
        self.resumed_serial = self._resume()
        # serials are ISSUED at submit time (a queued snapshot owns its
        # number before it hits disk); start past both the loaded serial
        # and whatever the directory already holds
        self._issued_serial = max(
            self.last_serial,
            model_io._next_checkpoint_serial(self.checkpoint_dir) - 1)
        get_recorder().register_provider("train_resilience",
                                         self._provider_state)

    # -- resume ------------------------------------------------------------

    def _dp(self) -> int:
        return self.ddp.dp if self.ddp is not None else 1

    def _resume(self) -> int:
        try:
            if self.ddp is not None:
                serial = self.ddp.load_checkpoint(self.checkpoint_dir,
                                                  self.scope)
            else:
                serial = model_io.load_checkpoint(
                    self.exe, self.checkpoint_dir, self.program,
                    scope=self.scope)
        except FileNotFoundError:
            return -1
        if serial < 0:
            return serial
        ts = model_io.read_train_state(
            model_io.checkpoint_serial_dir(self.checkpoint_dir, serial))
        if ts is not None:
            self.window = int(ts.get("window", 0))
            self.global_step = int(ts.get("step", 0))
            self.skipped_windows = [int(w) for w in
                                    ts.get("skipped_windows", [])]
            # PRNG lineage: the seed counter continues exactly where the
            # checkpointed run left it (docs §26)
            self.exe._step_seed = int(ts.get("step_seed",
                                             self.exe._step_seed))
            saved_dp = int(ts.get("dp", 1))
            if saved_dp != self._dp():
                ev = get_event_log()
                if ev.enabled:
                    ev.emit("elastic_resize", severity="info",
                            saved_dp=saved_dp, dp=self._dp(),
                            accum_steps=(self.ddp.accum_steps
                                         if self.ddp else 1),
                            zero_stage=(self.ddp.zero_stage
                                        if self.ddp else 0),
                            serial=serial)
        self.last_serial = serial
        return serial

    # -- preemption --------------------------------------------------------

    def request_preemption(self) -> None:
        """Flag a preemption; honored at the next window boundary with a
        grace snapshot + typed ``PreemptedError``."""
        self._preempt.set()

    def install_signal_handlers(self) -> None:
        """Opt-in SIGTERM hook (main thread only): the cloud scheduler's
        preemption notice becomes a flagged, grace-snapshotted exit."""
        self._old_sigterm = signal.signal(
            signal.SIGTERM, lambda signum, frame: self.request_preemption())

    def uninstall_signal_handlers(self) -> None:
        if self._old_sigterm is not None:
            signal.signal(signal.SIGTERM, self._old_sigterm)
            self._old_sigterm = None

    # -- snapshot pipeline -------------------------------------------------

    def _train_state(self) -> Dict[str, Any]:
        return {"schema": 1, "window": self.window,
                "step": self.global_step,
                "step_seed": int(self.exe._step_seed),
                "skipped_windows": sorted(set(self.skipped_windows)),
                "dp": self._dp(),
                "accum_steps": self.ddp.accum_steps if self.ddp else 1,
                "zero_stage": self.ddp.zero_stage if self.ddp else 0,
                "window_steps": self.window_steps}

    def _next_serial(self) -> int:
        self._issued_serial = max(
            self._issued_serial + 1,
            model_io._next_checkpoint_serial(self.checkpoint_dir))
        return self._issued_serial

    def snapshot(self, sync: Optional[bool] = None) -> Optional[int]:
        """Take one snapshot at the current boundary. Async mode copies
        device→host here (the only exposed cost) and hands the publish to
        the background thread; returns the serial it WILL get, or None if
        both buffers were in flight (skipped, counted). ZeRO-sharded
        state publishes inline: its per-shard save path reads the live
        placed arrays, which a host copy cannot represent."""
        sync = self.policy.sync if sync is None else sync
        serial = self._next_serial()
        state = self._train_state()
        if self.ddp is not None:
            t0 = time.monotonic()
            self.ddp.save_checkpoint(
                self.checkpoint_dir, self.scope, step=serial,
                max_num_checkpoints=self.policy.max_keep,
                train_state=state)
            self._published(serial, t0, sync=True)
            return serial
        t0 = time.monotonic()
        host_state = {}
        for v in self.program.list_vars():
            if not v.persistable:
                continue
            val = self.scope.get(v.name)
            if val is not None:
                host_state[v.name] = np.array(val, copy=True)
        copy_dur = time.monotonic() - t0
        acct = get_accountant()
        if acct.enabled:
            # the boundary copy is the snapshot's only exposed cost —
            # attribute it; the background write overlaps the next
            # window and sweeps under device_compute (hidden, ~free)
            acct.account("checkpoint", t0, copy_dur)
        # memory ledger (obs/mem.py): the host-side snapshot buffers are
        # real memory a double-buffered publisher holds up to two of —
        # tracked as snapshot_host (device="host", excluded from the
        # device reconcile), released when the publish lands
        from ..obs.mem import get_ledger

        mem = get_ledger().track("snapshot_host", f"snapshot s{serial}",
                                 host_state, device="host")
        if sync:
            try:
                self._publish(serial, host_state, state)
            finally:
                mem.release()
            return serial
        self._start_publisher()
        with self._pub_cv:
            if self._pub_err is not None:
                err, self._pub_err = self._pub_err, None
                mem.release()
                raise err
            if self._pub_pending >= 2:
                _resilience_metrics()["skipped"].inc()
                mem.release()
                return None
            self._pub_pending += 1
        self._pub_q.put({"serial": serial, "host_state": host_state,
                         "train_state": state, "mem": mem})
        return serial

    def _start_publisher(self) -> None:
        if self._pub_thread is None or not self._pub_thread.is_alive():
            self._pub_thread = threading.Thread(
                target=self._pub_loop, daemon=True,
                name="pt-ckpt-publisher")
            self._pub_thread.start()

    def _pub_loop(self) -> None:
        while True:
            item = self._pub_q.get()
            if item is None:
                return
            mem = item.pop("mem", None)
            try:
                self._publish(**item)
            except BaseException as e:  # surfaced at the next boundary
                with self._pub_cv:
                    self._pub_err = e
            finally:
                if mem is not None:
                    mem.release()
                with self._pub_cv:
                    self._pub_pending -= 1
                    self._pub_cv.notify_all()

    def _publish(self, serial: int, host_state: Dict[str, np.ndarray],
                 train_state: Dict[str, Any]) -> None:
        t0 = time.monotonic()
        host_scope = Scope()
        for name, arr in host_state.items():
            host_scope.set(name, arr)
        model_io.save_checkpoint(
            self.exe, self.checkpoint_dir, main_program=self.program,
            max_num_checkpoints=self.policy.max_keep, scope=host_scope,
            step=serial, train_state=train_state)
        acct = get_accountant()
        if acct.enabled:
            # exposed only in sync mode; async overlaps the next device
            # window and the priority sweep hides it under device_compute
            acct.account("checkpoint", t0, time.monotonic() - t0)
        self._published(serial, t0, sync=False)

    def _published(self, serial: int, t0: float, sync: bool) -> None:
        m = _resilience_metrics()
        m["saves"].inc()
        m["seconds"].inc(time.monotonic() - t0)
        m["last_serial"].set(float(serial))
        with self._pub_cv:
            self.last_serial = max(self.last_serial, serial)
        ev = get_event_log()
        if ev.enabled:
            ev.emit("checkpoint_saved", severity="info", serial=serial,
                    window=self.window, step=self.global_step, sync=sync)
        if self.chaos is not None:
            self.chaos.on_published(self.checkpoint_dir, serial)

    def flush(self, timeout: Optional[float] = 60.0) -> None:
        """Block until every queued snapshot is on disk; re-raise a
        background publish failure here rather than losing it."""
        with self._pub_cv:
            self._pub_cv.wait_for(lambda: self._pub_pending == 0, timeout)
            if self._pub_err is not None:
                err, self._pub_err = self._pub_err, None
                raise err

    def _abandon_pending(self) -> None:
        """Kill semantics: queued-but-unstarted snapshots die with the
        worker; an in-flight write is left to finish (a half-written dir
        would carry no ``_SUCCESS`` and the loader skips it anyway)."""
        while True:
            try:
                self._pub_q.get_nowait()
            except queue.Empty:
                break
            with self._pub_cv:
                self._pub_pending -= 1
                self._pub_cv.notify_all()
        with self._pub_cv:
            self._pub_cv.wait_for(lambda: self._pub_pending == 0, 30.0)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._pub_thread is not None and self._pub_thread.is_alive():
            self.flush()
            self._pub_q.put(None)
            self._pub_thread.join(timeout=10.0)
        self.uninstall_signal_handlers()

    # -- rollback ----------------------------------------------------------

    def _restore(self) -> int:
        """Roll back to the newest good snapshot: params, cursor and
        seed counter all come from the verified serial the loader picks
        (a torn newest falls back through the manifest)."""
        self.flush()
        if self.ddp is not None:
            serial = self.ddp.load_checkpoint(self.checkpoint_dir,
                                              self.scope)
        else:
            serial = model_io.load_checkpoint(
                self.exe, self.checkpoint_dir, self.program,
                scope=self.scope)
        ts = model_io.read_train_state(
            model_io.checkpoint_serial_dir(self.checkpoint_dir, serial)) \
            or {}
        skipped = set(self.skipped_windows) \
            | set(int(w) for w in ts.get("skipped_windows", []))
        self.skipped_windows = sorted(skipped)
        self.window = int(ts.get("window", 0))
        self.global_step = int(ts.get("step", 0))
        self.exe._step_seed = int(ts.get("step_seed", self.exe._step_seed))
        self.last_serial = serial
        return serial

    # -- the window loop ---------------------------------------------------

    def _run_window(self, feed) -> np.ndarray:
        k = self.window_steps
        if self.ddp is not None:
            out = self.ddp.run_window(feed, k=k,
                                      fetch_list=[self.loss_name],
                                      scope=self.scope, return_numpy=True)
            # [k, accum, dp, ...] -> per-step global-batch mean loss
            a = np.asarray(out[0])
            return a.reshape(k, -1).mean(axis=1)
        out = self.exe.run_steps(self.program, feed=feed, k=k,
                                 fetch_list=[self.loss_name],
                                 scope=self.scope, return_numpy=True)
        return np.asarray(out[0]).reshape(k, -1).mean(axis=1)

    def run(self, num_windows: int) -> List[Dict[str, Any]]:
        """Run windows ``self.window .. num_windows-1`` (resume-aware).
        Returns one record per executed window: the per-step loss stream,
        the snapshot serial it published (None = not due or skipped), and
        rollback bookkeeping. Raises ``PreemptedError`` on a flagged
        preemption (after the grace snapshot), ``RollbackExhausted`` past
        the backoff budget, ``WorkerKilled`` under chaos."""
        records: List[Dict[str, Any]] = []
        acct = get_accountant()
        if self.last_serial < 0:
            # anchor snapshot: a rollback (or kill) before the first
            # cadence snapshot needs a last-good to restore to
            self.snapshot(sync=True)
        consecutive = 0
        failed_window = None
        windows_since_snap = 0
        last_snap_t = time.monotonic()
        skipped = set(self.skipped_windows)
        while self.window < num_windows:
            w = self.window
            if w in skipped:
                self.window = w + 1
                continue
            if self._preempt.is_set():
                self._preempt_exit()
            action = None
            if self.chaos is not None:
                action = self.chaos.on_window(self, w)
                if self._preempt.is_set():
                    self._preempt_exit()
            feed = dict(self.feed_fn(w))
            if action == "nan":
                name = sorted(feed)[0]
                feed[name] = np.asarray(feed[name]) * np.float32("nan")
            if acct.enabled:
                acct.begin_window(f"resilient-w{w}")
            losses = self._run_window(feed)
            if self.chaos is not None:
                self.chaos.on_window_end(self, w)
            if not np.all(np.isfinite(losses)):
                if acct.enabled:
                    acct.end_window()
                consecutive += 1
                _resilience_metrics()["rollbacks"].inc()
                self.rollbacks += 1
                restored = self._restore()
                ev = get_event_log()
                if ev.enabled:
                    ev.emit("rollback", severity="error", window=w,
                            restored_serial=restored,
                            consecutive=consecutive,
                            skip=(failed_window == w))
                    get_recorder().maybe_dump(
                        {"type": "rollback", "window": w,
                         "restored_serial": restored})
                if consecutive > self.max_rollbacks:
                    raise RollbackExhausted(w, consecutive)
                if failed_window == w:
                    # second consecutive fault on the SAME window: the
                    # poison is in the data, not the weather — skip it
                    # (recorded in the cursor) instead of NaN'ing forever
                    skipped.add(w)
                    self.skipped_windows = sorted(skipped)
                    failed_window = None
                else:
                    failed_window = w
                if self.rollback_backoff > 0.0:
                    time.sleep(min(self.rollback_backoff_max,
                                   self.rollback_backoff
                                   * 2.0 ** min(consecutive - 1, 63)))
                continue
            consecutive = 0
            failed_window = None
            self.window = w + 1
            self.global_step += self.window_steps
            windows_since_snap += 1
            rec = {"window": w, "losses": [float(x) for x in losses],
                   "serial": None, "rollbacks": self.rollbacks}
            if self.policy.due(windows_since_snap,
                               time.monotonic() - last_snap_t):
                # snapshot INSIDE the accounting window: the boundary
                # copy (and a sync publish) is this window's exposed
                # checkpoint cost; the async write lands in the next
                # window's span, hidden under its device_compute
                rec["serial"] = self.snapshot()
                windows_since_snap = 0
                last_snap_t = time.monotonic()
            gw = acct.end_window() if acct.enabled else None
            if gw is not None:
                rec["goodput"] = gw
            records.append(rec)
            if self._preempt.is_set():
                self._preempt_exit()
        self.flush()
        return records

    def _preempt_exit(self) -> None:
        """Grace path: final sync snapshot, events + bundle, typed exit."""
        self._preempt.clear()
        self.flush()
        serial = self.snapshot(sync=True)
        _resilience_metrics()["preemptions"].inc()
        ev = get_event_log()
        if ev.enabled:
            ev.emit("preemption", severity="warn", serial=serial,
                    window=self.window, step=self.global_step)
            get_recorder().maybe_dump(
                {"type": "preemption", "serial": serial,
                 "window": self.window})
        raise PreemptedError(serial, self.window)

    def _provider_state(self) -> Dict[str, Any]:
        state = {"window": self.window, "global_step": self.global_step,
                 "last_serial": self.last_serial,
                 "rollbacks": self.rollbacks,
                 "skipped_windows": sorted(set(self.skipped_windows)),
                 "dp": self._dp(),
                 "resumed_serial": self.resumed_serial}
        if self.chaos is not None:
            state["chaos"] = self.chaos.snapshot()
        if self.plan is not None:
            state["plan"] = {"dp": self.plan.dp,
                             "accum_steps": self.plan.accum_steps,
                             "zero_stage": self.plan.zero_stage}
        return state

"""Sharded data-parallel training: ZeRO optimizer-state sharding inside
one compiled window (docs/design.md §24).

Fluid's reason to exist was distributed *training* (trainer + pserver +
NCCL); design.md §4 names the TPU-native mapping — collectives inside the
compiled step, overlapped with backward by XLA, and ``BuildStrategy.Reduce``
= ZeRO (optimizer state sharded over ``dp``). This module closes that gap:
``ShardedTrainStep`` wraps the same traced step function the Executor
compiles (``core/executor.build_step_fn``'s builder) in ``shard_map`` over
a flat ``('dp',)`` mesh, with the training-specific collective schedule:

* per-microbatch grads **reduce-scattered** (``lax.psum_scatter``), not
  all-reduced — each rank receives only its 1/dp slice of the mean
  gradient, so it updates only its 1/dp shard of parameters and optimizer
  state (ZeRO-1/2: params stay replicated, optimizer state and — under
  ``zero_stage=2`` — the gradient accumulation buffer shard 1/dp);
* the optimizer update ops (the suffix of the training block) run on
  flat 1-D shards — every dense update kernel in ops/optimizer_ops.py is
  elementwise, so the IR program needs no rewriting;
* updated parameter shards **all-gather** back to full replicated params
  for the next microbatch's forward;
* gradient-accumulation microbatching rides INSIDE the compiled window
  (``accum_steps`` microbatches per optimizer step, accumulated in f32),
  so the global batch decouples from per-device HBM: activations peak at
  one microbatch, and ``b_loc = B / (dp * accum_steps)``.

Everything — k optimizer steps x accum microbatches x the collectives —
is ONE jitted program (``lax.scan`` over steps, nested scan over
microbatches), so XLA schedules the reduce-scatters against the backward
exactly as §4 promised.

Contracts (tested in tests/test_ddp.py):

* ``dp=1, accum_steps=1`` delegates to ``Executor.run_steps`` — the
  byte-identical pre-PR path (same compile-cache key, same program).
* ``accum_steps=k`` at dp=1 computes the fused big-batch gradient
  algebraically: k microbatch means, summed in f32, divided by k. On
  dyadic-exact data this bit-matches the fused ``run_steps`` step; on
  arbitrary data the difference is reduction-order-only (documented
  tolerance, §24).
* dp>1 is deterministic across reruns: the mesh, the split, and the
  collective schedule are static, so the same seeds produce bit-identical
  loss trajectories.
* Sharded optimizer state lives in the scope as flat padded 1-D arrays
  sharded over the mesh — ``io.save_checkpoint`` writes per-shard files
  via its existing multi-shard path, and ``_prepare_state`` re-lays out
  whatever a checkpoint restores (any dp, or a plain logical-shaped
  array) for the current mesh: reshard-on-load for free.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

OPT_OP_TYPES = frozenset({
    "sgd", "momentum", "adam", "adamax", "adagrad", "decayed_adagrad",
    "adadelta", "rmsprop", "ftrl", "proximal_gd", "proximal_adagrad",
})

#: non-optimizer op types allowed inside the update segment: the per-param
#: lr scaling and adamax's trailing beta1_pow decay are both ``scale``
UPDATE_COMPANION_TYPES = frozenset({"scale"})


class ShardedTrainError(ValueError):
    """A program or configuration the sharded trainer refuses, loudly:
    sparse (SelectedRows) gradients, non-optimizer ops behind the first
    update op (ModelAverage), persistable writes in the grad segment
    (batch-norm stats would silently diverge per rank), batches that do
    not split, meshes the host cannot build."""


class TrainSplit:
    """The (grad segment | update segment) partition of a training block
    plus the var roles the ZeRO layout needs. Built once per program by
    ``split_train_block``."""

    __slots__ = ("block_idx", "split_idx", "param_names", "grad_names",
                 "sharded_acc_names", "scalar_state_names", "acc_param",
                 "update_written", "extra_names", "optimizer_types",
                 "grad_segment_writes")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))


def split_train_block(program, block_idx: int = 0) -> TrainSplit:
    """Partition ``block_idx`` at the first optimizer op and classify the
    training state (docs §24 layout):

    * params — the update ops' ``Param`` slots (replicated, full copy per
      rank);
    * sharded accumulators — param-shaped optimizer state (moments,
      velocity; IR-declared shape equals the param's), flat-sharded 1/dp;
    * scalar state — shape-() accumulators (Adam's beta pows),
      replicated and updated identically on every rank;
    * extras — grad-segment outputs the update segment reads (scaled
      per-param learning rates): scalars, passed through replicated.

    Typed refusals (``ShardedTrainError``) for every structure the ZeRO
    layout cannot honor — see the class docstring and §24's failure
    matrix.
    """
    block = program.blocks[block_idx]
    opt_idxs = [i for i, op in enumerate(block.ops)
                if op.type in OPT_OP_TYPES]
    if not opt_idxs:
        raise ShardedTrainError(
            "program has no optimizer update ops — build it with "
            "optimizer.minimize(loss) before wrapping it in a "
            "ShardedTrainStep")
    split_idx = opt_idxs[0]
    update_ops = block.ops[split_idx:]
    params: List[str] = []
    grads: List[str] = []
    opt_types: List[str] = []
    for op in update_ops:
        if op.type in OPT_OP_TYPES:
            ids = op.inputs.get("GradIds")
            if ids and ids[0]:
                raise ShardedTrainError(
                    f"param {op.inputs['Param'][0]!r} has a SelectedRows "
                    f"(is_sparse) gradient — row grads cannot be "
                    f"reduce-scattered by element range; drop "
                    f"is_sparse=True or train it on the host-table path")
            params.append(op.inputs["Param"][0])
            grads.append(op.inputs["Grad"][0])
            if op.type not in opt_types:
                opt_types.append(op.type)
        elif op.type not in UPDATE_COMPANION_TYPES:
            raise ShardedTrainError(
                f"op {op.type!r} follows the first optimizer update op — "
                f"the update segment must hold only optimizer ops (+ lr "
                f"scale); ModelAverage and other post-update passes do "
                f"not compose with ZeRO sharding")

    param_set = set(params)
    # names written by the update segment (persistable state)
    update_written: List[str] = []
    seen_w = set()
    for op in update_ops:
        for names in op.outputs.values():
            for n in names:
                if n and n not in seen_w:
                    seen_w.add(n)
                    var = block.find_var_recursive(n)
                    if var is not None and var.persistable:
                        update_written.append(n)
    # names the update segment reads that it does not itself produce
    produced_in_update = set()
    update_reads: List[str] = []
    seen_r = set()
    for op in update_ops:
        for names in op.inputs.values():
            for n in names:
                if n and n not in produced_in_update and n not in seen_r:
                    seen_r.add(n)
                    update_reads.append(n)
        for names in op.outputs.values():
            produced_in_update.update(n for n in names if n)

    # classify accumulators by IR-declared shape: param-shaped -> sharded,
    # anything else (the () beta pows) -> replicated scalar state
    acc_param: Dict[str, str] = {}
    for op in update_ops:
        if op.type not in OPT_OP_TYPES:
            continue
        p = op.inputs["Param"][0]
        for slot, names in list(op.inputs.items()) + list(op.outputs.items()):
            for n in names:
                if n and n != p and n not in acc_param \
                        and n in seen_w and n not in param_set:
                    acc_param[n] = p
    sharded_accs: List[str] = []
    scalar_state: List[str] = []
    for n in update_written:
        if n in param_set:
            continue
        var = block.find_var_recursive(n)
        pvar = block.find_var_recursive(acc_param.get(n, ""))
        if (var is not None and pvar is not None and var.shape
                and tuple(var.shape) == tuple(pvar.shape)):
            sharded_accs.append(n)
        else:
            scalar_state.append(n)

    # grad-segment persistable writes (batch-norm stats and kin): the
    # sharded path refuses these — per-rank updates would silently diverge
    grad_writes: List[str] = []
    produced = set()
    for op in block.ops[:split_idx]:
        for names in op.outputs.values():
            for n in names:
                if n and n not in produced:
                    produced.add(n)
                    var = block.find_var_recursive(n)
                    if var is not None and var.persistable:
                        grad_writes.append(n)

    # extras: update-segment reads produced by the grad segment (scaled
    # lr vars) — not state, not grads
    state_like = param_set | set(acc_param) | set(update_written)
    grad_set = set(grads)
    extras = [n for n in update_reads
              if n not in state_like and n not in grad_set
              and n in produced]

    return TrainSplit(
        block_idx=block_idx, split_idx=split_idx, param_names=params,
        grad_names=grads, sharded_acc_names=sharded_accs,
        scalar_state_names=scalar_state, acc_param=acc_param,
        update_written=update_written, extra_names=extras,
        optimizer_types=opt_types, grad_segment_writes=grad_writes)


class ShardedTrainStep:
    """Execute a training program's optimizer steps sharded over a
    ``('dp',)`` mesh with ZeRO-1/2 state sharding and in-window gradient
    accumulation (module docstring; docs §24).

    ``run_window(feed, k=...)`` is the sharded sibling of
    ``Executor.run_steps``: ``k`` optimizer steps fused into one device
    program. Each step consumes one GLOBAL batch of ``B`` rows with
    ``B % (dp * accum_steps) == 0``; rank ``r``'s microbatch ``j`` is
    rows ``[j*dp*b_loc + r*b_loc, ...)`` — at dp=1 the microbatches are
    the contiguous row chunks of the fused batch (the accumulation
    bit-match contract). Fetches return stacked ``[k, accum, dp, ...]``
    (one entry per microbatch per rank).

    ``zero_stage``: 1 = accumulate full local f32 grads, ONE
    reduce-scatter per optimizer step (accum x less collective traffic);
    2 = reduce-scatter every microbatch and accumulate only the 1/dp
    shard (the grad buffer shrinks 1/dp — the HBM account the
    ``TrainPlacementSearcher`` prices). Both compute the same mean
    gradient; they differ only in float reduction order.
    """

    def __init__(self, program, *, dp: int = 1, accum_steps: int = 1,
                 zero_stage: int = 2, tp: int = 1, pp: int = 1,
                 place=None, amp: bool = False,
                 executor=None, devices=None, link_gbps: float = 45.0,
                 zero3_bucket_mb: float = 4.0, measure_overlap: bool = False,
                 pp_microbatches: Optional[int] = None):
        from ..core.executor import Executor

        if dp < 1:
            raise ShardedTrainError(f"dp must be >= 1, got {dp}")
        if tp < 1:
            raise ShardedTrainError(f"tp must be >= 1, got {tp}")
        if pp < 1:
            raise ShardedTrainError(f"pp must be >= 1, got {pp}")
        if accum_steps < 1:
            raise ShardedTrainError(
                f"accum_steps must be >= 1, got {accum_steps}")
        if zero_stage not in (1, 2, 3):
            raise ShardedTrainError(
                f"zero_stage must be 1, 2 or 3, got {zero_stage}")
        if zero_stage == 3 and dp < 2:
            raise ShardedTrainError(
                "zero_stage=3 shards parameters over dp; dp=1 leaves "
                "nothing to shard — use zero_stage<=2 (docs/design.md §27 "
                "failure matrix)")
        if pp > 1 and zero_stage > 1:
            raise ShardedTrainError(
                f"zero_stage={zero_stage} does not compose with pipeline "
                f"stages (pp={pp}): stage gradients live per device on the "
                f"'pp' axis and cannot be reduce-scattered over 'dp' "
                f"element ranges — use zero_stage=1 with pp, or pp=1 "
                f"(docs/design.md §27 failure matrix)")
        if pp > 1 and accum_steps > 1:
            raise ShardedTrainError(
                f"accum_steps={accum_steps} does not compose with pp={pp}: "
                f"the pipeline's microbatch schedule IS the accumulation "
                f"window — raise pp_microbatches instead (docs/design.md "
                f"§27 failure matrix)")
        self.program = program
        self.dp = int(dp)
        self.tp = int(tp)
        self.pp = int(pp)
        self.accum_steps = int(accum_steps)
        self.zero_stage = int(zero_stage)
        self.link_bw = float(link_gbps) * 1e9
        self.zero3_bucket_bytes = max(0.0, float(zero3_bucket_mb)) * 2 ** 20
        self.measure_overlap = bool(measure_overlap)
        self.pp_microbatches = (int(pp_microbatches)
                                if pp_microbatches else None)
        self.pp_schedule: Optional[str] = None  # set by the pp path
        self.exe = executor if executor is not None else Executor(place,
                                                                  amp=amp)
        self.amp = self.exe.amp
        self.split = split_train_block(program, 0)
        if (self.dp > 1 or self.accum_steps > 1) \
                and self.split.grad_segment_writes:
            # batch-norm moving stats and kin: per-rank updates diverge
            # under dp, and the microbatched window would silently DROP
            # the writes (rank_fn carries only params/optimizer state) —
            # refuse loudly on every non-delegate path
            raise ShardedTrainError(
                f"the grad segment writes persistable state "
                f"{self.split.grad_segment_writes[:4]} — non-gradient "
                f"state (batch-norm moving stats) neither shards under "
                f"dp nor survives microbatching; train it unsharded "
                f"(dp=1, accum_steps=1) or move it behind the optimizer")
        self.mesh = None
        n_dev = self.dp * self.tp * self.pp
        if n_dev > 1:
            import jax

            from .mesh import train_mesh

            platform = self.exe._device.platform
            if devices is None:
                devices = jax.devices(platform)
            if n_dev > len(devices):
                raise ShardedTrainError(
                    f"dp*tp*pp={n_dev} needs {n_dev} devices, only "
                    f"{len(devices)} available (host meshes: set XLA_FLAGS="
                    f"--xla_force_host_platform_device_count=N before jax "
                    f"initializes)")
            self.mesh = train_mesh(self.dp, self.tp, self.pp,
                                   devices=devices[:n_dev])
        # name -> (LOCAL_shape, nelem_loc, padded_loc, shard_loc, np_dtype)
        # — local means this param's 1/tp column shard when tp-eligible,
        # the logical shape otherwise (self._tp_parts / self._logical)
        self._layout: Dict[str, Tuple] = {}
        self._logical: Dict[str, Tuple] = {}   # name -> full logical shape
        self._tp_parts: Dict[str, int] = {}    # name -> tp shard count (>=1)
        self._placed: Dict[str, Any] = {}  # identity cache of placed state
        self._cache: Dict[Any, Any] = {}   # compiled windows
        self._readonly_cache: Dict[Tuple, List[str]] = {}
        self._pp_cache: Dict[Any, Any] = {}
        self._mem_state = None  # ledger handle (obs/mem.py, lazy)

    def _mem_sync(self) -> None:
        """Resize the memory ledger's train_state entry to the currently
        placed bytes — the ZeRO/3D param + optimizer shards, labeled with
        the mesh axes (obs/mem.py, docs §28). One attribute read when the
        ledger is off."""
        from ..obs.mem import get_ledger

        led = get_ledger()
        if not led.enabled:
            return
        total = sum(int(getattr(v, "nbytes", 0))
                    for v in self._placed.values())
        if self._mem_state is None or self._mem_state.released:
            self._mem_state = led.track(
                "train_state", f"zero{self.zero_stage} placed state",
                total, shard=f"dp{self.dp}xtp{self.tp}xpp{self.pp}")
        else:
            self._mem_state.resize(total)

    # -- state layout -------------------------------------------------------
    def _spec(self, *axes):
        """Placement target: a NamedSharding on the mesh, or the plain
        executor device when dp=1 (the accumulation-only path needs no
        mesh — shard_map over one rank would only add identity
        collectives)."""
        if self.mesh is None:
            return self.exe._device
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec(*axes))

    def _tp_of(self, shape) -> int:
        """How many column shards a param of ``shape`` splits into on the
        'tp' axis: every >=2-D tensor whose LAST dim divides by tp
        column-shards (fc / matmul / fused-QKV weights). Bit-safety does
        not hinge on this classification — the window all-gathers the
        full weight at a static boundary before any contraction (docs
        §27), so sharding is purely a residency choice."""
        if self.tp > 1 and len(shape) >= 2 and shape[-1] % self.tp == 0:
            return self.tp
        return 1

    def _set_layout(self, name: str, logical_shape, dtype) -> None:
        """Record logical + LOCAL (1/tp column shard) flat layout for one
        param-shaped tensor."""
        logical = tuple(int(s) for s in logical_shape)
        tp_p = self._tp_of(logical)
        local = (logical[:-1] + (logical[-1] // tp_p,)) if tp_p > 1 \
            else logical
        nelem_loc = int(np.prod(local)) if local else 1
        shard_loc = -(-nelem_loc // self.dp)  # ceil
        self._logical[name] = logical
        self._tp_parts[name] = tp_p
        try:
            dt = np.dtype(dtype)
        except TypeError:
            dt = np.dtype(str(dtype))
        self._layout[name] = (local, nelem_loc, shard_loc * self.dp,
                              shard_loc, dt)

    def _flat_spec(self, name):
        """Sharding for a flat 1-D state array in the (tp-major,
        dp-padded) layout: P(('tp','dp')) when the tensor column-shards,
        P('dp') otherwise."""
        from jax.sharding import PartitionSpec

        if self._tp_parts.get(name, 1) > 1:
            return self._spec(("tp", "dp"))
        return self._spec("dp")

    def _flatten_local(self, host: np.ndarray, name: str) -> np.ndarray:
        """Logical host array -> flat 1-D (tp * padded_loc) in the layout
        ``_flat_spec`` shards: per tp rank, that rank's column shard
        flattened and zero-padded to a dp multiple, concatenated
        tp-major."""
        local, nelem_loc, padded_loc, _sh, _dt = self._layout[name]
        tp_p = self._tp_parts[name]
        host = np.asarray(host)
        pieces = []
        for t in range(tp_p):
            if tp_p > 1:
                cols = local[-1]
                piece = host[..., t * cols:(t + 1) * cols].reshape(-1)
            else:
                piece = host.reshape(-1)
            if padded_loc > nelem_loc:
                piece = np.concatenate(
                    [piece, np.zeros(padded_loc - nelem_loc, piece.dtype)])
            pieces.append(piece)
        return pieces[0] if tp_p == 1 else np.concatenate(pieces)

    def _unflatten_local(self, flat, name: str) -> np.ndarray:
        """Inverse of ``_flatten_local``: flat (tp * padded_loc) host
        array -> logical shape (column shards re-concatenated on the last
        dim)."""
        local, nelem_loc, _padded, _sh, _dt = self._layout[name]
        tp_p = self._tp_parts[name]
        flat = np.asarray(flat).reshape(-1)
        rows = flat.reshape(tp_p, -1)[:, :nelem_loc]
        parts = [r.reshape(local) for r in rows]
        out = parts[0] if tp_p == 1 else np.concatenate(parts, axis=-1)
        return out.reshape(self._logical[name])

    def _host_logical(self, val, name: str) -> np.ndarray:
        """Coerce a scope value to its logical host shape. Accepts the
        logical array (fresh startup, an io-restored checkpoint — io.py
        reconstructs column shards from the _ZERO.json layout stamp), a
        flat array in THIS config's layout, or a flat dp-only layout from
        a pre-tp checkpoint."""
        logical = self._logical[name]
        host = np.asarray(val)
        if tuple(host.shape) == logical:
            return host
        flat = host.reshape(-1)
        tp_p = self._tp_parts[name]
        _local, nelem_loc, padded_loc, _sh, _dt = self._layout[name]
        if flat.size == tp_p * padded_loc and tp_p > 1:
            return self._unflatten_local(flat, name)
        nelem = int(np.prod(logical)) if logical else 1
        if flat.size < nelem:
            raise ShardedTrainError(
                f"state {name!r} holds {flat.size} elements, fewer than "
                f"its logical {nelem} — the checkpoint does not match "
                f"this program")
        # dp-only flat layout (any previous dp): unpad is the reshard
        return flat[:nelem].reshape(logical)

    def _prepare_state(self, scope) -> None:
        """Lay the scope's training state out on the mesh (docs §24/§27):

        * params — zero_stage<=2: replicated over dp, column-sharded
          P(None, ..., 'tp') over tp when eligible; zero_stage=3: flat
          1-D (tp-major, dp-padded) shards — 1/(tp*dp) resident bytes;
        * param-shaped accumulators — always the flat layout;
        * scalar state — replicated.

        Accepts state in logical shape (a fresh startup run, an
        io-restored checkpoint of any layout) OR a flat array of any
        previous dp — reshard-on-load is this unpad/repad, not a special
        path."""
        import jax

        split = self.split
        repl = self._spec()
        for p in split.param_names:
            val = scope.get(p)
            if val is None:
                raise RuntimeError(
                    f"param {p!r} has no value in the scope; run the "
                    f"startup program first")
            if p not in self._layout:
                shape = (val.shape if hasattr(val, "shape")
                         else np.asarray(val).shape)
                dt = getattr(val, "dtype", None) or np.asarray(val).dtype
                # a flat zero-3 restore from THIS config: recover the
                # logical shape from the program declaration
                block = self.program.blocks[self.split.block_idx]
                var = block.find_var_recursive(p)
                if var is not None and var.shape and \
                        tuple(var.shape) != tuple(shape):
                    shape = tuple(var.shape)
                self._set_layout(p, shape, dt)
            if self._placed.get(p) is scope.get(p):
                continue
            host = self._host_logical(val, p)
            if self.zero_stage == 3:
                placed = jax.device_put(self._flatten_local(host, p),
                                        self._flat_spec(p))
            elif self._tp_parts[p] > 1:
                nd = len(self._logical[p])
                placed = jax.device_put(
                    host, self._spec(*((None,) * (nd - 1) + ("tp",))))
            else:
                placed = jax.device_put(host, repl)
            scope.set(p, placed)
            self._placed[p] = placed
        for a in split.sharded_acc_names:
            p = split.acc_param[a]
            val = scope.get(a)
            if val is None:
                raise RuntimeError(
                    f"optimizer state {a!r} has no value in the scope; "
                    f"run the startup program first")
            if self._placed.get(a) is scope.get(a):
                continue
            self._logical[a] = self._logical[p]
            self._tp_parts[a] = self._tp_parts[p]
            self._layout[a] = self._layout[p]
            host = self._host_logical(val, a)
            local, nelem_loc, padded_loc, shard_loc, _pd = self._layout[p]
            self._layout[a] = (local, nelem_loc, padded_loc, shard_loc,
                               np.dtype(str(host.dtype)))
            placed = jax.device_put(self._flatten_local(host, a),
                                    self._flat_spec(a))
            scope.set(a, placed)
            self._placed[a] = placed
        for s in split.scalar_state_names:
            val = scope.get(s)
            if val is None:
                raise RuntimeError(
                    f"optimizer state {s!r} has no value in the scope; "
                    f"run the startup program first")
            if self._placed.get(s) is not scope.get(s):
                placed = jax.device_put(val, repl)
                scope.set(s, placed)
                self._placed[s] = placed
        self._mem_sync()

    def gather_state(self, scope) -> None:
        """Convert the scope's ZeRO state back to logical shapes (host
        numpy): unflatten each flat (tp-major, dp-padded) array, restack
        column shards, and reshape to the param's logical shape. After
        this the scope drives the plain Executor again (or saves a
        layout-agnostic checkpoint)."""
        for a in self.split.sharded_acc_names:
            lay = self._layout.get(a)
            val = scope.get(a)
            if val is None:
                continue
            if lay is None:
                # pp path: accumulators are logically shaped (just
                # device-placed) — host round-trip is a plain copy
                scope.set(a, np.asarray(val))
            else:
                scope.set(a, self._unflatten_local(np.asarray(val), a))
            self._placed.pop(a, None)
        for p in self.split.param_names:
            val = scope.get(p)
            if val is None:
                continue
            host = np.asarray(val)
            if self.zero_stage == 3 and p in self._layout \
                    and host.ndim == 1 \
                    and tuple(host.shape) != self._logical.get(p):
                host = self._unflatten_local(host, p)
            scope.set(p, host)
            self._placed.pop(p, None)
        for s in self.split.scalar_state_names:
            val = scope.get(s)
            if val is not None:
                scope.set(s, np.asarray(val))
                self._placed.pop(s, None)
        # the scope now drives the plain (unsharded) executor again —
        # the dp gauge must not keep reporting this step's width
        from ..core.executor import _train_metrics

        _train_metrics()["dp"].set(1.0)
        self._mem_sync()  # placed state went back to host (leak gate)

    def zero_meta(self) -> Dict[str, Any]:
        """The reshard descriptor a checkpoint carries (io.py writes it
        as ``_ZERO.json``): the full 3D layout stamp — enough to validate
        a restore onto any (dp, tp) and to refuse a mismatched pp. Each
        flat-stored var records its logical shape plus the tp shard count
        its on-disk flat layout was built with, so io.load_checkpoint can
        reconstruct logical arrays without this class (schema 2; schema-1
        readers see the same dp/zero keys they always did)."""
        vars_meta: Dict[str, Any] = {}

        def entry(name):
            p = self.split.acc_param.get(name, name)
            if p not in self._logical:
                return None
            logical = self._logical[p]
            return {"param": p, "shape": list(logical),
                    "nelem": int(np.prod(logical)) if logical else 1,
                    "tp": self._tp_parts.get(p, 1)}

        for a in self.split.sharded_acc_names:
            e = entry(a)
            if e is not None:
                vars_meta[a] = e
        if self.zero_stage == 3:
            # zero-3 params are themselves stored flat — stamp them so a
            # plain (non-ddp) load restores logical arrays
            for p in self.split.param_names:
                e = entry(p)
                if e is not None:
                    vars_meta[p] = dict(e, kind="param")
        return {
            "schema": 2,
            "dp": self.dp,
            "tp": self.tp,
            "pp": self.pp,
            "pp_schedule": self.pp_schedule,
            "zero_stage": self.zero_stage,
            "accum_steps": self.accum_steps,
            "optimizer": list(self.split.optimizer_types),
            "vars": vars_meta,
        }

    def save_checkpoint(self, checkpoint_dir: str, scope,
                        **kw) -> int:
        """``io.save_checkpoint`` with the ZeRO reshard descriptor
        attached; sharded accumulators go to disk as per-shard files (the
        existing multi-shard save path — each rank-sized slice is its own
        ``.npy``)."""
        from .. import io as model_io

        return model_io.save_checkpoint(
            self.exe, checkpoint_dir, main_program=self.program,
            scope=scope, zero_meta=self.zero_meta(), **kw)

    def load_checkpoint(self, checkpoint_dir: str, scope,
                        serial: Optional[int] = None) -> int:
        """Load a checkpoint saved at ANY dp and re-lay it out for this
        mesh. Validates the ``_ZERO.json`` descriptor (when present)
        against this program's split — a checkpoint whose optimizer state
        belongs to a different program refuses instead of training on
        garbage."""
        from .. import io as model_io

        def _check_pp(m):
            ck_pp = int(m.get("pp", 1))
            if ck_pp != self.pp:
                raise ShardedTrainError(
                    f"checkpoint was trained with pp={ck_pp} pipeline "
                    f"stages, this step runs pp={self.pp} — stage-stacked "
                    f"parameters do not reshard across pipeline depths; "
                    f"rebuild the model with pp_stages={ck_pp} or "
                    f"re-partition offline (docs/design.md §27). dp/tp "
                    f"reshard-on-load stays free")

        # refuse a mismatched pipeline depth BEFORE any bytes touch the
        # scope — a stage-stacked layout cannot be repaired after load
        probe = (serial if serial is not None
                 else model_io._latest_checkpoint_serial(checkpoint_dir))
        if probe >= 0:
            pre = model_io.read_zero_meta(
                model_io.checkpoint_serial_dir(checkpoint_dir, probe))
            if pre is not None:
                _check_pp(pre)

        serial = model_io.load_checkpoint(
            self.exe, checkpoint_dir, main_program=self.program,
            scope=scope, serial=serial)
        meta = model_io.read_zero_meta(
            model_io.checkpoint_serial_dir(checkpoint_dir, serial))
        if meta is not None:
            # re-check: verification may have picked an older serial
            _check_pp(meta)
            self._prepare_layout_only(scope)
            for a, info in meta.get("vars", {}).items():
                if info.get("kind") == "param":
                    if a not in self.split.param_names:
                        raise ShardedTrainError(
                            f"checkpoint zero-3 param {a!r} is not part "
                            f"of this program — wrong program for this "
                            f"checkpoint")
                    p = a
                elif a not in self.split.acc_param:
                    raise ShardedTrainError(
                        f"checkpoint optimizer state {a!r} is not part of "
                        f"this program's update segment — wrong program "
                        f"for this checkpoint")
                else:
                    p = self.split.acc_param[a]
                logical = self._logical[p]
                want = int(np.prod(logical)) if logical else 1
                if int(info.get("nelem", want)) != want:
                    raise ShardedTrainError(
                        f"checkpoint state {a!r} has {info['nelem']} "
                        f"elements, this program's {p!r} needs {want} — "
                        f"refusing to reshard mismatched state")
        # force a re-layout on the next window (reshard-on-load)
        self._placed.clear()
        return serial

    def _prepare_layout_only(self, scope) -> None:
        """Param layouts from the PROGRAM's declared shapes (not the
        scope: a just-loaded checkpoint has already overwritten the
        scope's values, and the reshard validation must compare the
        checkpoint against THIS program, not against itself)."""
        block = self.program.blocks[self.split.block_idx]
        for p in self.split.param_names:
            if p in self._layout:
                continue
            var = block.find_var_recursive(p)
            if var is None or not var.shape:
                val = scope.get(p)
                if val is None:
                    continue
                shape = tuple(np.asarray(val).shape)
            else:
                shape = tuple(var.shape)
            self._set_layout(p, shape, np.float32)

    def state_bytes_per_device(self, scope) -> Dict[str, float]:
        """The live per-device residency vs the ZeRO account — the bench
        workload's gate compares these (arXiv 2512.02551: the account is
        only as good as the arrays it predicts)."""
        params = opt_shard = opt_logical = scalars = 0.0
        for p in self.split.param_names:
            v = scope.get(p)
            if v is not None:
                params += np.asarray(v).nbytes if not hasattr(v, "nbytes") \
                    else v.nbytes
        for a in self.split.sharded_acc_names:
            v = scope.get(a)
            if v is None:
                continue
            lay = self._layout.get(a)
            if lay is not None:
                opt_logical += lay[1] * lay[4].itemsize
            if hasattr(v, "addressable_shards") and \
                    (self.dp > 1 or self.tp > 1):
                opt_shard += v.addressable_shards[0].data.nbytes
            else:
                opt_shard += np.asarray(v).nbytes / max(self.dp, 1)
        for s in self.split.scalar_state_names:
            v = scope.get(s)
            if v is not None:
                scalars += np.asarray(v).nbytes
        return {
            "param_bytes": params,
            "opt_shard_bytes_per_device": opt_shard,
            "opt_logical_bytes": opt_logical,
            "scalar_bytes": scalars,
            # the account the searcher prices: logical/(dp*tp) plus at
            # most one padding element per tensor per rank (the _layout
            # rows are already per-tp-shard local, so /dp completes the
            # division)
            "zero_account_bytes": sum(
                (lay[1] + (lay[2] - lay[1])) * lay[4].itemsize / self.dp
                for a in self.split.sharded_acc_names
                for lay in [self._layout.get(a)] if lay is not None),
        }

    # -- window execution ---------------------------------------------------
    def run_window(self, feed, k: Optional[int] = None,
                   fetch_list: Optional[Sequence] = None, scope=None,
                   seed: Optional[int] = None, return_numpy: bool = True):
        """Run ``k`` sharded optimizer steps as one device program.

        ``feed``: ONE dict (same global batch every step; needs ``k``) or
        a sequence of ``k`` global-batch dicts. Fetches come back stacked
        ``[k, accum_steps, dp, ...]`` — one slice per microbatch per
        rank (at dp=1/accum=1 the delegate path reshapes ``run_steps``'s
        ``[k, ...]`` to match).
        """
        from ..core.executor import global_scope

        fetch_names = [f if isinstance(f, str) else f.name
                       for f in (fetch_list or [])]
        scope = scope if scope is not None else global_scope()
        if isinstance(feed, dict):
            if k is None or int(k) < 1:
                raise ValueError(
                    "run_window with a single feed dict needs k >= 1")
            k = int(k)
            feeds, invariant = feed, True
        else:
            feeds = list(feed or [])
            if not feeds:
                raise ValueError("run_window needs a feed dict or a "
                                 "non-empty sequence of feed dicts")
            if k is not None and int(k) != len(feeds):
                raise ValueError(f"k={k} but {len(feeds)} feed dicts given")
            k = len(feeds)
            invariant = False

        if self.pp > 1:
            # pipeline stages run at GSPMD level — the stacked-layer op
            # shard_maps over 'pp' internally, and shard_maps don't nest
            return self._run_pipeline(feeds, invariant, k, fetch_names,
                                      scope, seed, return_numpy)
        if self.dp == 1 and self.tp == 1 and self.accum_steps == 1:
            # the pre-PR path, byte for byte: same executor, same cache
            # key, same compiled program
            from ..core.executor import _train_metrics

            m = _train_metrics()
            m["dp"].set(1.0)
            m["tp"].set(1.0)
            m["pp"].set(1.0)
            out = self.exe.run_steps(
                self.program, feed=feeds, k=k,
                fetch_list=fetch_names, scope=scope,
                return_numpy=return_numpy, seed=seed)
            return [v.reshape((k, 1, 1) + tuple(v.shape[1:]))
                    for v in out]
        if self.dp == 1 and self.tp == 1:
            # accumulation without a mesh: same algebra on one device —
            # shard_map over a 1-rank mesh would only add identity
            # collectives to the program
            return self._run_sharded(feeds, invariant, k, fetch_names,
                                     scope, seed, return_numpy,
                                     mesh=False)
        return self._run_sharded(feeds, invariant, k, fetch_names, scope,
                                 seed, return_numpy, mesh=True)

    def _microbatch_seeds(self, k: int, seed: Optional[int]) -> List[int]:
        """One PRNG seed per microbatch, drawn from the executor's step
        counter — microbatch (i, j) of a window uses the seed sequential
        step ``i*accum + j`` would (the PR-3 key-parity rule extended to
        microbatches; dropout masks per microbatch match the sequential
        per-step stream)."""
        n = k * self.accum_steps
        if seed is None:
            base = self.exe._step_seed
            self.exe._step_seed += n
            return [base + 1 + i for i in range(n)]
        return [seed] * n

    def _run_sharded(self, feeds, invariant, k, fetch_names, scope, seed,
                     return_numpy, mesh: bool):
        import jax
        import jax.numpy as jnp

        from ..core.executor import _MISSING, _train_metrics
        from ..obs import get_tracer
        from ..obs.goodput import get_accountant

        acct = get_accountant()
        tr = get_tracer()
        split = self.split
        t_acct = time.monotonic() if acct.enabled else 0.0
        with tr.span("train/host_prep", cat="train", k=k, dp=self.dp,
                     accum=self.accum_steps):
            self._prepare_state(scope)
            feed_names = tuple(sorted(feeds if invariant else feeds[0]))
            feed_vals, step_sig = self._place_feeds(
                feeds, invariant, feed_names, k, acct)

        readonly = {}
        for n in self._readonly_names():
            v = scope.get(n, _MISSING)
            if v is _MISSING:
                raise RuntimeError(
                    f"variable {n!r} is read by the program but missing "
                    f"from the scope; run the startup program first")
            readonly[n] = v
        params = {p: scope.get(p) for p in split.param_names}
        shards = {a: scope.get(a) for a in split.sharded_acc_names}
        scalars = {s: scope.get(s) for s in split.scalar_state_names}

        seeds = self._microbatch_seeds(k, seed)
        rs = self.program.random_seed or 0
        keys = jnp.stack([jax.random.PRNGKey(np.uint32(s ^ rs))
                          for s in seeds]).reshape(k, self.accum_steps, 2)

        cache_key = (self.program.uid, self.program.version, step_sig,
                     tuple(fetch_names), self.amp, invariant, k,
                     self.dp, self.tp, self.accum_steps, self.zero_stage,
                     self.zero3_bucket_bytes)
        fn = self._cache.get(cache_key)
        if fn is None:
            _train_metrics()["compiles"].inc()
            t_c = time.monotonic() if acct.enabled else 0.0
            with tr.span("train/ddp_compile", cat="compile"):
                fn = self._compile_window(feed_names, fetch_names,
                                          invariant, k, mesh)
            if acct.enabled:
                acct.account("compile", t_c, time.monotonic() - t_c)
            self._cache[cache_key] = fn
            while len(self._cache) > 16:
                self._cache.pop(next(iter(self._cache)))
        twin = None
        if self.measure_overlap and (self.dp > 1 or self.tp > 1):
            # the collective-ablated twin (docs §27): same program with
            # every collective replaced by a local slice/tile, compiled
            # once per signature and NOT counted as a training compile
            # (it is a measurement instrument, not a window)
            tkey = cache_key + ("ablate",)
            twin = self._cache.get(tkey)
            if twin is None:
                t_c = time.monotonic() if acct.enabled else 0.0
                with tr.span("train/ddp_compile", cat="compile",
                             ablate=True):
                    twin = self._compile_window(feed_names, fetch_names,
                                                invariant, k, mesh,
                                                ablate=True)
                if acct.enabled:
                    acct.account("compile", t_c, time.monotonic() - t_c)
                self._cache[tkey] = twin
                while len(self._cache) > 16:
                    self._cache.pop(next(iter(self._cache)))
        if acct.enabled:
            acct.account("host_input", t_acct, time.monotonic() - t_acct)

        m = _train_metrics()
        m["dp"].set(float(self.dp))
        m["tp"].set(float(self.tp))
        m["pp"].set(1.0)
        twin_dur = None
        if twin is not None:
            # the twin runs FIRST (the real window donates the state
            # buffers) and its outputs are discarded after the sync
            t_tw = time.monotonic()
            with tr.span("train/ablate_twin", cat="train", k=k):
                tout = twin(feed_vals, readonly, params, shards, scalars,
                            keys)
                jax.block_until_ready(tout)
            twin_dur = time.monotonic() - t_tw
            del tout
        t_dev = time.monotonic()
        with tr.span("train/device_window", cat="train", k=k, dp=self.dp):
            fetches, new_params, new_shards, new_scalars = fn(
                feed_vals, readonly, params, shards, scalars, keys)
            if twin is not None:
                jax.block_until_ready((fetches, new_params, new_shards,
                                       new_scalars))
            for p, v in new_params.items():
                scope.set(p, v)
                self._placed[p] = v
            for a, v in new_shards.items():
                scope.set(a, v)
                self._placed[a] = v
            for s, v in new_scalars.items():
                scope.set(s, v)
                self._placed[s] = v
        self._mem_sync()
        dev_dur = time.monotonic() - t_dev
        if acct.enabled:
            acct.account("device_compute", t_dev, dev_dur)
        if self.dp > 1 or self.tp > 1:
            if twin_dur is not None:
                # measured overlap (docs §27): the modeled collective
                # seconds are the ring volumes at the configured link;
                # the EXPOSED share is the wall-clock the real window
                # lost vs. its collective-ablated twin; the rest was
                # hidden under compute by XLA's scheduler — a
                # measurement, not an assertion
                modeled = self.comm_seconds_per_step() * k
                exposed = min(max(dev_dur - twin_dur, 0.0), modeled)
                hidden = modeled - exposed
                m["collective"].inc(modeled)
                m["hidden_collective"].inc(hidden)
                if acct.enabled and exposed > 0:
                    acct.account("collective",
                                 t_dev + dev_dur - exposed, exposed)
                if acct.enabled and hidden > 0:
                    acct.account("collective_hidden", t_dev, hidden)
            else:
                # model-attributed collective seconds (docs §24): the
                # ring volumes are exact, the wall share is the
                # searcher's own link-bandwidth model clamped to the
                # measured window — an attribution, not a measurement
                # (XLA hides true overlap)
                comm_s = min(self.comm_seconds_per_step() * k, dev_dur)
                m["collective"].inc(comm_s)
                if acct.enabled and comm_s > 0:
                    acct.account("collective",
                                 t_dev + dev_dur - comm_s, comm_s)
        if return_numpy:
            t_f = time.monotonic() if acct.enabled else 0.0
            with tr.span("train/fetch_sync", cat="train"):
                fetches = [np.asarray(v) for v in fetches]
            if acct.enabled:
                acct.account("fetch_sync", t_f, time.monotonic() - t_f)
        m["steps"].inc(k)
        return fetches

    # -- pipeline execution (pp > 1, docs §27) ------------------------------
    def _find_stack_op(self):
        """The single pipelined_transformer_stack op the pp path drives —
        typed refusals for anything else (two stacks cannot share one
        'pp' axis schedule; a stage count that disagrees with the mesh
        would silently all-gather every step)."""
        block = self.program.blocks[self.split.block_idx]
        grad_ops = block.ops[:self.split.split_idx]
        idxs = [i for i, op in enumerate(grad_ops)
                if op.type == "pipelined_transformer_stack"]
        if len(idxs) != 1:
            raise ShardedTrainError(
                f"pp={self.pp} needs exactly one pipelined_transformer_"
                f"stack op in the forward, found {len(idxs)} — build the "
                f"model with pp_stages={self.pp} "
                f"(models/transformer.py transformer_lm)")
        op = grad_ops[idxs[0]]
        wq = block.find_var_recursive(op.inputs["WQ"][0])
        n_stages = int(wq.shape[0]) if wq is not None and wq.shape else -1
        if n_stages != self.pp:
            raise ShardedTrainError(
                f"the model's pipelined stack has {n_stages} stages but "
                f"this step runs pp={self.pp} — rebuild with "
                f"pp_stages={self.pp} or resize the mesh")
        return idxs[0], op

    def _prepare_pp_state(self, scope, names) -> None:
        """Place state for the GSPMD pipeline plane: the program's
        ParamAttr sharding hints place the stacked stage parameters
        P('pp', ...[, 'tp']); each optimizer accumulator inherits its
        param's spec (same shape, same placement); everything else
        replicates — the ParallelExecutor placement discipline, shared
        via ``mesh.param_sharding``."""
        import jax

        from .mesh import param_sharding, replicated

        block = self.program.global_block()
        acc_of = self.split.acc_param
        for n in names:
            v = scope.get(n)
            if v is None:
                raise RuntimeError(
                    f"variable {n!r} has no value in the scope; run the "
                    f"startup program first")
            if self._placed.get(n) is v:
                continue
            src = acc_of.get(n, n)
            var = block.find_var_recursive(src)
            sh = (param_sharding(self.mesh, var) if var is not None
                  else replicated(self.mesh))
            arr = np.asarray(v)
            if len(sh.spec) > arr.ndim:
                # scalar optimizer state (Adam's beta pows) inherits its
                # param's NAME mapping but not its rank — replicate
                sh = replicated(self.mesh)
            placed = jax.device_put(arr, sh)
            scope.set(n, placed)
            self._placed[n] = placed
        self._mem_sync()

    def _run_pipeline(self, feeds, invariant, k, fetch_names, scope, seed,
                      return_numpy):
        """pp > 1 window: GSPMD-level execution (the stack op's internal
        shard_map owns the 'pp' rotation — shard_maps do not nest, so
        this path mirrors ParallelExecutor rather than ``_run_sharded``).
        The schedule pick IS the gpipe/1F1B crossover rule
        (``one_f_one_b_preferred``): M <= 2S keeps the stack op's gpipe
        (the IR backward differentiates through it), M > 2S swaps the IR
        backward for the revived ``one_f_one_b`` engine — the warning
        that used to go to stderr now routes the plan (docs §27)."""
        import jax
        import jax.numpy as jnp

        from ..core.executor import _coerce_host, _train_metrics
        from ..obs import get_tracer
        from ..obs.goodput import get_accountant
        from .pipeline import one_f_one_b_preferred

        acct = get_accountant()
        tr = get_tracer()
        t_acct = time.monotonic() if acct.enabled else 0.0
        stack_idx, stack_op = self._find_stack_op()
        M = self.pp_microbatches or int(
            stack_op.attrs.get("microbatches", 4))
        schedule = "1f1b" if one_f_one_b_preferred(M, self.pp) else "gpipe"
        self.pp_schedule = schedule

        feed_names = tuple(sorted(feeds if invariant else feeds[0]))
        feed_list = [feeds] * k if invariant else list(feeds)
        seeds = self._microbatch_seeds(k, seed)

        ckey = (self.program.uid, self.program.version, feed_names,
                tuple(fetch_names), self.amp, schedule, M,
                self.dp, self.tp, self.pp)
        entry = self._pp_cache.get(ckey)
        if entry is None:
            _train_metrics()["compiles"].inc()
            t_c = time.monotonic() if acct.enabled else 0.0
            with tr.span("train/pp_compile", cat="compile",
                         schedule=schedule):
                if schedule == "gpipe":
                    entry = self._build_pp_gpipe_step(feed_names,
                                                      fetch_names)
                else:
                    entry = self._build_pp_1f1b_step(feed_names,
                                                     fetch_names,
                                                     stack_idx, stack_op,
                                                     M)
            if acct.enabled:
                acct.account("compile", t_c, time.monotonic() - t_c)
            self._pp_cache[ckey] = entry
            while len(self._pp_cache) > 8:
                self._pp_cache.pop(next(iter(self._pp_cache)))
        fn, readonly_names, donated_names, state_out = entry

        with tr.span("train/host_prep", cat="train", k=k, pp=self.pp):
            self._prepare_pp_state(scope, donated_names)
            self._prepare_pp_state(scope, readonly_names)
        if acct.enabled:
            acct.account("host_input", t_acct, time.monotonic() - t_acct)

        m = _train_metrics()
        m["dp"].set(float(self.dp))
        m["tp"].set(float(self.tp))
        m["pp"].set(float(self.pp))
        rs = self.program.random_seed or 0
        div = self.dp * (M if schedule == "1f1b" else 1)
        outs = []
        for i in range(k):
            fd = feed_list[i]
            feed_vals = {}
            for n in feed_names:
                host = _coerce_host(np.asarray(fd[n]), self.program, n)
                if host.ndim and host.shape[0] % div:
                    raise ShardedTrainError(
                        f"feed {n!r} batch {host.shape[0]} is not "
                        f"divisible by dp*microbatches = {div}")
                t_h2d = time.monotonic()
                spec = ("dp",) + (None,) * (host.ndim - 1) \
                    if host.ndim else ()
                feed_vals[n] = jax.device_put(host, self._spec(*spec))
                if acct.enabled:
                    acct.account("h2d", t_h2d, time.monotonic() - t_h2d)
            readonly = {n: scope.get(n) for n in readonly_names}
            donated = {n: scope.get(n) for n in donated_names}
            key = jax.random.PRNGKey(np.uint32(seeds[i] ^ rs))
            t_dev = time.monotonic()
            with tr.span("train/pp_window", cat="train", pp=self.pp,
                         schedule=schedule):
                with self.mesh:
                    fetches, new_state = fn(feed_vals, readonly, donated,
                                            key)
                for n in state_out:
                    if n in new_state:
                        scope.set(n, new_state[n])
                        self._placed[n] = new_state[n]
            if acct.enabled:
                acct.account("device_compute", t_dev,
                             time.monotonic() - t_dev)
            outs.append(fetches)
        self._mem_sync()
        m["steps"].inc(k)
        stacked = []
        for j in range(len(fetch_names)):
            v = jnp.stack([outs[i][j] for i in range(k)])
            v = v.reshape((k, 1, 1) + tuple(v.shape[1:]))
            stacked.append(np.asarray(v) if return_numpy else v)
        return stacked

    def _build_pp_gpipe_step(self, feed_names, fetch_names):
        """The M <= 2S schedule: one jitted GSPMD step over the WHOLE IR
        block — the stack op sees ctx.mesh and runs its internal gpipe
        shard_map; IR autodiff differentiates straight through it and
        the optimizer update runs on the P('pp')-sharded stacks."""
        import jax

        from ..core.executor import build_step_fn

        step, readonly_names, donated_names, state_out = build_step_fn(
            self.program, self.split.block_idx, feed_names,
            list(fetch_names), amp=self.amp, mesh=self.mesh)
        return (jax.jit(step, donate_argnums=(2,)), readonly_names,
                donated_names, state_out)

    def _build_pp_1f1b_step(self, feed_names, fetch_names, stack_idx,
                            stack_op, M):
        """The M > 2S schedule: strip the IR backward and drive the
        revived ``one_f_one_b`` engine (parallel/pipeline.py) directly.
        Surgery on the block, all at trace time:

        * forward prefix (embedding/positions) runs under ``jax.vjp`` so
          the pipeline's dx seeds its parameter grads;
        * the stack op is REPLACED by 1F1B over a stage_fn rebuilt from
          ops/pipelined_stack's ``_decoder_layer`` (same math, same
          Megatron tp psums);
        * the head (final LN + LM head + loss) becomes ``loss_grad_fn``,
          gated to the last stage per microbatch;
        * the optimizer update runs on the engine's grads through the
          ordinary update ops.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..core.executor import BlockProgramBuilder, _collect_block_io
        from ..core.registry import ExecContext
        from ..ops.pipelined_stack import _KEYS, _SLOTS, _decoder_layer
        from .pipeline import one_f_one_b

        split = self.split
        if split.grad_segment_writes:
            raise ShardedTrainError(
                f"the grad segment writes persistable state "
                f"{split.grad_segment_writes[:4]} — the 1F1B engine owns "
                f"the backward and would drop these writes; train with "
                f"the gpipe schedule (M <= 2*pp) or move the state "
                f"(docs/design.md §27 failure matrix)")
        block = self.program.blocks[split.block_idx]
        grad_ops = block.ops[:split.split_idx]
        update_ops = block.ops[split.split_idx:]
        fill_idx = loss_name = None
        for i, op in enumerate(grad_ops):
            if op.type == "fill_constant":
                outs = [n for ns in op.outputs.values() for n in ns]
                if outs and outs[0].endswith("@GRAD"):
                    fill_idx = i
                    loss_name = outs[0][:-len("@GRAD")]
                    break
        if fill_idx is None or fill_idx <= stack_idx:
            raise ShardedTrainError(
                "1F1B surgery found no gradient-seeding fill_constant "
                "behind the pipeline stack — the program has no IR "
                "backward to replace")
        bad = [n for n in fetch_names if n != loss_name]
        if bad:
            raise ShardedTrainError(
                f"pp={self.pp} under the 1F1B schedule can only fetch the "
                f"loss {loss_name!r} (got {bad}) — intermediate "
                f"activations live distributed across pipeline stages")
        pre_ops = grad_ops[:stack_idx]
        post_ops = grad_ops[stack_idx + 1:fill_idx]
        tail_ops = grad_ops[fill_idx:]

        # the update segment's extras (scaled lr chains) have their
        # producers in the stripped tail — keep the grad-free closure
        need = set(split.extra_names)
        extra_ops = []
        for op in reversed(tail_ops):
            outs = {n for ns in op.outputs.values() for n in ns if n}
            if need & outs:
                ins = [n for ns in op.inputs.values() for n in ns if n]
                if any(n.endswith("@GRAD") for n in ins):
                    raise ShardedTrainError(
                        f"op {op.type!r} feeds the update segment through "
                        f"gradient values — the 1F1B engine owns the "
                        f"gradients and cannot honor this program "
                        f"(docs/design.md §27 failure matrix)")
                extra_ops.append(op)
                need.update(ins)
        extra_ops.reverse()

        def reads(ops):
            out, seen = [], set()
            for op in ops:
                for ns in op.inputs.values():
                    for n in ns:
                        if n and n not in seen:
                            seen.add(n)
                            out.append(n)
            return out

        stack_param_names = {kk: stack_op.inputs[slot][0]
                             for kk, slot in zip(_KEYS, _SLOTS)}
        stack_in_name = stack_op.inputs["X"][0]
        stack_out_name = stack_op.outputs["Out"][0]
        pre_reads = set(reads(pre_ops))
        post_reads = set(reads(post_ops))
        stack_set = set(stack_param_names.values())
        pre_params = [p for p in split.param_names
                      if p in pre_reads and p not in stack_set]
        head_params = [p for p in split.param_names
                       if p in post_reads and p not in stack_set]
        label_feeds = [n for n in feed_names if n in post_reads]

        state_in, state_out = _collect_block_io(
            self.program, split.block_idx, feed_names)
        donated_names = [n for n in state_in if n in set(state_out)]
        readonly_names = [n for n in state_in if n not in set(donated_names)]

        builder = BlockProgramBuilder(self.program)
        grad_of = dict(zip(split.param_names, split.grad_names))
        amp = self.amp
        mesh = self.mesh
        n_heads = int(stack_op.attrs["n_heads"])
        causal = bool(stack_op.attrs.get("causal", True))
        tp_axis = ("tp" if bool(stack_op.attrs.get("tp_shard", False))
                   and self.tp > 1 else None)
        wq_var = block.find_var_recursive(stack_param_names["wq"])
        L = int(wq_var.shape[1])

        def stage_fn(p_stage, x_mb):
            out = x_mb
            for layer in range(L):
                p_l = {kk: v[layer] for kk, v in p_stage.items()}
                # the 1F1B engine runs jax.vjp INSIDE the shard_map body,
                # so the stage needs the explicit Megatron region
                # boundaries (see pipelined_stack._copy_to_tp)
                out = _decoder_layer(p_l, out, n_heads, causal, amp,
                                     tp_axis=tp_axis, inner_vjp=True)
            return out

        if tp_axis is not None:
            col = P("pp", None, None, "tp")
            row = P("pp", None, "tp", None)
            rep2 = P("pp", None, None)
            pspecs = {"ln1s": rep2, "ln1b": rep2, "wq": col, "wk": col,
                      "wv": col, "wo": row, "ln2s": rep2, "ln2b": rep2,
                      "wup": col, "bup": P("pp", None, "tp"),
                      "wdown": row, "bdown": rep2}
        else:
            pspecs = {kk: P("pp") for kk in _KEYS}

        def step(feed_vals, readonly, donated, key):
            env = {}
            env.update(readonly)
            env.update(donated)
            env.update(feed_vals)
            ctx = ExecContext(key=key, block_runner=builder, amp=amp,
                              mesh=mesh)
            pre_p = {p: env[p] for p in pre_params}

            def pre_fn(pp_):
                e = dict(env)
                e.update(pp_)
                for op in pre_ops:
                    builder.run_op(op, e, ctx)
                return e[stack_in_name]

            x, pre_vjp = jax.vjp(pre_fn, pre_p)
            stage_p = {kk: env[nm]
                       for kk, nm in stack_param_names.items()}
            head_p = {p: env[p] for p in head_params}
            labels = {n: env[n] for n in label_feeds}

            def head_fn(hp, y_mb, lbl):
                e = dict(env)
                e.update(hp)
                e[stack_out_name] = y_mb
                e.update(lbl)
                for op in post_ops:
                    builder.run_op(op, e, ctx)
                return e[loss_name]

            def loss_grad_fn(hp, y_mb, lbl):
                loss_mb, vjp = jax.vjp(
                    lambda h, y: head_fn(h, y, lbl), hp, y_mb)
                dh, dy = vjp(jnp.ones_like(loss_mb))
                return loss_mb, dy, dh

            loss, dstage, dhead, dx = one_f_one_b(
                stage_fn, loss_grad_fn, stage_p, head_p, x, labels,
                mesh, axis="pp", microbatches=M, batch_axes=("dp",),
                param_specs=pspecs, warn=False)
            (dpre,) = pre_vjp(dx)
            env[loss_name] = loss
            for kk, nm in stack_param_names.items():
                env[grad_of[nm]] = dstage[kk].astype(env[nm].dtype)
            for p in head_params:
                env[grad_of[p]] = dhead[p].astype(env[p].dtype)
            for p in pre_params:
                env[grad_of[p]] = dpre[p].astype(env[p].dtype)
            for op in extra_ops:
                builder.run_op(op, env, ctx)
            for op in update_ops:
                builder.run_op(op, env, ctx)
            fetches = [env[n] for n in fetch_names]
            new_state = {n: env[n] for n in state_out if n in env}
            return fetches, new_state

        return (jax.jit(step, donate_argnums=(2,)), readonly_names,
                donated_names, state_out)

    def comm_bytes_per_step(self) -> float:
        """Exact per-device ring-collective bytes per optimizer step,
        summed over the axes (docs §27). dp: reduce-scatter moves
        ``grad_bytes*(dp-1)/dp`` per scatter (``accum`` of them at
        zero_stage>=2, one at stage 1) + the param all-gather's
        ``param_bytes*(dp-1)/dp`` — the same bytes whether the gather
        trails the update (zero<=2) or prefetches the next step's
        forward (zero-3). tp: the once-per-step full-weight all-gather
        of every column-sharded param, ``nelem_loc*itemsize*(tp-1)``
        each. The dp terms use LOCAL (per-tp-rank) sizes — the dp
        collectives run inside each tp group."""
        dp_bytes = tp_bytes = 0.0
        if self.dp > 1:
            grad_bytes = sum(self._layout[p][1] * 4
                             for p in self.split.param_names
                             if p in self._layout)
            param_bytes = sum(
                self._layout[p][1] * self._layout[p][4].itemsize
                for p in self.split.param_names if p in self._layout)
            rs = self.accum_steps if self.zero_stage >= 2 else 1
            dp_bytes = ((rs * grad_bytes + param_bytes)
                        * (self.dp - 1) / self.dp)
        if self.tp > 1:
            tp_bytes = sum(
                self._layout[p][1] * self._layout[p][4].itemsize
                * (self._tp_parts[p] - 1)
                for p in self.split.param_names
                if p in self._layout and self._tp_parts.get(p, 1) > 1)
        return dp_bytes + tp_bytes

    def comm_seconds_per_step(self) -> float:
        return self.comm_bytes_per_step() / self.link_bw

    def _readonly_names(self) -> List[str]:
        """Scope vars the window reads but does not manage (the lr var
        and kin) — the O(ops) IR walk memoizes per feed-name set, the
        executor's once-per-cache-entry discipline."""
        from ..core.executor import _collect_block_io

        feed_names = getattr(self, "_last_feed_names", ())
        cached = self._readonly_cache.get(feed_names)
        if cached is not None:
            return cached
        state_in, _ = _collect_block_io(self.program,
                                        self.split.block_idx, feed_names)
        managed = (set(self.split.param_names)
                   | set(self.split.sharded_acc_names)
                   | set(self.split.scalar_state_names))
        out = [n for n in state_in if n not in managed]
        self._readonly_cache[feed_names] = out
        return out

    def _place_feeds(self, feeds, invariant, feed_names, k, acct):
        """Coerce + split each global batch into the
        ``[k?, accum, dp, b_loc, ...]`` layout with ONE device_put per
        feed name per window."""
        import jax

        from ..core.executor import _coerce_host
        from ..obs import get_tracer

        self._last_feed_names = feed_names
        d, a = self.dp, self.accum_steps
        out = {}
        sig = []
        tr = get_tracer()
        for n in feed_names:
            if invariant:
                host = _coerce_host(np.asarray(feeds[n]), self.program, n)
                B = host.shape[0]
                if B % (d * a):
                    raise ShardedTrainError(
                        f"feed {n!r} batch {B} is not divisible by "
                        f"dp*accum_steps = {d * a}")
                host = host.reshape((a, d, B // (d * a)) + host.shape[1:])
            else:
                stack = np.stack([_coerce_host(np.asarray(fd[n]),
                                               self.program, n)
                                  for fd in feeds])
                B = stack.shape[1]
                if B % (d * a):
                    raise ShardedTrainError(
                        f"feed {n!r} batch {B} is not divisible by "
                        f"dp*accum_steps = {d * a}")
                host = stack.reshape((k, a, d, B // (d * a))
                                     + stack.shape[2:])
            t_h2d = time.monotonic()
            with tr.span("train/h2d", cat="train", feed=n):
                if self.mesh is not None:
                    axes = (None, "dp") if invariant else (None, None, "dp")
                    out[n] = jax.device_put(host, self._spec(*axes))
                else:
                    out[n] = jax.device_put(host, self.exe._device)
            if acct.enabled:
                acct.account("h2d", t_h2d, time.monotonic() - t_h2d)
            sig.append((n, tuple(host.shape), str(host.dtype)))
        return out, tuple(sig)

    # -- compilation --------------------------------------------------------
    def _compile_window(self, feed_names, fetch_names, invariant, k,
                        use_mesh: bool, ablate: bool = False):
        """Build the jitted k-step window program (docs §24/§27).

        ``ablate=True`` builds the overlap-measurement twin: every
        collective is replaced by a LOCAL op of identical output shape
        (reduce-scatter -> slice, all-gather -> tile), so the twin's
        wall-clock is the window's compute floor and real - twin is the
        EXPOSED collective time (``run_window``'s overlap accounting).
        The twin's outputs are garbage and discarded; it never donates
        its inputs."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..core.executor import BlockProgramBuilder
        from ..core.registry import ExecContext, generic_grad_fwd_instances
        from ._compat import shard_map

        split = self.split
        block = self.program.blocks[split.block_idx]
        grad_ops = block.ops[:split.split_idx]
        update_ops = block.ops[split.split_idx:]
        builder = BlockProgramBuilder(self.program)
        wanted = generic_grad_fwd_instances(block)
        grad_of = dict(zip(split.param_names, split.grad_names))
        layout = dict(self._layout)
        logical = dict(self._logical)
        tp_parts = dict(self._tp_parts)
        dp, accum = self.dp, self.accum_steps
        zero2 = self.zero_stage >= 2
        zero3 = self.zero_stage == 3
        amp = self.amp
        denom = float(dp * accum)

        # ZeRO-3 prefetch buckets: params in FIRST-USE order (the order
        # the forward consumes them — issuing bucket gathers in that
        # order lets XLA's latency-hiding scheduler start bucket i+1's
        # all-gather while bucket i's consumers run: the double-buffer),
        # greedily packed to ``zero3_bucket_mb`` per dtype (the concat
        # needs one dtype per bucket). bucket_mb <= 0 -> one param per
        # bucket: the unbucketed reference the bit-match test runs.
        buckets: List[List[str]] = []
        if zero3:
            pset = set(split.param_names)
            order: List[str] = []
            seen = set()
            for op in grad_ops:
                for names in op.inputs.values():
                    for n in names:
                        if n in pset and n not in seen:
                            seen.add(n)
                            order.append(n)
            order += [p for p in split.param_names if p not in seen]
            cap = self.zero3_bucket_bytes
            cur: List[str] = []
            cur_b, cur_dt = 0, None
            for p in order:
                dt = layout[p][4]
                nb = layout[p][2] * dt.itemsize
                if cur and (cap <= 0 or dt != cur_dt or cur_b + nb > cap):
                    buckets.append(cur)
                    cur, cur_b = [], 0
                cur.append(p)
                cur_b += nb
                cur_dt = dt
            if cur:
                buckets.append(cur)

        def run_ops(ops, env, key):
            ctx = ExecContext(key=key, amp=amp)
            ctx.block_runner = builder
            ctx.vjp_wanted_types |= wanted
            for op in ops:
                builder.run_op(op, env, ctx)
            return env

        def flatpad(x, padded):
            flat = jnp.reshape(x, (-1,))
            if padded > flat.shape[0]:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((padded - flat.shape[0],), flat.dtype)])
            return flat

        def rank_fn(feed_local, readonly, params, shards, scalars, keys):
            r = jax.lax.axis_index("dp") if use_mesh else 0

            def scatter(flat):
                if not use_mesh:
                    return flat
                if ablate:
                    sh = flat.shape[0] // dp
                    return jax.lax.dynamic_slice(flat, (r * sh,), (sh,))
                return jax.lax.psum_scatter(flat, "dp",
                                            scatter_dimension=0, tiled=True)

            def ag_dp(flat):
                if not use_mesh:
                    return flat
                if ablate:
                    return jnp.tile(flat, dp)
                return jax.lax.all_gather(flat, "dp", tiled=True)

            def ag_tp(x, tp_p):
                if tp_p <= 1:
                    return x
                if ablate:
                    return jnp.tile(x, (1,) * (x.ndim - 1) + (tp_p,))
                return jax.lax.all_gather(x, "tp", axis=x.ndim - 1,
                                          tiled=True)

            def tp_cols(g, p):
                # this tp rank's column block of the full gradient (the
                # forward ran on the all-gathered weight, so dW is full
                # and — with replicated PRNG keys — identical across the
                # tp group; each rank keeps only its columns)
                tp_p = tp_parts.get(p, 1)
                if tp_p <= 1:
                    return g
                cols = layout[p][0][-1]
                t = jax.lax.axis_index("tp")
                return jax.lax.dynamic_slice_in_dim(
                    g, t * cols, cols, axis=g.ndim - 1)

            def materialize(params):
                """Full logical weights for the forward — the static
                all-gather boundary of docs §27: weights change only at
                the update, so this runs once per optimizer step and
                covers every accum microbatch. zero<=2: params already
                arrive in their storage layout (column shard or full) —
                only the tp gather runs. zero3: bucketed dp all-gathers
                first; the reshape(dp, -1) column-block walk is pure
                data movement, bitwise equal to per-param gathers."""
                full = {}
                if zero3:
                    flats = {}
                    for bucket in buckets:
                        cat = (params[bucket[0]] if len(bucket) == 1
                               else jnp.concatenate(
                                   [params[p] for p in bucket]))
                        mat = ag_dp(cat).reshape(dp, -1)
                        off = 0
                        for p in bucket:
                            sh = layout[p][3]
                            flats[p] = mat[:, off:off + sh].reshape(-1)
                            off += sh
                    for p in split.param_names:
                        local, nelem, _pad, _sh, _dt = layout[p]
                        w = flats[p][:nelem].reshape(local)
                        full[p] = ag_tp(w, tp_parts.get(p, 1))
                else:
                    for p in split.param_names:
                        full[p] = ag_tp(params[p], tp_parts.get(p, 1))
                return full

            def opt_step(carry, xs):
                params, shards, scalars = carry
                feed_step, keys_step = xs
                weights = materialize(params)

                def micro(acc, mxs):
                    feed_m, key_m = mxs
                    env = {}
                    env.update(readonly)
                    env.update(scalars)
                    env.update(weights)
                    env.update(feed_m)
                    run_ops(grad_ops, env, key_m)
                    fetches = []
                    for n in fetch_names:
                        if n not in env:
                            raise KeyError(
                                f"fetch var {n!r} is not produced by the "
                                f"grad segment (fetching optimizer-segment "
                                f"outputs is not supported under ZeRO)")
                        fetches.append(env[n])
                    extras = {n: env[n] for n in split.extra_names
                              if n in env}
                    nxt = {}
                    for p in split.param_names:
                        g = jnp.asarray(env[grad_of[p]], jnp.float32)
                        g = tp_cols(g, p)
                        if zero2:
                            g = scatter(flatpad(g, layout[p][2]))
                        nxt[p] = acc[p] + g
                    return nxt, (fetches, extras)

                acc0 = {}
                for p in split.param_names:
                    local, nelem, padded, shard, _pd = layout[p]
                    if zero2:
                        # the 1/dp grad shard IS the accumulation buffer
                        n0 = shard if use_mesh else padded
                        acc0[p] = jnp.zeros((n0,), jnp.float32)
                    else:
                        # zero-1 accumulates this rank's LOCAL column
                        # shard (the full logical tensor only at tp=1)
                        acc0[p] = jnp.zeros(local, jnp.float32)
                acc, (fetch_stack, extras_stack) = jax.lax.scan(
                    micro, acc0, (feed_step, keys_step))
                extras = jax.tree.map(lambda x: x[-1], extras_stack)

                env = {}
                env.update(readonly)
                env.update(extras)
                env.update(scalars)
                for p in split.param_names:
                    local, nelem, padded, shard, _pd = layout[p]
                    if zero2:
                        gshard = acc[p] / denom
                    else:
                        gshard = scatter(flatpad(acc[p], padded)) / denom
                    if zero3:
                        # the carried flat shard IS the update operand
                        pshard = params[p]
                    else:
                        pflat = flatpad(params[p], padded)
                        if use_mesh:
                            pshard = jax.lax.dynamic_slice(
                                pflat, (r * shard,), (shard,))
                        else:
                            pshard = pflat
                    env[p] = pshard
                    env[grad_of[p]] = gshard.astype(pshard.dtype)
                for a_n in split.sharded_acc_names:
                    env[a_n] = shards[a_n]
                run_ops(update_ops, env, None)
                new_params = {}
                for p in split.param_names:
                    local, nelem, padded, shard, _pd = layout[p]
                    if zero3:
                        # keep the flat shard — no trailing gather; the
                        # next step's materialize re-gathers (prefetch)
                        new_params[p] = env[p]
                    elif use_mesh:
                        full = ag_dp(env[p])
                        new_params[p] = full[:nelem].reshape(local)
                    else:
                        new_params[p] = env[p][:nelem].reshape(local)
                new_shards = {a_n: env[a_n]
                              for a_n in split.sharded_acc_names}
                new_scalars = {s: env[s]
                               for s in split.scalar_state_names}
                return (new_params, new_shards, new_scalars), \
                    (fetch_stack, extras_stack)

            if invariant:
                def body(carry, keys_step):
                    return opt_step(carry, (feed_local, keys_step))
                carry, (ys, _ex) = jax.lax.scan(
                    body, (params, shards, scalars), keys)
            else:
                carry, (ys, _ex) = jax.lax.scan(
                    opt_step, (params, shards, scalars),
                    (feed_local, keys))
            new_params, new_shards, new_scalars = carry
            # fetches: [k, accum, ...] per rank -> expose the dp axis
            ys = [jnp.expand_dims(y, 2) for y in ys]
            return ys, new_params, new_shards, new_scalars

        if not use_mesh:
            def window(feed_vals, readonly, params, shards, scalars, keys):
                feed_local = {n: (feed_vals[n][:, :, 0] if not invariant
                                  else feed_vals[n][:, 0])
                              for n in feed_names}
                return rank_fn(feed_local, readonly, params, shards,
                               scalars, keys)

            if ablate:
                return jax.jit(window)
            return jax.jit(window, donate_argnums=(2, 3, 4))

        feed_axis = P(None, "dp") if invariant else P(None, None, "dp")

        def pspec(p):
            """Storage spec of one param: zero-3 -> flat (tp-major,
            dp-padded) shards; else column-sharded logical over 'tp'
            when eligible, replicated otherwise."""
            if zero3:
                return (P(("tp", "dp")) if tp_parts.get(p, 1) > 1
                        else P("dp"))
            if tp_parts.get(p, 1) > 1:
                nd = len(logical[p])
                return P(*((None,) * (nd - 1) + ("tp",)))
            return P()

        def sspec(a):
            """Storage spec of one flat optimizer-state array."""
            return (P(("tp", "dp")) if tp_parts.get(a, 1) > 1
                    else P("dp"))

        def ranked(feed_vals, readonly, params, shards, scalars, keys):
            # shard_map hands each rank a size-1 slice along the dp dim;
            # squeeze it so the rank sees [k?, accum, b_loc, ...]
            ax = 1 if invariant else 2
            local = {n: jnp.squeeze(v, axis=ax)
                     for n, v in feed_vals.items()}
            return rank_fn(local, readonly, params, shards, scalars, keys)

        def window(feed_vals, readonly, params, shards, scalars, keys):
            in_specs = (
                {n: feed_axis for n in feed_names},
                jax.tree.map(lambda _: P(), readonly),
                {p: pspec(p) for p in params},
                {a: sspec(a) for a in shards},
                jax.tree.map(lambda _: P(), scalars),
                P(),
            )
            out_specs = (
                [P(None, None, "dp")] * len(fetch_names),
                {p: pspec(p) for p in params},
                {a: sspec(a) for a in shards},
                jax.tree.map(lambda _: P(), scalars),
            )
            fn = shard_map(ranked, mesh=self.mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
            return fn(feed_vals, readonly, params, shards, scalars, keys)

        if ablate:
            return jax.jit(window)
        return jax.jit(window, donate_argnums=(2, 3, 4))

    # -- introspection ------------------------------------------------------
    def lowered_text(self, feed, k: int = 1,
                     fetch_list: Optional[Sequence] = None,
                     scope=None) -> str:
        """Compiled-HLO text of the window program for ``feed`` — the
        collective-contract instrument (``measured_collectives``)."""
        import jax

        from ..core.executor import global_scope

        scope = scope if scope is not None else global_scope()
        fetch_names = [f if isinstance(f, str) else f.name
                       for f in (fetch_list or [])]
        self._prepare_state(scope)
        from ..obs.goodput import get_accountant

        feed_names = tuple(sorted(feed))
        feed_vals, _sig = self._place_feeds(feed, True, feed_names, k,
                                            get_accountant())
        readonly = {n: scope.get(n) for n in self._readonly_names()}
        params = {p: scope.get(p) for p in self.split.param_names}
        shards = {a: scope.get(a) for a in self.split.sharded_acc_names}
        scalars = {s: scope.get(s)
                   for s in self.split.scalar_state_names}
        import jax.numpy as jnp

        keys = jnp.zeros((k, self.accum_steps, 2), jnp.uint32)
        fn = self._compile_window(feed_names, fetch_names, True, k,
                                  self.mesh is not None)
        lowered = fn.lower(feed_vals, readonly, params, shards, scalars,
                           keys)
        try:
            return lowered.compile().as_text()
        except Exception:
            return lowered.as_text()

    def measured_collectives(self, feed, k: int = 1,
                             fetch_list: Optional[Sequence] = None,
                             scope=None) -> Dict[str, int]:
        """Count the collective ops XLA actually compiled into the
        window (reduce-scatter may legally lower as
        all-reduce+dynamic-slice on backends without a native kernel —
        both spellings count toward the reduce half)."""
        text = self.lowered_text(feed, k=k, fetch_list=fetch_list,
                                 scope=scope)
        return {
            "reduce_scatter": text.count("reduce-scatter("),
            "all_reduce": text.count("all-reduce("),
            "all_gather": text.count("all-gather("),
        }

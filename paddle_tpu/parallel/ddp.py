"""Sharded data-parallel training: ZeRO optimizer-state sharding inside
one compiled window (docs/design.md §24).

Fluid's reason to exist was distributed *training* (trainer + pserver +
NCCL); design.md §4 names the TPU-native mapping — collectives inside the
compiled step, overlapped with backward by XLA, and ``BuildStrategy.Reduce``
= ZeRO (optimizer state sharded over ``dp``). This module closes that gap:
``ShardedTrainStep`` wraps the same traced step function the Executor
compiles (``core/executor.build_step_fn``'s builder) in ``shard_map`` over
a flat ``('dp',)`` mesh, with the training-specific collective schedule:

* per-microbatch grads **reduce-scattered** (``lax.psum_scatter``), not
  all-reduced — each rank receives only its 1/dp slice of the mean
  gradient, so it updates only its 1/dp shard of parameters and optimizer
  state (ZeRO-1/2: params stay replicated, optimizer state and — under
  ``zero_stage=2`` — the gradient accumulation buffer shard 1/dp);
* the optimizer update ops (the suffix of the training block) run on
  flat 1-D shards — every dense update kernel in ops/optimizer_ops.py is
  elementwise, so the IR program needs no rewriting;
* updated parameter shards **all-gather** back to full replicated params
  for the next microbatch's forward;
* gradient-accumulation microbatching rides INSIDE the compiled window
  (``accum_steps`` microbatches per optimizer step, accumulated in f32),
  so the global batch decouples from per-device HBM: activations peak at
  one microbatch, and ``b_loc = B / (dp * accum_steps)``.

Everything — k optimizer steps x accum microbatches x the collectives —
is ONE jitted program (``lax.scan`` over steps, nested scan over
microbatches), so XLA schedules the reduce-scatters against the backward
exactly as §4 promised.

Contracts (tested in tests/test_ddp.py):

* ``dp=1, accum_steps=1`` delegates to ``Executor.run_steps`` — the
  byte-identical pre-PR path (same compile-cache key, same program).
* ``accum_steps=k`` at dp=1 computes the fused big-batch gradient
  algebraically: k microbatch means, summed in f32, divided by k. On
  dyadic-exact data this bit-matches the fused ``run_steps`` step; on
  arbitrary data the difference is reduction-order-only (documented
  tolerance, §24).
* dp>1 is deterministic across reruns: the mesh, the split, and the
  collective schedule are static, so the same seeds produce bit-identical
  loss trajectories.
* Sharded optimizer state lives in the scope as flat padded 1-D arrays
  sharded over the mesh — ``io.save_checkpoint`` writes per-shard files
  via its existing multi-shard path, and ``_prepare_state`` re-lays out
  whatever a checkpoint restores (any dp, or a plain logical-shaped
  array) for the current mesh: reshard-on-load for free.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

OPT_OP_TYPES = frozenset({
    "sgd", "momentum", "adam", "adamax", "adagrad", "decayed_adagrad",
    "adadelta", "rmsprop", "ftrl", "proximal_gd", "proximal_adagrad",
})

#: non-optimizer op types allowed inside the update segment: the per-param
#: lr scaling and adamax's trailing beta1_pow decay are both ``scale``
UPDATE_COMPANION_TYPES = frozenset({"scale"})


class ShardedTrainError(ValueError):
    """A program or configuration the sharded trainer refuses, loudly:
    sparse (SelectedRows) gradients, non-optimizer ops behind the first
    update op (ModelAverage), persistable writes in the grad segment
    (batch-norm stats would silently diverge per rank), batches that do
    not split, meshes the host cannot build."""


class TrainSplit:
    """The (grad segment | update segment) partition of a training block
    plus the var roles the ZeRO layout needs. Built once per program by
    ``split_train_block``."""

    __slots__ = ("block_idx", "split_idx", "param_names", "grad_names",
                 "sharded_acc_names", "scalar_state_names", "acc_param",
                 "update_written", "extra_names", "optimizer_types",
                 "grad_segment_writes")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))


def split_train_block(program, block_idx: int = 0) -> TrainSplit:
    """Partition ``block_idx`` at the first optimizer op and classify the
    training state (docs §24 layout):

    * params — the update ops' ``Param`` slots (replicated, full copy per
      rank);
    * sharded accumulators — param-shaped optimizer state (moments,
      velocity; IR-declared shape equals the param's), flat-sharded 1/dp;
    * scalar state — shape-() accumulators (Adam's beta pows),
      replicated and updated identically on every rank;
    * extras — grad-segment outputs the update segment reads (scaled
      per-param learning rates): scalars, passed through replicated.

    Typed refusals (``ShardedTrainError``) for every structure the ZeRO
    layout cannot honor — see the class docstring and §24's failure
    matrix.
    """
    block = program.blocks[block_idx]
    opt_idxs = [i for i, op in enumerate(block.ops)
                if op.type in OPT_OP_TYPES]
    if not opt_idxs:
        raise ShardedTrainError(
            "program has no optimizer update ops — build it with "
            "optimizer.minimize(loss) before wrapping it in a "
            "ShardedTrainStep")
    split_idx = opt_idxs[0]
    update_ops = block.ops[split_idx:]
    params: List[str] = []
    grads: List[str] = []
    opt_types: List[str] = []
    for op in update_ops:
        if op.type in OPT_OP_TYPES:
            ids = op.inputs.get("GradIds")
            if ids and ids[0]:
                raise ShardedTrainError(
                    f"param {op.inputs['Param'][0]!r} has a SelectedRows "
                    f"(is_sparse) gradient — row grads cannot be "
                    f"reduce-scattered by element range; drop "
                    f"is_sparse=True or train it on the host-table path")
            params.append(op.inputs["Param"][0])
            grads.append(op.inputs["Grad"][0])
            if op.type not in opt_types:
                opt_types.append(op.type)
        elif op.type not in UPDATE_COMPANION_TYPES:
            raise ShardedTrainError(
                f"op {op.type!r} follows the first optimizer update op — "
                f"the update segment must hold only optimizer ops (+ lr "
                f"scale); ModelAverage and other post-update passes do "
                f"not compose with ZeRO sharding")

    param_set = set(params)
    # names written by the update segment (persistable state)
    update_written: List[str] = []
    seen_w = set()
    for op in update_ops:
        for names in op.outputs.values():
            for n in names:
                if n and n not in seen_w:
                    seen_w.add(n)
                    var = block.find_var_recursive(n)
                    if var is not None and var.persistable:
                        update_written.append(n)
    # names the update segment reads that it does not itself produce
    produced_in_update = set()
    update_reads: List[str] = []
    seen_r = set()
    for op in update_ops:
        for names in op.inputs.values():
            for n in names:
                if n and n not in produced_in_update and n not in seen_r:
                    seen_r.add(n)
                    update_reads.append(n)
        for names in op.outputs.values():
            produced_in_update.update(n for n in names if n)

    # classify accumulators by IR-declared shape: param-shaped -> sharded,
    # anything else (the () beta pows) -> replicated scalar state
    acc_param: Dict[str, str] = {}
    for op in update_ops:
        if op.type not in OPT_OP_TYPES:
            continue
        p = op.inputs["Param"][0]
        for slot, names in list(op.inputs.items()) + list(op.outputs.items()):
            for n in names:
                if n and n != p and n not in acc_param \
                        and n in seen_w and n not in param_set:
                    acc_param[n] = p
    sharded_accs: List[str] = []
    scalar_state: List[str] = []
    for n in update_written:
        if n in param_set:
            continue
        var = block.find_var_recursive(n)
        pvar = block.find_var_recursive(acc_param.get(n, ""))
        if (var is not None and pvar is not None and var.shape
                and tuple(var.shape) == tuple(pvar.shape)):
            sharded_accs.append(n)
        else:
            scalar_state.append(n)

    # grad-segment persistable writes (batch-norm stats and kin): the
    # sharded path refuses these — per-rank updates would silently diverge
    grad_writes: List[str] = []
    produced = set()
    for op in block.ops[:split_idx]:
        for names in op.outputs.values():
            for n in names:
                if n and n not in produced:
                    produced.add(n)
                    var = block.find_var_recursive(n)
                    if var is not None and var.persistable:
                        grad_writes.append(n)

    # extras: update-segment reads produced by the grad segment (scaled
    # lr vars) — not state, not grads
    state_like = param_set | set(acc_param) | set(update_written)
    grad_set = set(grads)
    extras = [n for n in update_reads
              if n not in state_like and n not in grad_set
              and n in produced]

    return TrainSplit(
        block_idx=block_idx, split_idx=split_idx, param_names=params,
        grad_names=grads, sharded_acc_names=sharded_accs,
        scalar_state_names=scalar_state, acc_param=acc_param,
        update_written=update_written, extra_names=extras,
        optimizer_types=opt_types, grad_segment_writes=grad_writes)


class ShardedTrainStep:
    """Execute a training program's optimizer steps sharded over a
    ``('dp',)`` mesh with ZeRO-1/2 state sharding and in-window gradient
    accumulation (module docstring; docs §24).

    ``run_window(feed, k=...)`` is the sharded sibling of
    ``Executor.run_steps``: ``k`` optimizer steps fused into one device
    program. Each step consumes one GLOBAL batch of ``B`` rows with
    ``B % (dp * accum_steps) == 0``; rank ``r``'s microbatch ``j`` is
    rows ``[j*dp*b_loc + r*b_loc, ...)`` — at dp=1 the microbatches are
    the contiguous row chunks of the fused batch (the accumulation
    bit-match contract). Fetches return stacked ``[k, accum, dp, ...]``
    (one entry per microbatch per rank).

    ``zero_stage``: 1 = accumulate full local f32 grads, ONE
    reduce-scatter per optimizer step (accum x less collective traffic);
    2 = reduce-scatter every microbatch and accumulate only the 1/dp
    shard (the grad buffer shrinks 1/dp — the HBM account the
    ``TrainPlacementSearcher`` prices). Both compute the same mean
    gradient; they differ only in float reduction order.
    """

    def __init__(self, program, *, dp: int = 1, accum_steps: int = 1,
                 zero_stage: int = 2, place=None, amp: bool = False,
                 executor=None, devices=None, link_gbps: float = 45.0):
        from ..core.executor import Executor

        if dp < 1:
            raise ShardedTrainError(f"dp must be >= 1, got {dp}")
        if accum_steps < 1:
            raise ShardedTrainError(
                f"accum_steps must be >= 1, got {accum_steps}")
        if zero_stage not in (1, 2):
            raise ShardedTrainError(
                f"zero_stage must be 1 or 2, got {zero_stage}")
        self.program = program
        self.dp = int(dp)
        self.accum_steps = int(accum_steps)
        self.zero_stage = int(zero_stage)
        self.link_bw = float(link_gbps) * 1e9
        self.exe = executor if executor is not None else Executor(place,
                                                                  amp=amp)
        self.amp = self.exe.amp
        self.split = split_train_block(program, 0)
        if (self.dp > 1 or self.accum_steps > 1) \
                and self.split.grad_segment_writes:
            # batch-norm moving stats and kin: per-rank updates diverge
            # under dp, and the microbatched window would silently DROP
            # the writes (rank_fn carries only params/optimizer state) —
            # refuse loudly on every non-delegate path
            raise ShardedTrainError(
                f"the grad segment writes persistable state "
                f"{self.split.grad_segment_writes[:4]} — non-gradient "
                f"state (batch-norm moving stats) neither shards under "
                f"dp nor survives microbatching; train it unsharded "
                f"(dp=1, accum_steps=1) or move it behind the optimizer")
        self.mesh = None
        if self.dp > 1:
            import jax

            from .mesh import make_mesh

            platform = self.exe._device.platform
            if devices is None:
                devices = jax.devices(platform)
            if self.dp > len(devices):
                raise ShardedTrainError(
                    f"dp={self.dp} needs {self.dp} devices, only "
                    f"{len(devices)} available (host meshes: set XLA_FLAGS="
                    f"--xla_force_host_platform_device_count=N before jax "
                    f"initializes)")
            self.mesh = make_mesh({"dp": self.dp},
                                  devices=devices[:self.dp])
        # name -> (logical_shape, nelem, padded, shard, np_dtype)
        self._layout: Dict[str, Tuple] = {}
        self._placed: Dict[str, Any] = {}  # identity cache of placed state
        self._cache: Dict[Any, Any] = {}   # compiled windows
        self._readonly_cache: Dict[Tuple, List[str]] = {}

    # -- state layout -------------------------------------------------------
    def _spec(self, *axes):
        """Placement target: a NamedSharding on the mesh, or the plain
        executor device when dp=1 (the accumulation-only path needs no
        mesh — shard_map over one rank would only add identity
        collectives)."""
        if self.mesh is None:
            return self.exe._device
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec(*axes))

    def _prepare_state(self, scope) -> None:
        """Lay the scope's training state out on the mesh: params and
        scalar state replicated, param-shaped accumulators flattened,
        zero-padded to a dp multiple, and sharded 1/dp. Accepts state in
        logical shape (a fresh startup run, a dp=1 checkpoint) OR as the
        flat padded array of ANY previous dp (a sharded checkpoint
        restored onto a different mesh) — reshard-on-load is this
        unpad/repad, not a special path."""
        import jax

        split = self.split
        repl = self._spec()
        shard_spec = self._spec("dp")
        for p in split.param_names:
            val = scope.get(p)
            if val is None:
                raise RuntimeError(
                    f"param {p!r} has no value in the scope; run the "
                    f"startup program first")
            arr = np.asarray(val) if not hasattr(val, "sharding") else val
            nelem = int(np.prod(arr.shape)) if arr.shape else 1
            shard = -(-nelem // self.dp)  # ceil
            self._layout[p] = (tuple(arr.shape), nelem, shard * self.dp,
                               shard, np.dtype(str(arr.dtype)))
            if self._placed.get(p) is not scope.get(p):
                placed = jax.device_put(val, repl)
                scope.set(p, placed)
                self._placed[p] = placed
        for a in split.sharded_acc_names:
            p = split.acc_param[a]
            shape, nelem, padded, shard, _pd = self._layout[p]
            val = scope.get(a)
            if val is None:
                raise RuntimeError(
                    f"optimizer state {a!r} has no value in the scope; "
                    f"run the startup program first")
            if self._placed.get(a) is scope.get(a):
                continue
            host = np.asarray(val)
            flat = host.reshape(-1)
            if flat.size < nelem:
                raise ShardedTrainError(
                    f"optimizer state {a!r} holds {flat.size} elements, "
                    f"fewer than its param's {nelem} — the checkpoint does "
                    f"not match this program")
            flat = flat[:nelem]  # drop any previous dp's padding
            if padded > nelem:
                flat = np.concatenate(
                    [flat, np.zeros(padded - nelem, flat.dtype)])
            self._layout[a] = (shape, nelem, padded, shard, flat.dtype)
            placed = jax.device_put(flat, shard_spec)
            scope.set(a, placed)
            self._placed[a] = placed
        for s in split.scalar_state_names:
            val = scope.get(s)
            if val is None:
                raise RuntimeError(
                    f"optimizer state {s!r} has no value in the scope; "
                    f"run the startup program first")
            if self._placed.get(s) is not scope.get(s):
                placed = jax.device_put(val, repl)
                scope.set(s, placed)
                self._placed[s] = placed

    def gather_state(self, scope) -> None:
        """Convert the scope's ZeRO state back to logical shapes (host
        numpy): unpad each flat shard array and reshape to its param's
        shape. After this the scope drives the plain Executor again (or
        saves a dp-agnostic checkpoint)."""
        for a in self.split.sharded_acc_names:
            lay = self._layout.get(a)
            if lay is None:
                continue
            shape, nelem = lay[0], lay[1]
            val = scope.get(a)
            if val is None:
                continue
            host = np.asarray(val).reshape(-1)
            if host.size != nelem:
                host = host[:nelem]
            scope.set(a, host.reshape(shape))
            self._placed.pop(a, None)
        for p in self.split.param_names + self.split.scalar_state_names:
            val = scope.get(p)
            if val is not None:
                scope.set(p, np.asarray(val))
                self._placed.pop(p, None)
        # the scope now drives the plain (unsharded) executor again —
        # the dp gauge must not keep reporting this step's width
        from ..core.executor import _train_metrics

        _train_metrics()["dp"].set(1.0)

    def zero_meta(self) -> Dict[str, Any]:
        """The reshard descriptor a checkpoint carries (io.py writes it
        as ``_ZERO.json``): enough to validate a restore onto any dp."""
        return {
            "schema": 1,
            "dp": self.dp,
            "zero_stage": self.zero_stage,
            "accum_steps": self.accum_steps,
            "optimizer": list(self.split.optimizer_types),
            "vars": {a: {"param": self.split.acc_param[a],
                         "shape": list(self._layout[self.split.acc_param[a]][0]),
                         "nelem": self._layout[self.split.acc_param[a]][1]}
                     for a in self.split.sharded_acc_names
                     if self.split.acc_param[a] in self._layout},
        }

    def save_checkpoint(self, checkpoint_dir: str, scope,
                        **kw) -> int:
        """``io.save_checkpoint`` with the ZeRO reshard descriptor
        attached; sharded accumulators go to disk as per-shard files (the
        existing multi-shard save path — each rank-sized slice is its own
        ``.npy``)."""
        from .. import io as model_io

        return model_io.save_checkpoint(
            self.exe, checkpoint_dir, main_program=self.program,
            scope=scope, zero_meta=self.zero_meta(), **kw)

    def load_checkpoint(self, checkpoint_dir: str, scope,
                        serial: Optional[int] = None) -> int:
        """Load a checkpoint saved at ANY dp and re-lay it out for this
        mesh. Validates the ``_ZERO.json`` descriptor (when present)
        against this program's split — a checkpoint whose optimizer state
        belongs to a different program refuses instead of training on
        garbage."""
        from .. import io as model_io

        serial = model_io.load_checkpoint(
            self.exe, checkpoint_dir, main_program=self.program,
            scope=scope, serial=serial)
        meta = model_io.read_zero_meta(
            model_io.checkpoint_serial_dir(checkpoint_dir, serial))
        if meta is not None:
            self._prepare_layout_only(scope)
            for a, info in meta.get("vars", {}).items():
                if a not in self.split.acc_param:
                    raise ShardedTrainError(
                        f"checkpoint optimizer state {a!r} is not part of "
                        f"this program's update segment — wrong program "
                        f"for this checkpoint")
                p = self.split.acc_param[a]
                want = self._layout[p][1]
                if int(info.get("nelem", want)) != want:
                    raise ShardedTrainError(
                        f"checkpoint state {a!r} has {info['nelem']} "
                        f"elements, this program's {p!r} needs {want} — "
                        f"refusing to reshard mismatched state")
        # force a re-layout on the next window (reshard-on-load)
        self._placed.clear()
        return serial

    def _prepare_layout_only(self, scope) -> None:
        """Param layouts from the PROGRAM's declared shapes (not the
        scope: a just-loaded checkpoint has already overwritten the
        scope's values, and the reshard validation must compare the
        checkpoint against THIS program, not against itself)."""
        block = self.program.blocks[self.split.block_idx]
        for p in self.split.param_names:
            if p in self._layout:
                continue
            var = block.find_var_recursive(p)
            if var is None or not var.shape:
                val = scope.get(p)
                if val is None:
                    continue
                shape = tuple(np.asarray(val).shape)
            else:
                shape = tuple(var.shape)
            nelem = int(np.prod(shape)) if shape else 1
            shard = -(-nelem // self.dp)
            self._layout[p] = (shape, nelem, shard * self.dp, shard,
                               np.dtype(np.float32))

    def state_bytes_per_device(self, scope) -> Dict[str, float]:
        """The live per-device residency vs the ZeRO account — the bench
        workload's gate compares these (arXiv 2512.02551: the account is
        only as good as the arrays it predicts)."""
        params = opt_shard = opt_logical = scalars = 0.0
        for p in self.split.param_names:
            v = scope.get(p)
            if v is not None:
                params += np.asarray(v).nbytes if not hasattr(v, "nbytes") \
                    else v.nbytes
        for a in self.split.sharded_acc_names:
            v = scope.get(a)
            if v is None:
                continue
            lay = self._layout.get(a)
            if lay is not None:
                opt_logical += lay[1] * lay[4].itemsize
            if hasattr(v, "addressable_shards") and self.dp > 1:
                opt_shard += v.addressable_shards[0].data.nbytes
            else:
                opt_shard += np.asarray(v).nbytes / max(self.dp, 1)
        for s in self.split.scalar_state_names:
            v = scope.get(s)
            if v is not None:
                scalars += np.asarray(v).nbytes
        return {
            "param_bytes": params,
            "opt_shard_bytes_per_device": opt_shard,
            "opt_logical_bytes": opt_logical,
            "scalar_bytes": scalars,
            # the account the searcher prices: logical/dp plus at most one
            # padding element per tensor per rank
            "zero_account_bytes": opt_logical / self.dp + sum(
                (lay[2] - lay[1]) * lay[4].itemsize / self.dp
                for a in self.split.sharded_acc_names
                for lay in [self._layout.get(a)] if lay is not None),
        }

    # -- window execution ---------------------------------------------------
    def run_window(self, feed, k: Optional[int] = None,
                   fetch_list: Optional[Sequence] = None, scope=None,
                   seed: Optional[int] = None, return_numpy: bool = True):
        """Run ``k`` sharded optimizer steps as one device program.

        ``feed``: ONE dict (same global batch every step; needs ``k``) or
        a sequence of ``k`` global-batch dicts. Fetches come back stacked
        ``[k, accum_steps, dp, ...]`` — one slice per microbatch per
        rank (at dp=1/accum=1 the delegate path reshapes ``run_steps``'s
        ``[k, ...]`` to match).
        """
        from ..core.executor import global_scope

        fetch_names = [f if isinstance(f, str) else f.name
                       for f in (fetch_list or [])]
        scope = scope if scope is not None else global_scope()
        if isinstance(feed, dict):
            if k is None or int(k) < 1:
                raise ValueError(
                    "run_window with a single feed dict needs k >= 1")
            k = int(k)
            feeds, invariant = feed, True
        else:
            feeds = list(feed or [])
            if not feeds:
                raise ValueError("run_window needs a feed dict or a "
                                 "non-empty sequence of feed dicts")
            if k is not None and int(k) != len(feeds):
                raise ValueError(f"k={k} but {len(feeds)} feed dicts given")
            k = len(feeds)
            invariant = False

        if self.dp == 1 and self.accum_steps == 1:
            # the pre-PR path, byte for byte: same executor, same cache
            # key, same compiled program
            from ..core.executor import _train_metrics

            _train_metrics()["dp"].set(1.0)
            out = self.exe.run_steps(
                self.program, feed=feeds, k=k,
                fetch_list=fetch_names, scope=scope,
                return_numpy=return_numpy, seed=seed)
            return [v.reshape((k, 1, 1) + tuple(v.shape[1:]))
                    for v in out]
        if self.dp == 1:
            # accumulation without a mesh: same algebra on one device —
            # shard_map over a 1-rank mesh would only add identity
            # collectives to the program
            return self._run_sharded(feeds, invariant, k, fetch_names,
                                     scope, seed, return_numpy,
                                     mesh=False)
        return self._run_sharded(feeds, invariant, k, fetch_names, scope,
                                 seed, return_numpy, mesh=True)

    def _microbatch_seeds(self, k: int, seed: Optional[int]) -> List[int]:
        """One PRNG seed per microbatch, drawn from the executor's step
        counter — microbatch (i, j) of a window uses the seed sequential
        step ``i*accum + j`` would (the PR-3 key-parity rule extended to
        microbatches; dropout masks per microbatch match the sequential
        per-step stream)."""
        n = k * self.accum_steps
        if seed is None:
            base = self.exe._step_seed
            self.exe._step_seed += n
            return [base + 1 + i for i in range(n)]
        return [seed] * n

    def _run_sharded(self, feeds, invariant, k, fetch_names, scope, seed,
                     return_numpy, mesh: bool):
        import jax
        import jax.numpy as jnp

        from ..core.executor import _MISSING, _train_metrics
        from ..obs import get_tracer
        from ..obs.goodput import get_accountant

        acct = get_accountant()
        tr = get_tracer()
        split = self.split
        t_acct = time.monotonic() if acct.enabled else 0.0
        with tr.span("train/host_prep", cat="train", k=k, dp=self.dp,
                     accum=self.accum_steps):
            self._prepare_state(scope)
            feed_names = tuple(sorted(feeds if invariant else feeds[0]))
            feed_vals, step_sig = self._place_feeds(
                feeds, invariant, feed_names, k, acct)

        readonly = {}
        for n in self._readonly_names():
            v = scope.get(n, _MISSING)
            if v is _MISSING:
                raise RuntimeError(
                    f"variable {n!r} is read by the program but missing "
                    f"from the scope; run the startup program first")
            readonly[n] = v
        params = {p: scope.get(p) for p in split.param_names}
        shards = {a: scope.get(a) for a in split.sharded_acc_names}
        scalars = {s: scope.get(s) for s in split.scalar_state_names}

        seeds = self._microbatch_seeds(k, seed)
        rs = self.program.random_seed or 0
        keys = jnp.stack([jax.random.PRNGKey(np.uint32(s ^ rs))
                          for s in seeds]).reshape(k, self.accum_steps, 2)

        cache_key = (self.program.uid, self.program.version, step_sig,
                     tuple(fetch_names), self.amp, invariant, k,
                     self.dp, self.accum_steps, self.zero_stage)
        fn = self._cache.get(cache_key)
        if fn is None:
            _train_metrics()["compiles"].inc()
            t_c = time.monotonic() if acct.enabled else 0.0
            with tr.span("train/ddp_compile", cat="compile"):
                fn = self._compile_window(feed_names, fetch_names,
                                          invariant, k, mesh)
            if acct.enabled:
                acct.account("compile", t_c, time.monotonic() - t_c)
            self._cache[cache_key] = fn
            while len(self._cache) > 16:
                self._cache.pop(next(iter(self._cache)))
        if acct.enabled:
            acct.account("host_input", t_acct, time.monotonic() - t_acct)

        m = _train_metrics()
        m["dp"].set(float(self.dp))
        t_dev = time.monotonic()
        with tr.span("train/device_window", cat="train", k=k, dp=self.dp):
            fetches, new_params, new_shards, new_scalars = fn(
                feed_vals, readonly, params, shards, scalars, keys)
            for p, v in new_params.items():
                scope.set(p, v)
                self._placed[p] = v
            for a, v in new_shards.items():
                scope.set(a, v)
                self._placed[a] = v
            for s, v in new_scalars.items():
                scope.set(s, v)
                self._placed[s] = v
        dev_dur = time.monotonic() - t_dev
        if acct.enabled:
            acct.account("device_compute", t_dev, dev_dur)
        if self.dp > 1:
            # model-attributed collective seconds (docs §24): the ring
            # volumes are exact, the wall share is the searcher's own
            # link-bandwidth model clamped to the measured window — an
            # attribution, not a measurement (XLA hides true overlap)
            comm_s = min(self.comm_seconds_per_step() * k, dev_dur)
            m["collective"].inc(comm_s)
            if acct.enabled and comm_s > 0:
                acct.account("collective",
                             t_dev + dev_dur - comm_s, comm_s)
        if return_numpy:
            t_f = time.monotonic() if acct.enabled else 0.0
            with tr.span("train/fetch_sync", cat="train"):
                fetches = [np.asarray(v) for v in fetches]
            if acct.enabled:
                acct.account("fetch_sync", t_f, time.monotonic() - t_f)
        m["steps"].inc(k)
        return fetches

    def comm_bytes_per_step(self) -> float:
        """Exact ring-collective bytes per optimizer step: reduce-scatter
        moves ``grad_bytes*(dp-1)/dp`` per scatter (``accum`` of them at
        zero_stage=2, one at stage 1) + the param all-gather's
        ``param_bytes*(dp-1)/dp``."""
        if self.dp <= 1:
            return 0.0
        grad_bytes = sum(self._layout[p][1] * 4
                         for p in self.split.param_names
                         if p in self._layout)
        param_bytes = sum(
            self._layout[p][1] * self._layout[p][4].itemsize
            for p in self.split.param_names if p in self._layout)
        rs = self.accum_steps if self.zero_stage == 2 else 1
        return (rs * grad_bytes + param_bytes) * (self.dp - 1) / self.dp

    def comm_seconds_per_step(self) -> float:
        return self.comm_bytes_per_step() / self.link_bw

    def _readonly_names(self) -> List[str]:
        """Scope vars the window reads but does not manage (the lr var
        and kin) — the O(ops) IR walk memoizes per feed-name set, the
        executor's once-per-cache-entry discipline."""
        from ..core.executor import _collect_block_io

        feed_names = getattr(self, "_last_feed_names", ())
        cached = self._readonly_cache.get(feed_names)
        if cached is not None:
            return cached
        state_in, _ = _collect_block_io(self.program,
                                        self.split.block_idx, feed_names)
        managed = (set(self.split.param_names)
                   | set(self.split.sharded_acc_names)
                   | set(self.split.scalar_state_names))
        out = [n for n in state_in if n not in managed]
        self._readonly_cache[feed_names] = out
        return out

    def _place_feeds(self, feeds, invariant, feed_names, k, acct):
        """Coerce + split each global batch into the
        ``[k?, accum, dp, b_loc, ...]`` layout with ONE device_put per
        feed name per window."""
        import jax

        from ..core.executor import _coerce_host
        from ..obs import get_tracer

        self._last_feed_names = feed_names
        d, a = self.dp, self.accum_steps
        out = {}
        sig = []
        tr = get_tracer()
        for n in feed_names:
            if invariant:
                host = _coerce_host(np.asarray(feeds[n]), self.program, n)
                B = host.shape[0]
                if B % (d * a):
                    raise ShardedTrainError(
                        f"feed {n!r} batch {B} is not divisible by "
                        f"dp*accum_steps = {d * a}")
                host = host.reshape((a, d, B // (d * a)) + host.shape[1:])
            else:
                stack = np.stack([_coerce_host(np.asarray(fd[n]),
                                               self.program, n)
                                  for fd in feeds])
                B = stack.shape[1]
                if B % (d * a):
                    raise ShardedTrainError(
                        f"feed {n!r} batch {B} is not divisible by "
                        f"dp*accum_steps = {d * a}")
                host = stack.reshape((k, a, d, B // (d * a))
                                     + stack.shape[2:])
            t_h2d = time.monotonic()
            with tr.span("train/h2d", cat="train", feed=n):
                if self.mesh is not None:
                    axes = (None, "dp") if invariant else (None, None, "dp")
                    out[n] = jax.device_put(host, self._spec(*axes))
                else:
                    out[n] = jax.device_put(host, self.exe._device)
            if acct.enabled:
                acct.account("h2d", t_h2d, time.monotonic() - t_h2d)
            sig.append((n, tuple(host.shape), str(host.dtype)))
        return out, tuple(sig)

    # -- compilation --------------------------------------------------------
    def _compile_window(self, feed_names, fetch_names, invariant, k,
                        use_mesh: bool):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..core.executor import BlockProgramBuilder
        from ..core.registry import ExecContext, generic_grad_fwd_instances
        from ._compat import shard_map

        split = self.split
        block = self.program.blocks[split.block_idx]
        grad_ops = block.ops[:split.split_idx]
        update_ops = block.ops[split.split_idx:]
        builder = BlockProgramBuilder(self.program)
        wanted = generic_grad_fwd_instances(block)
        grad_of = dict(zip(split.param_names, split.grad_names))
        layout = dict(self._layout)
        dp, accum, zero2 = self.dp, self.accum_steps, self.zero_stage == 2
        amp = self.amp
        denom = float(dp * accum)

        def run_ops(ops, env, key):
            ctx = ExecContext(key=key, amp=amp)
            ctx.block_runner = builder
            ctx.vjp_wanted_types |= wanted
            for op in ops:
                builder.run_op(op, env, ctx)
            return env

        def flatpad(x, padded):
            flat = jnp.reshape(x, (-1,))
            if padded > flat.shape[0]:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((padded - flat.shape[0],), flat.dtype)])
            return flat

        def scatter(flat):
            if not use_mesh:
                return flat
            return jax.lax.psum_scatter(flat, "dp", scatter_dimension=0,
                                        tiled=True)

        def rank_fn(feed_local, readonly, params, shards, scalars, keys):
            r = jax.lax.axis_index("dp") if use_mesh else 0

            def opt_step(carry, xs):
                params, shards, scalars = carry
                feed_step, keys_step = xs

                def micro(acc, mxs):
                    feed_m, key_m = mxs
                    env = {}
                    env.update(readonly)
                    env.update(scalars)
                    env.update(params)
                    env.update(feed_m)
                    run_ops(grad_ops, env, key_m)
                    fetches = []
                    for n in fetch_names:
                        if n not in env:
                            raise KeyError(
                                f"fetch var {n!r} is not produced by the "
                                f"grad segment (fetching optimizer-segment "
                                f"outputs is not supported under ZeRO)")
                        fetches.append(env[n])
                    extras = {n: env[n] for n in split.extra_names
                              if n in env}
                    nxt = {}
                    for p in split.param_names:
                        g = jnp.asarray(env[grad_of[p]], jnp.float32)
                        if zero2:
                            g = scatter(flatpad(g, layout[p][2]))
                        nxt[p] = acc[p] + g
                    return nxt, (fetches, extras)

                acc0 = {}
                for p in split.param_names:
                    shape, nelem, padded, shard, _pd = layout[p]
                    if zero2:
                        # the 1/dp grad shard IS the accumulation buffer
                        n0 = shard if use_mesh else padded
                        acc0[p] = jnp.zeros((n0,), jnp.float32)
                    else:
                        acc0[p] = jnp.zeros(shape, jnp.float32)
                acc, (fetch_stack, extras_stack) = jax.lax.scan(
                    micro, acc0, (feed_step, keys_step))
                extras = jax.tree.map(lambda x: x[-1], extras_stack)

                env = {}
                env.update(readonly)
                env.update(extras)
                env.update(scalars)
                for p in split.param_names:
                    shape, nelem, padded, shard, _pd = layout[p]
                    if zero2:
                        gshard = acc[p] / denom
                    else:
                        gshard = scatter(flatpad(acc[p], padded)) / denom
                    pflat = flatpad(params[p], padded)
                    if use_mesh:
                        pshard = jax.lax.dynamic_slice(
                            pflat, (r * shard,), (shard,))
                    else:
                        pshard = pflat
                    env[p] = pshard
                    env[grad_of[p]] = gshard.astype(params[p].dtype)
                for a_n in split.sharded_acc_names:
                    env[a_n] = shards[a_n]
                run_ops(update_ops, env, None)
                new_params = {}
                for p in split.param_names:
                    shape, nelem, padded, shard, _pd = layout[p]
                    if use_mesh:
                        full = jax.lax.all_gather(env[p], "dp", tiled=True)
                    else:
                        full = env[p]
                    new_params[p] = full[:nelem].reshape(shape)
                new_shards = {a_n: env[a_n]
                              for a_n in split.sharded_acc_names}
                new_scalars = {s: env[s]
                               for s in split.scalar_state_names}
                return (new_params, new_shards, new_scalars), \
                    (fetch_stack, extras_stack)

            if invariant:
                def body(carry, keys_step):
                    return opt_step(carry, (feed_local, keys_step))
                carry, (ys, _ex) = jax.lax.scan(
                    body, (params, shards, scalars), keys)
            else:
                carry, (ys, _ex) = jax.lax.scan(
                    opt_step, (params, shards, scalars),
                    (feed_local, keys))
            new_params, new_shards, new_scalars = carry
            # fetches: [k, accum, ...] per rank -> expose the dp axis
            ys = [jnp.expand_dims(y, 2) for y in ys]
            return ys, new_params, new_shards, new_scalars

        if not use_mesh:
            def window(feed_vals, readonly, params, shards, scalars, keys):
                feed_local = {n: (feed_vals[n][:, :, 0] if not invariant
                                  else feed_vals[n][:, 0])
                              for n in feed_names}
                return rank_fn(feed_local, readonly, params, shards,
                               scalars, keys)

            return jax.jit(window, donate_argnums=(2, 3, 4))

        feed_axis = P(None, "dp") if invariant else P(None, None, "dp")

        def ranked(feed_vals, readonly, params, shards, scalars, keys):
            # shard_map hands each rank a size-1 slice along the dp dim;
            # squeeze it so the rank sees [k?, accum, b_loc, ...]
            ax = 1 if invariant else 2
            local = {n: jnp.squeeze(v, axis=ax)
                     for n, v in feed_vals.items()}
            return rank_fn(local, readonly, params, shards, scalars, keys)

        def window(feed_vals, readonly, params, shards, scalars, keys):
            in_specs = (
                {n: feed_axis for n in feed_names},
                jax.tree.map(lambda _: P(), readonly),
                jax.tree.map(lambda _: P(), params),
                jax.tree.map(lambda _: P("dp"), shards),
                jax.tree.map(lambda _: P(), scalars),
                P(),
            )
            out_specs = (
                [P(None, None, "dp")] * len(fetch_names),
                jax.tree.map(lambda _: P(), params),
                jax.tree.map(lambda _: P("dp"), shards),
                jax.tree.map(lambda _: P(), scalars),
            )
            fn = shard_map(ranked, mesh=self.mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
            return fn(feed_vals, readonly, params, shards, scalars, keys)

        return jax.jit(window, donate_argnums=(2, 3, 4))

    # -- introspection ------------------------------------------------------
    def lowered_text(self, feed, k: int = 1,
                     fetch_list: Optional[Sequence] = None,
                     scope=None) -> str:
        """Compiled-HLO text of the window program for ``feed`` — the
        collective-contract instrument (``measured_collectives``)."""
        import jax

        from ..core.executor import global_scope

        scope = scope if scope is not None else global_scope()
        fetch_names = [f if isinstance(f, str) else f.name
                       for f in (fetch_list or [])]
        self._prepare_state(scope)
        from ..obs.goodput import get_accountant

        feed_names = tuple(sorted(feed))
        feed_vals, _sig = self._place_feeds(feed, True, feed_names, k,
                                            get_accountant())
        readonly = {n: scope.get(n) for n in self._readonly_names()}
        params = {p: scope.get(p) for p in self.split.param_names}
        shards = {a: scope.get(a) for a in self.split.sharded_acc_names}
        scalars = {s: scope.get(s)
                   for s in self.split.scalar_state_names}
        import jax.numpy as jnp

        keys = jnp.zeros((k, self.accum_steps, 2), jnp.uint32)
        fn = self._compile_window(feed_names, fetch_names, True, k,
                                  self.mesh is not None)
        lowered = fn.lower(feed_vals, readonly, params, shards, scalars,
                           keys)
        try:
            return lowered.compile().as_text()
        except Exception:
            return lowered.as_text()

    def measured_collectives(self, feed, k: int = 1,
                             fetch_list: Optional[Sequence] = None,
                             scope=None) -> Dict[str, int]:
        """Count the collective ops XLA actually compiled into the
        window (reduce-scatter may legally lower as
        all-reduce+dynamic-slice on backends without a native kernel —
        both spellings count toward the reduce half)."""
        text = self.lowered_text(feed, k=k, fetch_list=fetch_list,
                                 scope=scope)
        return {
            "reduce_scatter": text.count("reduce-scatter("),
            "all_reduce": text.count("all-reduce("),
            "all_gather": text.count("all-gather("),
        }

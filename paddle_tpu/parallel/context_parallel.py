"""Sequence/context parallelism: ring attention over the 'sp' mesh axis.

The reference has NO long-context machinery (SURVEY.md §5.7) — this is the
TPU-native capability that replaces it at scale: shard the sequence dim over
the mesh's 'sp' axis and compute exact attention by rotating K/V blocks
around the ring with ``lax.ppermute`` while accumulating a numerically-stable
online softmax (flash-attention style log-sum-exp merging). Compute on the
current block overlaps with the ICI transfer of the next; memory per device
is O(T/sp). Gradients flow through ppermute, so jax.grad of the sharded
function is the ring-attention backward.

Public entry points:
  dense_attention(q, k, v, mask)        — single-device reference
  ring_attention(q, k, v, mesh, axis)   — shard_map'ed exact equivalent
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def dense_attention(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """q,k,v: [B, T, H, D]. Plain softmax attention (the oracle)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _ring_block(q, k, v, scale, q_offset, k_offset, causal):
    """Partial attention of local q against one k/v block with running
    (out, max, denom) statistics."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        qi = q_offset + jnp.arange(tq)[:, None]
        ki = k_offset + jnp.arange(tk)[None, :]
        logits = jnp.where(qi >= ki, logits, jnp.finfo(logits.dtype).min)
    m = jnp.max(logits, axis=-1)  # [B, H, Tq]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)  # [B, H, Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, m, l


def _merge(acc, new):
    """Log-sum-exp merge of two partial attention accumulators."""
    o1, m1, l1 = acc
    o2, m2, l2 = new
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    # o carries [B, T, H, D]; stats are [B, H, T] -> align axes
    o = o1 * jnp.moveaxis(a1, 1, 2)[..., None] + o2 * jnp.moveaxis(a2, 1, 2)[..., None]
    l = l1 * a1 + l2 * a2
    return o, m, l


def _merge_normalized(o1, lse1, o2, lse2):
    """Merge two NORMALIZED partial attention results via their LSEs.
    o_i: [B,T,H,D] f32, lse_i: [B,T,H] f32 (-inf = no contributions)."""
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    a1 = jnp.where(jnp.isneginf(lse1), 0.0, jnp.exp(lse1 - m_safe))
    a2 = jnp.where(jnp.isneginf(lse2), 0.0, jnp.exp(lse2 - m_safe))
    denom = jnp.maximum(a1 + a2, 1e-38)
    o = (o1 * a1[..., None] + o2 * a2[..., None]) / denom[..., None]
    lse = jnp.where(a1 + a2 == 0.0, -jnp.inf, m_safe + jnp.log(denom))
    return o, lse


def _flash_ring_local(*, axis, n_shards, causal, sc, interpret):
    """shard_map-local ring attention over the Pallas flash kernel.

    Forward: each ring step runs the flash kernel on the resident K/V block
    (causal on the diagonal block, dense below it, skipped above it) and
    merges the normalized (out, lse) pairs — the O(T^2) logits never
    materialize. Backward (custom_vjp): a second ring pass where the
    rotating (k, v) carry their grad accumulators; each step runs the FA-2
    backward kernels against the GLOBAL lse (so p = exp(s - lse) are the
    exact global probabilities) — dq accumulates locally, dk/dv ride the
    ring home. This is the FlashAttention-2 recipe distributed over ICI.
    """
    from ..ops.pallas_attention import flash_attention_bwd, flash_attention_fwd

    # a plain python float, NOT jnp.float32(-inf): a jax scalar created here
    # is born under whatever trace is active at closure-build time (e.g. the
    # jax.checkpoint trace of the FIRST call) and, captured by blk_skip,
    # leaks into later re-traces as an UnexpectedTracerError (the
    # test_flash_ring_under_remat failure carried since PR 2)
    neg_inf = float("-inf")
    perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]

    def blk_diag(args):
        q, k, v = args
        o, l = flash_attention_fwd(q, k, v, causal=True, scale=sc,
                                   return_lse=True, interpret=interpret)
        return o, l

    def blk_full(args):
        q, k, v = args
        o, l = flash_attention_fwd(q, k, v, causal=False, scale=sc,
                                   return_lse=True, interpret=interpret)
        return o, l

    def blk_skip(args):
        q, _, _ = args
        return jnp.zeros_like(q), jnp.full(q.shape[:3], neg_inf, jnp.float32)

    def ring_fwd(q, k, v):
        idx = lax.axis_index(axis)
        o0 = jnp.zeros(q.shape, jnp.float32)
        l0 = jnp.full(q.shape[:3], neg_inf, jnp.float32)

        def body(i, carry):
            (o, l), (k_i, v_i) = carry
            src = (idx + i) % n_shards
            if causal:
                o_n, l_n = lax.cond(
                    src == idx, blk_diag,
                    lambda a: lax.cond(src < idx, blk_full, blk_skip, a),
                    (q, k_i, v_i))
            else:
                o_n, l_n = blk_full((q, k_i, v_i))
            o, l = _merge_normalized(o, l, o_n.astype(jnp.float32), l_n)
            k_n = lax.ppermute(k_i, axis, perm)
            v_n = lax.ppermute(v_i, axis, perm)
            return (o, l), (k_n, v_n)

        (o, l), _ = lax.fori_loop(0, n_shards, body, ((o0, l0), (k, v)))
        return o.astype(q.dtype), l

    @jax.custom_vjp
    def ring(q, k, v):
        o, _ = ring_fwd(q, k, v)
        return o

    def ring_fwd_rule(q, k, v):
        o, l = ring_fwd(q, k, v)
        return o, (q, k, v, o, l)

    def ring_bwd_rule(res, do):
        q, k, v, out, lse = res
        idx = lax.axis_index(axis)

        def bwd_diag(args):
            k_j, v_j = args
            return flash_attention_bwd(q, k_j, v_j, out, lse, do,
                                       causal=True, scale=sc,
                                       interpret=interpret)

        def bwd_full(args):
            k_j, v_j = args
            return flash_attention_bwd(q, k_j, v_j, out, lse, do,
                                       causal=False, scale=sc,
                                       interpret=interpret)

        def bwd_skip(args):
            k_j, v_j = args
            return jnp.zeros_like(q), jnp.zeros_like(k_j), jnp.zeros_like(v_j)

        def body(i, carry):
            dq, k_j, v_j, dk_j, dv_j = carry
            src = (idx + i) % n_shards
            if causal:
                dq_n, dk_n, dv_n = lax.cond(
                    src == idx, bwd_diag,
                    lambda a: lax.cond(src < idx, bwd_full, bwd_skip, a),
                    (k_j, v_j))
            else:
                dq_n, dk_n, dv_n = bwd_full((k_j, v_j))
            dq = dq + dq_n.astype(jnp.float32)
            dk_j = dk_j + dk_n.astype(jnp.float32)
            dv_j = dv_j + dv_n.astype(jnp.float32)
            # k/v rotate WITH their grad accumulators; after n steps both
            # are home with one contribution from every device
            k_j = lax.ppermute(k_j, axis, perm)
            v_j = lax.ppermute(v_j, axis, perm)
            dk_j = lax.ppermute(dk_j, axis, perm)
            dv_j = lax.ppermute(dv_j, axis, perm)
            return dq, k_j, v_j, dk_j, dv_j

        dq0 = jnp.zeros(q.shape, jnp.float32)
        dq, _, _, dk, dv = lax.fori_loop(
            0, n_shards, body,
            (dq0, k, v, jnp.zeros(k.shape, jnp.float32),
             jnp.zeros(v.shape, jnp.float32)))
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    ring.defvjp(ring_fwd_rule, ring_bwd_rule)
    return ring


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp", causal: bool = False,
                   scale: Optional[float] = None,
                   batch_axis: Optional[str] = None,
                   impl: str = "flash",
                   interpret: Optional[bool] = None):
    """Exact attention with the sequence dim sharded over ``axis``.

    q,k,v: [B, T, H, D] global arrays (or shardings compatible with
    P(batch_axis, axis, None, None)). Returns [B, T, H, D] with the same
    sharding as q. ``impl='flash'`` (default) runs the Pallas flash kernel
    per K/V shard with LSE ring merging — and because the local ring is a
    ``jax.custom_vjp`` (the same remat-safe entry-point pattern as the
    flash_attention op, ops/pallas_attention.py), it composes with
    ``jax.checkpoint``: remat replays the kernel forward as a unit and the
    FA-2 ring backward provides the grads
    (tests/test_distributed.py::test_flash_ring_under_remat). Long context
    + recompute therefore keep the flash memory profile; ``impl='dense'``
    remains as the XLA-composed oracle for debugging.
    ``interpret`` overrides Pallas interpret mode; by default it follows the
    MESH's devices (a CPU mesh on a TPU-default host must interpret).
    """
    if impl not in ("flash", "dense"):
        raise ValueError(f"ring_attention impl must be 'flash' or 'dense', "
                         f"got {impl!r}")
    d = q.shape[-1]
    sc = scale if scale is not None else 1.0 / (d ** 0.5)
    n_shards = mesh.shape[axis]
    t_local = q.shape[1] // n_shards
    spec = P(batch_axis, axis, None, None)

    if impl == "flash":
        if interpret is None:
            interpret = any(d.platform != "tpu"
                            for d in mesh.devices.flat)
        local = _flash_ring_local(axis=axis, n_shards=n_shards,
                                  causal=causal, sc=sc, interpret=interpret)
        fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
        return fn(q, k, v)

    def local_fn(q, k, v):
        # q,k,v: local shards [B, T/sp, H, D]
        idx = lax.axis_index(axis)
        q_off = idx * t_local
        neg = jnp.finfo(q.dtype).min
        o0 = jnp.zeros_like(q)
        m0 = jnp.full(q.shape[:1] + (q.shape[2], q.shape[1]), neg, q.dtype)
        l0 = jnp.zeros_like(m0)
        perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]

        def body(i, carry):
            acc, kv = carry
            k_i, v_i = kv
            # block i currently resident came from shard (idx + i) % n
            src = (idx + i) % n_shards
            o, m, l = _ring_block(q, k_i, v_i, sc, q_off, src * t_local, causal)
            acc = _merge(acc, (o, m, l))
            # rotate k/v around the ring for the next iteration
            k_n = lax.ppermute(k_i, axis, perm)
            v_n = lax.ppermute(v_i, axis, perm)
            return acc, (k_n, v_n)

        (o, m, l), _ = lax.fori_loop(0, n_shards, body, ((o0, m0, l0), (k, v)))
        denom = jnp.moveaxis(l, 1, 2)[..., None]
        return o / jnp.maximum(denom, 1e-20)

    fn = shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)

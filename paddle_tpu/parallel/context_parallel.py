"""Sequence/context parallelism: ring attention over the 'sp' mesh axis.

The reference has NO long-context machinery (SURVEY.md §5.7) — this is the
TPU-native capability that replaces it at scale: shard the sequence dim over
the mesh's 'sp' axis and compute exact attention by rotating K/V blocks
around the ring with ``lax.ppermute`` while accumulating a numerically-stable
online softmax (flash-attention style log-sum-exp merging). Compute on the
current block overlaps with the ICI transfer of the next; memory per device
is O(T/sp). Gradients flow through ppermute, so jax.grad of the sharded
function is the ring-attention backward.

Public entry points:
  dense_attention(q, k, v, mask)        — single-device reference
  ring_attention(q, k, v, mesh, axis)   — shard_map'ed exact equivalent
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def dense_attention(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """q,k,v: [B, T, H, D]. Plain softmax attention (the oracle)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _ring_block(q, k, v, scale, q_offset, k_offset, causal):
    """Partial attention of local q against one k/v block with running
    (out, max, denom) statistics."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        qi = q_offset + jnp.arange(tq)[:, None]
        ki = k_offset + jnp.arange(tk)[None, :]
        logits = jnp.where(qi >= ki, logits, jnp.finfo(logits.dtype).min)
    m = jnp.max(logits, axis=-1)  # [B, H, Tq]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)  # [B, H, Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, m, l


def _merge(acc, new):
    """Log-sum-exp merge of two partial attention accumulators."""
    o1, m1, l1 = acc
    o2, m2, l2 = new
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    # o carries [B, T, H, D]; stats are [B, H, T] -> align axes
    o = o1 * jnp.moveaxis(a1, 1, 2)[..., None] + o2 * jnp.moveaxis(a2, 1, 2)[..., None]
    l = l1 * a1 + l2 * a2
    return o, m, l


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp", causal: bool = False,
                   scale: Optional[float] = None,
                   batch_axis: Optional[str] = None):
    """Exact attention with the sequence dim sharded over ``axis``.

    q,k,v: [B, T, H, D] global arrays (or shardings compatible with
    P(batch_axis, axis, None, None)). Returns [B, T, H, D] with the same
    sharding as q.
    """
    d = q.shape[-1]
    sc = scale if scale is not None else 1.0 / (d ** 0.5)
    n_shards = mesh.shape[axis]
    t_local = q.shape[1] // n_shards
    spec = P(batch_axis, axis, None, None)

    def local_fn(q, k, v):
        # q,k,v: local shards [B, T/sp, H, D]
        idx = lax.axis_index(axis)
        q_off = idx * t_local
        neg = jnp.finfo(q.dtype).min
        o0 = jnp.zeros_like(q)
        m0 = jnp.full(q.shape[:1] + (q.shape[2], q.shape[1]), neg, q.dtype)
        l0 = jnp.zeros_like(m0)
        perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]

        def body(i, carry):
            acc, kv = carry
            k_i, v_i = kv
            # block i currently resident came from shard (idx + i) % n
            src = (idx + i) % n_shards
            o, m, l = _ring_block(q, k_i, v_i, sc, q_off, src * t_local, causal)
            acc = _merge(acc, (o, m, l))
            # rotate k/v around the ring for the next iteration
            k_n = lax.ppermute(k_i, axis, perm)
            v_n = lax.ppermute(v_i, axis, perm)
            return acc, (k_n, v_n)

        (o, m, l), _ = lax.fori_loop(0, n_shards, body, ((o0, m0, l0), (k, v)))
        denom = jnp.moveaxis(l, 1, 2)[..., None]
        return o / jnp.maximum(denom, 1e-20)

    fn = shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)

"""Optimizers: IR passes appending per-parameter update ops.

<- python/paddle/fluid/optimizer.py:36-1105 (SGD, Momentum, Adagrad, Adam,
Adamax, DecayedAdagrad, Adadelta, RMSProp, Ftrl, ModelAverage).

``minimize(loss)`` = append_backward + one update op per parameter, exactly
like the reference. Because the whole block compiles to one XLA program, all
per-parameter update ops fuse into the backward — the TPU analogue of fused
optimizers.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .core.autodiff import append_backward
from .core.ir import Program, Variable, default_startup_program
from .core.types import DataType
from . import unique_name


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name: Optional[str] = None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._name = name
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self._lr_var: Optional[Variable] = None

    # -- learning rate --
    def _create_global_learning_rate(self, program: Program, startup: Program):
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
            return
        if self._lr_var is not None:
            return
        name = unique_name.generate("learning_rate")
        block = program.global_block()
        self._lr_var = block.create_var(
            name, dtype=DataType.FP32, shape=(), persistable=True, stop_gradient=True
        )
        sb = startup.global_block()
        sb.create_var(name, dtype=DataType.FP32, shape=(), persistable=True)
        sb.append_op(
            "fill_constant",
            outputs={"Out": [name]},
            attrs={"shape": [], "value": float(self._learning_rate), "dtype": DataType.FP32},
        )

    def _lr_for_param(self, param: Variable) -> Variable:
        # per-param lr scaling (ParamAttr.learning_rate) is applied by an
        # extra scale op only when != 1.0
        attr = getattr(param, "_param_attr", None)
        scale = attr.learning_rate if attr is not None else 1.0
        if scale == 1.0:
            return self._lr_var
        block = param.block.program.global_block()
        name = unique_name.generate(f"{param.name}.lr")
        out = block.create_var(name, dtype=DataType.FP32, shape=())
        block.append_op(
            "scale", {"X": [self._lr_var.name]}, {"Out": [name]}, {"scale": scale}
        )
        return out

    # -- accumulators --
    def _add_accumulator(
        self,
        name: str,
        param: Variable,
        startup: Program,
        fill_value: float = 0.0,
        shape=None,
    ) -> Variable:
        if self._accumulators.setdefault(name, {}).get(param.name) is not None:
            return self._accumulators[name][param.name]
        block = param.block.program.global_block()
        var_name = unique_name.generate(f"{param.name}_{name}")
        shape = tuple(shape if shape is not None else param.shape)
        var = block.create_var(
            var_name, dtype=param.dtype, shape=shape, persistable=True, stop_gradient=True
        )
        sb = startup.global_block()
        sb.create_var(var_name, dtype=param.dtype, shape=shape, persistable=True)
        sb.append_op(
            "fill_constant",
            outputs={"Out": [var_name]},
            attrs={"shape": list(shape), "value": fill_value, "dtype": param.dtype},
        )
        self._accumulators[name][param.name] = var
        return var

    def _create_accumulators(self, param: Variable, startup: Program):
        pass

    def _append_optimize_op(self, block, param: Variable, grad: Variable):
        raise NotImplementedError

    @staticmethod
    def _grad_ids(block, grad: Variable) -> Optional[Variable]:
        """The SelectedRows companion: lookup_table(is_sparse=True) grads
        come as (rows, ids) with the ids var named ``<grad>@IDS``
        (<- the reference's W@GRAD being VarType SelectedRows)."""
        return block.vars.get(grad.name + "@IDS")

    def _check_sparse_supported(self, block, params_grads):
        """Sparse (rows, ids) grads reach only the optimizers with a
        SelectedRows kernel (sgd/adam/adagrad — matching the reference's
        coverage) and do not compose with regularizers or gradient clip:
        decay/clip of a whole table through row grads would be silently
        wrong, and the reference's pserver path had the same boundary."""
        # a sparse table used MORE THAN ONCE sends its row grads (and even
        # its int ids) through autodiff's rename+sum dedup — elementwise
        # sums of rows belonging to DIFFERENT id sets, silently updating
        # wrong rows. Refuse: SelectedRows grads cannot be summed.
        # walk EVERY block: autodiff's rename+sum dedup can land inside a
        # control-flow sub-block (While/StaticRNN body), and a sparse lookup
        # there must not bypass the guard
        summed = set()
        for blk in block.program.blocks:
            for op in blk.ops:
                if op.type == "sum":
                    for names in op.outputs.values():
                        summed.update(names)
        for p, g in params_grads:
            if self._grad_ids(block, g) is None:
                continue
            if g.name in summed or g.name + "@IDS" in summed:
                raise NotImplementedError(
                    f"param {p.name!r}: an is_sparse embedding table must "
                    f"be looked up exactly once per program (SelectedRows "
                    f"row grads cannot be summed) — drop is_sparse=True or "
                    f"split the table")
            if not isinstance(self, (SGD, Adam, Adagrad)):
                raise NotImplementedError(
                    f"param {p.name!r} has a SelectedRows (is_sparse) "
                    f"gradient but {type(self).__name__} has no sparse "
                    f"kernel — use SGD, Adam, or Adagrad (the reference's "
                    f"SelectedRows coverage), or drop is_sparse=True")
            attr = getattr(p, "_param_attr", None)
            if self.regularization is not None or (
                    attr is not None and attr.regularizer is not None):
                raise NotImplementedError(
                    f"regularization on sparse-grad param {p.name!r} is "
                    f"unsupported (whole-table decay through row grads "
                    f"would be wrong) — drop is_sparse=True or the "
                    f"regularizer")
            if attr is not None and attr.gradient_clip is not None:
                raise NotImplementedError(
                    f"gradient_clip on sparse-grad param {p.name!r} is "
                    f"unsupported — unmerged duplicate rows would be "
                    f"mis-normed; drop is_sparse=True or the clip attr")

    # -- public --
    def minimize(
        self,
        loss: Variable,
        startup_program: Optional[Program] = None,
        parameter_list=None,
        no_grad_set=None,
    ) -> Tuple[List, List[Tuple[Variable, Variable]]]:
        startup = startup_program or default_startup_program()
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        params_grads = [
            (p, g)
            for p, g in params_grads
            if getattr(p, "_param_attr", None) is None or p._param_attr.trainable
        ]
        self._check_sparse_supported(loss.block, params_grads)
        self._apply_regularization(loss.block, params_grads)
        from .clip import append_gradient_clip_ops

        params_grads = append_gradient_clip_ops(loss.block, params_grads)
        program = loss.block.program
        self._create_global_learning_rate(program, startup)
        block = program.global_block()
        for p, g in params_grads:
            self._create_accumulators(p, startup)
        for p, g in params_grads:
            self._append_optimize_op(block, p, g)
        return [], params_grads

    def _apply_regularization(self, block, params_grads):
        from .regularizer import append_regularization_ops

        append_regularization_ops(block, params_grads, self.regularization)


class SGD(Optimizer):
    """<- optimizer.py SGDOptimizer / sgd_op.cc."""

    def _append_optimize_op(self, block, param, grad):
        ins = {"Param": [param], "Grad": [grad],
               "LearningRate": [self._lr_for_param(param)]}
        ids = self._grad_ids(block, grad)
        if ids is not None:
            ins["GradIds"] = [ids]
        block.append_op("sgd", ins, {"ParamOut": [param]})


class Momentum(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, param, startup):
        self._add_accumulator("velocity", param, startup)

    def _append_optimize_op(self, block, param, grad):
        v = self._accumulators["velocity"][param.name]
        block.append_op(
            "momentum",
            {"Param": [param], "Grad": [grad], "Velocity": [v],
             "LearningRate": [self._lr_for_param(param)]},
            {"ParamOut": [param], "VelocityOut": [v]},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, param, startup):
        self._add_accumulator("moment1", param, startup)
        self._add_accumulator("moment2", param, startup)
        self._add_accumulator("beta1_pow", param, startup, fill_value=self._beta1, shape=())
        self._add_accumulator("beta2_pow", param, startup, fill_value=self._beta2, shape=())

    def _append_optimize_op(self, block, param, grad):
        a = self._accumulators
        ins = {
            "Param": [param],
            "Grad": [grad],
            "Moment1": [a["moment1"][param.name]],
            "Moment2": [a["moment2"][param.name]],
            "LearningRate": [self._lr_for_param(param)],
            "Beta1Pow": [a["beta1_pow"][param.name]],
            "Beta2Pow": [a["beta2_pow"][param.name]],
        }
        ids = self._grad_ids(block, grad)
        if ids is not None:  # lazy/sparse Adam over SelectedRows grads
            ins["GradIds"] = [ids]
        block.append_op(
            "adam",
            ins,
            {
                "ParamOut": [param],
                "Moment1Out": [a["moment1"][param.name]],
                "Moment2Out": [a["moment2"][param.name]],
                "Beta1PowOut": [a["beta1_pow"][param.name]],
                "Beta2PowOut": [a["beta2_pow"][param.name]],
            },
            {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, param, startup):
        self._add_accumulator("moment", param, startup)
        self._add_accumulator("inf_norm", param, startup)
        self._add_accumulator("beta1_pow", param, startup, fill_value=self._beta1, shape=())

    def _append_optimize_op(self, block, param, grad):
        a = self._accumulators
        block.append_op(
            "adamax",
            {
                "Param": [param], "Grad": [grad],
                "Moment": [a["moment"][param.name]],
                "InfNorm": [a["inf_norm"][param.name]],
                "LearningRate": [self._lr_for_param(param)],
                "Beta1Pow": [a["beta1_pow"][param.name]],
            },
            {
                "ParamOut": [param],
                "MomentOut": [a["moment"][param.name]],
                "InfNormOut": [a["inf_norm"][param.name]],
            },
            {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )
        # beta1_pow update (reference does this on CPU side of adamax op)
        bp = a["beta1_pow"][param.name]
        block.append_op("scale", {"X": [bp]}, {"Out": [bp]}, {"scale": self._beta1})


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon

    def _create_accumulators(self, param, startup):
        self._add_accumulator("moment", param, startup)

    def _append_optimize_op(self, block, param, grad):
        m = self._accumulators["moment"][param.name]
        ins = {"Param": [param], "Grad": [grad], "Moment": [m],
               "LearningRate": [self._lr_for_param(param)]}
        ids = self._grad_ids(block, grad)
        if ids is not None:
            ins["GradIds"] = [ids]
        block.append_op(
            "adagrad",
            ins,
            {"ParamOut": [param], "MomentOut": [m]},
            {"epsilon": self._epsilon},
        )


class DecayedAdagrad(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, param, startup):
        self._add_accumulator("moment", param, startup)

    def _append_optimize_op(self, block, param, grad):
        m = self._accumulators["moment"][param.name]
        block.append_op(
            "decayed_adagrad",
            {"Param": [param], "Grad": [grad], "Moment": [m],
             "LearningRate": [self._lr_for_param(param)]},
            {"ParamOut": [param], "MomentOut": [m]},
            {"decay": self._decay, "epsilon": self._epsilon},
        )


class Adadelta(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, param, startup):
        self._add_accumulator("avg_squared_grad", param, startup)
        self._add_accumulator("avg_squared_update", param, startup)

    def _append_optimize_op(self, block, param, grad):
        a = self._accumulators
        block.append_op(
            "adadelta",
            {"Param": [param], "Grad": [grad],
             "AvgSquaredGrad": [a["avg_squared_grad"][param.name]],
             "AvgSquaredUpdate": [a["avg_squared_update"][param.name]]},
            {"ParamOut": [param],
             "AvgSquaredGradOut": [a["avg_squared_grad"][param.name]],
             "AvgSquaredUpdateOut": [a["avg_squared_update"][param.name]]},
            {"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon, self._momentum = rho, epsilon, momentum

    def _create_accumulators(self, param, startup):
        self._add_accumulator("mean_square", param, startup)
        self._add_accumulator("momentum", param, startup)

    def _append_optimize_op(self, block, param, grad):
        a = self._accumulators
        block.append_op(
            "rmsprop",
            {"Param": [param], "Grad": [grad],
             "MeanSquare": [a["mean_square"][param.name]],
             "Moment": [a["momentum"][param.name]],
             "LearningRate": [self._lr_for_param(param)]},
            {"ParamOut": [param],
             "MeanSquareOut": [a["mean_square"][param.name]],
             "MomentOut": [a["momentum"][param.name]]},
            {"decay": self._rho, "epsilon": self._epsilon, "momentum": self._momentum},
        )


class Ftrl(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, param, startup):
        self._add_accumulator("squared", param, startup)
        self._add_accumulator("linear", param, startup)

    def _append_optimize_op(self, block, param, grad):
        a = self._accumulators
        block.append_op(
            "ftrl",
            {"Param": [param], "Grad": [grad],
             "SquaredAccumulator": [a["squared"][param.name]],
             "LinearAccumulator": [a["linear"][param.name]],
             "LearningRate": [self._lr_for_param(param)]},
            {"ParamOut": [param],
             "SquaredAccumOut": [a["squared"][param.name]],
             "LinearAccumOut": [a["linear"][param.name]]},
            {"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


# fluid-style aliases
SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdagradOptimizer = Adagrad
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
DecayedAdagradOptimizer = DecayedAdagrad
AdadeltaOptimizer = Adadelta
RMSPropOptimizer = RMSProp
FtrlOptimizer = Ftrl


class ProximalGD(Optimizer):
    """<- optimizer.py ProximalGDOptimizer / proximal_gd_op.cc."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2 = l1, l2

    def _append_optimize_op(self, block, param, grad):
        block.append_op(
            "proximal_gd",
            {"Param": [param], "Grad": [grad],
             "LearningRate": [self._lr_for_param(param)]},
            {"ParamOut": [param]},
            {"l1": self._l1, "l2": self._l2},
        )


class ProximalAdagrad(Optimizer):
    """<- optimizer.py ProximalAdagradOptimizer / proximal_adagrad_op.cc."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2 = l1, l2

    def _create_accumulators(self, param, startup):
        self._add_accumulator("moment", param, startup)

    def _append_optimize_op(self, block, param, grad):
        m = self._accumulators["moment"][param.name]
        block.append_op(
            "proximal_adagrad",
            {"Param": [param], "Grad": [grad], "Moment": [m],
             "LearningRate": [self._lr_for_param(param)]},
            {"ParamOut": [param], "MomentOut": [m]},
            {"l1": self._l1, "l2": self._l2},
        )


class ModelAverage:
    """Sliding average of parameters for evaluation
    (<- optimizer.py:929 ModelAverage + average_accumulates_op.cc).

    Construct AFTER ``optimizer.minimize`` so the accumulate ops land behind
    the updates; during training every step feeds the sum windows. ``apply``
    swaps parameters to their window average (restoring on context exit),
    exactly the reference's usage::

        model_average = fluid.optimizer.ModelAverage(0.15)
        ...
        with model_average.apply(exe, scope):
            evaluate(...)
    """

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, main_program=None,
                 startup_program=None):
        from .core.ir import default_main_program, default_startup_program

        self.avg_window = average_window_rate
        self.min_window = min_average_window
        self.max_window = max_average_window
        program = main_program or default_main_program()
        startup = startup_program or default_startup_program()
        block = program.global_block()
        self._state: List[Tuple[str, Dict[str, str]]] = []
        params = [v for v in program.list_vars()
                  if getattr(v, "_param_attr", None) is not None and v.persistable]
        for p in params:
            names = {}
            for suffix, shape, fill in [
                ("sum_1", p.shape, 0.0), ("sum_2", p.shape, 0.0),
                ("sum_3", p.shape, 0.0), ("num_accumulates", (), 0.0),
                ("old_num_accumulates", (), 0.0), ("num_updates", (), 0.0),
            ]:
                n = unique_name.generate(f"{p.name}.avg_{suffix}")
                dtype = p.dtype if suffix.startswith("sum") else DataType.INT64
                block.create_var(n, dtype=dtype, shape=shape, persistable=True,
                                 stop_gradient=True)
                sb = startup.global_block()
                sb.create_var(n, dtype=dtype, shape=shape, persistable=True)
                sb.append_op("fill_constant", outputs={"Out": [n]},
                             attrs={"shape": list(shape), "value": fill,
                                    "dtype": dtype})
                names[suffix] = n
            block.append_op(
                "average_accumulates",
                {"param": [p.name], "in_sum_1": [names["sum_1"]],
                 "in_sum_2": [names["sum_2"]], "in_sum_3": [names["sum_3"]],
                 "in_num_accumulates": [names["num_accumulates"]],
                 "in_old_num_accumulates": [names["old_num_accumulates"]],
                 "in_num_updates": [names["num_updates"]]},
                {"out_sum_1": [names["sum_1"]], "out_sum_2": [names["sum_2"]],
                 "out_sum_3": [names["sum_3"]],
                 "out_num_accumulates": [names["num_accumulates"]],
                 "out_old_num_accumulates": [names["old_num_accumulates"]],
                 "out_num_updates": [names["num_updates"]]},
                {"average_window": self.avg_window,
                 "min_average_window": self.min_window,
                 "max_average_window": self.max_window},
            )
            self._state.append((p.name, names))
        self._saved: Dict[str, Any] = {}

    def _averaged(self, scope, names, dtype) -> Any:
        import numpy as np

        vals = {k: scope.get(v) for k, v in names.items()}
        missing = [names[k] for k, v in vals.items() if v is None]
        if missing:
            raise RuntimeError(
                f"ModelAverage accumulators missing from scope: {missing}; "
                f"run the startup program (and at least one training step)")
        s = (np.asarray(vals["sum_1"]) + np.asarray(vals["sum_2"])
             + np.asarray(vals["sum_3"]))
        cnt = (int(np.asarray(vals["num_accumulates"]))
               + int(np.asarray(vals["old_num_accumulates"])))
        if cnt == 0:
            raise RuntimeError(
                "ModelAverage.apply() before any training step: the average "
                "window is empty (zero accumulated samples)")
        return (s / cnt).astype(dtype)

    def apply(self, executor=None, scope=None, need_restore: bool = True):
        import contextlib

        import numpy as np

        from .core.executor import global_scope

        scope = scope or global_scope()

        @contextlib.contextmanager
        def guard():
            # compute EVERY average before mutating the scope: a failure on
            # parameter k must not leave parameters 0..k-1 swapped
            averaged = {}
            saved = {}
            for pname, names in self._state:
                cur = scope.get(pname)
                averaged[pname] = self._averaged(scope, names,
                                                 np.asarray(cur).dtype)
                saved[pname] = cur
            self._saved = saved
            for pname, value in averaged.items():
                scope.set(pname, value)
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor, scope)

        return guard()

    def restore(self, executor=None, scope=None):
        from .core.executor import global_scope

        scope = scope or global_scope()
        for pname, value in self._saved.items():
            scope.set(pname, value)
        self._saved = {}


ProximalGDOptimizer = ProximalGD
ProximalAdagradOptimizer = ProximalAdagrad

"""Profiler: host events + device traces (<- python/paddle/fluid/profiler.py
and platform/profiler.{h,cc} / device_tracer CUPTI integration).

The contract is the reference's — annotate regions, collect a per-event
min/max/avg table, dump a timeline a browser can open — re-based on
``jax.profiler``: device-side tracing produces a TensorBoard/perfetto trace
(the Chrome-trace analogue of tools/timeline.py), host-side RecordEvent keeps
the aggregate table that EnableProfiler/DisableProfiler printed.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

import jax

_events: Dict[str, List[float]] = defaultdict(list)
# timestamped records for the timeline tool: (name, start_s, dur_s, tid)
_records: List[tuple] = []
_enabled = False
_trace_dir: Optional[str] = None


class RecordEvent:
    """RAII region annotation (<- platform/profiler.h RecordEvent). Also
    pushes a jax named scope so the region shows up in device traces."""

    def __init__(self, name: str):
        self.name = name
        self._t0 = 0.0
        self._scope = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._scope = jax.named_scope(self.name)
        self._scope.__enter__()
        return self

    def __exit__(self, *exc):
        self._scope.__exit__(*exc)
        tracer = _obs_tracer()
        if _enabled or tracer is not None:
            dur = time.perf_counter() - self._t0
            if _enabled:
                _events[self.name].append(dur)
                _records.append((self.name, self._t0, dur,
                                 threading.get_ident() & 0xFFFF))
            if tracer is not None:
                # re-emit into the obs span tracer so profiler regions and
                # obs spans land in ONE merged Chrome trace (note: profiler
                # events ride perf_counter, obs spans time.monotonic — on
                # Linux both are CLOCK_MONOTONIC, so the lanes line up)
                tracer.add_span(self.name, self._t0, dur, cat="profiler")
        return False


def _obs_tracer():
    """The obs tracer iff live (import kept lazy + failure-proof: the
    profiler must work even if obs is mid-import)."""
    try:
        from .obs import get_tracer
    except Exception:
        return None
    t = get_tracer()
    return t if t.enabled else None


def start_profiler(state: str = "All", trace_dir: Optional[str] = None):
    """<- profiler.py start_profiler. state kept for API parity ('CPU'/'GPU'/
    'All' — device tracing is on whenever trace_dir is given)."""
    global _enabled, _trace_dir
    _enabled = True
    if trace_dir:
        _trace_dir = trace_dir
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key: str = "total", profile_path: Optional[str] = None):
    """<- profiler.py stop_profiler: stop tracing, print/append the table."""
    global _enabled, _trace_dir
    _enabled = False
    if _trace_dir:
        jax.profiler.stop_trace()
        _trace_dir = None
    table = summary(sorted_key)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(table)
    else:
        print(table)


def reset_profiler():
    """<- profiler.py reset_profiler."""
    _events.clear()
    _records.clear()


def dump_profile(path: str):
    """Write the raw timestamped host-event records as JSON — the input of
    tools/timeline.py (the analogue of the reference's profiler.proto file
    consumed by its timeline tool)."""
    with open(path, "w") as f:
        json.dump({"events": [
            {"name": n, "start": t0, "dur": dur, "tid": tid}
            for (n, t0, dur, tid) in _records
        ]}, f)


def summary(sorted_key: str = "total") -> str:
    rows = []
    for name, times in _events.items():
        rows.append((name, len(times), sum(times), min(times), max(times),
                     sum(times) / len(times)))
    key_idx = {"calls": 1, "total": 2, "min": 3, "max": 4, "ave": 5}.get(sorted_key, 2)
    rows.sort(key=lambda r: -r[key_idx])
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Min(s)':>10}"
             f"{'Max(s)':>10}{'Ave(s)':>10}"]
    for r in rows:
        lines.append(f"{r[0]:<40}{r[1]:>8}{r[2]:>12.6f}{r[3]:>10.6f}"
                     f"{r[4]:>10.6f}{r[5]:>10.6f}")
    return "\n".join(lines)


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None, trace_dir: Optional[str] = None):
    """<- profiler.py profiler context manager."""
    start_profiler(state, trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def slope_time(run_step, fetch, warmup: int = 5, iters: int = 50,
               prime: bool = False) -> float:
    """Per-step device seconds via the slope of two pipelined windows.

    Each window issues run_step() n-1 times then one fetch() (a call that
    synchronizes on a fetched value); the slope (t2-t1)/(n2-n1) cancels
    fixed per-window costs — RPC round trips, executable re-uploads —
    which on tunneled backends dwarf the step itself. ``prime=True`` runs
    one discarded window first to absorb idle-link transients. A
    degenerate (non-positive) slope falls back to the large-window mean.
    Shared by bench.py and benchmark/fluid_benchmark.py --slope_timing.
    """
    def window(n):
        t0 = time.perf_counter()
        for _ in range(n - 1):
            run_step()
        fetch()
        return time.perf_counter() - t0

    for _ in range(warmup):
        run_step()
    fetch()
    n2 = max(iters, 10)
    n1 = max(n2 // 5, 2)
    if prime:
        window(n1)
    t1 = window(n1)
    t2 = window(n2)
    step = (t2 - t1) / (n2 - n1)
    if step <= 0:
        step = t2 / n2
    return step


def chained_slope_ms(window, iters: int = 12, reps: int = 3, args=()):
    """Per-call milliseconds of a chained-kernel microbench via the slope
    of a 1x vs 4x window — the kernel-level sibling of ``slope_time``.

    ``window(n)`` must return a jitted callable running ``n`` serialized
    calls and returning a SCALAR that depends on every call (the caller
    builds the data-dependency chain — e.g. scaling an input by
    ``1 + out[0, 0] * 1e-30``, numerically identity but un-hoistable — so
    XLA can neither DCE a call nor lift it out of the loop: the r4 lesson
    where an unused output produced a 425%-"MFU" artifact). The scalar is
    fetched with ``float()`` to close the async dispatch chain (tunneled
    backends return from block_until_ready early). The slope
    ((t_4x - t_1x) / 3n) cancels per-window fixed costs; median of
    ``reps``. Shared by pallas_matmul.measure_dw / autotune and
    tools/probe_fa_gap.py so every kernel A/B uses one methodology."""
    r1, r4 = window(iters), window(4 * iters)
    float(r1(*args))  # compile + warm both windows
    float(r4(*args))
    slopes, big_means = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(r1(*args))
        t1 = time.perf_counter()
        float(r4(*args))
        t2 = time.perf_counter()
        slopes.append(((t2 - t1) - (t1 - t0)) / (3 * iters))
        big_means.append((t2 - t1) / (4 * iters))
    slopes.sort()
    med = slopes[len(slopes) // 2]
    if med <= 0:
        # a jitter burst under the 1x window can make the 4x window time
        # "faster"; a non-positive slope is meaningless and — fed raw into
        # autotune — would trivially pass any adoption margin. Same guard
        # as slope_time: fall back to the large-window mean.
        big_means.sort()
        med = big_means[len(big_means) // 2]
    return med * 1e3

"""Pipelined transformer stack op: the layers-API entry to the 'pp' axis.

<- capability target: the reference's layer-wise model parallelism
(gserver/gradientmachines/ParallelNeuralNetwork.h) re-expressed as GPipe
over a TPU mesh (SURVEY.md §2c 'pp' axis). One IR op carries the WHOLE
stack of S*L homogeneous pre-LN decoder layers with parameters stacked
[S, L, ...]; under a ParallelExecutor whose mesh has a 'pp' axis of size
S the kernel runs parallel/pipeline.py's lax.scan GPipe schedule
(parameters sharded P('pp'), microbatches rotating over ICI), and under a
single device (or pp=1) it runs the stages sequentially — identical math,
so single-device tests pin the pipeline's numerics.

The layer math mirrors models/transformer.py encoder_layer exactly
(pre-LN, flash attention via the custom_vjp entry point, relu FFN) with
the ops/_amp.py dtype policy: bf16 matmul operands under AMP, f32
normalization statistics, f32 master weights cast at point of use.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ._amp import low_precision
from .pallas_attention import flash_attention

_EPS = 1e-5


def _ln(x, scale, bias):
    xf = x.astype(jnp.float32) if low_precision(x.dtype) else x
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.maximum(jnp.mean(xf * xf, axis=-1, keepdims=True)
                      - mean * mean, 0.0)
    y = (xf - mean) * lax.rsqrt(var + _EPS)
    return (y * scale + bias).astype(x.dtype)


def _dot(x, w, amp):
    if amp:
        x = x.astype(jnp.bfloat16)
        w = w.astype(jnp.bfloat16)
    out = jnp.matmul(x, w, preferred_element_type=jnp.float32)
    return out.astype(x.dtype if amp else out.dtype)


# Megatron region boundaries for callers that run jax.vjp INSIDE the
# shard_map body (the 1F1B engine differentiates each stage per
# microbatch). There, psum's transpose rule is psum — which double-counts
# replicated cotangents — so the correct per-rank backward must be spelled
# out: identity-forward/psum-backward entering a column-parallel region,
# psum-forward/identity-backward leaving a row-parallel one. Differentiated
# from OUTSIDE the shard_map (the GPipe path), plain lax.psum is the
# correct spelling and these boundaries would be wrong — hence the
# ``inner_vjp`` switch instead of a blanket replacement.
@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _copy_to_tp(x, axis):
    return x


def _copy_to_tp_fwd(x, axis):
    return x, None


def _copy_to_tp_bwd(axis, _, ct):
    return (lax.psum(ct, axis),)


_copy_to_tp.defvjp(_copy_to_tp_fwd, _copy_to_tp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _reduce_from_tp(x, axis):
    return lax.psum(x, axis)


def _reduce_from_tp_fwd(x, axis):
    return lax.psum(x, axis), None


def _reduce_from_tp_bwd(axis, _, ct):
    return (ct,)


_reduce_from_tp.defvjp(_reduce_from_tp_fwd, _reduce_from_tp_bwd)


def _decoder_layer(p, x, n_heads, causal, amp, tp_axis=None,
                   inner_vjp=False):
    """Pre-LN block: x + MHA(LN(x)); x + FFN(LN(x)). p: single-layer dict.

    ``tp_axis``: when set, the layer runs as one Megatron shard inside a
    shard_map — wq/wk/wv/wup (and bup) hold this device's column slice,
    wo/wdown hold the row slice, and the two row matmuls produce partial
    sums reduced with ``lax.psum`` over the axis BEFORE the residual add /
    output bias, which keeps x and the LN statistics replicated across tp.
    """
    mb, t, d = x.shape
    d_head = d // n_heads
    n_heads_local = p["wq"].shape[-1] // d_head  # n_heads/tp under a shard
    a = _ln(x, p["ln1s"], p["ln1b"])
    if tp_axis is not None and inner_vjp:
        a = _copy_to_tp(a, tp_axis)
    q = _dot(a, p["wq"], amp).reshape(mb, t, n_heads_local, d_head)
    k = _dot(a, p["wk"], amp).reshape(mb, t, n_heads_local, d_head)
    v = _dot(a, p["wv"], amp).reshape(mb, t, n_heads_local, d_head)
    ctx_v = flash_attention(q, k, v, causal, None)
    ctx_v = ctx_v.reshape(mb, t, n_heads_local * d_head)
    attn = _dot(ctx_v, p["wo"], amp)
    if tp_axis is not None:
        attn = (_reduce_from_tp(attn, tp_axis) if inner_vjp
                else lax.psum(attn, tp_axis))
    x = x + attn.astype(x.dtype)
    f = _ln(x, p["ln2s"], p["ln2b"])
    if tp_axis is not None and inner_vjp:
        f = _copy_to_tp(f, tp_axis)
    h = _dot(f, p["wup"], amp) + p["bup"].astype(
        jnp.bfloat16 if amp else p["bup"].dtype)
    h = jax.nn.relu(h)
    f = _dot(h, p["wdown"], amp)
    if tp_axis is not None:
        f = (_reduce_from_tp(f, tp_axis) if inner_vjp
             else lax.psum(f, tp_axis))
    f = f + p["bdown"].astype(jnp.bfloat16 if amp else p["bdown"].dtype)
    return x + f.astype(x.dtype)


_SLOTS = ("LN1Scale", "LN1Bias", "WQ", "WK", "WV", "WO",
          "LN2Scale", "LN2Bias", "WUp", "BUp", "WDown", "BDown")
_KEYS = ("ln1s", "ln1b", "wq", "wk", "wv", "wo",
         "ln2s", "ln2b", "wup", "bup", "wdown", "bdown")


@register_op("pipelined_transformer_stack",
             inputs=("X",) + _SLOTS, outputs=("Out",),
             diff_inputs=("X",) + _SLOTS)
def pipelined_transformer_stack(ctx, ins, attrs):
    x = ins["X"][0]
    params = {k: ins[slot][0] for k, slot in zip(_KEYS, _SLOTS)}
    n_heads = int(attrs["n_heads"])
    causal = bool(attrs.get("causal", True))
    microbatches = int(attrs.get("microbatches", 4))
    remat = bool(attrs.get("remat", False))
    amp = bool(getattr(ctx, "amp", False))
    n_stages = params["wq"].shape[0]
    layers_per_stage = params["wq"].shape[1]

    mesh = getattr(ctx, "mesh", None)
    has_pp = (mesh is not None and "pp" in mesh.axis_names
              and mesh.shape["pp"] > 1)
    # tensor parallelism composes INSIDE the pipeline's shard_map: when the
    # model was BUILT with tp_shard and the mesh carries a 'tp' axis, the
    # stage weights are Megatron-sharded over it and the stage function
    # does the matching psums (shard_map is manual over every mesh axis,
    # so GSPMD cannot do it for us there). A stack built without tp_shard
    # ignores the mesh's tp axis — weights stay replicated over it.
    tp_axis = ("tp" if bool(attrs.get("tp_shard", False)) and has_pp
               and "tp" in mesh.axis_names and mesh.shape["tp"] > 1
               else None)
    if tp_axis is not None:
        tp = mesh.shape["tp"]
        if n_heads % tp or params["wup"].shape[-1] % tp:
            raise ValueError(
                f"n_heads {n_heads} and d_ff {params['wup'].shape[-1]} "
                f"must be divisible by the tp axis size {tp}")

    def stage_fn(p_stage, x_mb):
        # p_stage leaves: [L, ...]
        out = x_mb
        for l in range(layers_per_stage):
            p_l = {k: v[l] for k, v in p_stage.items()}
            out = _decoder_layer(p_l, out, n_heads, causal, amp,
                                 tp_axis=tp_axis)
        return out

    if has_pp and mesh.shape["pp"] != n_stages:
        raise ValueError(
            f"pipelined_transformer_stack built with {n_stages} stages but "
            f"the mesh 'pp' axis has size {mesh.shape['pp']}; a silent "
            f"sequential fallback would all-gather the stage weights every "
            f"step — rebuild the model with pp_stages={mesh.shape['pp']} "
            f"or resize the mesh")
    if has_pp and n_stages > 1:
        from jax.sharding import PartitionSpec as P

        from ..parallel.pipeline import gpipe

        if tp_axis is not None:
            # Megatron layout per stage: column-sharded wq/wk/wv/wup (+bup),
            # row-sharded wo/wdown; LN params and bdown replicated over tp
            col = P("pp", None, None, tp_axis)
            row = P("pp", None, tp_axis, None)
            rep2 = P("pp", None, None)
            pspecs = {"ln1s": rep2, "ln1b": rep2, "wq": col, "wk": col,
                      "wv": col, "wo": row, "ln2s": rep2, "ln2b": rep2,
                      "wup": col, "bup": P("pp", None, tp_axis),
                      "wdown": row, "bdown": rep2}
        else:
            pspecs = None
        out = gpipe(stage_fn, params, x, mesh, axis="pp",
                    microbatches=microbatches, remat=remat,
                    batch_axes=("dp",), param_specs=pspecs)
    else:
        # sequential semantics (single device / pp=1): same math, so this
        # path is the numerical oracle for the pipelined one
        out = x
        body = jax.checkpoint(stage_fn) if remat else stage_fn
        for s in range(n_stages):
            out = body({k: v[s] for k, v in params.items()}, out)
    return {"Out": [out]}

"""Pipelined transformer stack op: the layers-API entry to the 'pp' axis.

<- capability target: the reference's layer-wise model parallelism
(gserver/gradientmachines/ParallelNeuralNetwork.h) re-expressed as GPipe
over a TPU mesh (SURVEY.md §2c 'pp' axis). One IR op carries the WHOLE
stack of S*L homogeneous pre-LN decoder layers with parameters stacked
[S, L, ...]; under a ParallelExecutor whose mesh has a 'pp' axis of size
S the kernel runs parallel/pipeline.py's lax.scan GPipe schedule
(parameters sharded P('pp'), microbatches rotating over ICI), and under a
single device (or pp=1) it runs the stages sequentially — identical math,
so single-device tests pin the pipeline's numerics.

The layer math mirrors models/transformer.py encoder_layer exactly
(pre-LN, flash attention via the custom_vjp entry point, relu FFN) with
the ops/_amp.py dtype policy: bf16 matmul operands under AMP, f32
normalization statistics, f32 master weights cast at point of use.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ._amp import low_precision
from .pallas_attention import flash_attention

_EPS = 1e-5


def _ln(x, scale, bias):
    xf = x.astype(jnp.float32) if low_precision(x.dtype) else x
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.maximum(jnp.mean(xf * xf, axis=-1, keepdims=True)
                      - mean * mean, 0.0)
    y = (xf - mean) * lax.rsqrt(var + _EPS)
    return (y * scale + bias).astype(x.dtype)


def _dot(x, w, amp):
    if amp:
        x = x.astype(jnp.bfloat16)
        w = w.astype(jnp.bfloat16)
    out = jnp.matmul(x, w, preferred_element_type=jnp.float32)
    return out.astype(x.dtype if amp else out.dtype)


def _decoder_layer(p, x, n_heads, causal, amp):
    """Pre-LN block: x + MHA(LN(x)); x + FFN(LN(x)). p: single-layer dict."""
    mb, t, d = x.shape
    d_head = d // n_heads
    a = _ln(x, p["ln1s"], p["ln1b"])
    q = _dot(a, p["wq"], amp).reshape(mb, t, n_heads, d_head)
    k = _dot(a, p["wk"], amp).reshape(mb, t, n_heads, d_head)
    v = _dot(a, p["wv"], amp).reshape(mb, t, n_heads, d_head)
    ctx_v = flash_attention(q, k, v, causal, None)
    ctx_v = ctx_v.reshape(mb, t, d)
    x = x + _dot(ctx_v, p["wo"], amp).astype(x.dtype)
    f = _ln(x, p["ln2s"], p["ln2b"])
    h = _dot(f, p["wup"], amp) + p["bup"].astype(
        jnp.bfloat16 if amp else p["bup"].dtype)
    h = jax.nn.relu(h)
    f = _dot(h, p["wdown"], amp) + p["bdown"].astype(
        jnp.bfloat16 if amp else p["bdown"].dtype)
    return x + f.astype(x.dtype)


_SLOTS = ("LN1Scale", "LN1Bias", "WQ", "WK", "WV", "WO",
          "LN2Scale", "LN2Bias", "WUp", "BUp", "WDown", "BDown")
_KEYS = ("ln1s", "ln1b", "wq", "wk", "wv", "wo",
         "ln2s", "ln2b", "wup", "bup", "wdown", "bdown")


@register_op("pipelined_transformer_stack",
             inputs=("X",) + _SLOTS, outputs=("Out",),
             diff_inputs=("X",) + _SLOTS)
def pipelined_transformer_stack(ctx, ins, attrs):
    x = ins["X"][0]
    params = {k: ins[slot][0] for k, slot in zip(_KEYS, _SLOTS)}
    n_heads = int(attrs["n_heads"])
    causal = bool(attrs.get("causal", True))
    microbatches = int(attrs.get("microbatches", 4))
    remat = bool(attrs.get("remat", False))
    amp = bool(getattr(ctx, "amp", False))
    n_stages = params["wq"].shape[0]
    layers_per_stage = params["wq"].shape[1]

    def stage_fn(p_stage, x_mb):
        # p_stage leaves: [L, ...]
        out = x_mb
        for l in range(layers_per_stage):
            p_l = {k: v[l] for k, v in p_stage.items()}
            out = _decoder_layer(p_l, out, n_heads, causal, amp)
        return out

    mesh = getattr(ctx, "mesh", None)
    has_pp = (mesh is not None and "pp" in mesh.axis_names
              and mesh.shape["pp"] > 1)
    if has_pp and mesh.shape["pp"] != n_stages:
        raise ValueError(
            f"pipelined_transformer_stack built with {n_stages} stages but "
            f"the mesh 'pp' axis has size {mesh.shape['pp']}; a silent "
            f"sequential fallback would all-gather the stage weights every "
            f"step — rebuild the model with pp_stages={mesh.shape['pp']} "
            f"or resize the mesh")
    if has_pp and n_stages > 1:
        from ..parallel.pipeline import gpipe

        out = gpipe(stage_fn, params, x, mesh, axis="pp",
                    microbatches=microbatches, remat=remat,
                    batch_axes=("dp",))
    else:
        # sequential semantics (single device / pp=1): same math, so this
        # path is the numerical oracle for the pipelined one
        out = x
        body = jax.checkpoint(stage_fn) if remat else stage_fn
        for s in range(n_stages):
            out = body({k: v[s] for k, v in params.items()}, out)
    return {"Out": [out]}

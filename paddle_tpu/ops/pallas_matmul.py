"""Pallas TPU dW-orientation matmul (the backward-pass weight-grad kernel).

Why this exists (docs/perf.md "Transformer LM round 5"): the transformer
bench's forward and dx matmuls run at 176-180+ TF/s, but the SAME shapes in
the dW orientation — ``dW = X^T @ dOut``, contracting over the batch*time
rows — measure 114-129 TF/s (LM head, [1024, 32000] out with K=8192 rows)
and 146-160 (FFN). That 2-4 ms/step gap is XLA's lowering of the
rows-contracted dot, and the r5/r6 BASELINE bar treated it as "outside what
a framework above XLA controls". This module is the in-scope experiment the
round-5 verdict asked for: a hand-scheduled Pallas kernel that accumulates
``A^T @ B`` directly in MXU-friendly tiles, in the spirit of hand-tuned
kernels beating vendor lowerings (CUDA-L2, arxiv 2512.02551) and high-level
tiling abstractions recovering HPC rates (arxiv 2304.12576).

Two strategies ship, because the mechanism hypothesis has two sides:

* ``direct``  — each grid cell issues ``dot_general`` with BOTH operands
  contracting on dim 0 (the dW orientation) over [bk, bm] x [bk, bn] VMEM
  tiles; Mosaic feeds the MXU from the sublane dim. If XLA's slowness is
  scheduling (tile choice / HBM streaming), this wins.
* ``transpose`` — the "fast-orientation sibling with a cheap fixup": each
  A tile is relayouted [bk, bm] -> [bm, bk] IN VMEM and the cell runs the
  standard [bm, bk] @ [bk, bn] orientation. If Mosaic's dim-0-contraction
  lowering is itself the tax (r4 measured in-kernel ``swapaxes`` at 2.7x
  the HBM fold it replaced — in the attention kernels), this bounds it:
  the relayout touches only a [bk, bm] VMEM tile, never HBM.

Block shapes come from ``plan_blocks``: an exhaustive search over aligned
divisors minimizing HBM traffic (A is re-read once per N-tile, B once per
M-tile) under a VMEM budget — the planner is what makes the head-dW shape
([8192, 1024]^T @ [8192, 32000]) compute-bound (~1.0 GB moved vs the naive
512-tile plan's ~3 GB, against a 2.8 ms MXU floor at 190 TF/s).

Routing is opt-in via ``flags.pallas_dw_matmul`` and goes through a
``jax.custom_vjp`` whose FORWARD is the stock XLA dot (that orientation
already runs at peak) and whose backward computes dX via XLA and dW via the
Pallas kernel. The forward output carries ``checkpoint_name`` so selective
remat policies can keep it (remat-safe, like ops/pallas_attention.py).
Because this session's hot-path adoption is decided by measurement, the
``auto`` mode runs a slope-timed on-chip A/B per shape ONCE per process
(``autotune``) and routes only the shapes where a Pallas strategy beats XLA
by the margin — on a CPU/interpret backend it routes nothing, so the stock
path is byte-identical there.
"""
from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .. import flags
from .pallas_attention import _interpret_default

try:  # pltpu is TPU-plugin-scoped; interpret mode never touches it
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - exotic jax builds
    pltpu = None

# the audited bench dW shapes (m = d_in, n = d_out, k = contracted rows):
# LM head dW, FFN up/down dW, attention projection dW at T=1024 bs8, and
# their longcontext (T=4096, B=1, V=100352) siblings
BENCH_DW_SHAPES = (
    (1024, 32000, 8192),   # head dW: 114-129 TF/s under XLA (perf.md r5)
    (1024, 4096, 8192),    # FFN up dW: 146-160
    (4096, 1024, 8192),    # FFN down dW
    (1024, 1024, 8192),    # q/k/v/out projection dW
)
LC_DW_SHAPES = (
    (1024, 100352, 4096),
    (1024, 4096, 4096),
    (4096, 1024, 4096),
    (1024, 1024, 4096),
)
# the remat-required longcontext bench (B=4 x T=4096 -> K=16384 rows); its
# head runs through the streamed-CE op, so only projection/FFN dWs route
LCR_DW_SHAPES = (
    (1024, 4096, 16384),
    (4096, 1024, 16384),
    (1024, 1024, 16384),
)

# VMEM working-set budget for the planner: block inputs are double-buffered
# by the Pallas pipeline, and the f32 accumulator + output tile are resident.
# ~12 MB of the ~16 MB/core leaves room for Mosaic's own staging.
_VMEM_BUDGET = 12 * 1024 * 1024
_SMALL_SINGLE_BLOCK = 1 << 20  # total elements below which one block is fine


def _aligned_divisors(n, align, cap):
    """Divisors of ``n`` that are multiples of ``align``, capped, descending."""
    out = []
    for b in range(min(n, cap), 0, -align):
        if b % align == 0 and n % b == 0:
            out.append(b)
    return out


def plan_blocks(m, n, k, in_bytes=2, out_bytes=2):
    """(bm, bn, bk) minimizing HBM traffic under the VMEM budget, or None.

    Traffic model: the A operand ([k, m]) is streamed once per N-tile and B
    ([k, n]) once per M-tile, so  bytes = k*m*(n/bn) + k*n*(m/bm) + m*n
    (times element sizes). VMEM holds double-buffered [bk, bm] + [bk, bn]
    input tiles, the f32 [bm, bn] accumulator, and the output tile. All
    dims must split into lane-aligned (x128) divisors — a shape with no
    aligned split (truly ragged) returns None and the caller keeps the XLA
    path, mirroring the ``_fit_block`` contract in pallas_attention."""
    if min(m, n, k) <= 0:
        return None
    if m * k + k * n + m * n <= _SMALL_SINGLE_BLOCK:
        # small operands: one cell, whole arrays (Mosaic pads internally) —
        # the correctness/test regime; eligibility gates keep it off hot paths
        return (m, n, k)
    ranked = _ranked_plans(m, n, k, in_bytes, out_bytes)
    return ranked[0] if ranked else None


def _ranked_plans(m, n, k, in_bytes=2, out_bytes=2):
    """All VMEM-feasible aligned plans sorted by the traffic cost model
    (stable: ties keep the larger-block-first enumeration order, so the
    head of this list IS ``plan_blocks``'s choice)."""
    bms = _aligned_divisors(m, 128, 4096)
    bns = _aligned_divisors(n, 128, 4096)
    bks = _aligned_divisors(k, 128, 2048)
    if not (bms and bns and bks):
        return []
    plans = []
    for bm in bms:
        for bn in bns:
            acc_bytes = 4 * bm * bn + out_bytes * bm * bn
            for bk in bks:
                vmem = 2 * in_bytes * bk * (bm + bn) + acc_bytes
                if vmem > _VMEM_BUDGET:
                    continue
                traffic = in_bytes * (k * m * (n // bn) + k * n * (m // bm))
                # tie-break toward bigger k blocks (fewer grid cells)
                cost = (traffic, (m // bm) * (n // bn) * (k // bk))
                plans.append((cost, (bm, bn, bk)))
    plans.sort(key=lambda cp: cp[0])
    return [p for _c, p in plans]


def plan_candidates(m, n, k, in_bytes=2, out_bytes=2, top=3):
    """The cost model's ``top`` distinct block plans, best first — the
    sweep's search space beyond the planner's single answer (the traffic
    model is a model; `perf_lab.py tune` measures its runners-up too and
    lets the chip vote). Small/ragged shapes return what ``plan_blocks``
    would: one whole-array plan or nothing."""
    if min(m, n, k) <= 0:
        return []
    if m * k + k * n + m * n <= _SMALL_SINGLE_BLOCK:
        return [(m, n, k)]
    return _ranked_plans(m, n, k, in_bytes, out_bytes)[:max(1, int(top))]


def _dw_kernel(a_ref, b_ref, o_ref, acc_ref, *, nk, transpose):
    """One (i, j, k) grid cell: acc[i,j] += A[k,i]^T @ B[k,j].

    The grid's last dim (k) iterates fastest and the output block index
    does not depend on it, so the f32 accumulator lives in VMEM across the
    whole K loop and the bf16 output tile is written exactly once."""
    ki = pl.program_id(2)
    a = a_ref[...]  # [bk, bm] native dtype (bf16 under AMP)
    b = b_ref[...]  # [bk, bn]
    if transpose:
        # fast-orientation sibling: relayout the A tile in VMEM, then the
        # standard (1,),(0,) contraction the MXU pipeline is tuned for
        prod = lax.dot_general(
            jnp.swapaxes(a, 0, 1), b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        # dW orientation on the MXU: contract dim 0 of both operands
        prod = lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)

    @pl.when(ki == 0)
    def _():
        acc_ref[...] = prod

    @pl.when(ki > 0)
    def _():
        acc_ref[...] += prod

    @pl.when(ki == nk - 1)
    def _():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def dw_matmul(a, b, *, strategy="direct", out_dtype=None, blocks=None,
              interpret=None):
    """``A^T @ B`` with f32 accumulation: a [K, M], b [K, N] -> [M, N].

    This is the dW-orientation contraction itself — no input transposes in
    HBM. ``strategy``: 'direct' (dim-0 contraction in-cell) or 'transpose'
    (in-VMEM tile relayout + fast orientation). Falls back to the XLA
    lowering when the planner finds no aligned tiling."""
    if a.ndim != 2 or b.ndim != 2 or a.shape[0] != b.shape[0]:
        raise ValueError(f"dw_matmul wants [K,M]x[K,N], got {a.shape} {b.shape}")
    if strategy not in ("direct", "transpose"):
        raise ValueError(f"unknown dw_matmul strategy {strategy!r}")
    k, m = a.shape
    n = b.shape[1]
    out_dtype = jnp.dtype(out_dtype or a.dtype)
    if interpret is None:
        interpret = _interpret_default()
    in_bytes = jnp.dtype(a.dtype).itemsize
    plan = blocks or plan_blocks(m, n, k, in_bytes, out_dtype.itemsize)
    if plan is None:
        return lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32
                               ).astype(out_dtype)
    bm, bn, bk = plan
    if m % bm or n % bn or k % bk:
        # an explicit blocks= tuple must tile exactly — a truncated grid
        # would silently drop the tail rows' contribution to the grad
        raise ValueError(f"blocks {plan} do not divide operands "
                         f"[{k},{m}]x[{k},{n}]")
    nk = k // bk
    if pltpu is None:  # pragma: no cover - pltpu ships with jax
        return lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32
                               ).astype(out_dtype)
    kernel = functools.partial(_dw_kernel, nk=nk, transpose=(strategy ==
                                                             "transpose"))
    scratch = [pltpu.VMEM((bm, bn), jnp.float32)]
    return pl.pallas_call(
        kernel,
        grid=(m // bm, n // bn, nk),
        in_specs=[
            pl.BlockSpec((bk, bm), lambda i, j, ki: (ki, i)),
            pl.BlockSpec((bk, bn), lambda i, j, ki: (ki, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, ki: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=scratch,
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=in_bytes * (k * m * (n // bn) + k * n * (m // bm))
            + out_dtype.itemsize * m * n,
            transcendentals=0),
        interpret=interpret,
    )(a, b)


# ---------------------------------------------------------------------------
# differentiable entry point: stock-XLA forward, Pallas dW backward
# ---------------------------------------------------------------------------

# counts dot_dw routings in the current process — the opt-out test's witness
# that the flag cleanly restores the stock path (and the probe's sanity line)
route_count = 0


def _fwd_dot(x, y, store):
    pref = jnp.float32 if jnp.issubdtype(jnp.dtype(store), jnp.floating) \
        else None
    return jnp.dot(x, y, preferred_element_type=pref).astype(store)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def dot_dw(x, y, store, strategy):
    """x [R, M] @ y [M, N] whose vjp computes dY with the Pallas dW kernel.

    ``store``: output dtype name (bf16 under AMP — matches the stock path's
    fused store). ``strategy``: dw_matmul strategy for the backward. The
    forward IS the stock XLA dot: that orientation already runs at peak;
    only the rows-contracted weight grad is re-scheduled."""
    return _fwd_dot(x, y, store)


def _dot_dw_fwd(x, y, store, strategy):
    from jax.ad_checkpoint import checkpoint_name

    out = _fwd_dot(x, y, store)
    # named for selective remat (save_only_these_names): policies composed
    # in ops/control_flow.RECOMPUTE_POLICIES can keep the dot output so the
    # segment replay never re-runs it — same recipe as flash_out/flash_lse
    out = checkpoint_name(out, "dw_mm_out")
    return out, (x, y)


def _split_strategy(strategy):
    """The ``dot_dw`` strategy nondiff arg: either a bare strategy name or
    a ``(name, (bm, bn, bk))`` pair carrying a tuned block plan (PR 12 —
    the sweep can adopt a planner runner-up the chip measured faster)."""
    if isinstance(strategy, tuple):
        name, blocks = strategy
        return name, (tuple(int(b) for b in blocks) if blocks else None)
    return strategy, None


def _dot_dw_bwd(store, strategy, res, g):
    x, y = res
    global route_count
    route_count += 1
    # dX: fast orientation ([R, N] x [M, N]^T contracting n) — XLA's own
    # lowering measures 162-180 TF/s on the bench shapes; leave it alone
    dx = lax.dot_general(g, y, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32).astype(x.dtype)
    # dW: the rows-contracted orientation XLA runs at 114-160 TF/s
    name, blocks = _split_strategy(strategy)
    dy = dw_matmul(x, g, strategy=name, out_dtype=y.dtype, blocks=blocks)
    return dx, dy


dot_dw.defvjp(_dot_dw_fwd, _dot_dw_bwd)


# ---------------------------------------------------------------------------
# routing: consulted by the mul/matmul registry kernels
# ---------------------------------------------------------------------------

# shape -> (strategy, blocks|None), filled by autotune() (mode 'auto') —
# (m, n, k) keys in dW terms: m = x columns (d_in), n = y columns (d_out),
# k = rows. Since PR 12 this is a per-process VIEW of the persistent
# TuningDB (paddle_tpu/tune): a warm DB hydrates it with zero on-chip
# re-measurement; only misses are measured, and their verdicts are
# recorded back so the next process (and the next machine the artifact
# travels to) inherits the decision.
_PLAN = {}
_AUTOTUNED = set()

#: on-chip slope measurements performed this process — the warm-DB
#: contract's witness (bench.py's tuner workload asserts it stays flat)
measure_count = 0


def _normalize_plan_value(value):
    """'direct' | ('direct', blocks) | {'strategy':…, 'blocks':…} ->
    (strategy, blocks_tuple_or_None)."""
    if isinstance(value, str):
        name, blocks = value, None
    elif isinstance(value, dict):
        name, blocks = value.get("strategy"), value.get("blocks")
    else:
        name, blocks = value
    if name not in ("direct", "transpose"):
        raise ValueError(f"unknown dw_matmul strategy {name!r}")
    if blocks:
        blocks = tuple(int(b) for b in blocks)
        if len(blocks) != 3 or any(b <= 0 for b in blocks):
            # a malformed plan from a hand-edited DB must refuse HERE, not
            # crash the next trace inside dw_matmul
            raise ValueError(f"dw block plan must be 3 positive ints, "
                             f"got {blocks!r}")
    return name, (blocks or None)


def routed_dot(x2, y2, store):
    """The flag-gated dot for the fc/matmul kernels: returns the dot with a
    Pallas-dW backward when routing applies, else None (caller keeps the
    stock path). x2 [R, M] @ y2 [M, N]."""
    mode = flags.get_flag("pallas_dw_matmul")
    if mode == "off":
        return None
    if x2.ndim != 2 or y2.ndim != 2:
        return None
    if not (jnp.issubdtype(x2.dtype, jnp.floating)
            and jnp.issubdtype(y2.dtype, jnp.floating)):
        return None
    if (jnp.dtype(x2.dtype).itemsize > 4 or jnp.dtype(y2.dtype).itemsize > 4):
        # f64 programs (x64 mode) keep the stock path: the MXU has no f64
        # and this pipeline accumulates f32 — routing would silently
        # downgrade an f64 dot's accumulation precision
        return None
    r, m = x2.shape
    n = y2.shape[1]
    if mode == "auto":
        plan = _PLAN.get((m, n, r))
        if plan is None:
            return None
        name, blocks = plan
        strategy = (name, blocks) if blocks else name
    elif mode in ("direct", "transpose"):
        if (r < flags.get_flag("pallas_dw_min_k")
                or min(m, n) < flags.get_flag("pallas_dw_min_mn")):
            return None
        if plan_blocks(m, n, r, jnp.dtype(x2.dtype).itemsize) is None:
            return None
        strategy = mode
    else:
        raise ValueError(
            f"pallas_dw_matmul flag must be off/auto/direct/transpose, "
            f"got {mode!r}")
    return dot_dw(x2, y2, str(jnp.dtype(store)), strategy)


# ---------------------------------------------------------------------------
# on-chip autotune: the adoption decision is a measurement, not a belief
# ---------------------------------------------------------------------------


def measure_candidates(m, n, k, candidates, dtype=jnp.bfloat16, iters=12,
                       reps=3):
    """Slope-timed ms/call for named dW candidates on one shape, the 'xla'
    baseline always included — via the shared chained-window instrument
    (profiler.chained_slope_ms). ``candidates``: {name: (strategy,
    blocks-or-None)}. Shared by ``autotune`` (the two stock candidates)
    and the `perf_lab.py tune` sweep (strategy × ranked block plans).

    Serialization: each iteration scales A by (1 + out[0,0]*1e-30) —
    numerically identity in bf16 but a real data dependency, so XLA can
    neither DCE a call nor hoist the loop-invariant dot (the failure mode
    behind the r4 425%-"MFU" microbench artifact)."""
    global measure_count
    import numpy as np

    from ..profiler import chained_slope_ms

    measure_count += 1
    rng = np.random.RandomState(0)
    a0 = jnp.asarray(rng.randn(k, m), dtype)
    b0 = jnp.asarray(rng.randn(k, n), dtype)

    def window_for(fn):
        def window(n_calls):
            @jax.jit
            def run(a, b):
                def body(_, carry):
                    a, s = carry
                    o = fn(a, b)
                    s = o[0, 0].astype(jnp.float32)
                    a = (a * (1.0 + s * 1e-30).astype(a.dtype))
                    return a, s
                _, s = lax.fori_loop(0, n_calls, body, (a, jnp.float32(0.0)))
                return s
            return run
        return window

    def dw_fn(strategy, blocks):
        return lambda a, b: dw_matmul(a, b, strategy=strategy,
                                      out_dtype=dtype, blocks=blocks)

    fns = {"xla": lambda a, b: lax.dot_general(
        a, b, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dtype)}
    for name, (strategy, blocks) in candidates.items():
        fns[name] = dw_fn(strategy, blocks)
    return {name: chained_slope_ms(window_for(fn), iters=iters, reps=reps,
                                   args=(a0, b0))
            for name, fn in fns.items()}


def measure_dw(m, n, k, dtype=jnp.bfloat16, iters=12, reps=3):
    """Slope-timed ms/call for {xla, direct, transpose} on one dW shape —
    the autotune A/B (and tools/probe_dw_matmul's instrument)."""
    return measure_candidates(
        m, n, k, {"direct": ("direct", None), "transpose": ("transpose",
                                                            None)},
        dtype=dtype, iters=iters, reps=reps)


def autotune(shapes=BENCH_DW_SHAPES, dtype=jnp.bfloat16, margin=0.95,
             verbose=True):
    """Resolve the dW routing per shape, consulting the persistent
    TuningDB FIRST (PR 12): a warm DB answers with zero on-chip
    re-measurement — the adopt/reject verdict is replayed from the stored
    entry. Only misses are measured (ON THE CURRENT BACKEND, the PR-4
    discipline), routed only on a ``margin`` win, and recorded back —
    adopt AND reject — so the ledger of negatives is generated, not
    hand-kept, and the next warm process skips the A/B entirely. Stale
    entries (recorded under another backend/jaxlib) are reported by the
    service and pin the STOCK path without re-measuring — the offline
    sweep (`perf_lab.py tune`) owns re-measurement. On a non-TPU backend
    nothing is ever measured or routed, so the stock path stays
    byte-identical and tests/CPU runs are unaffected.

    Kernel-level microbenches were unstable under tunnel weather in r4, so
    the margin is deliberately wide (a 5% win on a 2.8-4.4 ms call is far
    outside the slope's noise) and the model-level probe
    (tools/probe_dw_matmul.py model) stays the authoritative instrument."""
    from .. import tune

    todo = [s for s in shapes if s not in _AUTOTUNED]
    if not todo:
        return dict(_PLAN)
    interp = _interpret_default()
    dt = str(jnp.dtype(dtype))
    for (m, n, k) in todo:
        _AUTOTUNED.add((m, n, k))
        ent, status = tune.lookup("dw_matmul", (m, n, k), dt)
        if status == "hit":
            # warm DB: replay the memo'd decision, zero re-measurement.
            # Routing still requires a real TPU — an adopted entry on a
            # non-TPU backend keeps the stock path (the PR-4 contract).
            if ent["decision"] == "adopt" and not interp:
                try:
                    name, blocks = _normalize_plan_value(
                        ent.get("config") or {})
                    if blocks and (m % blocks[0] or n % blocks[1]
                                   or k % blocks[2]):
                        # a tuned plan that can't tile THIS shape (DB
                        # edited, or a key collision) keeps the planner's
                        # own blocks rather than trace-crashing dw_matmul
                        blocks = None
                    _PLAN[(m, n, k)] = (name, blocks)
                except (ValueError, TypeError):
                    pass  # a malformed config routes nothing
            if verbose:
                print(f"DW_AUTOTUNE ({m},{n},{k}): tuning-DB "
                      f"{ent['decision']} (margin {ent.get('margin')}) — "
                      f"no re-measurement", file=sys.stderr)
            continue
        if status == "stale":
            # a backend/jaxlib-mismatched entry pins the STOCK path and is
            # never re-measured here: mid-round A/Bs on every environment
            # change are the exact cost the DB exists to remove (and the
            # bench contract forbids them). `perf_lab.py tune` is the
            # re-measurement path; the service already counted the stale.
            if verbose:
                print(f"DW_AUTOTUNE ({m},{n},{k}): tuning-DB entry is "
                      f"STALE (recorded under another backend/jaxlib) — "
                      f"stock XLA path until the offline sweep re-measures",
                      file=sys.stderr)
            continue
        if interp:
            if verbose:
                print(f"DW_AUTOTUNE ({m},{n},{k}): no TPU backend "
                      f"({status}) — stock XLA path", file=sys.stderr)
            continue
        try:
            res = measure_dw(m, n, k, dtype)
        except Exception as e:  # never let the tuner kill a bench round
            if verbose:
                print(f"DW_AUTOTUNE ({m},{n},{k}) failed: {e}",
                      file=sys.stderr)
            continue
        best = min(("direct", "transpose"), key=lambda s: res[s])
        tfs = 2 * m * n * k / 1e9  # GFLOP -> TF/s when divided by ms
        adopted = res[best] < margin * res["xla"]
        if adopted:
            _PLAN[(m, n, k)] = (best, None)
        try:
            tune.record(
                "dw_matmul", (m, n, k), dt,
                decision="adopt" if adopted else "reject",
                config=({"strategy": best, "blocks": None}
                        if adopted else None),
                baseline_ms=res["xla"], best_ms=res[best], slopes=res,
                source="pallas_matmul.autotune",
                save=False)  # batched: one flush after the loop
        except Exception:
            pass  # a broken DB must not kill the round either
        if verbose:
            print(f"DW_AUTOTUNE ({m},{n},{k}): "
                  + " ".join(f"{s}={res[s]:.3f}ms/{tfs / res[s]:.0f}TFs"
                             for s in ("xla", "direct", "transpose"))
                  + f" -> {best if adopted else 'xla'}", file=sys.stderr)
    try:
        tune.flush()  # ONE publish for every verdict measured this call
    except Exception:
        pass
    return dict(_PLAN)


def reset(plan=None):
    """Test/probe hook: drop the plan + autotune memo (optionally install
    an explicit {shape: strategy-or-(strategy, blocks)} plan for flag mode
    'auto'). Does NOT touch the persistent TuningDB — tune.configure/
    tune.reset own that."""
    _PLAN.clear()
    _AUTOTUNED.clear()
    for shape, value in (plan or {}).items():
        _PLAN[shape] = _normalize_plan_value(value)


#: the ISSUE-12 spelling; same hook
reset_autotune = reset

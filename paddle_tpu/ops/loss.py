"""Loss ops (<- paddle/fluid/operators/{cross_entropy,softmax_with_cross_entropy,
sigmoid_cross_entropy_with_logits,huber_loss,smooth_l1_loss,log_loss,hinge_loss,
rank_loss,margin_rank_loss,square_error_cost via squared_l2_distance}_op.cc).

Per-example losses keep the reference's [N, 1] shape so layer code and tests
line up; reductions to scalars happen via the ``mean`` op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.ir import grad_var_name
from ..core.registry import register_op
from ._amp import amp_operand as _amp_operand
from ._amp import f32_compute as _f32_compute
from ._amp import low_precision as _low_precision


def _gather_label(x, label):
    """x[i, label[i]] with label shaped [N] or [N, 1]."""
    if label.ndim == x.ndim:
        label = label.squeeze(-1)
    return jnp.take_along_axis(x, label[..., None].astype(jnp.int32), axis=-1)


@register_op("cross_entropy", inputs=("X", "Label"), outputs=("Y",), diff_inputs=("X",))
def cross_entropy(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    x = _f32_compute(ctx, x)  # AMP: the log and the per-example loss stay f32
    eps = 1e-12
    if attrs.get("soft_label", False):
        y = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        y = -jnp.log(_gather_label(x, label) + eps)
    return {"Y": [y]}


def _swce_grad_maker(op, no_grad_set):
    """Explicit grad: dLogits is rebuilt from the (bf16) logits and the
    Loss forward output — NOT from the Softmax output. The vjp-derived
    grad kept exp(logits - lse) as a residual, which for an LM/NMT head
    materializes the [N*T, V] f32 softmax in HBM purely for the backward
    (trace-measured ~2-3 ms/step of casts+subs on the 30k-vocab seq2seq
    bench, tools/trace_ops.py). With this maker the Softmax output is
    dead unless explicitly consumed, and XLA DCEs its computation."""
    inputs = {
        "Logits": list(op.inputs["Logits"]),
        "Label": list(op.inputs["Label"]),
        "Loss": list(op.outputs["Loss"]),
        "Loss@GRAD": [grad_var_name(n) for n in op.outputs["Loss"]],
        # optional: autodiff nulls this out when nothing consumed Softmax,
        # which is the common (training) case
        "Softmax@GRAD": [grad_var_name(n) for n in op.outputs["Softmax"]],
    }
    return [{
        "type": "softmax_with_cross_entropy_grad",
        "inputs": inputs,
        "outputs": {
            "Logits@GRAD": ["" if n in no_grad_set else grad_var_name(n)
                            for n in op.inputs["Logits"]],
        },
        "attrs": dict(op.attrs),
    }]


@register_op(
    "softmax_with_cross_entropy",
    inputs=("Logits", "Label"),
    outputs=("Softmax", "Loss"),
    diff_inputs=("Logits",),
    grad_maker=_swce_grad_maker,
)
def softmax_with_cross_entropy(ctx, ins, attrs):
    logits, label = ins["Logits"][0], ins["Label"][0]
    # compute on [N*T, V]: 3D [N, T, V] logits give XLA's layout assignment
    # two reasonable row-major choices and the backward ate a 1.5 ms pure
    # layout copy of the 0.5 GB dlogits (hlo_stats, seq2seq bench); in 2D
    # the reshapes are bitcasts and every consumer agrees on {1,0}
    lead = logits.shape[:-1]
    if logits.ndim > 2:
        v = logits.shape[-1]
        logits = logits.reshape(-1, v)
        # soft labels are a distribution over V; hard labels flatten to [N]
        label = (label.reshape(-1, v) if attrs.get("soft_label", False)
                 else label.reshape(-1))
        out = softmax_with_cross_entropy(
            ctx, {"Logits": [logits], "Label": [label]}, attrs)
        return {"Softmax": [out["Softmax"][0].reshape(lead + (-1,))],
                "Loss": [out["Loss"][0].reshape(lead + (1,))]}
    if attrs.get("soft_label", False):
        logits = _f32_compute(ctx, logits)
        log_p = jax.nn.log_softmax(logits, axis=-1)
        loss = -jnp.sum(label * log_p, axis=-1, keepdims=True)
        return {"Softmax": [jnp.exp(log_p)], "Loss": [loss]}
    # hard labels: loss = lse - picked directly — the full log-softmax
    # tensor never materializes (for an LM head that tensor is
    # [N*T, vocab] f32, the biggest buffer in the step); the Softmax
    # output is computed lazily and dead-code-eliminated when unused
    # (the explicit grad above never reads it)
    if getattr(ctx, "amp", False) and _low_precision(logits.dtype):
        # AMP: statistics accumulate f32 WITHOUT materializing an f32 copy
        # of the [N, V] logits. An up-front astype feeds max+sum+gather and
        # XLA materializes it as a standalone convert pass (trace-measured
        # 1.5 ms/step on the 30k-vocab seq2seq bench); structuring each
        # reduction as its own cast->sub->exp chain with a single consumer
        # lets every pass read the bf16 logits directly. max in bf16 is
        # exact (comparisons), exp/log/sum stay f32.
        m = jnp.max(logits, axis=-1, keepdims=True).astype(jnp.float32)
        s = jnp.sum(jnp.exp(logits.astype(jnp.float32) - m),
                    axis=-1, keepdims=True)
        lse = m + jnp.log(s)
        loss = lse - _gather_label(logits, label).astype(jnp.float32)
        softmax = jnp.exp(logits.astype(jnp.float32) - lse)
        return {"Softmax": [softmax], "Loss": [loss]}
    lse = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    loss = lse - _gather_label(logits, label)
    return {"Softmax": [jnp.exp(logits - lse)], "Loss": [loss]}


@register_op(
    "softmax_with_cross_entropy_grad",
    inputs=("Logits", "Label", "Loss", "Loss@GRAD", "Softmax@GRAD"),
    outputs=("Logits@GRAD",),
    no_grad=True,
)
def softmax_with_cross_entropy_grad(ctx, ins, attrs):
    """dLogits = (softmax - target) * dLoss with softmax REBUILT in the
    backward: for hard labels lse = loss + picked_logit (both cheap, no
    [N, V] residual), so exp(logits - lse) fuses into the consuming
    matmul's operand instead of living in HBM between fwd and bwd. The
    rare Softmax-consumer path adds the softmax jacobian term."""
    logits, label = ins["Logits"][0], ins["Label"][0]
    g = ins["Loss@GRAD"][0]
    gs = (ins["Softmax@GRAD"][0]
          if ins.get("Softmax@GRAD") and ins["Softmax@GRAD"][0] is not None
          else None)
    lead = logits.shape[:-1]
    if logits.ndim > 2:  # flatten to 2D — see forward
        v = logits.shape[-1]
        flat = {
            "Logits": [logits.reshape(-1, v)],
            "Label": [label.reshape(-1, v)
                      if attrs.get("soft_label", False)
                      else label.reshape(-1)],
            "Loss": [ins["Loss"][0].reshape(-1, 1)],
            "Loss@GRAD": [None if g is None else g.reshape(-1, 1)],
            "Softmax@GRAD": [None if gs is None else gs.reshape(-1, v)],
        }
        out = softmax_with_cross_entropy_grad(ctx, flat, attrs)
        return {"Logits@GRAD": [out["Logits@GRAD"][0].reshape(
            lead + (v,))]}
    amp_lp = getattr(ctx, "amp", False) and _low_precision(logits.dtype)
    if not amp_lp:
        logits = _f32_compute(ctx, logits)
    soft = attrs.get("soft_label", False)
    if soft or gs is not None:
        lf = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(lf, axis=-1, keepdims=True)
        p = jnp.exp(lf - lse)
    else:
        loss = ins["Loss"][0]
        picked = _gather_label(logits, label).astype(jnp.float32)
        lse = loss + picked  # loss = lse - picked, both [N, 1]
        # single-consumer cast->sub->exp chain: fuses into the dlogits
        # pass reading bf16 logits directly (see forward)
        p = jnp.exp(logits.astype(jnp.float32) - lse)
    if soft:
        # exact derivative for (possibly unnormalized) soft targets:
        # d/dlogits[-sum(label * log_softmax)] = p * sum(label) - label
        target = label
        g_p = jnp.sum(label, axis=-1, keepdims=True)
    else:
        g_p = None
        lbl = label.squeeze(-1) if label.ndim == logits.ndim else label
        target = jax.nn.one_hot(lbl.astype(jnp.int32), logits.shape[-1],
                                dtype=p.dtype)
    # Loss@GRAD can be nulled (Softmax-only consumers, e.g. distillation):
    # a missing cotangent means zero contribution, as the generic vjp did
    p_term = p * g_p if g_p is not None else p
    dlogits = (p_term - target) * g if g is not None else jnp.zeros_like(p)
    if gs is not None:
        # d/dlogits of softmax output: p * (gs - sum(gs * p))
        dlogits = dlogits + p * (gs - jnp.sum(gs * p, axis=-1, keepdims=True))
    return {"Logits@GRAD": [dlogits.astype(ins["Logits"][0].dtype)]}


@register_op(
    "fused_linear_cross_entropy",
    inputs=("X", "W", "Bias", "Label"),
    outputs=("Loss",),
    diff_inputs=("X", "W", "Bias"),
)
def fused_linear_cross_entropy(ctx, ins, attrs):
    """Streamed LM head: softmax cross-entropy of ``X @ W (+ Bias)`` without
    ever materializing the [N, V] logits in HBM. Net-new beyond the
    reference (whose head is fc + softmax_with_cross_entropy): the vocab dim
    is scanned in chunks under an online logsumexp, each chunk wrapped in
    jax.checkpoint so the backward recomputes its logits instead of saving
    them — the flash-attention trick applied to the vocabulary dimension.
    Accumulation is f32; X/W enter the MXU in bf16 under AMP."""
    x, w, label = ins["X"][0], ins["W"][0], ins["Label"][0]
    bias = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    chunk = int(attrs.get("chunk", 4096))
    lead = x.shape[:-1]
    d = x.shape[-1]
    v = w.shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    ids = label.reshape(-1).astype(jnp.int32)

    (x2,) = _amp_operand(ctx, x2)
    chunk = min(chunk, v)
    n_chunks = -(-v // chunk)

    def one_chunk(carry, c_idx):
        m, s, picked = carry
        # slice W per chunk (never a padded/transposed copy of the full
        # weight — at the huge-vocab scale this op exists for, that copy
        # would dwarf the logits saving). The last chunk's start clamps to
        # v - chunk; the validity mask below de-duplicates the overlap.
        start = jnp.minimum(c_idx * chunk, v - chunk)
        (w_i,) = _amp_operand(ctx, lax.dynamic_slice(w, (0, start), (d, chunk)))
        logits = jnp.dot(x2, w_i, preferred_element_type=jnp.float32)
        if bias is not None:
            logits = logits + lax.dynamic_slice(bias, (start,), (chunk,))
        col = start + jnp.arange(chunk)
        valid = col >= c_idx * chunk  # columns this chunk is responsible for
        logits = jnp.where(valid[None, :], logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        # the label's logit, if it falls in this chunk's window
        hi = jnp.minimum((c_idx + 1) * chunk, v)
        in_chunk = (ids >= c_idx * chunk) & (ids < hi)
        local = jnp.clip(ids - start, 0, chunk - 1)
        got = jnp.take_along_axis(logits, local[:, None], axis=-1)[:, 0]
        picked = jnp.where(in_chunk, got, picked)
        return (m_new, s, picked), None

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((n,), jnp.float32)
    p0 = jnp.zeros((n,), jnp.float32)
    (m, s, picked), _ = lax.scan(jax.checkpoint(one_chunk), (m0, s0, p0),
                                 jnp.arange(n_chunks))
    loss = (m + jnp.log(s)) - picked
    return {"Loss": [loss.reshape(lead + (1,))]}


@register_op(
    "sigmoid_cross_entropy_with_logits",
    inputs=("X", "Label"),
    outputs=("Out",),
    diff_inputs=("X",),
)
def sigmoid_cross_entropy_with_logits(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    # max(x,0) - x*z + log(1+exp(-|x|)) — numerically stable form
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": [loss]}


@register_op("square_error_cost", inputs=("X", "Y"), outputs=("Out",))
def square_error_cost(ctx, ins, attrs):
    d = ins["X"][0] - ins["Y"][0]
    return {"Out": [d * d]}


@register_op("huber_loss", inputs=("X", "Y"), outputs=("Out", "Residual"),
             diff_inputs=("X", "Y"))
def huber_loss(ctx, ins, attrs):
    delta = attrs.get("delta", 1.0)
    r = ins["Y"][0] - ins["X"][0]
    absr = jnp.abs(r)
    loss = jnp.where(absr <= delta, 0.5 * r * r, delta * (absr - 0.5 * delta))
    return {"Out": [loss], "Residual": [r]}


@register_op("smooth_l1_loss", inputs=("X", "Y", "InsideWeight", "OutsideWeight"),
             outputs=("Out", "Diff"), diff_inputs=("X", "Y"))
def smooth_l1_loss(ctx, ins, attrs):
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    x, y = ins["X"][0], ins["Y"][0]
    iw = ins["InsideWeight"][0] if ins.get("InsideWeight") and ins["InsideWeight"][0] is not None else 1.0
    ow = ins["OutsideWeight"][0] if ins.get("OutsideWeight") and ins["OutsideWeight"][0] is not None else 1.0
    d = (x - y) * iw
    absd = jnp.abs(d)
    val = jnp.where(absd < 1.0 / s2, 0.5 * d * d * s2, absd - 0.5 / s2)
    out = jnp.sum(val * ow, axis=tuple(range(1, x.ndim)), keepdims=False)[..., None]
    return {"Out": [out], "Diff": [d]}


@register_op("log_loss", inputs=("Predicted", "Labels"), outputs=("Loss",),
             diff_inputs=("Predicted",))
def log_loss(ctx, ins, attrs):
    eps = attrs.get("epsilon", 1e-4)
    p, l = ins["Predicted"][0], ins["Labels"][0]
    return {"Loss": [-l * jnp.log(p + eps) - (1 - l) * jnp.log(1 - p + eps)]}


@register_op("hinge_loss", inputs=("Logits", "Labels"), outputs=("Loss",),
             diff_inputs=("Logits",))
def hinge_loss(ctx, ins, attrs):
    x, y = ins["Logits"][0], ins["Labels"][0]
    return {"Loss": [jnp.maximum(0.0, 1.0 - (2.0 * y - 1.0) * x)]}


@register_op("rank_loss", inputs=("Label", "Left", "Right"), outputs=("Out",),
             diff_inputs=("Left", "Right"))
def rank_loss(ctx, ins, attrs):
    label, left, right = ins["Label"][0], ins["Left"][0], ins["Right"][0]
    d = left - right
    return {"Out": [jnp.log1p(jnp.exp(d)) - label * d]}


@register_op("margin_rank_loss", inputs=("X1", "X2", "Label"),
             outputs=("Out", "Activated"), diff_inputs=("X1", "X2"))
def margin_rank_loss(ctx, ins, attrs):
    m = attrs.get("margin", 0.0)
    x1, x2, label = ins["X1"][0], ins["X2"][0], ins["Label"][0]
    out = jnp.maximum(0.0, -label * (x1 - x2) + m)
    return {"Out": [out], "Activated": [(out > 0).astype(x1.dtype)]}


@register_op("modified_huber_loss", inputs=("X", "Y"),
             outputs=("Out", "IntermediateVal"), diff_inputs=("X",))
def modified_huber_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    z = (2.0 * y - 1.0) * x
    out = jnp.where(z >= 1.0, 0.0, jnp.where(z >= -1.0, (1.0 - z) ** 2, -4.0 * z))
    return {"Out": [out], "IntermediateVal": [z]}


@register_op("kldiv_loss", inputs=("X", "Target"), outputs=("Loss",), diff_inputs=("X",))
def kldiv_loss(ctx, ins, attrs):
    x, t = ins["X"][0], ins["Target"][0]
    loss = jnp.where(t > 0, t * (jnp.log(t) - x), 0.0)
    return {"Loss": [loss]}


@register_op("nce", inputs=("Input", "Label", "Weight", "Bias", "SampleWeight"),
             outputs=("Cost", "SampleLogits", "SampleLabels"),
             diff_inputs=("Input", "Weight", "Bias"), stochastic=True)
def nce(ctx, ins, attrs):
    """Noise-contrastive estimation (<- nce_op.cc), uniform sampler."""
    x, label, w = ins["Input"][0], ins["Label"][0], ins["Weight"][0]
    bias = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    num_classes = attrs["num_total_classes"]
    num_neg = attrs.get("num_neg_samples", 10)
    if label.ndim > 1:
        label = label[:, 0]
    n = x.shape[0]
    neg = jax.random.randint(ctx.next_key(), (n, num_neg), 0, num_classes)
    samples = jnp.concatenate([label[:, None], neg], axis=1)  # [n, 1+num_neg]
    sw = w[samples]  # [n, 1+num_neg, dim]
    logits = jnp.einsum("nd,nkd->nk", x, sw)
    if bias is not None:
        logits = logits + bias[samples]
    labels = jnp.concatenate(
        [jnp.ones((n, 1), x.dtype), jnp.zeros((n, num_neg), x.dtype)], axis=1
    )
    p_noise = 1.0 / num_classes
    # NCE logistic loss with uniform noise distribution
    logit_adj = logits - jnp.log(num_neg * p_noise)
    loss = jnp.maximum(logit_adj, 0) - logit_adj * labels + jnp.log1p(jnp.exp(-jnp.abs(logit_adj)))
    return {
        "Cost": [jnp.sum(loss, axis=1, keepdims=True)],
        "SampleLogits": [logits],
        "SampleLabels": [samples],
    }


def _nce_fixed_samples(x, w, bias, samples, num_neg, num_classes):
    n = x.shape[0]
    logits = jnp.einsum("nd,nkd->nk", x, w[samples])
    if bias is not None:
        logits = logits + bias[samples]
    labels = jnp.concatenate(
        [jnp.ones((n, 1), x.dtype), jnp.zeros((n, samples.shape[1] - 1), x.dtype)], axis=1
    )
    logit_adj = logits - jnp.log(num_neg * (1.0 / num_classes))
    loss = jnp.maximum(logit_adj, 0) - logit_adj * labels + jnp.log1p(
        jnp.exp(-jnp.abs(logit_adj))
    )
    return jnp.sum(loss, axis=1, keepdims=True)


@register_op(
    "nce_grad",
    inputs=("Input", "Label", "Weight", "Bias", "SampleWeight", "Cost",
            "SampleLogits", "SampleLabels", "Cost@GRAD", "SampleLogits@GRAD",
            "SampleLabels@GRAD"),
    outputs=("Input@GRAD", "Weight@GRAD", "Bias@GRAD"),
    no_grad=True,
)
def nce_grad(ctx, ins, attrs):
    """Custom grad: the forward is stochastic (negative sampling), so the
    backward must reuse the *saved* samples rather than letting the generic
    vjp machinery re-draw them."""
    x, w = ins["Input"][0], ins["Weight"][0]
    bias = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    samples = ins["SampleLabels"][0]
    g = ins["Cost@GRAD"][0]
    num_neg = attrs.get("num_neg_samples", 10)
    num_classes = attrs["num_total_classes"]
    diff = (x, w, bias) if bias is not None else (x, w)

    def f(*args):
        if bias is not None:
            xx, ww, bb = args
        else:
            (xx, ww), bb = args, None
        return _nce_fixed_samples(xx, ww, bb, samples, num_neg, num_classes)

    _, vjp = jax.vjp(f, *diff)
    grads = vjp(g)
    out = {"Input@GRAD": [grads[0]], "Weight@GRAD": [grads[1]]}
    if bias is not None:
        out["Bias@GRAD"] = [grads[2]]
    return out


@register_op(
    "hsigmoid",
    inputs=("X", "Label", "W", "Bias"),
    outputs=("Out",),
    diff_inputs=("X", "W", "Bias"),
)
def hsigmoid(ctx, ins, attrs):
    """Hierarchical sigmoid over the default complete binary tree
    (<- hierarchical_sigmoid_op.cc): num_classes leaves, num_classes-1
    internal nodes in heap order (children of p at 2p+1/2p+2, leaf of
    class c at index c + C - 1). Loss = sum over the root->leaf path of
    softplus(-side * (w_node . x + b_node)), side = +1 for a left edge.
    Paths are padded to ceil(log2 C) levels and masked, so shapes stay
    static. W: [C-1, dim]; Bias: [C-1]. The per-class losses form a
    proper distribution: sum_c exp(-loss(c)) == 1."""
    x, label, w = ins["X"][0], ins["Label"][0], ins["W"][0]
    bias = (ins["Bias"][0]
            if ins.get("Bias") and ins["Bias"][0] is not None else None)
    num_classes = int(attrs["num_classes"])
    if label.ndim > 1:
        label = label[..., 0]
    depth = max(1, int(np.ceil(np.log2(num_classes))))
    # walk each label's leaf up to the root, recording (parent, side)
    node = label.astype(jnp.int32) + (num_classes - 1)
    parents, sides, valid = [], [], []
    for _ in range(depth):
        at_root = node == 0
        parent = jnp.where(at_root, 0, (node - 1) // 2)
        # left child of p is 2p+1 (odd index)
        is_left = (node % 2) == 1
        parents.append(jnp.where(at_root, 0, parent))
        sides.append(jnp.where(is_left, 1.0, -1.0))
        valid.append(~at_root)
        node = parent
    path = jnp.stack(parents, axis=-1)          # [N, D]
    side = jnp.stack(sides, axis=-1).astype(jnp.float32)
    mask = jnp.stack(valid, axis=-1).astype(jnp.float32)
    xf = _f32_compute(ctx, x)
    w_sel = w[path].astype(jnp.float32)         # [N, D, dim]
    z = jnp.einsum("nd,nkd->nk", xf, w_sel)
    if bias is not None:
        z = z + bias[path].astype(jnp.float32)
    # -log sigmoid(side*z) = softplus(-side*z), numerically stable form
    a = -side * z
    loss = jnp.sum(mask * (jnp.maximum(a, 0) + jnp.log1p(
        jnp.exp(-jnp.abs(a)))), axis=-1, keepdims=True)
    return {"Out": [loss]}

"""Variable-length sequence ops — the LoDTensor redesign.

The reference packs ragged sequences into one tensor plus host-side offset
tables (LoDTensor, framework/lod_tensor.h) and every sequence op walks the
offsets. That representation is hostile to XLA (dynamic shapes, host
metadata), so here sequences are **dense padded [batch, max_len, ...] with an
explicit per-example Length tensor** (int32 [batch]) — static shapes, masks
instead of offset walks, everything traceable and TPU-tileable.

Ops mirror paddle/fluid/operators/sequence_*.cc semantics on that
representation; the Length input replaces the LoD. Grads come from the
generic vjp machinery (masks are constants w.r.t. differentiation).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _mask(x, length, dtype=None):
    """[N, T, 1...] validity mask from per-example lengths."""
    n, t = x.shape[0], x.shape[1]
    m = jnp.arange(t)[None, :] < length.reshape(-1, 1)
    m = m.reshape((n, t) + (1,) * (x.ndim - 2))
    return m if dtype is None else m.astype(dtype)


@register_op("sequence_pool", inputs=("X", "Length"), outputs=("Out", "MaxIndex"),
             diff_inputs=("X",))
def sequence_pool(ctx, ins, attrs):
    """<- sequence_pool_op.cc / math/sequence_pooling.cc.
    pooltype in {SUM, AVERAGE, SQRT, MAX, LAST, FIRST}."""
    x, length = ins["X"][0], ins["Length"][0]
    ptype = attrs.get("pooltype", "SUM").upper()
    m = _mask(x, length, x.dtype)
    lf = jnp.maximum(length.astype(x.dtype), 1).reshape((-1,) + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(x * m, axis=1) / lf
    elif ptype == "SQRT":
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(lf)
    elif ptype == "MAX":
        neg = jnp.finfo(x.dtype).min
        out = jnp.max(jnp.where(m > 0, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(length - 1, 0).astype(jnp.int32)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype!r}")
    max_index = jnp.argmax(jnp.where(m > 0, x, jnp.finfo(x.dtype).min), axis=1)
    return {"Out": [out], "MaxIndex": [max_index.astype(jnp.int32)]}


@register_op("sequence_softmax", inputs=("X", "Length"), outputs=("Out",),
             diff_inputs=("X",))
def sequence_softmax(ctx, ins, attrs):
    """Softmax over the valid time steps of each sequence
    (<- sequence_softmax_op.cc). X: [N, T] or [N, T, 1]."""
    x, length = ins["X"][0], ins["Length"][0]
    m = _mask(x, length)
    neg = jnp.finfo(x.dtype).min
    logits = jnp.where(m, x, neg)
    out = jax.nn.softmax(logits, axis=1)
    return {"Out": [out * m.astype(x.dtype)]}


@register_op("sequence_expand", inputs=("X", "Y", "Length"), outputs=("Out",),
             diff_inputs=("X",))
def sequence_expand(ctx, ins, attrs):
    """Broadcast per-sequence rows X [N, D] along Y's time dim
    (<- sequence_expand_op.cc at ref_level=0): Out[n, t] = X[n]."""
    x, y = ins["X"][0], ins["Y"][0]
    t = y.shape[1]
    if x.ndim == 2:
        return {"Out": [jnp.broadcast_to(x[:, None, :], (x.shape[0], t, x.shape[1]))]}
    return {"Out": [jnp.broadcast_to(x, (x.shape[0], t) + x.shape[2:])]}


@register_op("sequence_concat", inputs=("X", "Length"), outputs=("Out", "OutLength"),
             diff_inputs=("X",))
def sequence_concat(ctx, ins, attrs):
    """Concatenate two padded sequence batches along time, compacting padding
    (<- sequence_concat_op.cc). Inputs: X = [A, B] with matching Lengths
    [LenA, LenB]."""
    a, b = ins["X"][0], ins["X"][1]
    la, lb = ins["Length"][0], ins["Length"][1]
    n, ta = a.shape[0], a.shape[1]
    tb = b.shape[1]
    tout = ta + tb
    # target position of each b element: la + t
    pos_b = la.reshape(-1, 1) + jnp.arange(tb)[None, :]
    out = jnp.zeros((n, tout) + a.shape[2:], a.dtype)
    out = out.at[:, :ta].set(a * _mask(a, la, a.dtype))
    out = out.at[jnp.arange(n)[:, None], pos_b].add(b * _mask(b, lb, b.dtype))
    return {"Out": [out], "OutLength": [la + lb]}


@register_op("sequence_reshape", inputs=("X", "Length"), outputs=("Out", "OutLength"),
             diff_inputs=("X",))
def sequence_reshape(ctx, ins, attrs):
    """Change feature dim by folding time (<- sequence_reshape_op.cc):
    new_dim attr; T*D must be divisible."""
    x, length = ins["X"][0], ins["Length"][0]
    new_dim = attrs["new_dim"]
    n, t, d = x.shape
    factor = d / new_dim
    out = x.reshape(n, int(t * factor), new_dim)
    return {"Out": [out], "OutLength": [(length * d) // new_dim]}


@register_op("sequence_slice", inputs=("X", "Offset", "Length"), outputs=("Out",),
             diff_inputs=("X",))
def sequence_slice(ctx, ins, attrs):
    """Per-sequence time slice (<- sequence_slice_op.cc): Out[n] =
    X[n, offset[n]:offset[n]+length[n]] left-aligned into a [N, max_len, D]
    buffer."""
    x, offset, length = ins["X"][0], ins["Offset"][0], ins["Length"][0]
    offset = offset.reshape(-1).astype(jnp.int32)
    length = length.reshape(-1).astype(jnp.int32)
    t = x.shape[1]
    idx = offset[:, None] + jnp.arange(t)[None, :]
    idx = jnp.minimum(idx, t - 1)
    gathered = jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    return {"Out": [gathered * _mask(gathered, length, x.dtype)]}


@register_op("sequence_erase", inputs=("X", "Length"), outputs=("Out", "OutLength"),
             no_grad=True)
def sequence_erase(ctx, ins, attrs):
    """Remove tokens in attr 'tokens' from each int sequence, compacting left
    (<- sequence_erase_op.cc). X: [N, T] int."""
    x, length = ins["X"][0], ins["Length"][0]
    tokens = jnp.asarray(attrs.get("tokens", []), x.dtype)
    valid = _mask(x[..., None], length)[..., 0]
    keep = valid & ~jnp.isin(x, tokens)
    new_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    # stable compaction: position = cumsum of keep - 1
    pos = jnp.cumsum(keep, axis=1) - 1
    n, t = x.shape
    out = jnp.zeros_like(x)
    out = out.at[
        jnp.arange(n)[:, None], jnp.where(keep, pos, t - 1)
    ].max(jnp.where(keep, x, 0))
    return {"Out": [out], "OutLength": [new_len]}


@register_op("sequence_conv", inputs=("X", "Filter", "Length"), outputs=("Out",),
             diff_inputs=("X", "Filter"))
def sequence_conv(ctx, ins, attrs):
    """Context-window projection over time (<- sequence_conv_op.cc +
    math/context_project.h): for each t, concat rows
    [t+start, t+start+ctx_len) (zero outside the sequence) then matmul with
    Filter [ctx_len*D, M]."""
    x, w = ins["X"][0], ins["Filter"][0]
    length = ins["Length"][0]
    ctx_len = attrs.get("contextLength", 3)
    start = attrs.get("contextStart", -((ctx_len - 1) // 2) - (ctx_len - 1) % 2)
    n, t, d = x.shape
    xm = x * _mask(x, length, x.dtype)
    cols = []
    for i in range(ctx_len):
        shift = start + i
        if shift < 0:
            shifted = jnp.pad(xm, ((0, 0), (-shift, 0), (0, 0)))[:, :t]
        elif shift > 0:
            shifted = jnp.pad(xm, ((0, 0), (0, shift), (0, 0)))[:, shift:]
        else:
            shifted = xm
        cols.append(shifted)
    ctx_mat = jnp.concatenate(cols, axis=-1)  # [N, T, ctx_len*D]
    out = jnp.einsum("ntc,cm->ntm", ctx_mat, w)
    return {"Out": [out * _mask(out, length, out.dtype)]}


@register_op("sequence_pad", inputs=("X", "Length"), outputs=("Out",), diff_inputs=("X",))
def sequence_pad(ctx, ins, attrs):
    """Zero out positions beyond each length (dense-representation analogue of
    math/sequence_padding.cc)."""
    x, length = ins["X"][0], ins["Length"][0]
    return {"Out": [x * _mask(x, length, x.dtype)]}


@register_op("lod_reset", inputs=("X", "Y"), outputs=("Out",))
def lod_reset(ctx, ins, attrs):
    """Identity on dense data (<- lod_reset_op.cc re-binds LoD; lengths travel
    separately here, so data is unchanged)."""
    return {"Out": [ins["X"][0]]}


@register_op("sequence_reverse", inputs=("X", "Length"), outputs=("Y",),
             diff_inputs=("X",))
def sequence_reverse(ctx, ins, attrs):
    """Reverse each sequence within its valid length."""
    x, length = ins["X"][0], ins["Length"][0]
    t = x.shape[1]
    idx = length.reshape(-1, 1) - 1 - jnp.arange(t)[None, :]
    idx = jnp.where(idx >= 0, idx, jnp.arange(t)[None, :])
    out = jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1)
    return {"Y": [out]}


@register_op("sequence_mask", inputs=("X",), outputs=("Y",), no_grad=True)
def sequence_mask(ctx, ins, attrs):
    """Lengths [N] -> mask [N, maxlen] (<- sequence_mask in later reference
    versions; needed for masked losses over padded sequences)."""
    length = ins["X"][0].reshape(-1)
    maxlen = attrs["maxlen"]
    from ..core.types import DataType

    dt = attrs.get("out_dtype", DataType.FP32)
    dt = DataType.from_any(dt).jnp_dtype
    return {"Y": [(jnp.arange(maxlen)[None, :] < length[:, None]).astype(dt)]}


@register_op("edit_distance", inputs=("Hyps", "Refs", "HypLength", "RefLength"),
             outputs=("Out", "SequenceNum"), no_grad=True)
def edit_distance(ctx, ins, attrs):
    """Levenshtein distance per pair (<- edit_distance_op.cc), computed with a
    scan over the DP table rows (static shapes)."""
    hyp, ref = ins["Hyps"][0], ins["Refs"][0]
    hlen = ins["HypLength"][0].reshape(-1)
    rlen = ins["RefLength"][0].reshape(-1)
    n, th = hyp.shape
    tr = ref.shape[1]

    def per_pair(h, r, hl, rl):
        init = jnp.arange(tr + 1, dtype=jnp.float32)

        def row(prev, i):
            hi = h[i]

            def col(carry, j):
                row_prev = carry
                cost = jnp.where(hi == r[j], 0.0, 1.0)
                val = jnp.minimum(
                    jnp.minimum(row_prev + 1.0, prev[j + 1] + 1.0),
                    prev[j] + cost,
                )
                return val, val

            _, vals = lax.scan(col, i + 1.0, jnp.arange(tr))
            new_row = jnp.concatenate([jnp.array([i + 1.0]), vals])
            return new_row, new_row

        _, rows = lax.scan(row, init, jnp.arange(th))
        table = jnp.concatenate([init[None], rows])  # [th+1, tr+1]
        return table[hl, rl]

    dists = jax.vmap(per_pair)(hyp, ref, hlen, rlen)
    if attrs.get("normalized", False):
        dists = dists / jnp.maximum(rlen.astype(jnp.float32), 1.0)
    return {"Out": [dists.reshape(-1, 1)],
            "SequenceNum": [jnp.asarray(n, jnp.int32)]}


# ---------------------------------------------------------------------------
# LoD structural compat ops. The reference moves variable-length batches
# through LoDRankTable / LoDTensorArray plumbing (lod_rank_table_op.cc,
# lod_tensor_to_array_op.cc, array_to_lod_tensor_op.cc, split_lod_tensor_op.cc,
# merge_lod_tensor_op.cc, reorder_lod_tensor_by_rank_op.cc,
# max_sequence_len_op.cc, shrink_rnn_memory_op.cc). Dense redesign: sequences
# are padded [N, T, ...] + Length [N]; the "rank table" is (Index, Length)
# sorted by descending length; tensor arrays are stacked time-major tensors;
# "shrinking" freezes finished rows by mask instead of changing shapes —
# all static-shape, all XLA-compilable.
# ---------------------------------------------------------------------------


@register_op("lod_rank_table", inputs=("X",), outputs=("Index", "OutLength"),
             no_grad=True)
def lod_rank_table(ctx, ins, attrs):
    length = ins["X"][0].reshape(-1).astype(jnp.int32)
    # stable sort by descending length (reference sorts (idx, len) pairs)
    order = jnp.argsort(-length, stable=True).astype(jnp.int32)
    return {"Index": [order], "OutLength": [length[order]]}


@register_op("max_sequence_len", inputs=("RankTable",), outputs=("Out",),
             no_grad=True)
def max_sequence_len(ctx, ins, attrs):
    return {"Out": [jnp.max(ins["RankTable"][0]).astype(jnp.int32)]}


@register_op("reorder_lod_tensor_by_rank", inputs=("X", "RankTable"),
             outputs=("Out",), diff_inputs=("X",))
def reorder_lod_tensor_by_rank(ctx, ins, attrs):
    x, idx = ins["X"][0], ins["RankTable"][0].reshape(-1).astype(jnp.int32)
    return {"Out": [x[idx]]}


@register_op("lod_tensor_to_array", inputs=("X", "RankTable"), outputs=("Out",),
             diff_inputs=("X",))
def lod_tensor_to_array(ctx, ins, attrs):
    """[N, T, ...] batch-major -> [T, N, ...] time-major array, rows ordered
    longest-first so step t's active rows are a prefix (as in the reference)."""
    x, idx = ins["X"][0], ins["RankTable"][0].reshape(-1).astype(jnp.int32)
    return {"Out": [jnp.moveaxis(x[idx], 0, 1)]}


@register_op("array_to_lod_tensor", inputs=("X", "RankTable"), outputs=("Out",),
             diff_inputs=("X",))
def array_to_lod_tensor(ctx, ins, attrs):
    """Inverse of lod_tensor_to_array: un-transpose and undo the rank reorder."""
    x, idx = ins["X"][0], ins["RankTable"][0].reshape(-1).astype(jnp.int32)
    batch_major = jnp.moveaxis(x, 0, 1)  # [N, T, ...]
    inv = jnp.zeros_like(idx).at[idx].set(jnp.arange(idx.shape[0], dtype=jnp.int32))
    return {"Out": [batch_major[inv]]}


def _row_mask(mask, x):
    m = mask.reshape(mask.shape[0], *([1] * (x.ndim - 1)))
    return m.astype(bool)


@register_op("split_lod_tensor", inputs=("X", "Mask"),
             outputs=("OutTrue", "OutFalse"), diff_inputs=("X",))
def split_lod_tensor(ctx, ins, attrs):
    """Route rows by boolean mask (<- split_lod_tensor_op.cc, the IfElse
    scaffold). Dense: both outputs keep the full static shape; non-selected
    rows are zeroed, and merge_lod_tensor recombines by the same mask."""
    x, mask = ins["X"][0], ins["Mask"][0]
    m = _row_mask(mask, x)
    zero = jnp.zeros_like(x)
    return {"OutTrue": [jnp.where(m, x, zero)],
            "OutFalse": [jnp.where(m, zero, x)]}


@register_op("merge_lod_tensor", inputs=("InTrue", "InFalse", "Mask"),
             outputs=("Out",), diff_inputs=("InTrue", "InFalse"))
def merge_lod_tensor(ctx, ins, attrs):
    t, f, mask = ins["InTrue"][0], ins["InFalse"][0], ins["Mask"][0]
    return {"Out": [jnp.where(_row_mask(mask, t), t, f)]}


@register_op("shrink_rnn_memory", inputs=("X", "RankTable", "I"),
             outputs=("Out",), diff_inputs=("X",))
def shrink_rnn_memory(ctx, ins, attrs):
    """Freeze finished sequences at step I (<- shrink_rnn_memory_op.cc).

    The reference physically truncates the batch to the rows still active
    (rows are sorted longest-first so they form a prefix); dense analogue
    zero-masks rows whose length <= I, keeping the shape static for XLA.
    """
    x = ins["X"][0]
    length = ins["RankTable"][0].reshape(-1)
    i = jnp.reshape(ins["I"][0], ()).astype(length.dtype)
    keep = (length > i).astype(x.dtype)
    return {"Out": [x * keep.reshape(-1, *([1] * (x.ndim - 1)))]}

"""Control-flow ops: while / cond / recurrent (scan) / row_cond / tensor arrays.

<- paddle/fluid/operators/{while_op.cc:35, recurrent_op.cc:222,
conditional_block_op.cc, compare_op.cc, logical_op.cc, is_empty_op.cc,
tensor_array_read_write_op.cc} re-imagined for XLA:

* The reference interprets a sub-BlockDesc per iteration inside a C++ op with
  per-step `StepScopes` (recurrent_op.cc:53). Here the sub-block is *traced
  once* into the body of `lax.while_loop` / `lax.scan` / `lax.cond`, so the
  whole loop is one compiled XLA computation — no per-iteration dispatch, and
  scan bodies are reverse-differentiable (the grad of a `recurrent` op falls
  out of `jax.vjp`, replacing while_grad / recurrent_grad sub-programs and
  `shrink_rnn_memory`-style bookkeeping with masking).
* `while` maps to `lax.while_loop` (forward-only — its role in the reference
  is inference-time generation/beam search; training recurrence uses
  `recurrent`).
* IfElse's row partitioning (split_lod_tensor/merge_lod_tensor) becomes
  `row_cond`: run both branches on the full batch and merge with `where` —
  static shapes, XLA-friendly, mathematically identical.
* LoDTensorArray read/write become fixed-capacity dense buffers updated with
  `lax.dynamic_update_slice` (static shapes under jit).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import ExecContext, register_op

# ---------------------------------------------------------------------------
# compare / logical ops (<- compare_op.cc, logical_op.cc)
# ---------------------------------------------------------------------------

for _name, _fn in [
    ("less_than", jnp.less),
    ("less_equal", jnp.less_equal),
    ("greater_than", jnp.greater),
    ("greater_equal", jnp.greater_equal),
    ("equal", jnp.equal),
    ("not_equal", jnp.not_equal),
]:
    def _make(fn):
        def impl(ctx, ins, attrs):
            return {"Out": [fn(ins["X"][0], ins["Y"][0])]}
        return impl

    register_op(_name, inputs=("X", "Y"), outputs=("Out",), no_grad=True)(_make(_fn))

for _name, _fn in [
    ("logical_and", jnp.logical_and),
    ("logical_or", jnp.logical_or),
    ("logical_xor", jnp.logical_xor),
]:
    def _make2(fn):
        def impl(ctx, ins, attrs):
            return {"Out": [fn(ins["X"][0], ins["Y"][0])]}
        return impl

    register_op(_name, inputs=("X", "Y"), outputs=("Out",), no_grad=True)(_make2(_fn))


@register_op("logical_not", inputs=("X",), outputs=("Out",), no_grad=True)
def logical_not(ctx, ins, attrs):
    return {"Out": [jnp.logical_not(ins["X"][0])]}


@register_op("is_empty", inputs=("X",), outputs=("Out",), no_grad=True)
def is_empty(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.asarray(x.size == 0)]}


def _scalar_bool(x):
    return jnp.reshape(x, ()).astype(bool)


def _sub_ctx(ctx: ExecContext, key) -> ExecContext:
    sub = ExecContext(key=key, block_runner=ctx.block_runner,
                      is_test=ctx.is_test, amp=ctx.amp,
                      mesh=getattr(ctx, "mesh", None))
    # nested blocks inside a recompute segment inherit the remat marker
    # (pallas fallbacks must hold through while/cond bodies too). The base
    # key becomes this body's PER-ITERATION key: a recompute segment inside
    # a scan/while body must draw different randomness each timestep (one
    # shared dropout mask across T steps would silently bias training),
    # while still being stable across the segment's own checkpoint replay.
    sub.in_remat = getattr(ctx, "in_remat", False)
    sub.base_key = key
    return sub


# ---------------------------------------------------------------------------
# while (<- while_op.cc:35)
# ---------------------------------------------------------------------------


@register_op("while", inputs=("Carry", "Hold"), outputs=("Out",), no_grad=True)
def while_op(ctx, ins, attrs):
    """Run ``sub_block`` while the carried condition var is true.

    attrs: sub_block, carry_names (vars the body reads AND writes, including
    the condition), hold_names (read-only closure), cond_name.
    Carry structure (shape/dtype of every carried var) must be loop-invariant
    — the XLA contract, enforced by lax.while_loop.
    """
    carry_names = list(attrs["carry_names"])
    cond_idx = carry_names.index(attrs["cond_name"])
    hold = dict(zip(attrs.get("hold_names", ()), ins.get("Hold", [])))
    runner = ctx.block_runner
    sub_idx = attrs["sub_block"]

    def cond_fn(state):
        carry, _ = state
        return _scalar_bool(carry[cond_idx])

    def body_fn(state):
        carry, key = state
        key, sub = jax.random.split(key)
        env = dict(hold)
        env.update(zip(carry_names, carry))
        runner.run_block(sub_idx, env, _sub_ctx(ctx, sub))
        return tuple(env[n] for n in carry_names), key

    init = (tuple(ins["Carry"]), ctx.next_key())
    carry, _ = lax.while_loop(cond_fn, body_fn, init)
    return {"Out": list(carry)}


# ---------------------------------------------------------------------------
# cond (scalar predicate; <- conditional_block_op.cc + layers.cond)
# ---------------------------------------------------------------------------


@register_op("cond", inputs=("Cond", "Hold"), outputs=("Out",),
             diff_inputs=("Hold",))
def cond_op(ctx, ins, attrs):
    """lax.cond over two sub-blocks; only the selected branch executes.

    attrs: sub_true, sub_false, hold_names, true_out_names, false_out_names.
    Branch outputs pair positionally and must match shape/dtype.
    """
    pred = _scalar_bool(ins["Cond"][0])
    hold_names = list(attrs.get("hold_names", ()))
    hold_vals = tuple(ins.get("Hold", []))
    runner = ctx.block_runner

    def make_branch(sub_idx, out_names):
        out_names = list(out_names)

        def branch(args):
            vals, key = args
            env = dict(zip(hold_names, vals))
            runner.run_block(sub_idx, env, _sub_ctx(ctx, key))
            return tuple(env[n] for n in out_names)

        return branch

    out = lax.cond(
        pred,
        make_branch(attrs["sub_true"], attrs["true_out_names"]),
        make_branch(attrs["sub_false"], attrs["false_out_names"]),
        (hold_vals, ctx.next_key()),
    )
    return {"Out": list(out)}


# ---------------------------------------------------------------------------
# row_cond (per-row predicate; <- IfElse + split/merge_lod_tensor_op.cc)
# ---------------------------------------------------------------------------


@register_op("row_cond", inputs=("Cond", "Hold"), outputs=("Out",),
             diff_inputs=("Hold",))
def row_cond(ctx, ins, attrs):
    """IfElse the XLA way: both branches run on the FULL batch, outputs merge
    row-wise with ``where(mask, true, false)``.

    The reference physically partitions rows (split_lod_tensor_op.cc) into two
    dynamic-length tensors — dynamic shapes XLA can't compile. Computing both
    branches keeps shapes static; XLA fuses the select into the producers.
    """
    mask = ins["Cond"][0]
    mask = mask.reshape(mask.shape[0])  # (N,) bool
    hold_names = list(attrs.get("hold_names", ()))
    hold_vals = list(ins.get("Hold", []))
    runner = ctx.block_runner

    def run_branch(sub_idx, out_names):
        env = dict(zip(hold_names, hold_vals))
        runner.run_block(sub_idx, env, _sub_ctx(ctx, ctx.next_key()))
        return [env[n] for n in out_names]

    t_outs = run_branch(attrs["sub_true"], attrs["true_out_names"])
    f_outs = run_branch(attrs["sub_false"], attrs["false_out_names"])
    outs = []
    for t, f in zip(t_outs, f_outs):
        m = mask.reshape((-1,) + (1,) * (t.ndim - 1))
        outs.append(jnp.where(m, t, f))
    return {"Out": outs}


# ---------------------------------------------------------------------------
# recurrent: StaticRNN / DynamicRNN via lax.scan (<- recurrent_op.cc:222)
# ---------------------------------------------------------------------------


@register_op("recurrent", inputs=("Seq", "Boot", "Hold", "Length"),
             outputs=("Out", "Last"), diff_inputs=("Seq", "Boot", "Hold"))
def recurrent(ctx, ins, attrs):
    """One lax.scan over time replaces the reference's per-step interpreter
    with StepScopes (recurrent_op.cc:39-120).

    attrs: sub_block; step_input_names (per-step vars inside the block, one
    per Seq input, which are batch-major [N, T, ...]); pre_names/post_names
    (memory state before/after one step); step_output_names (per-step outputs
    to stack); hold_names. Optional Length (N,) masks steps past each row's
    length: memories hold their last real value (<- shrink_rnn_memory_op.cc
    semantics via masking, not batch shrinking) and outputs are zero-padded.
    """
    sub_idx = attrs["sub_block"]
    step_in = list(attrs.get("step_input_names", ()))
    pre = list(attrs.get("pre_names", ()))
    post = list(attrs.get("post_names", ()))
    inner_outs = list(attrs.get("step_output_names", ()))
    hold = dict(zip(attrs.get("hold_names", ()), ins.get("Hold", [])))
    seqs = [jnp.swapaxes(v, 0, 1) for v in ins.get("Seq", [])]  # [T, N, ...]
    boots = tuple(ins.get("Boot", []))
    lengths = ins.get("Length") or [None]
    lengths = lengths[0]
    runner = ctx.block_runner

    if seqs:
        T = seqs[0].shape[0]
    else:
        T = int(attrs["max_len"])
    keys = jax.random.split(ctx.next_key(), T)
    ts = jnp.arange(T, dtype=jnp.int32)

    def body(mems, xs):
        step_vals, key, t = xs
        env = dict(hold)
        env.update(zip(step_in, step_vals))
        env.update(zip(pre, mems))
        runner.run_block(sub_idx, env, _sub_ctx(ctx, key))
        new_mems = [env[p] for p in post]
        outs = [env[o] for o in inner_outs]
        if lengths is not None:
            active = t < lengths  # (N,) bool
            def rowmask(v):
                return active.reshape((-1,) + (1,) * (v.ndim - 1))
            new_mems = [jnp.where(rowmask(n), n, o) for n, o in zip(new_mems, mems)]
            outs = [jnp.where(rowmask(o), o, jnp.zeros_like(o)) for o in outs]
        return tuple(new_mems), tuple(outs)

    last, ys = lax.scan(body, boots, (tuple(seqs), keys, ts))
    outs_bm = [jnp.swapaxes(y, 0, 1) for y in ys]  # back to [N, T, ...]
    return {"Out": outs_bm, "Last": list(last)}


# ---------------------------------------------------------------------------
# tensor arrays (<- tensor_array_read_write_op.cc, LoDTensorArray)
# ---------------------------------------------------------------------------


@register_op("array_write", inputs=("Array", "X", "I"), outputs=("Out",),
             diff_inputs=("Array", "X"))
def array_write(ctx, ins, attrs):
    arr, x, i = ins["Array"][0], ins["X"][0], ins["I"][0]
    i = jnp.reshape(i, ()).astype(jnp.int32)
    return {"Out": [lax.dynamic_update_index_in_dim(arr, x, i, 0)]}


@register_op("array_read", inputs=("Array", "I"), outputs=("Out",),
             diff_inputs=("Array",))
def array_read(ctx, ins, attrs):
    arr, i = ins["Array"][0], ins["I"][0]
    i = jnp.reshape(i, ()).astype(jnp.int32)
    return {"Out": [lax.dynamic_index_in_dim(arr, i, 0, keepdims=False)]}


@register_op("array_length", inputs=("Len",), outputs=("Out",), no_grad=True)
def array_length(ctx, ins, attrs):
    return {"Out": [jnp.reshape(ins["Len"][0], ()).astype(jnp.int32)]}


# ---------------------------------------------------------------------------
# recompute: rematerialized segment (jax.checkpoint) — the TPU-native form of
# the reference's memory_optimization_transpiler (activations of the segment
# are NOT kept for backward; they are recomputed from the segment inputs,
# trading MXU FLOPs for HBM).
# ---------------------------------------------------------------------------


# one registry for BOTH the build-time membership check (layers.recompute)
# and the kernel dispatch — policies cannot drift between the two.
# None/'nothing' = save nothing, full replay; 'dots' = selective
# checkpointing keeping matmul/conv outputs (near-zero extra FLOPs,
# memory between full remat and none); 'flash' = save ONLY the flash
# attention kernel's named outputs (out + lse, ops/pallas_attention.py
# _fa_fwd) so the backward replays elementwise/matmul glue but never
# re-runs the Pallas forward — full remat minus the one segment member a
# policy could not previously split (it rematerialized "as a UNIT")
RECOMPUTE_POLICIES = {
    None: None,
    "nothing": None,
    # 'dots' composes with the named dW-routed dot output: a dot routed
    # through the pallas_dw custom_vjp (ops/pallas_matmul.py) is opaque to
    # dots_with_no_batch_dims_saveable (the dot hides inside the custom_vjp
    # call), so the name keeps the policy's meaning when the flag is on —
    # without it, enabling the kernel would silently change what 'dots'
    # saves and the backward would replay those matmuls.
    "dots": jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        jax.checkpoint_policies.save_only_these_names("dw_mm_out")),
    # 'flash' stays minimal on purpose: it saves ONLY the flash kernel
    # outputs; projection/FFN dot outputs (dw_mm_out included) are exactly
    # the activations the policy exists to drop.
    "flash": jax.checkpoint_policies.save_only_these_names(
        "flash_out", "flash_lse"),
    # dots_flash: keep matmul outputs AND the flash kernel outputs — the
    # backward replays only elementwise glue (near-zero extra FLOPs); the
    # memory cost over 'flash' is the saved projection/FFN activations
    "dots_flash": jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse", "dw_mm_out")),
}


@register_op("recompute", inputs=("Hold",), outputs=("Out",),
             diff_inputs=("Hold",))
def recompute_op(ctx, ins, attrs):
    """Run ``sub_block`` under jax.checkpoint.

    attrs: sub_block, hold_names (segment inputs, read from outside),
    out_names (vars the segment produces, surfaced to the parent).
    The grad op is the default vjp of this kernel — vjp of a checkpointed
    function re-executes the segment on backward, and prevent_cse stops XLA
    from folding that recompute back into the stored forward.
    """
    hold_names = list(attrs["hold_names"])
    out_names = list(attrs["out_names"])
    runner = ctx.block_runner
    sub_idx = attrs["sub_block"]
    # the segment key must be IDENTICAL in the forward op and in the grad
    # op's vjp replay (both re-run this kernel in the same trace) — consuming
    # ctx.next_key() would hand them different positions of the sequential
    # chain and stochastic segment ops (dropout) would use different masks
    # for loss vs gradients. Fold the static sub-block index into the step's
    # base key instead: stable per op, unique per segment.
    base = getattr(ctx, "base_key", None)
    key = (jax.random.fold_in(base, sub_idx) if base is not None else None)

    def segment(*hold_vals):
        env = dict(zip(hold_names, hold_vals))
        sub = _sub_ctx(ctx, key)
        # inside this segment, gradients come from jax.vjp of the whole
        # checkpointed function rather than IR-level grad ops; kernels built
        # on primitives WITHOUT an AD rule (bare pallas_call) consult this
        # marker and switch to a differentiable form — e.g. flash_attention
        # routes through its custom_vjp entry point (pallas_attention.py),
        # which remat replays as a unit
        sub.in_remat = True
        runner.run_block(sub_idx, env, sub)
        return tuple(env[n] for n in out_names)

    policy_name = attrs.get("policy")
    if policy_name not in RECOMPUTE_POLICIES:
        raise ValueError(
            f"unknown recompute policy {policy_name!r} "
            f"(expected one of {sorted(k for k in RECOMPUTE_POLICIES if k)}"
            f" or None)")
    policy = RECOMPUTE_POLICIES[policy_name]
    ckpt = (jax.checkpoint(segment) if policy is None
            else jax.checkpoint(segment, policy=policy))
    outs = ckpt(*ins["Hold"])
    return {"Out": list(outs)}

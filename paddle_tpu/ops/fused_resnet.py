"""Fused bottleneck residual block: the TPU answer to cuDNN's fused
spatial batch norm (<- paddle/fluid/operators/batch_norm_op.cu.cc:26-150).

A ResNet bottleneck in training mode is, per conv layer, five HBM passes
under XLA (conv write, stats read, normalize read+write, next-conv read)
plus a backward where dX, dW and the BN reductions each re-read the same
gradients and activations. This module composes the pallas_conv kernels so
that per layer exactly ONE raw conv-output tensor is written and read —
BN-apply+relu rides the next kernel's prologue, BN statistics ride the
producing kernel's epilogue, and the backward's dX + dW + BN reductions
share a single read of (gradient, activation).

`bottleneck_fused` is a jax.custom_vjp over [N, H, W, C] bf16 activations,
covering the stride-1 identity bottleneck blocks (12 of ResNet-50's 16).

STATUS (r3, measured — docs/perf.md "ResNet roofline"): the XLA-native
path remains the framework's default engine. On-chip, XLA's whole-graph
fusion already achieves fused-level HBM traffic, and the opaque custom-call
boundaries around these kernels DE-fuse the surrounding glue (standalone
convert/reduce passes), making the full model SLOWER despite the combined
backward kernel itself beating XLA's equivalent work. The kernels and this
block stay in-tree as numerically-pinned building blocks
(tests/test_pallas_conv.py) and as the documented measured attempt; the
only callers are the tests and tools/probe_resnet_split.py.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .pallas_conv import (bn_affine, bn_bwd_coefs, fused_bwd_conv3x3_bn,
                          fused_bwd_matmul_bn, fused_conv3x3_bn,
                          fused_matmul_bn, moments_from_sums)

EPS = 1e-5


def _fold(stats, gamma, beta, count):
    mean, var = moments_from_sums(stats, count)
    a, b = bn_affine(mean, var, gamma, beta, EPS)
    return mean, var, a, b


@jax.custom_vjp
def bottleneck_fused(z, w1, w2, w3, g1, b1, g2, b2, g3, b3):
    """Identity-shortcut bottleneck: zout = relu(BN3(conv3) + z).

    z: [N, H, W, C4] bf16 (a REAL activation — the previous block's
    materialized output). w1: [C4, C] (1x1), w2: [3, 3, C, C] (HWIO),
    w3: [C, C4]; g*/b* the BN scale/bias pairs. Returns (zout,
    (mean1, var1, mean2, var2, mean3, var3)) — batch moments for the
    caller's running-stat update (non-differentiable)."""
    zout, stats, _res = _bottleneck_fwd_impl(
        z, w1, w2, w3, g1, b1, g2, b2, g3, b3)
    return zout, stats


def _bottleneck_fwd_impl(z, w1, w2, w3, g1, b1, g2, b2, g3, b3):
    n, h, wd, c4 = z.shape
    c = w1.shape[1]
    m = n * h * wd
    z2 = z.reshape(m, c4)
    y1, st1 = fused_matmul_bn(z2, w1, affine=None, stats=True)
    mean1, var1, a1, b1f = _fold(st1, g1, b1, m)
    y2, st2 = fused_conv3x3_bn(y1.reshape(n, h, wd, c), w2, (a1, b1f),
                               relu=True, stats=True)
    mean2, var2, a2, b2f = _fold(st2, g2, b2, m)
    y3, st3 = fused_matmul_bn(y2.reshape(m, c), w3, (a2, b2f), relu=True,
                              stats=True)
    mean3, var3, a3, b3f = _fold(st3, g3, b3, m)
    q = (y3.astype(jnp.float32) * a3[None, :] + b3f[None, :]
         + z2.astype(jnp.float32))
    zout2 = jnp.maximum(q, 0.0).astype(z.dtype)
    zout = zout2.reshape(n, h, wd, c4)
    stats = (mean1, var1, mean2, var2, mean3, var3)
    res = (z, zout, y1, y2, y3,
           (mean1, var1, a1, b1f), (mean2, var2, a2, b2f),
           (mean3, var3, a3, b3f), (w1, w2, w3), (g1, g2, g3))
    return zout, stats, res


def _bottleneck_fwd(z, w1, w2, w3, g1, b1, g2, b2, g3, b3):
    zout, stats, res = _bottleneck_fwd_impl(
        z, w1, w2, w3, g1, b1, g2, b2, g3, b3)
    return (zout, stats), res


def _bottleneck_bwd(res, cts):
    dzout = cts[0]  # stats cotangents are zero (running-stat updates are
    # stop_gradient on the caller side)
    (z, zout, y1, y2, y3, bn1, bn2, bn3, ws, gs) = res
    mean1, var1, a1, b1f = bn1
    mean2, var2, a2, b2f = bn2
    mean3, var3, a3, b3f = bn3
    w1, w2, w3 = ws
    g1, g2, g3 = gs
    n, h, wd, c4 = z.shape
    m = n * h * wd
    c = w1.shape[1]

    # join backward: j = dn3 = dzout masked by the output relu — also the
    # identity-shortcut gradient. XLA fuses this with the j/y3 reductions.
    dz2 = dzout.reshape(m, c4)
    j = jnp.where(zout.reshape(m, c4) > 0, dz2.astype(jnp.float32), 0.0)
    s1_3 = jnp.sum(j, axis=0)
    s2_3 = jnp.sum(j * y3.astype(jnp.float32), axis=0)
    jj = j.astype(z.dtype)
    al3, be3, de3, dg3, db3 = bn_bwd_coefs(s1_3, s2_3, mean3, var3, g3, m,
                                           EPS)

    # conv3 (1x1, C -> C4): P2, dW3, sums for BN2
    p2, dw3, st_p2 = fused_bwd_matmul_bn(
        jj, y3, y2.reshape(m, c), w3, coefs=(al3, be3, de3),
        xaffine=(a2, b2f), xrelu=True, stats=True)
    al2, be2, de2, dg2, db2 = bn_bwd_coefs(st_p2[0], st_p2[1], mean2, var2,
                                           g2, m, EPS)

    # conv2 (3x3, C -> C): P1, dW2, sums for BN1
    p1, dw2, st_p1 = fused_bwd_conv3x3_bn(
        p2.reshape(n, h, wd, c), y2.reshape(n, h, wd, c),
        y1.reshape(n, h, wd, c), w2, coefs=(al2, be2, de2),
        xaffine=(a1, b1f), xrelu=True, stats=True)
    al1, be1, de1, dg1, db1 = bn_bwd_coefs(st_p1[0], st_p1[1], mean1, var1,
                                           g1, m, EPS)

    # conv1 (1x1, C4 -> C): dZ_main, dW1 (input is the real activation z)
    dz_main, dw1, _ = fused_bwd_matmul_bn(
        p1.reshape(m, c), y1, z.reshape(m, c4), w1,
        coefs=(al1, be1, de1), xaffine=None, stats=False)

    dz = (dz_main.astype(jnp.float32) + j).astype(z.dtype).reshape(z.shape)
    return (dz, dw1.astype(w1.dtype), dw2.astype(w2.dtype),
            dw3.astype(w3.dtype), dg1.astype(g1.dtype), db1.astype(g1.dtype),
            dg2.astype(g2.dtype), db2.astype(g2.dtype),
            dg3.astype(g3.dtype), db3.astype(g3.dtype))


bottleneck_fused.defvjp(_bottleneck_fwd, _bottleneck_bwd)


@jax.custom_vjp
def bottleneck_hybrid(z, w1, w2, w3, g1, b1, g2, b2, g3, b3):
    """Identity-shortcut bottleneck, hybrid engine selection (measured on
    chip, tools/probe_fused_conv.py): XLA forward — its conv emitter already
    rides the HBM bound and beats the Pallas im2col 3x3 by ~2.5x — plus the
    Pallas combined backward for the two 1x1 layers, where one kernel's
    read of (gradient, activation) yields dX, dW and the BN reductions that
    XLA computes with separate convs and reduce passes. The 3x3 backward
    stays on XLA's conv kernels."""
    zout, stats, _ = _hybrid_fwd_impl(z, w1, w2, w3, g1, b1, g2, b2, g3, b3)
    return zout, stats


def _hybrid_fwd_impl(z, w1, w2, w3, g1, b1, g2, b2, g3, b3):
    n, h, wd, c4 = z.shape
    c = w1.shape[1]
    m = n * h * wd
    z2 = z.astype(jnp.bfloat16).reshape(m, c4)
    y1 = jax.lax.dot_general(z2, w1.astype(jnp.bfloat16),
                             (((1,), (0,)), ((), ()))).astype(jnp.bfloat16)
    y1f = y1.astype(jnp.float32)
    st1 = jnp.stack([jnp.sum(y1f, 0), jnp.sum(y1f * y1f, 0)])
    mean1, var1, a1, b1f = _fold(st1, g1, b1, m)
    xhat1 = jnp.maximum(y1f * a1 + b1f, 0.0).astype(jnp.bfloat16)
    y2 = jax.lax.conv_general_dilated(
        xhat1.reshape(n, h, wd, c), w2.astype(jnp.bfloat16), (1, 1),
        [(1, 1), (1, 1)], dimension_numbers=("NHWC", "HWIO", "NHWC")
    ).astype(jnp.bfloat16)
    y2f = y2.astype(jnp.float32)
    st2 = jnp.stack([jnp.sum(y2f, (0, 1, 2)), jnp.sum(y2f * y2f, (0, 1, 2))])
    mean2, var2, a2, b2f = _fold(st2, g2, b2, m)
    xhat2 = jnp.maximum(y2f * a2 + b2f, 0.0).astype(jnp.bfloat16)
    y3 = jax.lax.dot_general(xhat2.reshape(m, c), w3.astype(jnp.bfloat16),
                             (((1,), (0,)), ((), ()))).astype(jnp.bfloat16)
    y3f = y3.astype(jnp.float32)
    st3 = jnp.stack([jnp.sum(y3f, 0), jnp.sum(y3f * y3f, 0)])
    mean3, var3, a3, b3f = _fold(st3, g3, b3, m)
    q = y3f * a3 + b3f + z2.astype(jnp.float32)
    zout = jnp.maximum(q, 0.0).astype(z.dtype).reshape(z.shape)
    stats = (mean1, var1, mean2, var2, mean3, var3)
    res = (z, zout, y1, y2, y3,
           (mean1, var1, a1, b1f), (mean2, var2, a2, b2f),
           (mean3, var3, a3, b3f), (w1, w2, w3), (g1, g2, g3))
    return zout, stats, res


def _hybrid_fwd(z, w1, w2, w3, g1, b1, g2, b2, g3, b3):
    zout, stats, res = _hybrid_fwd_impl(z, w1, w2, w3, g1, b1, g2, b2,
                                        g3, b3)
    return (zout, stats), res


def _hybrid_bwd(res, cts):
    dzout = cts[0]
    (z, zout, y1, y2, y3, bn1, bn2, bn3, ws, gs) = res
    mean1, var1, a1, b1f = bn1
    mean2, var2, a2, b2f = bn2
    mean3, var3, a3, b3f = bn3
    w1, w2, w3 = ws
    g1, g2, g3 = gs
    n, h, wd, c4 = z.shape
    m = n * h * wd
    c = w1.shape[1]

    dz2 = dzout.reshape(m, c4)
    j = jnp.where(zout.reshape(m, c4) > 0, dz2.astype(jnp.float32), 0.0)
    s1_3 = jnp.sum(j, axis=0)
    s2_3 = jnp.sum(j * y3.astype(jnp.float32), axis=0)
    jj = j.astype(jnp.bfloat16)
    al3, be3, de3, dg3, db3 = bn_bwd_coefs(s1_3, s2_3, mean3, var3, g3, m,
                                           EPS)

    # conv3 (1x1): one Pallas pass -> P2, dW3, BN2 sums
    p2, dw3, st_p2 = fused_bwd_matmul_bn(
        jj, y3, y2.reshape(m, c), w3, coefs=(al3, be3, de3),
        xaffine=(a2, b2f), xrelu=True, stats=True)
    al2, be2, de2, dg2, db2 = bn_bwd_coefs(st_p2[0], st_p2[1], mean2, var2,
                                           g2, m, EPS)

    # conv2 (3x3): XLA backward (its conv kernels beat the im2col Pallas
    # form on-chip); corrections are XLA elementwise around it
    g2c = (p2.astype(jnp.float32) * al2 + y2.reshape(m, c).astype(jnp.float32)
           * be2 + de2).astype(jnp.bfloat16).reshape(n, h, wd, c)
    y1f = y1.astype(jnp.float32)
    pre1 = y1f * a1 + b1f
    xhat1 = jnp.maximum(pre1, 0.0).astype(jnp.bfloat16).reshape(n, h, wd, c)
    _, conv_vjp = jax.vjp(
        lambda xx, ww: jax.lax.conv_general_dilated(
            xx, ww, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC")),
        xhat1, w2.astype(jnp.bfloat16))
    dxhat1, dw2 = conv_vjp(g2c)
    p1 = jnp.where(pre1 > 0.0, dxhat1.reshape(m, c).astype(jnp.float32), 0.0)
    s1_1 = jnp.sum(p1, axis=0)
    s2_1 = jnp.sum(p1 * y1f, axis=0)
    al1, be1, de1, dg1, db1 = bn_bwd_coefs(s1_1, s2_1, mean1, var1, g1, m,
                                           EPS)

    # conv1 (1x1): one Pallas pass -> dZ_main, dW1
    dz_main, dw1, _ = fused_bwd_matmul_bn(
        p1.astype(jnp.bfloat16), y1, z.reshape(m, c4), w1,
        coefs=(al1, be1, de1), xaffine=None, stats=False)

    dz = (dz_main.astype(jnp.float32) + j).astype(z.dtype).reshape(z.shape)
    return (dz, dw1.astype(w1.dtype), dw2.astype(w2.dtype),
            dw3.astype(w3.dtype), dg1.astype(g1.dtype), db1.astype(g1.dtype),
            dg2.astype(g2.dtype), db2.astype(g2.dtype),
            dg3.astype(g3.dtype), db3.astype(g3.dtype))


bottleneck_hybrid.defvjp(_hybrid_fwd, _hybrid_bwd)


def bottleneck_reference(z, w1, w2, w3, g1, b1, g2, b2, g3, b3):
    """Dense-XLA oracle with identical math (bf16 activations, f32 BN):
    used by tests and as documentation of the fused block's semantics."""
    n, h, wd, c4 = z.shape

    def bn(x, gamma, beta):
        xf = x.astype(jnp.float32)
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(xf, axis=axes)
        var = jnp.maximum(jnp.mean(xf * xf, axis=axes) - mean * mean,
                          0.0)
        a, b = bn_affine(mean, var, gamma, beta, EPS)
        return (xf * a + b), (mean, var)

    y1 = jax.lax.dot_general(z.astype(jnp.bfloat16).reshape(-1, c4),
                             w1.astype(jnp.bfloat16),
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    y1 = y1.astype(jnp.bfloat16)
    x1, (m1, v1) = bn(y1, g1, b1)
    x1 = jnp.maximum(x1, 0.0).astype(jnp.bfloat16).reshape(n, h, wd, -1)
    # no preferred_element_type: lax's conv transpose rule requires the
    # cotangent dtype to match the operands (cf. ops/nn.py conv2d AMP note)
    y2 = jax.lax.conv_general_dilated(
        x1, w2.astype(jnp.bfloat16), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    x2, (m2, v2) = bn(y2, g2, b2)
    x2 = jnp.maximum(x2, 0.0).astype(jnp.bfloat16).reshape(-1, w2.shape[3])
    y3 = jax.lax.dot_general(x2, w3.astype(jnp.bfloat16),
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    y3 = y3.astype(jnp.bfloat16)
    x3, (m3, v3) = bn(y3, g3, b3)
    q = x3 + z.astype(jnp.float32).reshape(-1, c4)
    zout = jnp.maximum(q, 0.0).astype(z.dtype).reshape(z.shape)
    return zout, (m1, v1, m2, v2, m3, v3)

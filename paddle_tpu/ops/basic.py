"""Creation / casting / misc ops.

<- paddle/fluid/operators/{fill_constant,uniform_random,gaussian_random,
cast,assign,shape,scale,clip,sign,sum,increment}_op.cc. Kernels are jnp
expressions that trace into the enclosing block's single XLA computation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.types import DataType


def _dtype_attr(attrs, default=DataType.FP32):
    d = attrs.get("dtype", default)
    return DataType.from_any(d).jnp_dtype


@register_op("fill_constant", inputs=(), outputs=("Out",), no_grad=True)
def fill_constant(ctx, ins, attrs):
    shape = tuple(attrs.get("shape", ()))
    value = attrs.get("value", 0.0)
    return {"Out": [jnp.full(shape, value, dtype=_dtype_attr(attrs))]}


@register_op("fill_constant_batch_size_like", inputs=("Input",), outputs=("Out",), no_grad=True)
def fill_constant_batch_size_like(ctx, ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs.get("shape", ()))
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    return {"Out": [jnp.full(tuple(shape), attrs.get("value", 0.0), dtype=_dtype_attr(attrs))]}


@register_op("fill_zeros_like", inputs=("X",), outputs=("Out",), no_grad=True)
def fill_zeros_like(ctx, ins, attrs):
    return {"Out": [jnp.zeros_like(ins["X"][0])]}


def _op_key(ctx, attrs):
    """Honor an explicit nonzero 'seed' attr (reference semantics: seed=0
    means 'draw from the global source'), else thread the executor's key."""
    seed = attrs.get("seed", 0)
    if seed:
        return jax.random.PRNGKey(seed)
    return ctx.next_key()


@register_op("uniform_random", inputs=(), outputs=("Out",), no_grad=True, stochastic=True)
def uniform_random(ctx, ins, attrs):
    shape = tuple(attrs.get("shape", ()))
    lo, hi = attrs.get("min", -1.0), attrs.get("max", 1.0)
    dt = _dtype_attr(attrs)
    return {"Out": [jax.random.uniform(_op_key(ctx, attrs), shape, dt, lo, hi)]}


@register_op("gaussian_random", inputs=(), outputs=("Out",), no_grad=True, stochastic=True)
def gaussian_random(ctx, ins, attrs):
    shape = tuple(attrs.get("shape", ()))
    mean, std = attrs.get("mean", 0.0), attrs.get("std", 1.0)
    dt = _dtype_attr(attrs)
    return {"Out": [mean + std * jax.random.normal(_op_key(ctx, attrs), shape, dt)]}


@register_op("cast", inputs=("X",), outputs=("Out",))
def cast(ctx, ins, attrs):
    return {"Out": [ins["X"][0].astype(_dtype_attr(attrs, attrs.get("out_dtype", DataType.FP32)))]}


@register_op("assign", inputs=("X",), outputs=("Out",))
def assign(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


@register_op("shape", inputs=("Input",), outputs=("Out",), no_grad=True)
def shape(ctx, ins, attrs):
    return {"Out": [jnp.asarray(ins["Input"][0].shape, dtype=jnp.int32)]}


@register_op("scale", inputs=("X",), outputs=("Out",))
def scale(ctx, ins, attrs):
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    after = attrs.get("bias_after_scale", True)
    x = ins["X"][0]
    return {"Out": [x * s + b if after else (x + b) * s]}


@register_op("increment", inputs=("X",), outputs=("Out",), no_grad=True)
def increment(ctx, ins, attrs):
    return {"Out": [ins["X"][0] + attrs.get("step", 1.0)]}


@register_op("clip", inputs=("X",), outputs=("Out",))
def clip(ctx, ins, attrs):
    return {"Out": [jnp.clip(ins["X"][0], attrs.get("min"), attrs.get("max"))]}


@register_op("sign", inputs=("X",), outputs=("Out",), no_grad=True)
def sign(ctx, ins, attrs):
    return {"Out": [jnp.sign(ins["X"][0])]}


@register_op("sum", inputs=("X",), outputs=("Out",))
def sum_op(ctx, ins, attrs):
    """Add N tensors (grad accumulation uses this, <- sum_op.cc)."""
    xs = [x for x in ins["X"] if x is not None]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@register_op("assign_value", inputs=(), outputs=("Out",), no_grad=True)
def assign_value(ctx, ins, attrs):
    vals = attrs["values"]
    return {"Out": [jnp.asarray(vals).astype(_dtype_attr(attrs))]}


@register_op("label_smooth", inputs=("X",), outputs=("Out",))
def label_smooth(ctx, ins, attrs):
    eps = attrs.get("epsilon", 0.0)
    x = ins["X"][0]
    k = x.shape[-1]
    return {"Out": [(1.0 - eps) * x + eps / k]}


def _print_grad_maker(op, no_grad_set):
    """<- print_op.cc PrintOpProtoAndCheckGradOpMaker: the gradient passes
    straight through (Out@GRAD -> In@GRAD), printed when print_phase says."""
    from ..core.ir import grad_var_name

    return [{
        "type": "print_grad",
        "inputs": {"Out@GRAD": [grad_var_name(n) for n in op.outputs["Out"]]},
        "outputs": {"In@GRAD": [
            "" if n in no_grad_set else grad_var_name(n) for n in op.inputs["In"]
        ]},
        "attrs": dict(op.attrs),
    }]


@register_op("print", inputs=("In",), outputs=("Out",),
             grad_maker=_print_grad_maker)
def print_op(ctx, ins, attrs):
    """Debug print (<- print_op.cc): identity passthrough that prints the
    tensor from inside the compiled program via a host callback at execution
    time, honoring first_n (prints stop after N executions), summarize
    (truncate to the first N elements), and print_phase like the reference.
    Gradients pass through unchanged."""
    x = ins["In"][0]
    if attrs.get("print_phase", "both").lower() == "backward":
        return {"Out": [x]}
    return {"Out": [_print_emit(ctx, ins["In"][0], attrs)]}


@register_op("print_grad", inputs=("Out@GRAD",), outputs=("In@GRAD",),
             no_grad=True)
def print_grad_op(ctx, ins, attrs):
    g = ins["Out@GRAD"][0]
    if attrs.get("print_phase", "both").lower() == "forward":
        return {"In@GRAD": [g]}
    # NOTE: attrs is the grad op's own persistent IR dict — _print_emit mints
    # its tag in place, so the first_n counter survives jit retraces (a
    # per-call dict(attrs) copy here would reset it on every recompilation)
    return {"In@GRAD": [_print_emit(ctx, g, attrs, msg_suffix="@GRAD ")]}


def _print_emit(ctx, x, attrs, msg_suffix=""):
    msg = (attrs.get("message", "") or "") + msg_suffix
    summarize = attrs.get("summarize", -1)
    first_n = attrs.get("first_n", -1)
    shown = x.reshape(-1)[:summarize] if summarize and summarize > 0 else x
    if not _host_callbacks_supported():
        # the axon tunnel backend rejects host send/recv at execution time
        # (UNIMPLEMENTED); Print degrades to identity there rather than
        # failing the whole program — fetch the tensor to inspect it
        return x
    # first_n counts per IR op, not per compilation: key the counter by a
    # stable per-op tag minted at first trace and stored INTO attrs (id()
    # of a dead dict can be recycled, inheriting an exhausted counter)
    tag = attrs.get("_print_tag")
    if tag is None:
        tag = attrs["_print_tag"] = f"print{len(_PRINT_COUNTS)}"
    count = _PRINT_COUNTS.setdefault(tag, {"n": 0})

    def _host_print(val):
        if first_n is None or first_n < 0 or count["n"] < first_n:
            count["n"] += 1
            print(f"{msg}{val}", flush=True)

    jax.debug.callback(_host_print, shown)
    return x


def _host_callbacks_supported() -> bool:
    """False when the computation targets the axon tunnel backend.

    The Executor/ParallelExecutor always pin ``jax.default_device`` to the
    target place/mesh before tracing, so inside the framework the check is
    precise. Bare callers tracing without a pinned default on a machine
    where axon is the default backend conservatively get the identity
    degrade (the callback would abort at execution time there).
    """
    dev = jax.config.jax_default_device
    if dev is not None and dev.platform != "tpu":
        return True
    try:
        import jax.extend.backend as jeb

        version = getattr(jeb.get_backend(), "platform_version", "")
    except Exception:
        return True
    return "axon" not in version


_PRINT_COUNTS: dict = {}

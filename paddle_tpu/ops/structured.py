"""Structured-prediction ops: linear-chain CRF, Viterbi decode, CTC loss,
CTC alignment, chunk evaluation.

<- paddle/fluid/operators/{linear_chain_crf_op.cc, crf_decoding_op.cc,
warpctc_op.cc, ctc_align_op.cc, chunk_eval_op.cc} re-imagined for XLA:

* The reference iterates per-sequence over LoD offsets in C++ loops
  (linear_chain_crf_op.h forward/backward); here sequences are dense padded
  ``[N, T, ...]`` with a ``Length`` companion and the whole batch runs one
  masked ``lax.scan`` over time — batched on the MXU, differentiable by
  ``jax.vjp`` (the hand-written CRF backward in the reference falls out of
  autodiff).
* warpctc's custom CUDA kernel becomes the standard log-space CTC
  alpha-recursion as a scan — no external library.
* Transition layout matches the reference: row 0 = start weights, row 1 =
  stop weights, rows 2.. = the [K, K] transition matrix
  (linear_chain_crf_op.cc op doc).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.scipy.special import logsumexp

from ..core.registry import register_op

_NEG_INF = -1e30


def _lengths_or_full(ins, n, t):
    length = ins.get("Length", [None])
    length = length[0] if length else None
    if length is None:
        return jnp.full((n,), t, jnp.int32)
    return jnp.reshape(length, (n,)).astype(jnp.int32)


def _split_transition(trans):
    """[K+2, K] -> (start[K], stop[K], A[K, K])."""
    return trans[0], trans[1], trans[2:]


@register_op("linear_chain_crf", inputs=("Emission", "Transition", "Label", "Length"),
             outputs=("LogLikelihood",), diff_inputs=("Emission", "Transition"))
def linear_chain_crf(ctx, ins, attrs):
    """Per-sequence negative log-likelihood of the gold tag path.

    Emission [N, T, K], Transition [K+2, K], Label [N, T] (or [N, T, 1]),
    Length [N]. Output [N, 1] — used as a cost, like the reference's
    LogLikelihood output (linear_chain_crf_op.cc).
    """
    em = ins["Emission"][0]
    trans = ins["Transition"][0]
    label = ins["Label"][0]
    n, t, k = em.shape
    label = jnp.reshape(label, (n, t)).astype(jnp.int32)
    length = _lengths_or_full(ins, n, t)
    start, stop, A = _split_transition(trans)

    ts = jnp.arange(t)
    mask = ts[None, :] < length[:, None]  # [N, T]

    # log-partition via masked forward recursion
    em_t = jnp.swapaxes(em, 0, 1)        # [T, N, K]
    mask_t = jnp.swapaxes(mask, 0, 1)
    alpha0 = start[None, :] + em_t[0]

    def step(alpha, xs):
        e, m = xs
        nxt = logsumexp(alpha[:, :, None] + A[None, :, :], axis=1) + e
        return jnp.where(m[:, None], nxt, alpha), None

    if t > 1:
        alpha, _ = lax.scan(step, alpha0, (em_t[1:], mask_t[1:]))
    else:
        alpha = alpha0
    log_z = logsumexp(alpha + stop[None, :], axis=1)

    # gold path score
    em_sc = jnp.take_along_axis(em, label[..., None], axis=2)[..., 0]
    em_score = jnp.sum(em_sc * mask, axis=1)
    trans_score = jnp.sum(
        A[label[:, :-1], label[:, 1:]] * mask[:, 1:], axis=1) if t > 1 else 0.0
    last_idx = jnp.clip(length - 1, 0, t - 1)
    last_lbl = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    gold = em_score + trans_score + start[label[:, 0]] + stop[last_lbl]

    nll = jnp.where(length > 0, log_z - gold, 0.0)
    return {"LogLikelihood": [nll.reshape(n, 1)]}


@register_op("crf_decoding", inputs=("Emission", "Transition", "Label", "Length"),
             outputs=("ViterbiPath",), no_grad=True)
def crf_decoding(ctx, ins, attrs):
    """Viterbi decode. Without Label: best path [N, T] int64, zero past each
    length. With Label: per-token correctness mask (reference semantics,
    crf_decoding_op.cc)."""
    em = ins["Emission"][0]
    trans = ins["Transition"][0]
    n, t, k = em.shape
    length = _lengths_or_full(ins, n, t)
    start, stop, A = _split_transition(trans)

    ts = jnp.arange(t)
    mask = ts[None, :] < length[:, None]
    em_t = jnp.swapaxes(em, 0, 1)
    mask_t = jnp.swapaxes(mask, 0, 1)
    delta0 = start[None, :] + em_t[0]
    identity_bp = jnp.broadcast_to(jnp.arange(k)[None, :], (n, k))

    def step(delta, xs):
        e, m = xs
        scores = delta[:, :, None] + A[None, :, :]       # [N, Kprev, K]
        best_prev = jnp.argmax(scores, axis=1)           # [N, K]
        nxt = jnp.max(scores, axis=1) + e
        delta_new = jnp.where(m[:, None], nxt, delta)
        bp = jnp.where(m[:, None], best_prev, identity_bp)
        return delta_new, bp

    if t > 1:
        delta, bps = lax.scan(step, delta0, (em_t[1:], mask_t[1:]))
    else:
        delta, bps = delta0, jnp.zeros((0, n, k), jnp.int32)
    last_tag = jnp.argmax(delta + stop[None, :], axis=1)  # [N]

    def back(cur, bp):
        prev = jnp.take_along_axis(bp, cur[:, None], axis=1)[:, 0]
        return prev, cur

    first_tag, rest = lax.scan(back, last_tag, bps, reverse=True)
    path = jnp.concatenate([first_tag[None], rest], axis=0)  # [T, N]
    path = jnp.swapaxes(path, 0, 1) * mask  # zero past length
    label = ins.get("Label", [None])
    label = label[0] if label else None
    if label is not None:
        label = jnp.reshape(label, (n, t)).astype(path.dtype)
        return {"ViterbiPath": [((path == label) & mask).astype(jnp.int32)]}
    return {"ViterbiPath": [path.astype(jnp.int32)]}


@register_op("warpctc", inputs=("Logits", "Label", "LogitsLength", "LabelLength"),
             outputs=("Loss",), diff_inputs=("Logits",))
def warpctc(ctx, ins, attrs):
    """CTC negative log-likelihood via the log-space alpha recursion.

    Logits [N, T, C] raw (softmax applied inside, like warpctc), Label
    [N, L] padded, lengths per row. attr blank (default 0). One lax.scan
    over T for the whole batch; grads via jax.vjp — replaces the warp-ctc
    CUDA dependency (warpctc_op.cc, platform/dynload/warpctc).
    """
    logits = ins["Logits"][0]
    label = ins["Label"][0]
    n, t, c = logits.shape
    l = label.shape[1]
    blank = int(attrs.get("blank", 0))
    logit_len = jnp.reshape(ins["LogitsLength"][0], (n,)).astype(jnp.int32)
    label_len = jnp.reshape(ins["LabelLength"][0], (n,)).astype(jnp.int32)
    label = jnp.reshape(label, (n, l)).astype(jnp.int32)

    logp = logits - logsumexp(logits, axis=2, keepdims=True)  # log-softmax

    # extended label sequence: blank, l1, blank, l2, ..., blank  [N, S], S=2L+1
    s = 2 * l + 1
    ext = jnp.full((n, s), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)
    pos = jnp.arange(s)
    in_label = (pos[None, :] < (2 * label_len + 1)[:, None])  # valid ext positions
    # skip-connection allowed at odd positions whose label differs from s-2
    can_skip = jnp.zeros((n, s), bool)
    if l > 1:
        can_skip = can_skip.at[:, 3::2].set(label[:, 1:] != label[:, :-1])

    def gather_logp(lp_t, ext):
        return jnp.take_along_axis(lp_t, ext, axis=1)  # [N, S]

    logp_t = jnp.swapaxes(logp, 0, 1)  # [T, N, C]
    alpha0 = jnp.full((n, s), _NEG_INF)
    alpha0 = alpha0.at[:, 0].set(logp_t[0][:, blank])
    first_lbl = gather_logp(logp_t[0], ext)[:, 1]
    alpha0 = alpha0.at[:, 1].set(jnp.where(label_len > 0, first_lbl, _NEG_INF))

    def shift(a, by):
        pad = jnp.full((n, by), _NEG_INF)
        return jnp.concatenate([pad, a[:, :-by]], axis=1) if by else a

    ts_idx = jnp.arange(1, t)

    def step(alpha, xs):
        lp, ti = xs
        stay = alpha
        from_prev = shift(alpha, 1)
        from_skip = jnp.where(can_skip, shift(alpha, 2), _NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(stay, from_prev), from_skip)
        nxt = merged + gather_logp(lp, ext)
        nxt = jnp.where(in_label, nxt, _NEG_INF)
        active = (ti < logit_len)[:, None]
        return jnp.where(active, nxt, alpha), None

    if t > 1:
        alpha, _ = lax.scan(step, alpha0, (logp_t[1:], ts_idx))
    else:
        alpha = alpha0

    # total prob: alpha at the last blank (2*label_len) and last label (2*label_len-1)
    idx_last = (2 * label_len)[:, None]
    idx_prev = jnp.clip(2 * label_len - 1, 0, s - 1)[:, None]
    a_last = jnp.take_along_axis(alpha, idx_last, axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, idx_prev, axis=1)[:, 0]
    a_prev = jnp.where(label_len > 0, a_prev, _NEG_INF)
    loss = -jnp.logaddexp(a_last, a_prev)
    if attrs.get("norm_by_times"):
        # reference semantics: normalize the GRADIENTS by time steps, loss
        # value untouched (warpctc_op.cc). value(loss) = loss, d(loss) = d/T:
        scaled = loss / jnp.maximum(logit_len, 1).astype(loss.dtype)
        loss = scaled + lax.stop_gradient(loss - scaled)
    return {"Loss": [loss.reshape(n, 1)]}


@register_op("ctc_align", inputs=("Input", "Length"), outputs=("Output", "OutLength"),
             no_grad=True)
def ctc_align(ctx, ins, attrs):
    """Greedy CTC collapse: merge repeats, drop blanks (<- ctc_align_op.cc).

    Input [N, T] token ids + Length [N]; output [N, T] front-packed, padded
    with attr ``pad_value`` (default 0), plus per-row collapsed lengths.
    Scatter-based — no per-row Python loops, static shapes.
    """
    x = ins["Input"][0]
    n, t = x.shape[0], x.shape[1]
    x = jnp.reshape(x, (n, t)).astype(jnp.int32)
    blank = int(attrs.get("blank", 0))
    pad_value = int(attrs.get("pad_value", 0))
    length = _lengths_or_full(ins, n, t)
    mask = jnp.arange(t)[None, :] < length[:, None]

    prev = jnp.concatenate([jnp.full((n, 1), -1, jnp.int32), x[:, :-1]], axis=1)
    keep = (x != blank) & (x != prev) & mask
    slot = jnp.cumsum(keep, axis=1) - 1                 # target position
    slot = jnp.where(keep, slot, t)                     # dump discarded to slot T
    out = jnp.full((n, t + 1), pad_value, jnp.int32)
    rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, t))
    out = out.at[rows, slot].set(jnp.where(keep, x, pad_value))
    return {"Output": [out[:, :t].astype(jnp.int32)],
            "OutLength": [keep.sum(axis=1).astype(jnp.int32)]}


@register_op("chunk_eval", inputs=("Inference", "Label", "Length"),
             outputs=("Precision", "Recall", "F1-Score",
                      "NumInferChunks", "NumLabelChunks", "NumCorrectChunks"),
             no_grad=True)
def chunk_eval(ctx, ins, attrs):
    """IOB chunk precision/recall/F1 (<- chunk_eval_op.cc), vectorized.

    Tags follow the reference encoding: ``tag = chunk_type * 2 + (0 for B,
    1 for I)``; anything outside ``[0, 2*num_chunk_types)`` is Outside.
    Chunk boundaries are computed with shifted comparisons and one reverse
    scan for end positions — no per-sequence loops.
    """
    inf = ins["Inference"][0]
    lbl = ins["Label"][0]
    n = inf.shape[0]
    t = inf.shape[1] if inf.ndim > 1 else 1
    inf = jnp.reshape(inf, (n, t)).astype(jnp.int32)
    lbl = jnp.reshape(lbl, (n, t)).astype(jnp.int32)
    ntypes = int(attrs["num_chunk_types"])
    length = _lengths_or_full(ins, n, t)
    mask = jnp.arange(t)[None, :] < length[:, None]

    excluded = [int(e) for e in attrs.get("excluded_chunk_types") or ()]

    def chunks(tags):
        valid = mask & (tags >= 0) & (tags < 2 * ntypes)
        typ = tags // 2
        for e in excluded:  # excluded types count as Outside
            valid = valid & (typ != e)
        is_i = valid & (tags % 2 == 1)
        prev_valid = jnp.concatenate([jnp.zeros((n, 1), bool), valid[:, :-1]], 1)
        prev_typ = jnp.concatenate([jnp.full((n, 1), -1, jnp.int32), typ[:, :-1]], 1)
        cont = is_i & prev_valid & (prev_typ == typ)   # continues previous chunk
        start = valid & ~cont
        nxt_cont = jnp.concatenate([cont[:, 1:], jnp.zeros((n, 1), bool)], 1)
        end = valid & ~nxt_cont

        # end position of the chunk containing t: reverse scan
        def back(carry, xs):
            e_t, idx_t = xs
            pos = jnp.where(e_t, idx_t, carry)
            return pos, pos

        idxs = jnp.arange(t, dtype=jnp.int32)
        xs = (jnp.swapaxes(end, 0, 1),
              jnp.broadcast_to(idxs[:, None], (t, n)))
        _, endpos_t = lax.scan(back, jnp.full((n,), -1, jnp.int32), xs,
                               reverse=True)
        return start, typ, jnp.swapaxes(endpos_t, 0, 1)

    s_i, t_i, e_i = chunks(inf)
    s_l, t_l, e_l = chunks(lbl)
    num_inf = s_i.sum()
    num_lbl = s_l.sum()
    correct = (s_i & s_l & (t_i == t_l) & (e_i == e_l)).sum()

    p = jnp.where(num_inf > 0, correct / num_inf, 0.0).astype(jnp.float32)
    r = jnp.where(num_lbl > 0, correct / num_lbl, 0.0).astype(jnp.float32)
    f1 = jnp.where(p + r > 0, 2 * p * r / (p + r), 0.0).astype(jnp.float32)
    return {"Precision": [p], "Recall": [r], "F1-Score": [f1],
            "NumInferChunks": [num_inf.astype(jnp.int32)],
            "NumLabelChunks": [num_lbl.astype(jnp.int32)],
            "NumCorrectChunks": [correct.astype(jnp.int32)]}

"""Metric ops (<- paddle/fluid/operators/{accuracy,auc,precision_recall,
mean_iou}_op.cc). Pure functions of predictions/labels; streaming state is
kept in persistable vars updated functionally like any other state."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op


@register_op("accuracy", inputs=("Out", "Indices", "Label"),
             outputs=("Accuracy", "Correct", "Total"), no_grad=True)
def accuracy(ctx, ins, attrs):
    idx, label = ins["Indices"][0], ins["Label"][0]
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label.squeeze(-1)
    hit = jnp.any(idx == label[:, None], axis=1)
    correct = jnp.sum(hit.astype(jnp.int32))
    total = jnp.asarray(idx.shape[0], jnp.int32)
    return {
        "Accuracy": [correct.astype(jnp.float32) / total.astype(jnp.float32)],
        "Correct": [correct],
        "Total": [total],
    }


@register_op("auc", inputs=("Predict", "Label", "TP", "FP", "TN", "FN"),
             outputs=("AUC", "TPOut", "FPOut", "TNOut", "FNOut"), no_grad=True)
def auc(ctx, ins, attrs):
    """Streaming AUC over threshold buckets (<- auc_op.cc)."""
    pred, label = ins["Predict"][0], ins["Label"][0]
    tp, fp, tn, fn = (ins[k][0] for k in ("TP", "FP", "TN", "FN"))
    num_t = attrs.get("num_thresholds", 200)
    if label.ndim == 2:
        label = label.squeeze(-1)
    pos_score = pred[:, -1] if pred.ndim == 2 else pred
    thresholds = (jnp.arange(num_t) + 1.0) / (num_t + 1.0)
    above = pos_score[None, :] >= thresholds[:, None]  # [T, N]
    is_pos = (label > 0)[None, :]
    tp_new = tp + jnp.sum(above & is_pos, axis=1)
    fp_new = fp + jnp.sum(above & ~is_pos, axis=1)
    fn_new = fn + jnp.sum(~above & is_pos, axis=1)
    tn_new = tn + jnp.sum(~above & ~is_pos, axis=1)
    tpr = tp_new / jnp.maximum(tp_new + fn_new, 1)
    fpr = fp_new / jnp.maximum(fp_new + tn_new, 1)
    # trapezoid over descending thresholds
    auc_val = jnp.sum((fpr[:-1] - fpr[1:]) * (tpr[:-1] + tpr[1:]) / 2.0)
    return {
        "AUC": [jnp.abs(auc_val)],
        "TPOut": [tp_new],
        "FPOut": [fp_new],
        "TNOut": [tn_new],
        "FNOut": [fn_new],
    }


@register_op("mean_iou", inputs=("Predictions", "Labels"),
             outputs=("OutMeanIou", "OutWrong", "OutCorrect"), no_grad=True)
def mean_iou(ctx, ins, attrs):
    pred, label = ins["Predictions"][0], ins["Labels"][0]
    n = attrs["num_classes"]
    pred = pred.reshape(-1).astype(jnp.int32)
    label = label.reshape(-1).astype(jnp.int32)
    conf = jnp.zeros((n, n), jnp.int32).at[label, pred].add(1)
    inter = jnp.diagonal(conf)
    union = conf.sum(0) + conf.sum(1) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
    wrong = conf.sum(1) - inter
    return {"OutMeanIou": [miou], "OutWrong": [wrong.astype(jnp.int32)],
            "OutCorrect": [inter.astype(jnp.int32)]}


@register_op("precision_recall",
             inputs=("MaxProbs", "Indices", "Labels", "Weights", "StatesInfo"),
             outputs=("BatchMetrics", "AccumMetrics", "AccumStatesInfo"), no_grad=True)
def precision_recall(ctx, ins, attrs):
    """Macro/micro precision-recall-F1 (<- precision_recall_op.cc)."""
    idx, labels = ins["Indices"][0], ins["Labels"][0]
    states = ins["StatesInfo"][0]  # [C, 4]: TP, FP, TN, FN
    c = attrs["class_number"]
    if labels.ndim == 2:
        labels = labels.squeeze(-1)
    pred = idx[:, 0].astype(jnp.int32)
    onehot_p = jnp.zeros((pred.shape[0], c)).at[jnp.arange(pred.shape[0]), pred].set(1)
    onehot_l = jnp.zeros((pred.shape[0], c)).at[jnp.arange(pred.shape[0]), labels.astype(jnp.int32)].set(1)
    tp = jnp.sum(onehot_p * onehot_l, axis=0)
    fp = jnp.sum(onehot_p * (1 - onehot_l), axis=0)
    fn = jnp.sum((1 - onehot_p) * onehot_l, axis=0)
    tn = pred.shape[0] - tp - fp - fn

    def metrics(tp, fp, tn, fn):
        prec = tp / jnp.maximum(tp + fp, 1e-12)
        rec = tp / jnp.maximum(tp + fn, 1e-12)
        f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-12)
        macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
        tps, fps, fns = tp.sum(), fp.sum(), fn.sum()
        mprec = tps / jnp.maximum(tps + fps, 1e-12)
        mrec = tps / jnp.maximum(tps + fns, 1e-12)
        mf1 = 2 * mprec * mrec / jnp.maximum(mprec + mrec, 1e-12)
        return jnp.concatenate([macro, jnp.stack([mprec, mrec, mf1])])

    batch = metrics(tp, fp, tn, fn)
    acc_states = states + jnp.stack([tp, fp, tn, fn], axis=1)
    accum = metrics(acc_states[:, 0], acc_states[:, 1], acc_states[:, 2], acc_states[:, 3])
    return {"BatchMetrics": [batch], "AccumMetrics": [accum],
            "AccumStatesInfo": [acc_states]}


@register_op(
    "positive_negative_pair",
    inputs=("Score", "Label", "QueryID", "AccumulatePositivePair",
            "AccumulateNegativePair", "AccumulateNeutralPair"),
    outputs=("PositivePair", "NegativePair", "NeutralPair"),
    no_grad=True,
)
def positive_negative_pair(ctx, ins, attrs):
    """Ranking pair statistics per query group (<- positive_negative_pair_op.cc).

    For every pair of items within the same query: a pair is *positive* when
    the better-labelled item scored higher, *negative* when lower, *neutral*
    on score ties. O(N^2) masked comparison — metric-sized N, not a hot op.
    """
    score = ins["Score"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1)
    qid = ins["QueryID"][0].reshape(-1)
    same_q = qid[:, None] == qid[None, :]
    lab_gt = label[:, None] > label[None, :]  # i is the better-labelled item
    valid = same_q & lab_gt
    s_i, s_j = score[:, None], score[None, :]
    pos = jnp.sum(valid & (s_i > s_j))
    neg = jnp.sum(valid & (s_i < s_j))
    neu = jnp.sum(valid & (s_i == s_j))
    f32 = jnp.float32
    def acc(slot, v):
        prev = ins[slot][0] if ins.get(slot) and ins[slot][0] is not None else jnp.zeros((1,), f32)
        return (v.astype(f32) + prev.reshape(-1)[0]).reshape(1)
    return {"PositivePair": [acc("AccumulatePositivePair", pos)],
            "NegativePair": [acc("AccumulateNegativePair", neg)],
            "NeutralPair": [acc("AccumulateNeutralPair", neu)]}

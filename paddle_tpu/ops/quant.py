"""Quantization ops (<- paddle/fluid/operators/fake_quantize_op.cc,
fake_dequantize_op.cc).

Fake-quant simulates int8/intN inference inside the float graph: quantize to
the integer grid, keep float dtype. On TPU the straight-through estimator
gradient (identity within range) keeps training in bf16/f32 while the MXU
sees quantization-aware values.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _ste_round(x):
    """round() with a straight-through gradient."""
    return x + jax.lax.stop_gradient(jnp.round(x) - x)


@register_op("fake_quantize_abs_max", inputs=("X",), outputs=("Out", "OutScale"),
             diff_inputs=("X",))
def fake_quantize_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    bit_length = attrs.get("bit_length", 8)
    bin_cnt = (1 << (bit_length - 1)) - 1
    # stop_gradient: the STE grad must be pure identity (reference grad is
    # dX = dOut); a differentiable scale would leak -x*127/scale^2 into the
    # max-|x| element through the vjp-derived grad
    scale = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    scale = jnp.where(scale == 0, jnp.ones_like(scale), scale)
    out = _ste_round(x / scale * bin_cnt)
    out = jnp.clip(out, -bin_cnt, bin_cnt)
    return {"Out": [out], "OutScale": [scale.reshape(1)]}


@register_op("fake_quantize_range_abs_max",
             inputs=("X", "InScale", "Iter"),
             outputs=("Out", "OutScale", "OutScales"),
             diff_inputs=("X",), no_grad=False)
def fake_quantize_range_abs_max(ctx, ins, attrs):
    """Running-max variant used in QAT: scale = max(|x|, decayed history).

    The reference keeps a window_size-deep history of per-step scales and
    takes its max; here the history is one exponentially-decayed scalar
    (decay = 1 - 1/window_size), a stateless approximation that likewise
    forgets outliers after ~window_size steps without carrying the window
    buffer through the compiled step.
    """
    x = ins["X"][0]
    bit_length = attrs.get("bit_length", 8)
    window_size = max(int(attrs.get("window_size", 10000)), 1)
    is_test = attrs.get("is_test", False) or ctx.is_test
    bin_cnt = (1 << (bit_length - 1)) - 1
    in_scale = (ins["InScale"][0].reshape(-1)[0]
                if ins.get("InScale") and ins["InScale"][0] is not None else jnp.float32(0))
    cur = jax.lax.stop_gradient(jnp.max(jnp.abs(x))).astype(jnp.float32)
    decayed = in_scale * jnp.float32(1.0 - 1.0 / window_size)
    scale = jnp.where(is_test, in_scale, jnp.maximum(cur, decayed))
    scale = jnp.where(scale == 0, jnp.ones_like(scale), scale)
    out = jnp.clip(_ste_round(x / scale * bin_cnt), -bin_cnt, bin_cnt)
    return {"Out": [out], "OutScale": [scale.reshape(1)],
            "OutScales": [scale.reshape(1)]}


@register_op("fake_dequantize_max_abs", inputs=("X", "Scale"), outputs=("Out",),
             diff_inputs=("X",))
def fake_dequantize_max_abs(ctx, ins, attrs):
    """<- fake_dequantize_op.cc: Out = Scale * X / max_range (in X's dtype)."""
    x, scale = ins["X"][0], ins["Scale"][0]
    max_range = attrs.get("max_range", 127.0)
    s = scale.reshape(-1)[0].astype(x.dtype)
    return {"Out": [x * s / jnp.asarray(max_range, x.dtype)]}


# ---------------------------------------------------------------------------
# Weight-only quantized serving kernels (docs/design.md §20)
#
# Unlike the fake-quant ops above (QAT: float values snapped to the int grid
# inside a TRAINING graph), these are the inference-side kernels: the weight
# is STORED quantized — per-output-channel symmetric int8 (+ one f32 scale
# per column) or bf16 — and dequantized on the fly inside the contraction
# with f32 accumulation (``preferred_element_type``). The scale folds into
# the convert pass the dot operand materializes anyway (weight-side; see
# dequant_matmul for why an output-epilogue scale breaks cross-layout
# bit-equality). serving/quant.py owns quantization/calibration; this module
# owns the one matmul kernel so the serving forwards (models/transformer.py)
# and the op registry lane below share a single definition.
# ---------------------------------------------------------------------------


def dequant_matmul(x2, q, scale=None):
    """``x2 [M, K] @ dequant(q) -> [M, N] f32`` with f32 accumulation.

    ``q`` is an int8 ``[K, N]`` weight with per-output-channel ``scale``
    ``[N]``, or a bf16/f16 ``[K, N]`` weight (``scale=None`` — bf16 storage
    needs no scale, the convert IS the dequant). An f32 ``q`` passes
    through the stock dot unchanged (byte-identical serving when the
    quantized lane is off).

    The scale rides the WEIGHT side of the contraction —
    ``dot(x, convert(q) * s)`` — deliberately, not the output epilogue:
    the dot operand must materialize anyway (the convert pass), so the
    scale folds into that same elementwise pass for free, and the dot's
    output feeds downstream residual adds WITHOUT an adjacent multiply.
    An output-epilogue ``dot(..) * s`` is one flop cheaper on paper but
    XLA fuses it into a following add as a single-rounded FMA in some
    layouts and not others (the sharded program has an all-gather in
    between) — measured on XLA CPU as a 1e-5-class logit divergence that
    breaks the §18 cross-layout bit-equality contract;
    ``optimization_barrier`` does NOT suppress that FMA. Weight-side
    scaling keeps every multiply an elementwise pre-pass whose per-column
    results are identical under any column split."""
    if q.dtype == jnp.int8:
        w = q.astype(jnp.float32)
        if scale is not None:
            w = w * scale
        return jnp.dot(x2, w, preferred_element_type=jnp.float32)
    w = q if q.dtype == jnp.float32 else q.astype(jnp.float32)
    return jnp.dot(x2, w, preferred_element_type=jnp.float32)


def dequant_rows(q, ids, scale=None):
    """Embedding-table sibling of ``dequant_matmul``: gather rows of a
    quantized ``[V, D]`` table. The dequant (convert · scale) applies to
    the TABLE and the gather picks dequantized rows — same rationale as
    the weight-side scale above: a row-side ``gathered * s`` would FMA
    into the following position add in layout-dependent ways."""
    if q.dtype == jnp.int8:
        table = q.astype(jnp.float32)
        if scale is not None:
            table = table * scale
        return jnp.take(table, ids, axis=0)
    rows = jnp.take(q, ids, axis=0)
    return rows if rows.dtype == jnp.float32 else rows.astype(jnp.float32)


@register_op("weight_only_quant_matmul", inputs=("X", "QWeight", "Scale"),
             outputs=("Out",), no_grad=True)
def weight_only_quant_matmul(ctx, ins, attrs):
    """Inference-only fc over a quantized weight store: the op-registry
    lane of the CPU serving tier (docs/design.md §20). ``QWeight`` is the
    int8 (with per-column ``Scale``) or bf16 stored weight; the kernel is
    the same weight-side-scaled f32-accumulated dot the quantized serving
    engines run (see ``dequant_matmul`` for why the scale must NOT move
    to an output epilogue), so a program using this op serves
    bit-identically to ``QuantizedServingEngine`` on the same store."""
    x, q = ins["X"][0], ins["QWeight"][0]
    scale = ins["Scale"][0] if ins.get("Scale") and ins["Scale"] else None
    x2 = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
    out = dequant_matmul(x2, q, scale)
    return {"Out": [out.reshape(x.shape[:-1] + (q.shape[-1],))]}

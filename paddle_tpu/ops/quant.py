"""Quantization ops (<- paddle/fluid/operators/fake_quantize_op.cc,
fake_dequantize_op.cc).

Fake-quant simulates int8/intN inference inside the float graph: quantize to
the integer grid, keep float dtype. On TPU the straight-through estimator
gradient (identity within range) keeps training in bf16/f32 while the MXU
sees quantization-aware values.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _ste_round(x):
    """round() with a straight-through gradient."""
    return x + jax.lax.stop_gradient(jnp.round(x) - x)


@register_op("fake_quantize_abs_max", inputs=("X",), outputs=("Out", "OutScale"),
             diff_inputs=("X",))
def fake_quantize_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    bit_length = attrs.get("bit_length", 8)
    bin_cnt = (1 << (bit_length - 1)) - 1
    # stop_gradient: the STE grad must be pure identity (reference grad is
    # dX = dOut); a differentiable scale would leak -x*127/scale^2 into the
    # max-|x| element through the vjp-derived grad
    scale = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    scale = jnp.where(scale == 0, jnp.ones_like(scale), scale)
    out = _ste_round(x / scale * bin_cnt)
    out = jnp.clip(out, -bin_cnt, bin_cnt)
    return {"Out": [out], "OutScale": [scale.reshape(1)]}


@register_op("fake_quantize_range_abs_max",
             inputs=("X", "InScale", "Iter"),
             outputs=("Out", "OutScale", "OutScales"),
             diff_inputs=("X",), no_grad=False)
def fake_quantize_range_abs_max(ctx, ins, attrs):
    """Running-max variant used in QAT: scale = max(|x|, decayed history).

    The reference keeps a window_size-deep history of per-step scales and
    takes its max; here the history is one exponentially-decayed scalar
    (decay = 1 - 1/window_size), a stateless approximation that likewise
    forgets outliers after ~window_size steps without carrying the window
    buffer through the compiled step.
    """
    x = ins["X"][0]
    bit_length = attrs.get("bit_length", 8)
    window_size = max(int(attrs.get("window_size", 10000)), 1)
    is_test = attrs.get("is_test", False) or ctx.is_test
    bin_cnt = (1 << (bit_length - 1)) - 1
    in_scale = (ins["InScale"][0].reshape(-1)[0]
                if ins.get("InScale") and ins["InScale"][0] is not None else jnp.float32(0))
    cur = jax.lax.stop_gradient(jnp.max(jnp.abs(x))).astype(jnp.float32)
    decayed = in_scale * jnp.float32(1.0 - 1.0 / window_size)
    scale = jnp.where(is_test, in_scale, jnp.maximum(cur, decayed))
    scale = jnp.where(scale == 0, jnp.ones_like(scale), scale)
    out = jnp.clip(_ste_round(x / scale * bin_cnt), -bin_cnt, bin_cnt)
    return {"Out": [out], "OutScale": [scale.reshape(1)],
            "OutScales": [scale.reshape(1)]}


@register_op("fake_dequantize_max_abs", inputs=("X", "Scale"), outputs=("Out",),
             diff_inputs=("X",))
def fake_dequantize_max_abs(ctx, ins, attrs):
    """<- fake_dequantize_op.cc: Out = Scale * X / max_range (in X's dtype)."""
    x, scale = ins["X"][0], ins["Scale"][0]
    max_range = attrs.get("max_range", 127.0)
    s = scale.reshape(-1)[0].astype(x.dtype)
    return {"Out": [x * s / jnp.asarray(max_range, x.dtype)]}

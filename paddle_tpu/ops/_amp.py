"""Shared AMP (bf16 mixed-precision) dtype policy helpers.

One place for the rules every kernel applies under ``ctx.amp``:

* activations flow bf16 end-to-end (HBM bandwidth is the bottleneck);
* master parameters stay f32 in the scope — kernels cast them to bf16 at
  the point of use, and the vjp of that cast accumulates the param grad
  back in f32 automatically;
* matmul/conv accumulate in f32 (requested explicitly via
  ``preferred_element_type``) and store bf16;
* precision-sensitive math (softmax/log/normalization statistics) computes
  in f32 and casts the result back to the activation dtype.
"""
from __future__ import annotations

import jax.numpy as jnp


def low_precision(dtype) -> bool:
    """True for sub-32-bit floats (bf16/f16/f8...)."""
    return jnp.issubdtype(dtype, jnp.floating) and jnp.finfo(dtype).bits < 32


def amp_operand(ctx, *xs):
    """Cast float operands to bf16 when AMP is on (matmul/conv inputs)."""
    if getattr(ctx, "amp", False):
        return tuple(
            x.astype(jnp.bfloat16)
            if x is not None and jnp.issubdtype(x.dtype, jnp.floating) else x
            for x in xs)
    return xs


def recurrent_cast(amp: bool, weights=(), carries=()):
    """AMP recipe for recurrences (lstm/gru/lstmp/attention decoder):
    weights go bf16 once outside the scan, carries go f32 — the recurrent
    state is an accumulator across T steps and bf16 drift compounds; step
    bodies cast the carry to the weight dtype right before each matmul.
    Returns (weights, carries) unchanged when ``amp`` is False."""
    if amp:
        weights = tuple(w.astype(jnp.bfloat16) for w in weights)
        carries = tuple(c.astype(jnp.float32) for c in carries)
    return weights, carries


def emit_cast(amp: bool, *vals):
    """AMP dtype for a scan's STACKED per-step emits: bf16 when amp (the
    consumers cast them into their matmuls anyway; only the carry is an
    accumulator and stays f32 — see recurrent_cast), unchanged otherwise.
    One helper so every recurrence (lstm, gru, attention decoder) applies
    the same recipe; measured -1.3 ms/step on the seq2seq bench
    (docs/perf.md "Seq2seq round 5")."""
    import jax.numpy as jnp

    if not amp:
        return vals if len(vals) != 1 else vals[0]
    out = tuple(v.astype(jnp.bfloat16) for v in vals)
    return out if len(out) != 1 else out[0]


def f32_compute(ctx, x):
    """Upcast a low-precision tensor to f32 for precision-sensitive math.

    The caller is responsible for casting the result back (``x.dtype``) if
    the value feeds further bf16 activation flow.
    """
    if getattr(ctx, "amp", False) and low_precision(x.dtype):
        return x.astype(jnp.float32)
    return x

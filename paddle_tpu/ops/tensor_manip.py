"""Shape/layout manipulation ops.

<- paddle/fluid/operators/{reshape,transpose,concat,split,expand,gather,
scatter,pad,crop,reverse,squeeze/unsqueeze(absent in ref),stack,multiplex,
slice(sequence_slice)}_op.cc. These are pure metadata/data-movement ops; XLA
folds most of them into neighbouring computations.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op


@register_op("reshape", inputs=("X",), outputs=("Out",))
def reshape(ctx, ins, attrs):
    x = ins["X"][0]
    shape = list(attrs["shape"])
    # reference semantics: 0 = copy input dim at that position, -1 = infer
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return {"Out": [x.reshape(shape)]}


@register_op("reshape2", inputs=("X",), outputs=("Out", "XShape"), diff_inputs=("X",))
def reshape2(ctx, ins, attrs):
    out = reshape(ctx, ins, attrs)
    return {"Out": out["Out"], "XShape": [jnp.zeros((0,) + ins["X"][0].shape)]}


@register_op("transpose", inputs=("X",), outputs=("Out",))
def transpose(ctx, ins, attrs):
    return {"Out": [jnp.transpose(ins["X"][0], attrs["axis"])]}


@register_op("concat", inputs=("X",), outputs=("Out",))
def concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("split", inputs=("X",), outputs=("Out",))
def split(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if num:
        parts = jnp.split(x, num, axis=axis)
    else:
        idx = []
        acc = 0
        for s in sections[:-1]:
            acc += s
            idx.append(acc)
        parts = jnp.split(x, idx, axis=axis)
    return {"Out": parts}


@register_op("expand", inputs=("X",), outputs=("Out",))
def expand(ctx, ins, attrs):
    return {"Out": [jnp.tile(ins["X"][0], attrs["expand_times"])]}


@register_op("gather", inputs=("X", "Index"), outputs=("Out",), diff_inputs=("X",))
def gather(ctx, ins, attrs):
    idx = ins["Index"][0]
    if idx.ndim == 2 and idx.shape[-1] == 1:
        idx = idx.squeeze(-1)
    return {"Out": [jnp.take(ins["X"][0], idx.astype(jnp.int32), axis=0)]}


@register_op("scatter", inputs=("X", "Ids", "Updates"), outputs=("Out",),
             diff_inputs=("X", "Updates"))
def scatter(ctx, ins, attrs):
    x, ids, upd = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    if ids.ndim == 2 and ids.shape[-1] == 1:
        ids = ids.squeeze(-1)
    ids = ids.astype(jnp.int32)
    if attrs.get("overwrite", True):
        return {"Out": [x.at[ids].set(upd)]}
    return {"Out": [x.at[ids].add(upd)]}


@register_op("pad", inputs=("X",), outputs=("Out",))
def pad(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs["paddings"]  # flat [before0, after0, before1, after1, ...]
    pairs = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, pairs, constant_values=attrs.get("pad_value", 0.0))]}


@register_op("crop", inputs=("X", "Y"), outputs=("Out",), diff_inputs=("X",))
def crop(ctx, ins, attrs):
    x = ins["X"][0]
    offsets = attrs.get("offsets")
    shape = attrs.get("shape")
    if ins.get("Y") and ins["Y"][0] is not None:
        shape = ins["Y"][0].shape
    slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return {"Out": [x[slices]]}


@register_op("slice", inputs=("Input",), outputs=("Out",), diff_inputs=("Input",))
def slice_op(ctx, ins, attrs):
    x = ins["Input"][0]
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    sl = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        sl[ax] = slice(st, en)
    return {"Out": [x[tuple(sl)]]}


@register_op("reverse", inputs=("X",), outputs=("Out",))
def reverse(ctx, ins, attrs):
    axes = attrs.get("axis", [0])
    if isinstance(axes, int):
        axes = [axes]
    x = ins["X"][0]
    for ax in axes:
        x = jnp.flip(x, ax)
    return {"Out": [x]}


@register_op("stack", inputs=("X",), outputs=("Y",))
def stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("unstack", inputs=("X",), outputs=("Y",))
def unstack(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    n = x.shape[axis]
    return {"Y": [jnp.squeeze(p, axis) for p in jnp.split(x, n, axis)]}


@register_op("squeeze", inputs=("X",), outputs=("Out",))
def squeeze(ctx, ins, attrs):
    axes = attrs.get("axes", [])
    x = ins["X"][0]
    if not axes:
        return {"Out": [jnp.squeeze(x)]}
    return {"Out": [jnp.squeeze(x, axis=tuple(axes))]}


@register_op("unsqueeze", inputs=("X",), outputs=("Out",))
def unsqueeze(ctx, ins, attrs):
    x = ins["X"][0]
    for ax in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, ax)
    return {"Out": [x]}


@register_op("multiplex", inputs=("Ids", "X"), outputs=("Out",), diff_inputs=("X",))
def multiplex(ctx, ins, attrs):
    ids = ins["Ids"][0]
    stackx = jnp.stack(ins["X"], axis=0)  # [K, N, D]
    if ids.ndim == 2:
        ids = ids.squeeze(-1)
    n = stackx.shape[1]
    return {"Out": [stackx[ids.astype(jnp.int32), jnp.arange(n)]]}


@register_op("flatten", inputs=("X",), outputs=("Out",))
def flatten(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    lead = 1
    for d in x.shape[:axis]:
        lead *= d
    return {"Out": [x.reshape(lead, -1)]}

"""Activation functors (<- paddle/fluid/operators/activation_op.cc ~25
functors, softmax_op.cc, prelu_op.cc). One registration helper; grads come
from the registry's generic vjp machinery so every activation's backward is
exactly consistent with its forward."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ._amp import f32_compute as _f32_compute


def _register_act(name, fn):
    @register_op(name, inputs=("X",), outputs=("Out",))
    def impl(ctx, ins, attrs, _fn=fn):
        return {"Out": [_fn(ins["X"][0], attrs)]}


_ACTS = {
    "relu": lambda x, a: jnp.maximum(x, 0),
    "sigmoid": lambda x, a: jax.nn.sigmoid(x),
    "logsigmoid": lambda x, a: jax.nn.log_sigmoid(x),
    "tanh": lambda x, a: jnp.tanh(x),
    "tanh_shrink": lambda x, a: x - jnp.tanh(x),
    "exp": lambda x, a: jnp.exp(x),
    "log": lambda x, a: jnp.log(x),
    "sqrt": lambda x, a: jnp.sqrt(x),
    "abs": lambda x, a: jnp.abs(x),
    "square": lambda x, a: x * x,
    "reciprocal": lambda x, a: 1.0 / x,
    "ceil": lambda x, a: jnp.ceil(x),
    "floor": lambda x, a: jnp.floor(x),
    "round": lambda x, a: jnp.round(x),
    "softplus": lambda x, a: jax.nn.softplus(x),
    "softsign": lambda x, a: x / (1 + jnp.abs(x)),
    "softshrink": lambda x, a: jnp.where(
        x > a.get("lambda", 0.5), x - a.get("lambda", 0.5),
        jnp.where(x < -a.get("lambda", 0.5), x + a.get("lambda", 0.5), 0.0)),
    "hard_shrink": lambda x, a: jnp.where(
        jnp.abs(x) > a.get("threshold", 0.5), x, 0.0),
    "hard_sigmoid": lambda x, a: jnp.clip(
        a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0),
    "relu6": lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)),
    "brelu": lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)),
    "leaky_relu": lambda x, a: jnp.where(x >= 0, x, a.get("alpha", 0.02) * x),
    "elu": lambda x, a: jnp.where(x >= 0, x, a.get("alpha", 1.0) * (jnp.exp(x) - 1)),
    "swish": lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x),
    "stanh": lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(a.get("scale_a", 2.0 / 3.0) * x),
    "thresholded_relu": lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0),
    "pow": lambda x, a: jnp.power(x, a.get("factor", 1.0)),
}

for _n, _f in _ACTS.items():
    _register_act(_n, _f)


@register_op("softmax", inputs=("X",), outputs=("Out",))
def softmax(ctx, ins, attrs):
    """AMP: the exp/normalize runs in f32 (the rowmax subtraction needs the
    mantissa) but the result is stored back in the activation dtype —
    attention probabilities are the largest tensor in a transformer and must
    not be materialized f32. Loss-head consumers (cross_entropy) re-upcast."""
    x = ins["X"][0]
    xf = _f32_compute(ctx, x)
    return {"Out": [jax.nn.softmax(xf, axis=attrs.get("axis", -1)).astype(x.dtype)]}


@register_op("log_softmax", inputs=("X",), outputs=("Out",))
def log_softmax(ctx, ins, attrs):
    x = ins["X"][0]
    xf = _f32_compute(ctx, x)
    return {"Out": [jax.nn.log_softmax(xf, axis=attrs.get("axis", -1)).astype(x.dtype)]}


@register_op("prelu", inputs=("X", "Alpha"), outputs=("Out",))
def prelu(ctx, ins, attrs):
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "channel" and alpha.ndim == 1 and x.ndim == 4:
        alpha = alpha.reshape(1, -1, 1, 1)
    return {"Out": [jnp.where(x >= 0, x, alpha * x)]}


@register_op("maxout", inputs=("X",), outputs=("Out",))
def maxout(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    groups = attrs["groups"]
    n, c, h, w = x.shape
    return {"Out": [x.reshape(n, c // groups, groups, h, w).max(axis=2)]}

"""Operator library: importing this package registers every op.

The inventory tracks SURVEY.md §2b / paddle/fluid/operators; each module's
docstring cites the reference files it re-imagines for TPU/XLA.
"""
from ..core.registry import get_op_def, has_op, register_op, registered_ops  # noqa: F401

from . import basic  # noqa: F401
from . import math  # noqa: F401
from . import activations  # noqa: F401
from . import loss  # noqa: F401
from . import nn  # noqa: F401
from . import tensor_manip  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import metrics_ops  # noqa: F401
from . import sequence  # noqa: F401
from . import rnn  # noqa: F401
from . import attention  # noqa: F401
from . import pallas_attention  # noqa: F401
from . import pallas_matmul  # noqa: F401
from . import pipelined_stack  # noqa: F401
from . import control_flow  # noqa: F401
from . import structured  # noqa: F401
from . import detection  # noqa: F401
from . import quant  # noqa: F401

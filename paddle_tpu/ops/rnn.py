"""Recurrent ops: LSTM / GRU over dense padded sequences via lax.scan.

<- paddle/fluid/operators/{lstm,lstm_unit,gru,gru_unit}_op.cc and the cell
kernels in operators/math/detail/. The reference iterates host-side over LoD
batches (sequence2batch reordering + shrink_rnn_memory as short sequences
finish); here the whole recurrence is ONE lax.scan compiled by XLA, and
"shrinking" is a per-step mask that freezes finished sequences — same math,
no host loop, MXU-friendly [N, 4H] gate matmuls at every step.

Gate order convention: i, f, c(candidate), o for LSTM; u(update), r(reset),
c(candidate) for GRU. Documented here because the reference's blob layout
differs; capability parity, not byte layout, is the contract.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ._amp import emit_cast as _emit_cast
from ._amp import recurrent_cast as _recurrent_cast

_ACT = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": lambda x: jnp.maximum(x, 0),
    "identity": lambda x: x,
}


def _lstm_scan(x, h0, c0, w, bias, peephole, length, gate_act, cell_act, cand_act,
               is_reverse=False, amp=False):
    """x: [N, T, 4H] (input projection already applied), w: [H, 4H].

    AMP recipe for recurrences: the carry (h, c) stays f32 — the cell state
    is an accumulator across T steps and bf16 drift compounds — while the
    recurrent matmul runs bf16 (h cast per step, weight cast once). The
    scan's carry dtype is then stable by construction.
    """
    n, t, h4 = x.shape
    h = h4 // 4
    (w,), (h0, c0) = _recurrent_cast(amp, weights=(w,), carries=(h0, c0))
    if is_reverse:
        # reverse within valid region
        idx = length.reshape(-1, 1) - 1 - jnp.arange(t)[None, :]
        idx = jnp.where(idx >= 0, idx, jnp.arange(t)[None, :])
        x = jnp.take_along_axis(x, idx[..., None].astype(jnp.int32), axis=1)
    xs = jnp.moveaxis(x, 1, 0)  # [T, N, 4H]
    step_mask = (jnp.arange(t)[:, None] < length.reshape(1, -1)).astype(x.dtype)

    def step(carry, inp):
        h_prev, c_prev = carry
        xt, m = inp
        gates = xt + h_prev.astype(w.dtype) @ w
        i, f, c_bar, o = jnp.split(gates + bias, 4, axis=-1)
        if peephole is not None:
            p_i, p_f, p_o = jnp.split(peephole, 3)
            i = i + c_prev * p_i
            f = f + c_prev * p_f
        i = gate_act(i)
        f = gate_act(f)
        c_new = f * c_prev + i * cand_act(c_bar)
        if peephole is not None:
            o = o + c_new * p_o
        o = gate_act(o)
        h_new = o * cell_act(c_new)
        m = m[:, None]
        h_out = m * h_new + (1 - m) * h_prev
        c_out = m * c_new + (1 - m) * c_prev
        # AMP: the stacked per-step OUTPUTS emit bf16 (consumers cast them
        # for their matmuls anyway) while the carry stays f32 — the
        # accumulator across T steps keeps full precision, only the
        # exported sequence rounds. Halves the scan-output stacking
        # traffic the seq2seq profile charges ~1.8 ms/step for.
        return (h_out, c_out), _emit_cast(amp, h_out * m, c_out * m)

    (hT, cT), (hs, cs) = lax.scan(step, (h0, c0), (xs, step_mask))
    hidden = jnp.moveaxis(hs, 0, 1)
    cell = jnp.moveaxis(cs, 0, 1)
    if is_reverse:
        idx = length.reshape(-1, 1) - 1 - jnp.arange(t)[None, :]
        idx = jnp.where(idx >= 0, idx, jnp.arange(t)[None, :])
        hidden = jnp.take_along_axis(hidden, idx[..., None].astype(jnp.int32), axis=1)
        cell = jnp.take_along_axis(cell, idx[..., None].astype(jnp.int32), axis=1)
    return hidden, cell, hT, cT


@register_op(
    "lstm",
    inputs=("Input", "H0", "C0", "Weight", "Bias", "Length"),
    outputs=("Hidden", "Cell", "LastH", "LastC"),
    diff_inputs=("Input", "H0", "C0", "Weight", "Bias"),
)
def lstm(ctx, ins, attrs):
    x = ins["Input"][0]
    n, t, h4 = x.shape
    h = h4 // 4
    w = ins["Weight"][0]
    bias_in = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    use_peep = attrs.get("use_peepholes", False)
    if bias_in is None:
        bias = jnp.zeros((h4,), x.dtype)
        peephole = jnp.zeros((3 * h,), x.dtype) if use_peep else None
    else:
        b = bias_in.reshape(-1)
        if use_peep:
            bias, peephole = b[:h4], b[h4 : h4 + 3 * h]
        else:
            bias, peephole = b[:h4], None
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else jnp.zeros((n, h), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") and ins["C0"][0] is not None else jnp.zeros((n, h), x.dtype)
    length = (ins["Length"][0] if ins.get("Length") and ins["Length"][0] is not None
              else jnp.full((n,), t, jnp.int32))
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACT[attrs.get("cell_activation", "tanh")]
    cand_act = _ACT[attrs.get("candidate_activation", "tanh")]
    hidden, cell, hT, cT = _lstm_scan(
        x, h0, c0, w, bias, peephole, length, gate_act, cell_act, cand_act,
        is_reverse=attrs.get("is_reverse", False),
        amp=getattr(ctx, "amp", False),
    )
    return {"Hidden": [hidden], "Cell": [cell], "LastH": [hT], "LastC": [cT]}


@register_op(
    "gru",
    inputs=("Input", "H0", "Weight", "Bias", "Length"),
    outputs=("Hidden", "LastH"),
    diff_inputs=("Input", "H0", "Weight", "Bias"),
)
def gru(ctx, ins, attrs):
    """x: [N, T, 3H] gate order (u, r, c); w packs [H, 2H] for u,r and
    [H, H] for the candidate (<- gru_op.cc layout, re-expressed)."""
    x = ins["Input"][0]
    n, t, h3 = x.shape
    h = h3 // 3
    w = ins["Weight"][0]  # [H, 3H]
    w_ur, w_c = w[:, : 2 * h], w[:, 2 * h :]
    bias = (ins["Bias"][0].reshape(-1) if ins.get("Bias") and ins["Bias"][0] is not None
            else jnp.zeros((h3,), x.dtype))
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else jnp.zeros((n, h), x.dtype)
    length = (ins["Length"][0] if ins.get("Length") and ins["Length"][0] is not None
              else jnp.full((n,), t, jnp.int32))
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACT[attrs.get("activation", "tanh")]
    amp = getattr(ctx, "amp", False)
    (w_ur, w_c), (h0,) = _recurrent_cast(
        amp, weights=(w_ur, w_c), carries=(h0,))
    is_reverse = attrs.get("is_reverse", False)
    if is_reverse:
        idx = length.reshape(-1, 1) - 1 - jnp.arange(t)[None, :]
        idx = jnp.where(idx >= 0, idx, jnp.arange(t)[None, :])
        x = jnp.take_along_axis(x, idx[..., None].astype(jnp.int32), axis=1)
    xs = jnp.moveaxis(x, 1, 0)
    step_mask = (jnp.arange(t)[:, None] < length.reshape(1, -1)).astype(x.dtype)

    def step(h_prev, inp):
        xt, m = inp
        ur = gate_act(xt[:, : 2 * h] + h_prev.astype(w_ur.dtype) @ w_ur
                      + bias[: 2 * h])
        u, r = ur[:, :h], ur[:, h:]
        c = cand_act(xt[:, 2 * h :] + (r * h_prev).astype(w_c.dtype) @ w_c
                     + bias[2 * h :])
        h_new = u * h_prev + (1 - u) * c
        m = m[:, None]
        h_out = m * h_new + (1 - m) * h_prev
        return h_out, _emit_cast(amp, h_out * m)

    hT, hs = lax.scan(step, h0, (xs, step_mask))
    hidden = jnp.moveaxis(hs, 0, 1)
    if is_reverse:
        idx = length.reshape(-1, 1) - 1 - jnp.arange(t)[None, :]
        idx = jnp.where(idx >= 0, idx, jnp.arange(t)[None, :])
        hidden = jnp.take_along_axis(hidden, idx[..., None].astype(jnp.int32), axis=1)
    return {"Hidden": [hidden], "LastH": [hT]}


@register_op(
    "lstm_unit",
    inputs=("X", "C_prev"),
    outputs=("C", "H"),
    diff_inputs=("X", "C_prev"),
)
def lstm_unit(ctx, ins, attrs):
    """Single LSTM step on pre-projected gates X=[N,4H] (<- lstm_unit_op.cc)."""
    x, c_prev = ins["X"][0], ins["C_prev"][0]
    forget_bias = attrs.get("forget_bias", 0.0)
    i, f, c_bar, o = jnp.split(x, 4, axis=-1)
    c = jax.nn.sigmoid(f + forget_bias) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(c_bar)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return {"C": [c], "H": [h]}


@register_op(
    "gru_unit",
    inputs=("Input", "HiddenPrev", "Weight", "Bias"),
    outputs=("Gate", "ResetHiddenPrev", "Hidden"),
    diff_inputs=("Input", "HiddenPrev", "Weight", "Bias"),
)
def gru_unit(ctx, ins, attrs):
    """Single GRU step (<- gru_unit_op.cc). Input [N,3H] pre-projected."""
    x, h_prev, w = ins["Input"][0], ins["HiddenPrev"][0], ins["Weight"][0]
    h = h_prev.shape[-1]
    bias = (ins["Bias"][0].reshape(-1) if ins.get("Bias") and ins["Bias"][0] is not None
            else jnp.zeros((3 * h,), x.dtype))
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACT[attrs.get("activation", "tanh")]
    w_ur, w_c = w[:, : 2 * h], w[:, 2 * h :]
    ur = gate_act(x[:, : 2 * h] + h_prev @ w_ur + bias[: 2 * h])
    u, r = ur[:, :h], ur[:, h:]
    r_h = r * h_prev
    c = cand_act(x[:, 2 * h :] + r_h @ w_c + bias[2 * h :])
    h_new = u * h_prev + (1 - u) * c
    gate = jnp.concatenate([u, r, c], axis=-1)
    return {"Gate": [gate], "ResetHiddenPrev": [r_h], "Hidden": [h_new]}


@register_op(
    "lstmp",
    inputs=("Input", "H0", "C0", "Weight", "ProjWeight", "Bias", "Length"),
    outputs=("Projection", "Cell", "LastH", "LastC"),
    diff_inputs=("Input", "H0", "C0", "Weight", "ProjWeight", "Bias"),
)
def lstmp(ctx, ins, attrs):
    """LSTM with recurrent projection layer (<- lstmp_op.cc).

    Input [N, T, 4H] pre-projected gates; Weight [P, 4H] acts on the
    *projected* recurrent state r [N, P]; ProjWeight [H, P] maps the cell
    output h to the projection. Same masked lax.scan as ``lstm`` — the
    projection matmul rides the MXU inside the scan body.
    """
    x = ins["Input"][0]
    n, t, h4 = x.shape
    h = h4 // 4
    w = ins["Weight"][0]           # [P, 4H]
    w_proj = ins["ProjWeight"][0]  # [H, P]
    p = w_proj.shape[1]
    use_peep = attrs.get("use_peepholes", False)
    bias_in = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    if bias_in is None:
        bias = jnp.zeros((h4,), x.dtype)
        peephole = jnp.zeros((3 * h,), x.dtype) if use_peep else None
    else:
        b = bias_in.reshape(-1)
        bias = b[:h4]
        peephole = b[h4 : h4 + 3 * h] if use_peep else None
    r0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else jnp.zeros((n, p), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") and ins["C0"][0] is not None else jnp.zeros((n, h), x.dtype)
    length = (ins["Length"][0] if ins.get("Length") and ins["Length"][0] is not None
              else jnp.full((n,), t, jnp.int32))
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACT[attrs.get("cell_activation", "tanh")]
    cand_act = _ACT[attrs.get("candidate_activation", "tanh")]
    proj_act = _ACT[attrs.get("proj_activation", "tanh")]
    (w, w_proj), (r0, c0) = _recurrent_cast(
        getattr(ctx, "amp", False), weights=(w, w_proj), carries=(r0, c0))
    xs = jnp.moveaxis(x, 1, 0)
    step_mask = (jnp.arange(t)[:, None] < length.reshape(1, -1)).astype(x.dtype)

    def step(carry, inp):
        r_prev, c_prev = carry
        xt, m = inp
        gates = xt + r_prev.astype(w.dtype) @ w + bias
        i, f, c_bar, o = jnp.split(gates, 4, axis=-1)
        if peephole is not None:
            p_i, p_f, p_o = jnp.split(peephole, 3)
            i = i + c_prev * p_i
            f = f + c_prev * p_f
        i, f = gate_act(i), gate_act(f)
        c_new = f * c_prev + i * cand_act(c_bar)
        if peephole is not None:
            o = o + c_new * p_o
        o = gate_act(o)
        h_new = o * cell_act(c_new)
        r_new = proj_act(h_new.astype(w_proj.dtype) @ w_proj)
        m = m[:, None]
        r_out = m * r_new + (1 - m) * r_prev
        c_out = m * c_new + (1 - m) * c_prev
        return (r_out, c_out), (r_out * m, c_out * m)

    (rT, cT), (rs, cs) = lax.scan(step, (r0, c0), (xs, step_mask))
    return {"Projection": [jnp.moveaxis(rs, 0, 1)], "Cell": [jnp.moveaxis(cs, 0, 1)],
            "LastH": [rT], "LastC": [cT]}

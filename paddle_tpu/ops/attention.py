"""Fused attention decoder + beam search.

The reference builds seq2seq attention decoding out of DynamicRNN pieces
(book/08.machine_translation: rnn_encoder_decoder with attention built from
matmul/softmax/sequence_expand inside a DynamicRNN block) and decodes with the
step-wise beam_search/beam_search_decode op pair over LoD arrays
(operators/beam_search_op.cc, beam_search_decode_op.cc).

Neither maps well to XLA (host-driven step loops, ragged beam state), so the
TPU-native design fuses each into ONE op:

* ``attention_lstm_decoder`` — teacher-forced training decoder: a single
  lax.scan whose body does masked dot-product attention over the encoder
  states + one LSTM cell step. XLA keeps the whole recurrence on-device.
* ``attention_lstm_beam_decode`` — inference: lax.scan over decode steps
  carrying a fixed-capacity beam (tokens [N, K, L], scores [N, K]), with
  top-k expansion per step and EOS freezing — the fixed-shape re-design of
  the reference's growing LoD beams.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ._amp import emit_cast as _emit_cast
from ._amp import recurrent_cast as _recurrent_cast


def _attend(h, enc, enc_mask, encw):
    """Luong general attention: scores = h Wa enc^T, masked softmax, context.

    ``encw`` is enc @ Wa^T, precomputed ONCE outside the recurrence —
    (h Wa) . enc == h . (enc Wa^T), so hoisting the projection onto the
    (step-invariant) encoder states removes one [N, H] x [H, H] matmul
    from every scan step (the decoder runs T of them, fwd and bwd).

    Dtype-driven AMP: callers cast ``encw``/``enc`` to bf16 and carry ``h``
    in f32; the matmuls then run bf16 while the softmax normalizes in f32.
    """
    scores = jnp.einsum("nh,nth->nt", h.astype(encw.dtype), encw)
    scores = jnp.where(enc_mask, scores.astype(jnp.float32),
                       jnp.finfo(jnp.float32).min)
    alpha = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("nt,nth->nh", alpha.astype(enc.dtype), enc)
    return ctx, alpha


def _decoder_step(pre_t, h_prev, c_prev, enc, enc_mask, encw, wch, b):
    """One attention-LSTM step. ``pre_t`` is this step's share of the
    embedding projection (computed for ALL steps in one batched matmul
    outside the scan — the per-step scan body then runs a single fused
    [N, H+H] x [2H, 4H] matmul over [ctx, h] instead of three small ones;
    the recurrence itself is the only work that must stay sequential)."""
    ctx, alpha = _attend(h_prev, enc, enc_mask, encw)
    ch = jnp.concatenate([ctx, h_prev.astype(ctx.dtype)], axis=-1)
    gates = pre_t + ch.astype(wch.dtype) @ wch + b
    i, f, c_bar, o = jnp.split(gates, 4, axis=-1)
    c_new = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(c_bar)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    return h_new, c_new, ctx, alpha


@register_op(
    "attention_lstm_decoder",
    inputs=("TrgEmb", "EncOut", "EncLength", "InitH", "InitC",
            "AttnW", "InputW", "HiddenW", "Bias", "TrgLength"),
    outputs=("Hidden", "Context"),
    diff_inputs=("TrgEmb", "EncOut", "InitH", "InitC", "AttnW", "InputW",
                 "HiddenW", "Bias"),
)
def attention_lstm_decoder(ctx_, ins, attrs):
    emb = ins["TrgEmb"][0]  # [N, Td, E]
    enc = ins["EncOut"][0]  # [N, Ts, H]
    enc_len = ins["EncLength"][0]
    h0, c0 = ins["InitH"][0], ins["InitC"][0]
    wa, wx, wh = ins["AttnW"][0], ins["InputW"][0], ins["HiddenW"][0]
    b = (ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None
         else jnp.zeros((wx.shape[1],), emb.dtype))
    n, td, _ = emb.shape
    ts = enc.shape[1]
    amp = getattr(ctx_, "amp", False)
    (wa, wx, wh, enc, emb), (h0, c0) = _recurrent_cast(
        amp, weights=(wa, wx, wh, enc, emb), carries=(h0, c0))
    enc_mask = jnp.arange(ts)[None, :] < enc_len.reshape(-1, 1)
    trg_len = (ins["TrgLength"][0] if ins.get("TrgLength") and ins["TrgLength"][0] is not None
               else jnp.full((n,), td, jnp.int32))
    step_mask = (jnp.arange(td)[:, None] < trg_len.reshape(1, -1)).astype(emb.dtype)
    # hoist the embedding half of the input projection out of the scan:
    # wx rows split [emb | ctx]; emb @ wx_e is context-independent, so it
    # runs as ONE [N*Td, E] x [E, 4H] MXU matmul instead of Td small ones
    e = emb.shape[-1]
    wx_e, wx_c = wx[:e], wx[e:]
    pre = jnp.einsum("nte,eg->ntg", emb, wx_e)
    # fuse the two remaining per-step matmuls: [ctx, h] @ [[wx_c], [wh]]
    wch = jnp.concatenate([wx_c, wh], axis=0)
    # hoist the attention projection onto the (fixed) encoder states
    encw = jnp.einsum("ntj,ij->nti", enc, wa)

    def step(carry, inp):
        h_prev, c_prev = carry
        pre_t, m = inp
        h_new, c_new, ctx_t, _ = _decoder_step(
            pre_t, h_prev, c_prev, enc, enc_mask, encw, wch, b)
        m = m[:, None]
        h_out = m * h_new + (1 - m) * h_prev
        c_out = m * c_new + (1 - m) * c_prev
        # bf16 stacked emits under AMP; f32 carry (see ops/rnn.py)
        return (h_out, c_out), _emit_cast(amp, h_out * m, ctx_t * m)

    (_, _), (hs, ctxs) = lax.scan(step, (h0, c0), (jnp.moveaxis(pre, 1, 0), step_mask))
    return {"Hidden": [jnp.moveaxis(hs, 0, 1)], "Context": [jnp.moveaxis(ctxs, 0, 1)]}


@register_op(
    "attention_lstm_beam_decode",
    inputs=("EncOut", "EncLength", "InitH", "InitC", "Embedding",
            "AttnW", "InputW", "HiddenW", "Bias", "OutW", "OutB"),
    outputs=("Ids", "Scores"),
    no_grad=True,
)
def attention_lstm_beam_decode(ctx_, ins, attrs):
    """Beam search over the attention decoder.

    attrs: beam_size K, max_len L, bos_id, eos_id.
    Outputs Ids [N, K, L] (eos-padded) and Scores [N, K] (sum log-prob),
    beams sorted best-first — the dense analogue of beam_search_decode's
    LoD sentence tensor.
    """
    enc, enc_len = ins["EncOut"][0], ins["EncLength"][0]
    h0, c0 = ins["InitH"][0], ins["InitC"][0]
    table = ins["Embedding"][0]  # [V, E]
    wa, wx, wh = ins["AttnW"][0], ins["InputW"][0], ins["HiddenW"][0]
    b = (ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None
         else jnp.zeros((wx.shape[1],), enc.dtype))
    ow = ins["OutW"][0]  # [H, V]
    ob = (ins["OutB"][0] if ins.get("OutB") and ins["OutB"][0] is not None
          else jnp.zeros((ow.shape[1],), enc.dtype))
    K = attrs.get("beam_size", 4)
    L = attrs.get("max_len", 32)
    bos = attrs.get("bos_id", 0)
    eos = attrs.get("eos_id", 1)
    n, ts, h = enc.shape[0], enc.shape[1], h0.shape[-1]
    v = ow.shape[1]

    enc_mask = jnp.arange(ts)[None, :] < enc_len.reshape(-1, 1)
    # beam-expanded encoder state: [N*K, Ts, H]
    encK = jnp.repeat(enc, K, axis=0)
    enc_maskK = jnp.repeat(enc_mask, K, axis=0)

    tokens0 = jnp.full((n, K), bos, jnp.int32)
    # only beam 0 is live initially (others -inf) so step 1 picks distinct tokens
    scores0 = jnp.where(jnp.arange(K)[None, :] == 0, 0.0,
                        jnp.finfo(enc.dtype).min).astype(enc.dtype)
    scores0 = jnp.broadcast_to(scores0, (n, K))
    hK = jnp.repeat(h0, K, axis=0)
    cK = jnp.repeat(c0, K, axis=0)
    ids0 = jnp.full((n, K, L), eos, jnp.int32)
    finished0 = jnp.zeros((n, K), bool)

    # same split/fuse as the training decoder (see attention_lstm_decoder):
    # tokens are data-dependent so the emb projection stays per step, but
    # it still fuses with the gate add, and [ctx, h] shares one matmul
    e_dim = table.shape[1]
    wx_e, wx_c = wx[:e_dim], wx[e_dim:]
    wch = jnp.concatenate([wx_c, wh], axis=0)
    encwK = jnp.repeat(jnp.einsum("ntj,ij->nti", enc, wa), K, axis=0)

    def step(carry, t):
        tokens, scores, hK, cK, ids, finished = carry
        emb_t = table[tokens.reshape(-1)]  # [N*K, E]
        pre_t = emb_t.astype(wx_e.dtype) @ wx_e
        h_new, c_new, _, _ = _decoder_step(pre_t, hK, cK, encK, enc_maskK,
                                           encwK, wch, b)
        logp = jax.nn.log_softmax(h_new @ ow + ob)  # [N*K, V]
        logp = logp.reshape(n, K, v)
        # finished beams only extend with EOS at zero cost
        eos_only = jnp.full((v,), jnp.finfo(enc.dtype).min).at[eos].set(0.0)
        logp = jnp.where(finished[..., None], eos_only[None, None, :], logp)
        cand = scores[..., None] + logp  # [N, K, V]
        flat = cand.reshape(n, K * v)
        top_scores, top_idx = lax.top_k(flat, K)  # [N, K]
        beam_src = (top_idx // v).astype(jnp.int32)
        new_tok = (top_idx % v).astype(jnp.int32)
        gather = lambda x: jnp.take_along_axis(x, beam_src, axis=1)
        batch_ix = jnp.arange(n)[:, None]
        h_newK = h_new.reshape(n, K, h)[batch_ix, beam_src].reshape(n * K, h)
        c_newK = c_new.reshape(n, K, h)[batch_ix, beam_src].reshape(n * K, h)
        new_finished = gather(finished) | (new_tok == eos)
        ids = ids[batch_ix, beam_src]  # reorder histories
        ids = ids.at[:, :, t].set(new_tok)
        return (new_tok, top_scores, h_newK, c_newK, ids, new_finished), None

    (tokens, scores, hK, cK, ids, finished), _ = lax.scan(
        step, (tokens0, scores0, hK, cK, ids0, finished0), jnp.arange(L))
    # sort beams best-first
    order = jnp.argsort(-scores, axis=1)
    ids = jnp.take_along_axis(ids, order[..., None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    return {"Ids": [ids], "Scores": [scores]}


@register_op(
    "beam_search",
    inputs=("pre_ids", "pre_scores", "scores"),
    outputs=("selected_ids", "selected_scores", "parent_idx"),
    no_grad=True,
)
def beam_search(ctx_, ins, attrs):
    """One generic beam-search step (<- beam_search_op.cc), dense redesign.

    The reference grows LoD candidate lists per source sentence; here the
    beam state is fixed-capacity: pre_ids/pre_scores [N, K], scores [N, K, V]
    per-beam next-token log-probs. Selects the global top-K of
    pre_scores + scores per source, emitting the chosen tokens, their
    accumulated scores, and the source-beam index (parent_idx) that
    beam_search_decode backtraces — the role the reference's LoD links play.
    Finished beams (pre_id == end_id) only extend with end_id at no cost.
    """
    pre_ids = ins["pre_ids"][0]
    pre_scores = ins["pre_scores"][0]
    scores = ins["scores"][0]
    n, k, v = scores.shape
    end_id = attrs.get("end_id", 1)
    beam_size = attrs.get("beam_size", k)
    neg_inf = jnp.finfo(scores.dtype).min
    finished = pre_ids == end_id
    eos_only = jnp.full((v,), neg_inf, scores.dtype).at[end_id].set(0.0)
    step_scores = jnp.where(finished[..., None], eos_only[None, None, :], scores)
    cand = pre_scores[..., None] + step_scores  # [N, K, V]
    top_scores, top_idx = lax.top_k(cand.reshape(n, k * v), beam_size)
    parent = (top_idx // v).astype(jnp.int32)
    tok = (top_idx % v).astype(jnp.int32)
    return {"selected_ids": [tok], "selected_scores": [top_scores],
            "parent_idx": [parent]}


@register_op(
    "beam_search_decode",
    inputs=("Ids", "ParentIdx", "Scores"),
    outputs=("SentenceIds", "SentenceScores"),
    no_grad=True,
)
def beam_search_decode(ctx_, ins, attrs):
    """Backtrace stacked per-step beam outputs into full sentences
    (<- beam_search_decode_op.cc). Ids/ParentIdx [T, N, K] from T
    ``beam_search`` steps; emits SentenceIds [N, K, T] best-first and the
    final accumulated SentenceScores [N, K]."""
    ids = ins["Ids"][0]          # [T, N, K]
    parents = ins["ParentIdx"][0]
    scores = ins["Scores"][0]    # [T, N, K] accumulated
    t, n, k = ids.shape
    batch_ix = jnp.arange(n)[:, None]

    def back(beam_ix, step):
        tok = ids[step][batch_ix, beam_ix]       # [N, K]
        prev = parents[step][batch_ix, beam_ix]
        return prev, tok

    _, toks = lax.scan(back, jnp.broadcast_to(jnp.arange(k)[None, :], (n, k)),
                       jnp.arange(t - 1, -1, -1))
    sent = jnp.flip(jnp.moveaxis(toks, 0, 2), axis=2)  # [N, K, T]
    final = scores[-1]
    order = jnp.argsort(-final, axis=1)
    sent = jnp.take_along_axis(sent, order[..., None], axis=1)
    final = jnp.take_along_axis(final, order, axis=1)
    return {"SentenceIds": [sent], "SentenceScores": [final]}

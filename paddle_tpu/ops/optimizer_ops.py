"""Optimizer update ops.

<- paddle/fluid/operators/{sgd,momentum,adam,adamax,adagrad,decayed_adagrad,
adadelta,rmsprop,ftrl,proximal_gd,proximal_adagrad}_op.cc (python driver:
python/paddle/fluid/optimizer.py:36-1105).

Each op's outputs reuse its state-input var names (ParamOut <- Param etc.), so
the executor's functional env-update gives exactly the reference's in-place
semantics; with buffer donation XLA updates parameters in place in HBM, and
because the whole block is one XLA program the optimizer fuses with the
backward pass (no separate update kernel launches).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _merge_rows(ids, rows, num_rows):
    """SelectedRows duplicate merge (<- selected_rows_functor MergeAdd):
    sort ids, segment-sum duplicate rows, return (uids, merged, drop) with
    static shape [N] — position i < U holds unique id uids[i] and its
    summed gradient; padded tail positions get DISTINCT out-of-range
    indices in ``drop`` so the caller's row scatters stay unique-indexed
    (TPU parallelizes a scatter it knows is duplicate-free; an unannotated
    set-scatter must serialize for last-write-wins order — trace-measured
    16.2 vs 2.9 ms/step on the 2M-row probe, tools/probe_sparse_rows.py)
    and dropped by mode='drop'. Every building block here is commutative
    (segment_sum / segment_max), never an ordered scatter."""
    n = ids.shape[0]
    order = jnp.argsort(ids)
    sid = ids[order]
    srows = rows[order]
    head = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    seg = jnp.cumsum(head) - 1                      # [N] 0..U-1
    merged = jax.ops.segment_sum(srows, seg, num_segments=n)
    # sid is constant within a segment, so a commutative segment_max
    # recovers each segment's id without an ordered scatter
    uids = jax.ops.segment_max(sid, seg, num_segments=n)
    valid = jnp.arange(n) < seg[-1] + 1
    # distinct past-the-table index per padded slot: scatters stay
    # unique-indexed AND the padding is dropped by mode='drop'
    drop = jnp.where(valid, uids, num_rows + jnp.arange(n)).astype(jnp.int32)
    return uids, merged, drop


def _sparse_rows(ins):
    """(ids, rows) when the grad is a SelectedRows pair, else None."""
    if not (ins.get("GradIds") and ins["GradIds"][0] is not None):
        return None
    return ins["GradIds"][0], ins["Grad"][0]


@register_op("sgd", inputs=("Param", "Grad", "LearningRate", "GradIds"),
             outputs=("ParamOut",), no_grad=True)
def sgd(ctx, ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    sparse = _sparse_rows(ins)
    if sparse is not None:
        # SelectedRows update (<- sgd_op.cc:72-76): SGD is linear in the
        # grad, so duplicate rows need no merge — one scatter-add applies
        # the whole update without any full-table pass (and without the
        # sort+segment merge the nonlinear optimizers need)
        ids, rows = sparse
        return {"ParamOut": [p.at[ids].add(
            (-lr * rows).astype(p.dtype), mode="drop")]}
    return {"ParamOut": [p - lr * g]}


@register_op(
    "momentum",
    inputs=("Param", "Grad", "Velocity", "LearningRate"),
    outputs=("ParamOut", "VelocityOut"),
    no_grad=True,
)
def momentum(ctx, ins, attrs):
    p, g, v, lr = (ins[k][0] for k in ("Param", "Grad", "Velocity", "LearningRate"))
    mu = attrs.get("mu", 0.9)
    v_new = mu * v + g
    if attrs.get("use_nesterov", False):
        p_new = p - lr * (g + mu * v_new)
    else:
        p_new = p - lr * v_new
    return {"ParamOut": [p_new], "VelocityOut": [v_new]}


@register_op(
    "adam",
    inputs=("Param", "Grad", "Moment1", "Moment2", "LearningRate", "Beta1Pow",
            "Beta2Pow", "GradIds"),
    outputs=("ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"),
    no_grad=True,
)
def adam(ctx, ins, attrs):
    p, g, m1, m2, lr, b1p, b2p = (
        ins[k][0]
        for k in ("Param", "Grad", "Moment1", "Moment2", "LearningRate", "Beta1Pow", "Beta2Pow")
    )
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    sparse = _sparse_rows(ins)
    if sparse is not None:
        # lazy/sparse Adam (<- adam_op.h SelectedRows kernel): gather the
        # touched rows' moments, update, scatter back — untouched rows'
        # moments do NOT decay this step (the reference's lazy-mode
        # semantic; dense Adam decays every row every step). Whole-table
        # passes disappear: on the bench transformer this replaces 1.26 ms
        # of dense Adam + 0.63 ms of dense scatter-add per step.
        ids, rows = sparse
        uids, merged, drop = _merge_rows(ids, rows, p.shape[0])
        gr = merged
        m1r = m1[uids]
        m2r = m2[uids]
        m1n = b1 * m1r + (1 - b1) * gr
        m2n = b2 * m2r + (1 - b2) * gr * gr
        # updates land as ADD-scatters of row deltas, not set-scatters:
        # XLA lowers set-scatter on [V, E] with a {0,1} minor-major layout
        # and then transposes the WHOLE donated table (and both moments)
        # back to {1,0} — trace-measured 2.4 ms/scatter + 2.1 ms/transpose
        # per array on a 2M x 64 table. add-scatter keeps the operand
        # layout (it is the same lowering as the dense grad's
        # scatter-add). Padded slots carry OOB indices and drop.
        d_m1 = (m1n - m1r).astype(m1.dtype)
        d_m2 = (m2n - m2r).astype(m2.dtype)
        d_p = (-lr_t * m1n / (jnp.sqrt(m2n) + eps)).astype(p.dtype)
        return {
            "ParamOut": [p.at[drop].add(d_p, mode="drop",
                                        unique_indices=True)],
            "Moment1Out": [m1.at[drop].add(d_m1, mode="drop",
                                           unique_indices=True)],
            "Moment2Out": [m2.at[drop].add(d_m2, mode="drop",
                                           unique_indices=True)],
            "Beta1PowOut": [b1p * b1],
            "Beta2PowOut": [b2p * b2],
        }
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * g * g
    pn = p - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    return {
        "ParamOut": [pn],
        "Moment1Out": [m1n],
        "Moment2Out": [m2n],
        "Beta1PowOut": [b1p * b1],
        "Beta2PowOut": [b2p * b2],
    }


@register_op(
    "adamax",
    inputs=("Param", "Grad", "Moment", "InfNorm", "LearningRate", "Beta1Pow"),
    outputs=("ParamOut", "MomentOut", "InfNormOut"),
    no_grad=True,
)
def adamax(ctx, ins, attrs):
    p, g, m, u, lr, b1p = (
        ins[k][0] for k in ("Param", "Grad", "Moment", "InfNorm", "LearningRate", "Beta1Pow")
    )
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    mn = b1 * m + (1 - b1) * g
    un = jnp.maximum(b2 * u, jnp.abs(g))
    pn = p - (lr / (1 - b1p)) * mn / (un + eps)
    return {"ParamOut": [pn], "MomentOut": [mn], "InfNormOut": [un]}


@register_op(
    "adagrad",
    inputs=("Param", "Grad", "Moment", "LearningRate", "GradIds"),
    outputs=("ParamOut", "MomentOut"),
    no_grad=True,
)
def adagrad(ctx, ins, attrs):
    p, g, m, lr = (ins[k][0] for k in ("Param", "Grad", "Moment", "LearningRate"))
    eps = attrs.get("epsilon", 1e-6)
    sparse = _sparse_rows(ins)
    if sparse is not None:
        # <- adagrad_op.h SelectedRows kernel (merge + per-row update)
        ids, rows = sparse
        uids, merged, drop = _merge_rows(ids, rows, p.shape[0])
        mr = m[uids] + merged * merged
        # add-scatters of deltas, not set-scatters — see adam
        d_p = (-lr * merged / (jnp.sqrt(mr) + eps)).astype(p.dtype)
        return {"ParamOut": [p.at[drop].add(d_p, mode="drop",
                                            unique_indices=True)],
                "MomentOut": [m.at[drop].add(
                    (merged * merged).astype(m.dtype), mode="drop",
                    unique_indices=True)]}
    mn = m + g * g
    return {"ParamOut": [p - lr * g / (jnp.sqrt(mn) + eps)], "MomentOut": [mn]}


@register_op(
    "decayed_adagrad",
    inputs=("Param", "Grad", "Moment", "LearningRate"),
    outputs=("ParamOut", "MomentOut"),
    no_grad=True,
)
def decayed_adagrad(ctx, ins, attrs):
    p, g, m, lr = (ins[k][0] for k in ("Param", "Grad", "Moment", "LearningRate"))
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mn = decay * m + (1 - decay) * g * g
    return {"ParamOut": [p - lr * g / (jnp.sqrt(mn) + eps)], "MomentOut": [mn]}


@register_op(
    "adadelta",
    inputs=("Param", "Grad", "AvgSquaredGrad", "AvgSquaredUpdate"),
    outputs=("ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"),
    no_grad=True,
)
def adadelta(ctx, ins, attrs):
    p, g, ag, au = (
        ins[k][0] for k in ("Param", "Grad", "AvgSquaredGrad", "AvgSquaredUpdate")
    )
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    agn = rho * ag + (1 - rho) * g * g
    update = -jnp.sqrt((au + eps) / (agn + eps)) * g
    aun = rho * au + (1 - rho) * update * update
    return {"ParamOut": [p + update], "AvgSquaredGradOut": [agn], "AvgSquaredUpdateOut": [aun]}


@register_op(
    "rmsprop",
    inputs=("Param", "Grad", "MeanSquare", "Moment", "LearningRate"),
    outputs=("ParamOut", "MeanSquareOut", "MomentOut"),
    no_grad=True,
)
def rmsprop(ctx, ins, attrs):
    p, g, ms, mom, lr = (
        ins[k][0] for k in ("Param", "Grad", "MeanSquare", "Moment", "LearningRate")
    )
    rho = attrs.get("decay", 0.9)
    mu = attrs.get("momentum", 0.0)
    eps = attrs.get("epsilon", 1e-10)
    msn = rho * ms + (1 - rho) * g * g
    momn = mu * mom + lr * g / jnp.sqrt(msn + eps)
    return {"ParamOut": [p - momn], "MeanSquareOut": [msn], "MomentOut": [momn]}


@register_op(
    "ftrl",
    inputs=("Param", "Grad", "SquaredAccumulator", "LinearAccumulator", "LearningRate"),
    outputs=("ParamOut", "SquaredAccumOut", "LinearAccumOut"),
    no_grad=True,
)
def ftrl(ctx, ins, attrs):
    p, g, sq, lin, lr = (
        ins[k][0]
        for k in ("Param", "Grad", "SquaredAccumulator", "LinearAccumulator", "LearningRate")
    )
    l1 = attrs.get("l1", 0.0) + 1e-10
    l2 = attrs.get("l2", 0.0) + 1e-10
    lr_power = attrs.get("lr_power", -0.5)
    new_sq = sq + g * g
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (new_sq ** (-lr_power) - sq ** (-lr_power)) / lr
    new_lin = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = new_sq ** (-lr_power) / lr + 2 * l2
    x = l1 * jnp.sign(new_lin) - new_lin
    pn = jnp.where(jnp.abs(new_lin) > l1, x / denom, 0.0)
    return {"ParamOut": [pn], "SquaredAccumOut": [new_sq], "LinearAccumOut": [new_lin]}


@register_op("proximal_gd", inputs=("Param", "Grad", "LearningRate"),
             outputs=("ParamOut",), no_grad=True)
def proximal_gd(ctx, ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p - lr * g
    pn = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
    return {"ParamOut": [pn]}


@register_op(
    "proximal_adagrad",
    inputs=("Param", "Grad", "Moment", "LearningRate"),
    outputs=("ParamOut", "MomentOut"),
    no_grad=True,
)
def proximal_adagrad(ctx, ins, attrs):
    p, g, m, lr = (ins[k][0] for k in ("Param", "Grad", "Moment", "LearningRate"))
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    mn = m + g * g
    lr_t = lr / jnp.sqrt(mn + 1e-12)
    prox = p - lr_t * g
    pn = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_t * l1, 0.0) / (1.0 + lr_t * l2)
    return {"ParamOut": [pn], "MomentOut": [mn]}


@register_op(
    "average_accumulates",
    inputs=("param", "in_sum_1", "in_sum_2", "in_sum_3", "in_num_accumulates",
            "in_old_num_accumulates", "in_num_updates"),
    outputs=("out_sum_1", "out_sum_2", "out_sum_3", "out_num_accumulates",
             "out_old_num_accumulates", "out_num_updates"),
    no_grad=True,
)
def average_accumulates(ctx, ins, attrs):
    """Sliding parameter average state machine (<- average_accumulates_op.h,
    used by ModelAverage, optimizer.py:929). Invariant the consumer relies
    on: sum_1+sum_2 hold exactly num_accumulates samples and sum_3 holds
    exactly old_num_accumulates samples, so
    (sum_1+sum_2+sum_3)/(num_accumulates+old_num_accumulates) is the true
    window average."""
    p = ins["param"][0]
    s1, s2, s3 = ins["in_sum_1"][0], ins["in_sum_2"][0], ins["in_sum_3"][0]
    num_acc = ins["in_num_accumulates"][0]
    old_num = ins["in_old_num_accumulates"][0]
    num_upd = ins["in_num_updates"][0]
    avg_window = attrs.get("average_window", 0.0)
    max_avg = attrs.get("max_average_window", 10000)
    min_avg = attrs.get("min_average_window", 10000)
    k_max_chunk = 16384  # <- kMaxNumAccumulates: numeric chunking of sum_1

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    s1 = s1 + p
    # chunk overflow: periodically fold sum_1 into sum_2 (same sample pool)
    chunk = num_upd % k_max_chunk == 0
    s2 = jnp.where(chunk, s2 + s1, s2)
    s1 = jnp.where(chunk, jnp.zeros_like(s1), s1)
    # window complete: rotate the CURRENT pool into sum_3 wholesale, carrying
    # its sample count into old_num (the reference's condition)
    window = jnp.minimum(
        jnp.asarray(max_avg, jnp.int32),
        (num_upd * avg_window).astype(jnp.int32))
    roll = (num_acc >= min_avg) & (num_acc >= window)
    s3 = jnp.where(roll, s1 + s2, s3)
    old_num = jnp.where(roll, num_acc, old_num)
    s1 = jnp.where(roll, jnp.zeros_like(s1), s1)
    s2 = jnp.where(roll, jnp.zeros_like(s2), s2)
    num_acc = jnp.where(roll, jnp.zeros_like(num_acc), num_acc)
    return {
        "out_sum_1": [s1],
        "out_sum_2": [s2],
        "out_sum_3": [s3],
        "out_num_accumulates": [num_acc],
        "out_old_num_accumulates": [old_num],
        "out_num_updates": [num_upd],
    }

"""Pallas TPU fused conv+BN kernels for the ResNet family.

The reference answers training-BN's memory problem with cuDNN's fused
spatial BN (paddle/fluid/operators/batch_norm_op.cu.cc:26-150,
CUDNN_BATCHNORM_SPATIAL): one library call that keeps the conv output in
cache while computing statistics. The TPU-native equivalent built here goes
further and removes the normalize pass from HBM entirely:

- every 1x1 conv is a matmul over [M=N*H*W, K] rows; the kernel applies the
  PREVIOUS layer's BN as a prologue — x_hat = relu(a*y_raw + b) with
  a = gamma*rsqrt(var+eps), b = beta - mean*a — in registers while the tile
  is already in VMEM, and accumulates this layer's BN statistics
  (sum, sum-of-squares per channel) as an epilogue while the output tile is
  still in VMEM. Raw conv outputs are the only activations that touch HBM.
- every 3x3 conv in the bottleneck ResNets is stride-1 and its per-image
  input plane fits VMEM, so the kernel loads one (prologue-normalized,
  zero-padded in scratch) plane, builds the 9-tap im2col patches in VMEM and
  contracts over 9*K — a full-width MXU contraction even where K=64 would
  half-fill the systolic array (the measured reason XLA's own conv runs at
  92-152 TF/s on the early high-resolution layers).

Training-mode BN forward traffic per conv+BN+relu therefore drops from
XLA's read(conv) + write(conv) + read(stats) + read+write(normalize) to
read + write of the raw conv output only.

Layout is NHWC (channels in lanes). All kernels take bf16 activations and
weights, accumulate in f32 on the MXU, and keep the BN arithmetic in f32
(matching ops/nn.py batch_norm's AMP contract).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_attention import _interpret_default

# jax renamed pltpu.TPUCompilerParams -> CompilerParams across releases;
# accept whichever this jax ships (carried tier-1 failure since PR 4: the
# two fused-kernel tests died on the old name under the new jax, not on
# numerics)
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def bn_affine(mean, var, gamma, beta, eps=1e-5):
    """Fold BN stats+params into the per-channel affine (a, b) the kernel
    prologues apply: x_hat = a * y_raw + b."""
    a = gamma * lax.rsqrt(var + eps)
    return a, beta - mean * a


def moments_from_sums(stats, count):
    """(sum, sumsq) [2, C] -> (mean, var) with the same clamp as
    ops/nn.py batch_norm (f32 cancellation can push var slightly negative)."""
    mean = stats[0] / count
    var = jnp.maximum(stats[1] / count - mean * mean, 0.0)
    return mean, var


def bn_bwd_coefs(s1, s2, mean, var, gamma, count, eps=1e-5):
    """Per-channel linearization of the batch-norm backward.

    With dn the (relu-masked) gradient w.r.t. the BN output and
    n_hat = (Y - mean) * rsqrt(var+eps), the gradient w.r.t. the RAW conv
    output is dY = a*(dn - mean(dn) - n_hat*mean(dn*n_hat)) — linear in
    (dn, Y):  dY = alpha*dn + beta*Y + delta. Given s1 = sum(dn) and
    s2 = sum(dn*Y) (the fused kernels' epilogue sums), returns
    (alpha, beta, delta, dgamma, dbeta). This is what lets the backward
    correction ride as a register-level prologue in the NEXT kernel instead
    of an extra HBM pass."""
    inv = lax.rsqrt(var + eps)
    a = gamma * inv
    m1 = s1 / count
    m2 = inv * (s2 / count - mean * m1)
    alpha = a
    beta = -a * inv * m2
    delta = a * (inv * m2 * mean - m1)
    dgamma = inv * (s2 - mean * s1)
    dbeta = s1
    return alpha, beta, delta, dgamma, dbeta


# ---------------------------------------------------------------------------
# fused matmul (1x1 conv): prologue BN-apply+relu, epilogue BN-stats
# ---------------------------------------------------------------------------


def _mm_bn_kernel(x_ref, w_ref, a_ref, b_ref, y_ref, stats_ref, *,
                  prologue, relu, stats):
    i = pl.program_id(0)
    x = x_ref[...]
    if prologue:
        xf = x.astype(jnp.float32) * a_ref[0][None, :] + b_ref[0][None, :]
        if relu:
            xf = jnp.maximum(xf, 0.0)
        x = xf.astype(jnp.bfloat16)
    y = lax.dot_general(x, w_ref[...], (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    if stats:
        @pl.when(i == 0)
        def _init():
            stats_ref[...] = jnp.zeros_like(stats_ref)

        stats_ref[0, :] += jnp.sum(y, axis=0)
        stats_ref[1, :] += jnp.sum(y * y, axis=0)


def fused_matmul_bn(x, w, affine=None, relu=True, stats=True,
                    block_m=2048, interpret=None):
    """y_raw[M,N] = x_hat @ w with x_hat = relu(a*x + b) (when ``affine``
    is (a, b)); also returns per-channel (sum, sumsq) of y_raw as [2, N]
    f32 when ``stats``. x: [M, K] bf16 raw previous-layer output (or real
    activations when affine is None); w: [K, N] bf16."""
    m, k = x.shape
    n = w.shape[1]
    if interpret is None:
        interpret = _interpret_default()
    bm = min(block_m, m)
    while m % bm:
        bm //= 2
    prologue = affine is not None
    if prologue:
        a, b = affine
        a = a.astype(jnp.float32).reshape(1, k)
        b = b.astype(jnp.float32).reshape(1, k)
    else:
        a = jnp.zeros((1, k), jnp.float32)
        b = jnp.zeros((1, k), jnp.float32)

    kernel = functools.partial(_mm_bn_kernel, prologue=prologue, relu=relu,
                               stats=stats)
    out_shape = [jax.ShapeDtypeStruct((m, n), jnp.bfloat16),
                 jax.ShapeDtypeStruct((2, n), jnp.float32)]
    y, st = pl.pallas_call(
        kernel,
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((2, n), lambda i: (0, 0)),
        ],
        out_shape=out_shape,
        interpret=interpret,
    )(x.astype(jnp.bfloat16), w.astype(jnp.bfloat16), a, b)
    return (y, st) if stats else (y, None)


# ---------------------------------------------------------------------------
# fused 3x3 stride-1 conv: per-image plane in VMEM, 9-tap im2col contraction
# ---------------------------------------------------------------------------


def _conv3_bn_kernel(x_ref, w_ref, a_ref, b_ref, y_ref, stats_ref, xpad_ref,
                     patches_ref, *, prologue, relu, stats):
    g = pl.program_id(0)
    nb, h, w, k = x_ref.shape
    sums = None
    for img in range(nb):
        x = x_ref[img]
        if prologue:
            xf = (x.astype(jnp.float32) * a_ref[0][None, None, :]
                  + b_ref[0][None, None, :])
            if relu:
                xf = jnp.maximum(xf, 0.0)
            x = xf.astype(jnp.bfloat16)
        xpad_ref[...] = jnp.zeros_like(xpad_ref)
        xpad_ref[1:h + 1, 1:w + 1, :] = x.astype(xpad_ref.dtype)
        # 9-tap im2col staged through VMEM scratch. The dy shifts move only
        # the (untiled) leading dim, so a lane-concat over dy is vreg-exact;
        # the dx shifts move the sublane dim, which Mosaic cannot lane-concat
        # directly ("offset mismatch on non-concat dimension") — three
        # relayout stores handle those. Lane order is (dx, dy, k); the
        # caller pre-transposes the weight matrix to match.
        xp = xpad_ref[...]
        col = jnp.concatenate([xp[dy:dy + h, :, :] for dy in range(3)],
                              axis=2)  # [h, w+2, 3k], aligned
        for dx in range(3):
            patches_ref[:, :, dx * 3 * k:(dx + 1) * 3 * k] = \
                col[:, dx:dx + w, :]
        y = lax.dot_general(patches_ref[...], w_ref[...],
                            (((2,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [H, W, N]
        y_ref[img] = y.astype(y_ref.dtype)
        if stats:
            s = jnp.stack([jnp.sum(y, axis=(0, 1)),
                           jnp.sum(y * y, axis=(0, 1))])
            sums = s if sums is None else sums + s
    if stats:
        @pl.when(g == 0)
        def _init():
            stats_ref[...] = jnp.zeros_like(stats_ref)

        stats_ref[...] += sums


# ---------------------------------------------------------------------------
# fused BACKWARD kernels: one read of (P, Y_out, Y_in) yields dX (masked),
# dW (accumulated across the grid) and the upstream BN's reduction sums.
# XLA cannot share the gradient read between its dX conv, dW conv and the
# BN-backward reductions — these kernels are why the fused path wins in
# backward, where the trace shows 27.7 of the 44.3 ms step lives.
# ---------------------------------------------------------------------------


def _bwd1x1_kernel(p_ref, yout_ref, yin_ref, w_ref, cg_ref, cx_ref,
                   pin_ref, dw_ref, stats_ref, *, correct, xaffine, xrelu,
                   stats):
    i = pl.program_id(0)
    p = p_ref[...].astype(jnp.float32)
    if correct:
        alpha = cg_ref[0][None, :]
        beta = cg_ref[1][None, :]
        delta = cg_ref[2][None, :]
        g = p * alpha + yout_ref[...].astype(jnp.float32) * beta + delta
    else:
        g = p
    g16 = g.astype(jnp.bfloat16)
    yin = yin_ref[...]
    if xaffine:
        n = (yin.astype(jnp.float32) * cx_ref[0][None, :]
             + cx_ref[1][None, :])
        xhat = jnp.maximum(n, 0.0) if xrelu else n
        xhat16 = xhat.astype(jnp.bfloat16)
    else:
        xhat16 = yin
    # dW = Xhat^T @ G, accumulated over the M grid
    dw = lax.dot_general(xhat16, g16, (((0,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    @pl.when(i == 0)
    def _init_dw():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dw_ref[...] += dw
    # dXhat = G @ W^T, masked into the upstream pre-relu gradient
    dx = lax.dot_general(g16, w_ref[...], (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    if xaffine and xrelu:
        dx = jnp.where(n > 0.0, dx, 0.0)
    pin_ref[...] = dx.astype(pin_ref.dtype)
    if stats:
        @pl.when(i == 0)
        def _init_st():
            stats_ref[...] = jnp.zeros_like(stats_ref)

        stats_ref[0, :] += jnp.sum(dx, axis=0)
        stats_ref[1, :] += jnp.sum(dx * yin.astype(jnp.float32), axis=0)


def fused_bwd_matmul_bn(p, yout, yin, w, coefs=None, xaffine=None,
                        xrelu=True, stats=True, block_m=2048,
                        interpret=None):
    """Combined backward for a fused 1x1-conv layer Y_out = Xhat_in @ W with
    Xhat_in = relu(a*Y_in + b).

    p:    [M, N] upstream dn (relu-masked grad w.r.t. this layer's BN
          output), or the plain gradient when ``coefs`` is None.
    yout: [M, N] this layer's raw conv output (read only when coefs given).
    yin:  [M, K] upstream raw conv output (or a real activation when
          ``xaffine`` is None).
    coefs: (alpha, beta, delta) from bn_bwd_coefs — folds this layer's BN
          backward into the kernel prologue: G = alpha*p + beta*yout + delta.
    Returns (pin [M, K] bf16 — masked grad w.r.t. Xhat_in's pre-relu value,
    dW [K, N] f32, sums [2, K] f32 = (sum pin, sum pin*yin) or None)."""
    m, n = p.shape
    k = yin.shape[1]
    if interpret is None:
        interpret = _interpret_default()
    bm = min(block_m, m)
    while m % bm:
        bm //= 2
    correct = coefs is not None
    if correct:
        cg = jnp.stack([c.astype(jnp.float32) for c in coefs[:3]])
    else:
        cg = jnp.zeros((3, n), jnp.float32)
    if xaffine is not None:
        cx = jnp.stack([xaffine[0].astype(jnp.float32),
                        xaffine[1].astype(jnp.float32)])
    else:
        cx = jnp.zeros((2, k), jnp.float32)

    kernel = functools.partial(_bwd1x1_kernel, correct=correct,
                               xaffine=xaffine is not None, xrelu=xrelu,
                               stats=stats)
    pin, dw, st = pl.pallas_call(
        kernel,
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
            pl.BlockSpec((3, n), lambda i: (0, 0)),
            pl.BlockSpec((2, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
            pl.BlockSpec((2, k), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, k), jnp.bfloat16),
            jax.ShapeDtypeStruct((k, n), jnp.float32),
            jax.ShapeDtypeStruct((2, k), jnp.float32),
        ],
        interpret=interpret,
    )(p.astype(jnp.bfloat16), yout.astype(jnp.bfloat16),
      yin.astype(jnp.bfloat16), w.astype(jnp.bfloat16), cg, cx)
    return pin, dw, (st if stats else None)


def _bwd3x3_kernel(p_ref, yout_ref, yin_ref, wrot_ref, cg_ref, cx_ref,
                   pin_ref, dw_ref, stats_ref, xpad_ref, gpad_ref,
                   patches_ref, *, correct, xaffine, xrelu, stats):
    gi = pl.program_id(0)
    nb, h, w, k = yin_ref.shape
    nout = p_ref.shape[3]

    @pl.when(gi == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        if stats:
            stats_ref[...] = jnp.zeros_like(stats_ref)

    for img in range(nb):
        p = p_ref[img].astype(jnp.float32)
        if correct:
            g = (p * cg_ref[0][None, None, :]
                 + yout_ref[img].astype(jnp.float32) * cg_ref[1][None, None, :]
                 + cg_ref[2][None, None, :])
        else:
            g = p
        g16 = g.astype(jnp.bfloat16)
        if xaffine:
            n = (yin_ref[img].astype(jnp.float32) * cx_ref[0][None, None, :]
                 + cx_ref[1][None, None, :])
            xhat = jnp.maximum(n, 0.0) if xrelu else n
            xhat16 = xhat.astype(jnp.bfloat16)
        else:
            xhat16 = yin_ref[img]
        # stage padded xhat and g
        xpad_ref[...] = jnp.zeros_like(xpad_ref)
        xpad_ref[1:h + 1, 1:w + 1, :] = xhat16
        gpad_ref[...] = jnp.zeros_like(gpad_ref)
        gpad_ref[1:h + 1, 1:w + 1, :] = g16
        # dW: per tap, contract shifted xhat against g over the plane
        g2d = g16.reshape(h * w, nout)
        for dx in range(3):
            for dy in range(3):
                sh = xpad_ref[dy:dy + h, dx:dx + w, :].reshape(h * w, k)
                tap = dx * 3 + dy
                dw_ref[tap * k:(tap + 1) * k, :] += lax.dot_general(
                    sh, g2d, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
        # dXhat: full correlation = conv of padded g with rotated weights
        gp = gpad_ref[...]
        col = jnp.concatenate([gp[dy:dy + h, :, :] for dy in range(3)],
                              axis=2)
        for dx in range(3):
            patches_ref[:, :, dx * 3 * nout:(dx + 1) * 3 * nout] = \
                col[:, dx:dx + w, :]
        dxh = lax.dot_general(patches_ref[...], wrot_ref[...],
                              (((2,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
        if xaffine and xrelu:
            dxh = jnp.where(n > 0.0, dxh, 0.0)
        pin_ref[img] = dxh.astype(pin_ref.dtype)
        if stats:
            stats_ref[0, :] += jnp.sum(dxh, axis=(0, 1))
            stats_ref[1, :] += jnp.sum(
                dxh * yin_ref[img].astype(jnp.float32), axis=(0, 1))


def fused_bwd_conv3x3_bn(p, yout, yin, w, coefs=None, xaffine=None,
                         xrelu=True, stats=True, block_images=None,
                         interpret=None):
    """Combined backward for a fused 3x3 stride-1 conv layer
    Y_out = conv3x3(Xhat_in, W), Xhat_in = relu(a*Y_in + b). Arguments as
    fused_bwd_matmul_bn but over NHWC planes; w is the forward HWIO weight.
    Returns (pin [N,H,W,K] bf16, dW [3,3,K,C] f32 (HWIO), sums [2,K])."""
    nimg, h, wdt, k = yin.shape
    c = w.shape[3]
    assert h == wdt, "square planes only (ResNet geometry)"
    if interpret is None:
        interpret = _interpret_default()
    if block_images is None:
        # one image per grid step: multi-image Python loops multiply the
        # generated Mosaic code (the 567 KB MLIR OOM-killed the compiler)
        # and the grid pipeline already overlaps the DMAs
        block_images = 1
    nb = block_images
    while nimg % nb:
        nb -= 1
    correct = coefs is not None
    cg = (jnp.stack([cc.astype(jnp.float32) for cc in coefs[:3]])
          if correct else jnp.zeros((3, c), jnp.float32))
    if xaffine is not None:
        cx = jnp.stack([xaffine[0].astype(jnp.float32),
                        xaffine[1].astype(jnp.float32)])
    else:
        cx = jnp.zeros((2, k), jnp.float32)
    # rotated/transposed weights for the full correlation, in the kernel's
    # (dx, dy, channel) patch lane order
    wrot = (w.astype(jnp.bfloat16)[::-1, ::-1].transpose(1, 0, 3, 2)
            .reshape(9 * c, k))

    kernel = functools.partial(_bwd3x3_kernel, correct=correct,
                               xaffine=xaffine is not None, xrelu=xrelu,
                               stats=stats)
    pin, dwmat, st = pl.pallas_call(
        kernel,
        grid=(nimg // nb,),
        in_specs=[
            pl.BlockSpec((nb, h, wdt, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((nb, h, wdt, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((nb, h, wdt, k), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((9 * c, k), lambda i: (0, 0)),
            pl.BlockSpec((3, c), lambda i: (0, 0)),
            pl.BlockSpec((2, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((nb, h, wdt, k), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((9 * k, c), lambda i: (0, 0)),
            pl.BlockSpec((2, k), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nimg, h, wdt, k), jnp.bfloat16),
            jax.ShapeDtypeStruct((9 * k, c), jnp.float32),
            jax.ShapeDtypeStruct((2, k), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((h + 2, wdt + 2, k), jnp.bfloat16),
                        pltpu.VMEM((h + 2, wdt + 2, c), jnp.bfloat16),
                        pltpu.VMEM((h, wdt, 9 * c), jnp.bfloat16)],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(p.astype(jnp.bfloat16), yout.astype(jnp.bfloat16),
      yin.astype(jnp.bfloat16), wrot, cg, cx)
    # [9K, C] in (dx, dy, k) row order -> HWIO [3, 3, K, C]
    dw = dwmat.reshape(3, 3, k, c).transpose(1, 0, 2, 3)
    return pin, dw, (st if stats else None)


def fused_conv3x3_bn(x, w, affine=None, relu=True, stats=True,
                     block_images=None, interpret=None):
    """3x3 stride-1 pad-1 conv over NHWC with fused BN prologue/epilogue.
    x: [N, H, W, K]; w: [3, 3, K, C]. Returns (y_raw [N, H, W, C] bf16,
    stats [2, C] f32 or None)."""
    nimg, h, wdt, k = x.shape
    c = w.shape[3]
    if interpret is None:
        interpret = _interpret_default()
    if block_images is None:
        # one image per grid step (see fused_bwd_conv3x3_bn note)
        block_images = 1
    nb = block_images
    while nimg % nb:
        nb -= 1
    prologue = affine is not None
    if prologue:
        a, b = affine
        a = a.astype(jnp.float32).reshape(1, k)
        b = b.astype(jnp.float32).reshape(1, k)
    else:
        a = jnp.zeros((1, k), jnp.float32)
        b = jnp.zeros((1, k), jnp.float32)
    # kernel lane order is (dx, dy, k): transpose HWIO -> (dx, dy, k, c)
    wmat = (w.astype(jnp.bfloat16).transpose(1, 0, 2, 3)
            .reshape(9 * k, c))

    kernel = functools.partial(_conv3_bn_kernel, prologue=prologue,
                               relu=relu, stats=stats)
    y, st = pl.pallas_call(
        kernel,
        grid=(nimg // nb,),
        in_specs=[
            pl.BlockSpec((nb, h, wdt, k), lambda n: (n, 0, 0, 0)),
            pl.BlockSpec((9 * k, c), lambda n: (0, 0)),
            pl.BlockSpec((1, k), lambda n: (0, 0)),
            pl.BlockSpec((1, k), lambda n: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((nb, h, wdt, c), lambda n: (n, 0, 0, 0)),
            pl.BlockSpec((2, c), lambda n: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nimg, h, wdt, c), jnp.bfloat16),
            jax.ShapeDtypeStruct((2, c), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((h + 2, wdt + 2, k), jnp.bfloat16),
                        pltpu.VMEM((h, wdt, 9 * k), jnp.bfloat16)],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(x.astype(jnp.bfloat16), wmat, a, b)
    return (y, st) if stats else (y, None)

"""Pallas TPU fused conv+BN kernels for the ResNet family.

The reference answers training-BN's memory problem with cuDNN's fused
spatial BN (paddle/fluid/operators/batch_norm_op.cu.cc:26-150,
CUDNN_BATCHNORM_SPATIAL): one library call that keeps the conv output in
cache while computing statistics. The TPU-native equivalent built here goes
further and removes the normalize pass from HBM entirely:

- every 1x1 conv is a matmul over [M=N*H*W, K] rows; the kernel applies the
  PREVIOUS layer's BN as a prologue — x_hat = relu(a*y_raw + b) with
  a = gamma*rsqrt(var+eps), b = beta - mean*a — in registers while the tile
  is already in VMEM, and accumulates this layer's BN statistics
  (sum, sum-of-squares per channel) as an epilogue while the output tile is
  still in VMEM. Raw conv outputs are the only activations that touch HBM.
- every 3x3 conv in the bottleneck ResNets is stride-1 and its per-image
  input plane fits VMEM, so the kernel loads one (prologue-normalized,
  zero-padded in scratch) plane, builds the 9-tap im2col patches in VMEM and
  contracts over 9*K — a full-width MXU contraction even where K=64 would
  half-fill the systolic array (the measured reason XLA's own conv runs at
  92-152 TF/s on the early high-resolution layers).

Training-mode BN forward traffic per conv+BN+relu therefore drops from
XLA's read(conv) + write(conv) + read(stats) + read+write(normalize) to
read + write of the raw conv output only.

Layout is NHWC (channels in lanes). All kernels take bf16 activations and
weights, accumulate in f32 on the MXU, and keep the BN arithmetic in f32
(matching ops/nn.py batch_norm's AMP contract).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_attention import _interpret_default


def bn_affine(mean, var, gamma, beta, eps=1e-5):
    """Fold BN stats+params into the per-channel affine (a, b) the kernel
    prologues apply: x_hat = a * y_raw + b."""
    a = gamma * lax.rsqrt(var + eps)
    return a, beta - mean * a


def moments_from_sums(stats, count):
    """(sum, sumsq) [2, C] -> (mean, var) with the same clamp as
    ops/nn.py batch_norm (f32 cancellation can push var slightly negative)."""
    mean = stats[0] / count
    var = jnp.maximum(stats[1] / count - mean * mean, 0.0)
    return mean, var


# ---------------------------------------------------------------------------
# fused matmul (1x1 conv): prologue BN-apply+relu, epilogue BN-stats
# ---------------------------------------------------------------------------


def _mm_bn_kernel(x_ref, w_ref, a_ref, b_ref, y_ref, stats_ref, *,
                  prologue, relu, stats):
    i = pl.program_id(0)
    x = x_ref[...]
    if prologue:
        xf = x.astype(jnp.float32) * a_ref[0][None, :] + b_ref[0][None, :]
        if relu:
            xf = jnp.maximum(xf, 0.0)
        x = xf.astype(jnp.bfloat16)
    y = lax.dot_general(x, w_ref[...], (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    if stats:
        @pl.when(i == 0)
        def _init():
            stats_ref[...] = jnp.zeros_like(stats_ref)

        stats_ref[0, :] += jnp.sum(y, axis=0)
        stats_ref[1, :] += jnp.sum(y * y, axis=0)


def fused_matmul_bn(x, w, affine=None, relu=True, stats=True,
                    block_m=2048, interpret=None):
    """y_raw[M,N] = x_hat @ w with x_hat = relu(a*x + b) (when ``affine``
    is (a, b)); also returns per-channel (sum, sumsq) of y_raw as [2, N]
    f32 when ``stats``. x: [M, K] bf16 raw previous-layer output (or real
    activations when affine is None); w: [K, N] bf16."""
    m, k = x.shape
    n = w.shape[1]
    if interpret is None:
        interpret = _interpret_default()
    bm = min(block_m, m)
    while m % bm:
        bm //= 2
    prologue = affine is not None
    if prologue:
        a, b = affine
        a = a.astype(jnp.float32).reshape(1, k)
        b = b.astype(jnp.float32).reshape(1, k)
    else:
        a = jnp.zeros((1, k), jnp.float32)
        b = jnp.zeros((1, k), jnp.float32)

    kernel = functools.partial(_mm_bn_kernel, prologue=prologue, relu=relu,
                               stats=stats)
    out_shape = [jax.ShapeDtypeStruct((m, n), jnp.bfloat16),
                 jax.ShapeDtypeStruct((2, n), jnp.float32)]
    y, st = pl.pallas_call(
        kernel,
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((2, n), lambda i: (0, 0)),
        ],
        out_shape=out_shape,
        interpret=interpret,
    )(x.astype(jnp.bfloat16), w.astype(jnp.bfloat16), a, b)
    return (y, st) if stats else (y, None)


# ---------------------------------------------------------------------------
# fused 3x3 stride-1 conv: per-image plane in VMEM, 9-tap im2col contraction
# ---------------------------------------------------------------------------


def _conv3_bn_kernel(x_ref, w_ref, a_ref, b_ref, y_ref, stats_ref, xpad_ref,
                     patches_ref, *, prologue, relu, stats):
    g = pl.program_id(0)
    nb, h, w, k = x_ref.shape
    sums = None
    for img in range(nb):
        x = x_ref[img]
        if prologue:
            xf = (x.astype(jnp.float32) * a_ref[0][None, None, :]
                  + b_ref[0][None, None, :])
            if relu:
                xf = jnp.maximum(xf, 0.0)
            x = xf.astype(jnp.bfloat16)
        xpad_ref[...] = jnp.zeros_like(xpad_ref)
        xpad_ref[1:h + 1, 1:w + 1, :] = x.astype(xpad_ref.dtype)
        # 9-tap im2col staged through VMEM scratch. The dy shifts move only
        # the (untiled) leading dim, so a lane-concat over dy is vreg-exact;
        # the dx shifts move the sublane dim, which Mosaic cannot lane-concat
        # directly ("offset mismatch on non-concat dimension") — three
        # relayout stores handle those. Lane order is (dx, dy, k); the
        # caller pre-transposes the weight matrix to match.
        xp = xpad_ref[...]
        col = jnp.concatenate([xp[dy:dy + h, :, :] for dy in range(3)],
                              axis=2)  # [h, w+2, 3k], aligned
        for dx in range(3):
            patches_ref[:, :, dx * 3 * k:(dx + 1) * 3 * k] = \
                col[:, dx:dx + w, :]
        y = lax.dot_general(patches_ref[...], w_ref[...],
                            (((2,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [H, W, N]
        y_ref[img] = y.astype(y_ref.dtype)
        if stats:
            s = jnp.stack([jnp.sum(y, axis=(0, 1)),
                           jnp.sum(y * y, axis=(0, 1))])
            sums = s if sums is None else sums + s
    if stats:
        @pl.when(g == 0)
        def _init():
            stats_ref[...] = jnp.zeros_like(stats_ref)

        stats_ref[...] += sums


def fused_conv3x3_bn(x, w, affine=None, relu=True, stats=True,
                     block_images=None, interpret=None):
    """3x3 stride-1 pad-1 conv over NHWC with fused BN prologue/epilogue.
    x: [N, H, W, K]; w: [3, 3, K, C]. Returns (y_raw [N, H, W, C] bf16,
    stats [2, C] f32 or None)."""
    nimg, h, wdt, k = x.shape
    c = w.shape[3]
    if interpret is None:
        interpret = _interpret_default()
    if block_images is None:
        # amortize per-grid-step overhead on small planes; ~target one
        # VMEM-resident working set of a few MB
        block_images = max(1, min(nimg, (28 * 28) // (h * wdt) * 2 or 1))
    nb = block_images
    while nimg % nb:
        nb -= 1
    prologue = affine is not None
    if prologue:
        a, b = affine
        a = a.astype(jnp.float32).reshape(1, k)
        b = b.astype(jnp.float32).reshape(1, k)
    else:
        a = jnp.zeros((1, k), jnp.float32)
        b = jnp.zeros((1, k), jnp.float32)
    # kernel lane order is (dx, dy, k): transpose HWIO -> (dx, dy, k, c)
    wmat = (w.astype(jnp.bfloat16).transpose(1, 0, 2, 3)
            .reshape(9 * k, c))

    kernel = functools.partial(_conv3_bn_kernel, prologue=prologue,
                               relu=relu, stats=stats)
    y, st = pl.pallas_call(
        kernel,
        grid=(nimg // nb,),
        in_specs=[
            pl.BlockSpec((nb, h, wdt, k), lambda n: (n, 0, 0, 0)),
            pl.BlockSpec((9 * k, c), lambda n: (0, 0)),
            pl.BlockSpec((1, k), lambda n: (0, 0)),
            pl.BlockSpec((1, k), lambda n: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((nb, h, wdt, c), lambda n: (n, 0, 0, 0)),
            pl.BlockSpec((2, c), lambda n: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nimg, h, wdt, c), jnp.bfloat16),
            jax.ShapeDtypeStruct((2, c), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((h + 2, wdt + 2, k), jnp.bfloat16),
                        pltpu.VMEM((h, wdt, 9 * k), jnp.bfloat16)],
        interpret=interpret,
    )(x.astype(jnp.bfloat16), wmat, a, b)
    return (y, st) if stats else (y, None)

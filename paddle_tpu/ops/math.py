"""Linear algebra, elementwise (broadcasting), and reduction ops.

<- paddle/fluid/operators/{mul,matmul,elementwise_*,reduce_*,top_k,arg_max,
cumsum,cos_sim,clip_by_norm,norm}_op.cc and elementwise_op_function.h
broadcast semantics. All of these map directly onto MXU-friendly jnp/lax
primitives; XLA fuses the elementwise ops into neighbouring matmuls.
"""
from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ._amp import amp_operand as _amp_cast
from ._amp import low_precision as _low_prec


def _flatten2(x, num_col_dims):
    """Flatten to 2D as the reference's mul op does (mul_op.cc)."""
    lead = 1
    for d in x.shape[:num_col_dims]:
        lead *= d
    rest = 1
    for d in x.shape[num_col_dims:]:
        rest *= d
    return x.reshape(lead, rest)


def _dot_dtypes(ctx, *dtypes):
    """(preferred_element_type, storage dtype) for a dot product.

    The accumulator is always the promoted f32 type (requested explicitly —
    the MXU accumulates f32 anyway, but interpret/CPU paths would not);
    under AMP the *stored* result is bf16, with the convert fused into the
    dot's epilogue so activations stay bf16 in HBM.
    """
    acc = functools.reduce(jnp.promote_types, dtypes)
    if not jnp.issubdtype(acc, jnp.floating):
        return None, acc
    if getattr(ctx, "amp", False):
        return jnp.float32, jnp.bfloat16
    return acc, acc


def _routed_or_plain_dot(x2, y2, pref, store):
    """2D dot, optionally through the Pallas-dW custom_vjp (the fc/matmul
    weight-grad path, ops/pallas_matmul.py). Off-flag and non-float dots are
    the stock XLA lowering, byte-identical to pre-flag behavior."""
    if pref is not None:  # float dot: the dW routing may apply
        from .pallas_matmul import routed_dot

        out = routed_dot(x2, y2, store)
        if out is not None:
            return out
    return jnp.dot(x2, y2, preferred_element_type=pref).astype(store)


@register_op("mul", inputs=("X", "Y"), outputs=("Out",))
def mul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xnc = attrs.get("x_num_col_dims", 1)
    ync = attrs.get("y_num_col_dims", 1)
    pref, store = _dot_dtypes(ctx, x.dtype, y.dtype)
    x2, y2 = _amp_cast(ctx, _flatten2(x, xnc), _flatten2(y, ync))
    out = _routed_or_plain_dot(x2, y2, pref, store)
    out_shape = x.shape[:xnc] + y.shape[ync:]
    return {"Out": [out.reshape(out_shape)]}


@register_op("matmul", inputs=("X", "Y"), outputs=("Out",))
def matmul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    pref, store = _dot_dtypes(ctx, x.dtype, y.dtype)
    xc, yc = _amp_cast(ctx, x, y)
    if xc.ndim == 2 and yc.ndim == 2:
        out = _routed_or_plain_dot(xc, yc, pref, store)
    else:
        out = jnp.matmul(xc, yc, preferred_element_type=pref).astype(store)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


def _broadcast_y(x, y, axis):
    """Reference elementwise broadcast: align Y's dims to X starting at axis
    (elementwise_op_function.h)."""
    if x.shape == y.shape:
        return y
    if axis is None or axis == -1:
        axis = x.ndim - y.ndim
    # append trailing 1s so numpy broadcasting matches the axis-aligned rule
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


def _register_elementwise(name, fn):
    @register_op(f"elementwise_{name}", inputs=("X", "Y"), outputs=("Out",))
    def impl(ctx, ins, attrs, _fn=fn):
        x, y = ins["X"][0], ins["Y"][0]
        if (getattr(ctx, "amp", False)
                and jnp.issubdtype(x.dtype, jnp.floating)
                and jnp.issubdtype(y.dtype, jnp.floating)
                and _low_prec(x.dtype) != _low_prec(y.dtype)):
            # AMP: a bf16 activation meeting a *broadcast* f32 param (fc
            # bias, scale vector, ...) stays bf16 instead of promoting the
            # whole activation back to f32. A same-size f32 operand keeps
            # its precision (deliberately-f32 values like the loss head
            # must not be silently downcast by an elementwise op).
            xs, ys = x.size, y.size
            if _low_prec(x.dtype) and ys < xs:
                y = y.astype(x.dtype)
            elif _low_prec(y.dtype) and xs < ys:
                x = x.astype(y.dtype)
        y = _broadcast_y(x, y, attrs.get("axis", -1))
        return {"Out": [_fn(x, y)]}


for _name, _fn in [
    ("add", jnp.add),
    ("sub", jnp.subtract),
    ("mul", jnp.multiply),
    ("div", jnp.divide),
    ("max", jnp.maximum),
    ("min", jnp.minimum),
    ("pow", jnp.power),
    ("mod", jnp.mod),
    ("floordiv", jnp.floor_divide),
]:
    _register_elementwise(_name, _fn)


def _reduce_axes(x, attrs):
    if attrs.get("reduce_all", False):
        return None
    dim = attrs.get("dim", [0])
    if isinstance(dim, int):
        dim = [dim]
    return tuple(d % x.ndim for d in dim)


def _register_reduce(name, fn):
    @register_op(f"reduce_{name}", inputs=("X",), outputs=("Out",))
    def impl(ctx, ins, attrs, _fn=fn):
        x = ins["X"][0]
        axes = _reduce_axes(x, attrs)
        return {"Out": [_fn(x, axis=axes, keepdims=attrs.get("keep_dim", False))]}


for _name, _fn in [
    ("sum", jnp.sum),
    ("mean", jnp.mean),
    ("max", jnp.max),
    ("min", jnp.min),
    ("prod", jnp.prod),
]:
    _register_reduce(_name, _fn)


@register_op("mean", inputs=("X",), outputs=("Out",))
def mean(ctx, ins, attrs):
    return {"Out": [jnp.mean(ins["X"][0])]}


@register_op("cumsum", inputs=("X",), outputs=("Out",))
def cumsum(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
        if attrs.get("exclusive", False):
            out = out - x
    return {"Out": [out]}


@register_op("arg_max", inputs=("X",), outputs=("Out",), no_grad=True)
def arg_max(ctx, ins, attrs):
    return {"Out": [jnp.argmax(ins["X"][0], axis=attrs.get("axis", -1)).astype(jnp.int32)]}


@register_op("arg_min", inputs=("X",), outputs=("Out",), no_grad=True)
def arg_min(ctx, ins, attrs):
    return {"Out": [jnp.argmin(ins["X"][0], axis=attrs.get("axis", -1)).astype(jnp.int32)]}


@register_op("top_k", inputs=("X",), outputs=("Out", "Indices"), no_grad=True)
def top_k(ctx, ins, attrs):
    vals, idx = lax.top_k(ins["X"][0], attrs.get("k", 1))
    return {"Out": [vals], "Indices": [idx.astype(jnp.int32)]}


@register_op("cos_sim", inputs=("X", "Y"), outputs=("Out", "XNorm", "YNorm"),
             diff_inputs=("X", "Y"))
def cos_sim(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}


@register_op("clip_by_norm", inputs=("X",), outputs=("Out",))
def clip_by_norm(ctx, ins, attrs):
    x = ins["X"][0]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(x * x))
    return {"Out": [jnp.where(norm > max_norm, x * (max_norm / norm), x)]}


@register_op("norm", inputs=("X",), outputs=("Out", "Norm"), diff_inputs=("X",))
def norm(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    eps = attrs.get("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {"Out": [x / n], "Norm": [n]}


@register_op("l1_norm", inputs=("X",), outputs=("Out",))
def l1_norm(ctx, ins, attrs):
    return {"Out": [jnp.sum(jnp.abs(ins["X"][0]))]}


@register_op("squared_l2_norm", inputs=("X",), outputs=("Out",))
def squared_l2_norm(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.sum(x * x)]}


@register_op("squared_l2_distance", inputs=("X", "Y"), outputs=("Out", "sub_result"),
             diff_inputs=("X", "Y"))
def squared_l2_distance(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sub = x - y
    return {"Out": [jnp.sum(sub * sub, axis=-1, keepdims=True)], "sub_result": [sub]}


@register_op("bilinear_tensor_product", inputs=("X", "Y", "Weight", "Bias"),
             outputs=("Out",), diff_inputs=("X", "Y", "Weight", "Bias"))
def bilinear_tensor_product(ctx, ins, attrs):
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    # out[b, k] = x[b] @ w[k] @ y[b]
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        out = out + ins["Bias"][0]
    return {"Out": [out]}


@register_op("minus", inputs=("X", "Y"), outputs=("Out",))
def minus(ctx, ins, attrs):
    return {"Out": [ins["X"][0] - ins["Y"][0]]}

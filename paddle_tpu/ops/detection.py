"""Detection op family, TPU-native.

<- paddle/fluid/operators/detection/{prior_box,box_coder,iou_similarity,
bipartite_match,target_assign,mine_hard_examples,multiclass_nms,
polygon_box_transform}_op.cc, detection_map_op.cc, roi_pool_op.cc.

Redesigned for XLA: every op is dense, fixed-shape, and masked.  The
reference's LoD-batched variable-count boxes become padded [N, M, ...]
tensors with explicit validity masks; NMS is sort + iterative suppression
under ``lax.fori_loop`` instead of data-dependent loops; bipartite matching
is a greedy global-argmax loop of static trip count.  Outputs that the
reference emits as variable-length LoDTensors (e.g. multiclass_nms) come out
as fixed-capacity buffers with a ``-1`` label marking empty slots — the same
convention the reference uses for "no detection" rows.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import register_op


@register_op("prior_box", inputs=("Input", "Image"), outputs=("Boxes", "Variances"),
             no_grad=True)
def prior_box(ctx, ins, attrs):
    """SSD prior (anchor) boxes for one feature map (<- prior_box_op.cc).

    Returns Boxes/Variances of shape [H, W, num_priors, 4] in normalized
    [xmin, ymin, xmax, ymax] corner form.
    """
    feat, image = ins["Input"][0], ins["Image"][0]
    h, w = feat.shape[-2], feat.shape[-1]
    img_h, img_w = image.shape[-2], image.shape[-1]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError(
            f"prior_box: len(max_sizes)={len(max_sizes)} must equal "
            f"len(min_sizes)={len(min_sizes)}")
    ratios = [float(r) for r in attrs.get("aspect_ratios", [1.0])]
    flip = attrs.get("flip", True)
    clip = attrs.get("clip", True)
    variances = [float(v) for v in attrs.get("variances", [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0)) or float(img_w) / w
    step_h = float(attrs.get("step_h", 0.0)) or float(img_h) / h
    offset = float(attrs.get("offset", 0.5))

    # expand aspect ratios like ExpandAspectRatios (prior_box_op.h)
    out_ratios = [1.0]
    for r in ratios:
        if not any(abs(r - o) < 1e-6 for o in out_ratios):
            out_ratios.append(r)
            if flip:
                out_ratios.append(1.0 / r)

    # per-prior (width, height) in pixels, order matches reference:
    # for each min_size: ratio-1 box, [max_size geometric-mean box], other ratios
    ws, hs = [], []
    for k, ms in enumerate(min_sizes):
        ws.append(ms)
        hs.append(ms)
        if max_sizes:
            big = (ms * max_sizes[k]) ** 0.5
            ws.append(big)
            hs.append(big)
        for r in out_ratios:
            if abs(r - 1.0) < 1e-6:
                continue
            ws.append(ms * r ** 0.5)
            hs.append(ms / r ** 0.5)
    ws = jnp.asarray(ws, jnp.float32)  # [P]
    hs = jnp.asarray(hs, jnp.float32)
    num_priors = ws.shape[0]

    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * step_w  # [W]
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * step_h  # [H]
    cx = cx[None, :, None]  # [1, W, 1]
    cy = cy[:, None, None]  # [H, 1, 1]
    half_w = ws[None, None, :] / 2.0  # [1, 1, P]
    half_h = hs[None, None, :] / 2.0
    xmin = (cx - half_w) / img_w
    ymin = (cy - half_h) / img_h
    xmax = (cx + half_w) / img_w
    ymax = (cy + half_h) / img_h
    boxes = jnp.stack(
        [jnp.broadcast_to(a, (h, w, num_priors)) for a in (xmin, ymin, xmax, ymax)],
        axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (h, w, num_priors, 4))
    return {"Boxes": [boxes], "Variances": [var]}


def _corner_to_center(boxes):
    """[..., 4] corner -> (cx, cy, w, h)."""
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    cx = boxes[..., 0] + w / 2.0
    cy = boxes[..., 1] + h / 2.0
    return cx, cy, w, h


@register_op("box_coder", inputs=("PriorBox", "PriorBoxVar", "TargetBox"),
             outputs=("OutputBox",), no_grad=True)
def box_coder(ctx, ins, attrs):
    """Encode/decode boxes against priors in center-size form (<- box_coder_op.cc).

    encode_center_size: TargetBox [N, 4] gt boxes vs PriorBox [M, 4]
        -> [N, M, 4] offsets.
    decode_center_size: TargetBox [N, M, 4] offsets -> [N, M, 4] corner boxes.
    """
    prior = ins["PriorBox"][0]  # [M, 4]
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None  # [M, 4]
    target = ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    pcx, pcy, pw, ph = _corner_to_center(prior)  # [M]
    if pvar is None:
        pvar = jnp.ones(prior.shape[-1:], jnp.float32)
    if code_type == "encode_center_size":
        tcx, tcy, tw, th = _corner_to_center(target)  # [N]
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10))
        dh = jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10))
        out = jnp.stack([dx, dy, dw, dh], axis=-1) / pvar
    else:  # decode_center_size
        d = target * pvar
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph + pcy
        w = jnp.exp(d[..., 2]) * pw
        h = jnp.exp(d[..., 3]) * ph
        out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    return {"OutputBox": [out]}


def pairwise_iou(a, b):
    """IoU between [N, 4] and [M, 4] corner boxes -> [N, M]."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(b[:, 3] - b[:, 1], 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register_op("iou_similarity", inputs=("X", "Y"), outputs=("Out",), no_grad=True)
def iou_similarity(ctx, ins, attrs):
    """Pairwise IoU matrix (<- iou_similarity_op.cc)."""
    return {"Out": [pairwise_iou(ins["X"][0], ins["Y"][0])]}


def _greedy_bipartite(sim, row_valid):
    """Greedy global-argmax bipartite match (<- bipartite_match_op.cc).

    sim: [N, M] similarity (rows = gt, cols = priors); row_valid: [N] mask.
    Returns (match_idx [M] int32 row-or--1, match_dist [M]).
    """
    n, m = sim.shape
    sim = jnp.where(row_valid[:, None], sim, -1.0)

    def body(_, state):
        s, midx, mdist = state
        flat = jnp.argmax(s)
        i, j = flat // m, flat % m
        best = s[i, j]
        take = best > 0
        midx = jnp.where(take, midx.at[j].set(i.astype(jnp.int32)), midx)
        mdist = jnp.where(take, mdist.at[j].set(best), mdist)
        # retire the matched row and column
        s = jnp.where(take, s.at[i, :].set(-1.0).at[:, j].set(-1.0), s)
        return s, midx, mdist

    midx0 = jnp.full((m,), -1, jnp.int32)
    mdist0 = jnp.zeros((m,), sim.dtype)
    _, midx, mdist = lax.fori_loop(0, n, body, (sim, midx0, mdist0))
    return midx, mdist


def _match_priors(sim, row_valid, match_type, thr):
    """Shared matching recipe: greedy bipartite, optionally topped up with
    per-prediction argmax matches above ``thr`` (<- bipartite_match_op.cc
    match_type). Returns (match_idx [M], match_dist [M])."""
    midx, mdist = _greedy_bipartite(sim, row_valid)
    if match_type == "per_prediction":
        simv = jnp.where(row_valid[:, None], sim, -1.0)
        best_row = jnp.argmax(simv, axis=0).astype(jnp.int32)
        best = jnp.max(simv, axis=0)
        extra = (midx < 0) & (best >= thr)
        midx = jnp.where(extra, best_row, midx)
        mdist = jnp.where(extra, best, mdist)
    return midx, mdist


def _mine_negatives(loss, matched, neg_pos_ratio, mining_type, sample_size):
    """Shared hard-negative mining (<- mine_hard_examples_op.cc).

    loss: [B, M] per-prior loss; matched: [B, M] bool. Returns bool mask of
    selected negatives, capped per image at neg_pos_ratio * num_positives
    (max_negative) or sample_size (hard_example)."""
    neg_loss = jnp.where(~matched, loss, -jnp.inf)
    order = jnp.argsort(-neg_loss, axis=1)
    rank = jnp.argsort(order, axis=1)
    num_pos = jnp.sum(matched.astype(jnp.int32), axis=1, keepdims=True)
    if mining_type == "hard_example" and sample_size > 0:
        limit = jnp.full_like(num_pos, sample_size)
    else:
        limit = (num_pos.astype(jnp.float32) * neg_pos_ratio).astype(jnp.int32)
    return (~matched) & (rank < limit) & jnp.isfinite(neg_loss)


@register_op("bipartite_match", inputs=("DistMat", "RowValid"),
             outputs=("ColToRowMatchIndices", "ColToRowMatchDist"), no_grad=True)
def bipartite_match(ctx, ins, attrs):
    """Batched greedy bipartite matching (<- bipartite_match_op.cc).

    DistMat: [B, N, M]; RowValid: [B, N] bool mask of real gt rows (the
    reference uses LoD to delimit per-image gt counts).  match_type
    'per_prediction' additionally matches any unmatched column whose best
    row-distance exceeds overlap_threshold.
    """
    dist = ins["DistMat"][0]
    row_valid = ins["RowValid"][0].astype(bool) if ins.get("RowValid") else \
        jnp.ones(dist.shape[:-1], bool)
    match_type = attrs.get("match_type", "bipartite")
    thr = float(attrs.get("dist_threshold", 0.5))

    midx, mdist = jax.vmap(
        lambda sim, rv: _match_priors(sim, rv, match_type, thr))(dist, row_valid)
    return {"ColToRowMatchIndices": [midx], "ColToRowMatchDist": [mdist]}


@register_op("target_assign", inputs=("X", "MatchIndices", "NegIndices"),
             outputs=("Out", "OutWeight"), no_grad=True)
def target_assign(ctx, ins, attrs):
    """Gather per-prior targets by match indices (<- target_assign_op.cc).

    X: [B, N, K] per-gt targets; MatchIndices: [B, M] (-1 = unmatched).
    Out[b, m] = X[b, MatchIndices[b, m]] with mismatch_value fill,
    OutWeight = 1 for matched (or negative-listed) entries.
    """
    x = ins["X"][0]
    midx = ins["MatchIndices"][0]
    mismatch = attrs.get("mismatch_value", 0)
    safe = jnp.maximum(midx, 0)
    out = jnp.take_along_axis(x, safe[..., None].astype(jnp.int32), axis=1)
    matched = (midx >= 0)[..., None]
    out = jnp.where(matched, out, jnp.asarray(mismatch, x.dtype))
    w = matched.astype(jnp.float32)
    if ins.get("NegIndices"):
        # NegIndices: [B, M] bool/int mask of hard negatives to include
        neg = ins["NegIndices"][0].astype(bool)[..., None]
        out = jnp.where(neg & ~matched, jnp.asarray(mismatch, x.dtype), out)
        w = jnp.maximum(w, neg.astype(jnp.float32))
    return {"Out": [out], "OutWeight": [w]}


@register_op("mine_hard_examples", inputs=("ClsLoss", "LocLoss", "MatchIndices"),
             outputs=("NegMask", "UpdatedMatchIndices"), no_grad=True)
def mine_hard_examples(ctx, ins, attrs):
    """Hard-negative mining (<- mine_hard_examples_op.cc).

    Selects the highest-loss unmatched priors per image, capped at
    neg_pos_ratio * num_positives (max_negative) or sample_size (hard_example).
    Returns a dense bool NegMask [B, M] instead of the reference's LoD index
    list.
    """
    cls_loss = ins["ClsLoss"][0]  # [B, M]
    midx = ins["MatchIndices"][0]  # [B, M]
    loss = cls_loss
    if ins.get("LocLoss"):
        loss = loss + ins["LocLoss"][0]
    neg_pos_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    mining_type = attrs.get("mining_type", "max_negative")
    sample_size = int(attrs.get("sample_size", 0))

    neg_mask = _mine_negatives(loss, midx >= 0, neg_pos_ratio, mining_type,
                               sample_size)
    return {"NegMask": [neg_mask],
            "UpdatedMatchIndices": [jnp.where(neg_mask, -1, midx)]}


def _nms_single_class(iou_all, scores, valid, iou_thr, top_k):
    """Greedy NMS over one class; returns keep mask [M].

    ``iou_all`` is the class-independent [M, M] pairwise IoU of the shared
    boxes — computed ONCE per image and re-indexed per class (only the score
    order differs between classes)."""
    m = scores.shape[0]
    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
    v = valid[order]
    iou = iou_all[order][:, order]

    def body(i, keep):
        # suppressed if any earlier-kept box overlaps > thr
        earlier = jnp.arange(m) < i
        sup = jnp.sum(jnp.where(earlier, keep * (iou[i] > iou_thr), 0.0)) > 0
        ki = jnp.where(v[i] & ~sup, 1.0, 0.0)
        return keep.at[i].set(ki)

    keep_sorted = lax.fori_loop(0, m, body, jnp.zeros((m,), jnp.float32))
    if top_k > 0:
        csum = jnp.cumsum(keep_sorted)
        keep_sorted = jnp.where(csum <= top_k, keep_sorted, 0.0)
    keep = jnp.zeros((m,), jnp.float32).at[order].set(keep_sorted)
    return keep > 0


@register_op("multiclass_nms", inputs=("BBoxes", "Scores"), outputs=("Out",),
             no_grad=True)
def multiclass_nms(ctx, ins, attrs):
    """Per-class NMS + cross-class keep_top_k (<- multiclass_nms_op.cc).

    BBoxes: [B, M, 4]; Scores: [B, C, M].  Out: [B, keep_top_k, 6] rows of
    [label, score, xmin, ymin, xmax, ymax]; empty slots have label -1 —
    fixed capacity replacing the reference's LoD output.
    """
    bboxes, scores = ins["BBoxes"][0], ins["Scores"][0]
    score_thr = float(attrs.get("score_threshold", 0.0))
    nms_thr = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", 0))
    keep_top_k = int(attrs.get("keep_top_k", -1))
    background = int(attrs.get("background_label", 0))
    c = scores.shape[1]
    m = scores.shape[2]
    if keep_top_k <= 0:
        keep_top_k = c * m
    # non-background classes only: background never reaches NMS
    fg = np.asarray([cls for cls in range(c) if cls != background], np.int32)

    def per_image(bb, sc):
        iou_all = pairwise_iou(bb, bb)  # shared across classes
        sc_fg = sc[fg]  # [C-1, M]

        def per_class(cls_scores):
            valid = cls_scores > score_thr
            return _nms_single_class(iou_all, cls_scores, valid, nms_thr,
                                     nms_top_k)

        keep = jax.vmap(per_class)(sc_fg)  # [C-1, M]
        flat_scores = jnp.where(keep, sc_fg, -jnp.inf).reshape(-1)
        # fixed [keep_top_k] capacity even when (C-1)*M < keep_top_k: pad the
        # candidate pool with -inf slots so the output shape is static
        pad = max(0, keep_top_k - flat_scores.shape[0])
        if pad:
            flat_scores = jnp.concatenate(
                [flat_scores, jnp.full((pad,), -jnp.inf, flat_scores.dtype)])
        order = jnp.argsort(-flat_scores)[:keep_top_k]
        sel_scores = flat_scores[order]
        safe = jnp.minimum(order, fg.shape[0] * m - 1)
        sel_labels = jnp.asarray(fg)[safe // m].astype(jnp.float32)
        sel_boxes = bb[safe % m]
        ok = jnp.isfinite(sel_scores)
        rows = jnp.concatenate(
            [jnp.where(ok, sel_labels, -1.0)[:, None],
             jnp.where(ok, sel_scores, 0.0)[:, None],
             jnp.where(ok[:, None], sel_boxes, 0.0)], axis=1)
        return rows

    return {"Out": [jax.vmap(per_image)(bboxes, scores)]}


@register_op("polygon_box_transform", inputs=("Input",), outputs=("Output",),
             no_grad=True)
def polygon_box_transform(ctx, ins, attrs):
    """Quad offset field -> absolute vertex coordinates
    (<- polygon_box_transform_op.cc).  Input [N, 8k, H, W]: even channels are
    x-offsets, odd channels y-offsets from the pixel center grid."""
    x = ins["Input"][0]
    n, cch, h, w = x.shape
    col = jnp.broadcast_to(jnp.arange(w, dtype=x.dtype)[None, :], (h, w))
    row = jnp.broadcast_to(jnp.arange(h, dtype=x.dtype)[:, None], (h, w))
    is_x = (jnp.arange(cch) % 2 == 0)[None, :, None, None]
    grid = jnp.where(is_x, col[None, None], row[None, None])
    return {"Output": [4.0 * grid - x]}


@register_op("roi_pool", inputs=("X", "ROIs", "ROIsBatch"), outputs=("Out",),
             diff_inputs=("X",))
def roi_pool(ctx, ins, attrs):
    """Max-pool each ROI into a fixed pooled grid (<- roi_pool_op.cc).

    X: [N, C, H, W]; ROIs: [R, 4] (x1, y1, x2, y2) at input scale;
    ROIsBatch: [R] image index per roi.  Quantization matches the reference
    (floor/ceil of scaled coords, bins clamped to >=1 element).  Implemented
    as a masked max over the full spatial map per bin — dense and fusable,
    no gather with data-dependent extents; grads flow via the max.
    """
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    batch_idx = ins["ROIsBatch"][0].astype(jnp.int32) if ins.get("ROIsBatch") \
        else jnp.zeros((rois.shape[0],), jnp.int32)
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    scale = float(attrs.get("spatial_scale", 1.0))
    n, c, h, w = x.shape

    def one_roi(roi, bi):
        x1 = jnp.round(roi[0] * scale)
        y1 = jnp.round(roi[1] * scale)
        x2 = jnp.round(roi[2] * scale)
        y2 = jnp.round(roi[3] * scale)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        py = jnp.arange(ph, dtype=jnp.float32)
        px = jnp.arange(pw, dtype=jnp.float32)
        hstart = jnp.clip(jnp.floor(py * bin_h) + y1, 0, h)  # [ph]
        hend = jnp.clip(jnp.ceil((py + 1) * bin_h) + y1, 0, h)
        wstart = jnp.clip(jnp.floor(px * bin_w) + x1, 0, w)
        wend = jnp.clip(jnp.ceil((px + 1) * bin_w) + x1, 0, w)
        hh = jnp.arange(h, dtype=jnp.float32)
        ww = jnp.arange(w, dtype=jnp.float32)
        hmask = (hh[None, :] >= hstart[:, None]) & (hh[None, :] < hend[:, None])
        wmask = (ww[None, :] >= wstart[:, None]) & (ww[None, :] < wend[:, None])
        mask = hmask[:, None, :, None] & wmask[None, :, None, :]  # [ph,pw,h,w]
        img = x[bi]  # [C, H, W]
        masked = jnp.where(mask[None], img[:, None, None, :, :], -jnp.inf)
        out = jnp.max(masked, axis=(-2, -1))  # [C, ph, pw]
        empty = ~jnp.any(mask, axis=(-2, -1))  # [ph, pw]
        return jnp.where(empty[None], 0.0, out)

    out = jax.vmap(one_roi)(rois.astype(jnp.float32), batch_idx)
    return {"Out": [out]}


@register_op("ssd_loss",
             inputs=("Location", "Confidence", "GTBox", "GTLabel",
                     "PriorBox", "PriorBoxVar", "GTValid"),
             outputs=("Loss",), diff_inputs=("Location", "Confidence"))
def ssd_loss(ctx, ins, attrs):
    """Fused SSD multibox loss (<- python layers/detection.py ssd_loss).

    One op covering the reference's 5-step recipe: IoU matching, per-prior
    conf loss, hard-negative mining, target assignment, weighted
    smooth-l1 + softmax losses normalized by positive count.  Matching and
    mining are wrapped in stop_gradient; grads flow only through the
    smooth-l1/softmax terms w.r.t. Location/Confidence.
    """
    loc = ins["Location"][0]        # [B, M, 4]
    conf = ins["Confidence"][0]     # [B, M, C]
    gt_box = ins["GTBox"][0]        # [B, G, 4]
    gt_label = ins["GTLabel"][0]    # [B, G]
    prior = ins["PriorBox"][0]      # [M, 4]
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else \
        jnp.ones((4,), jnp.float32)
    gt_valid = ins["GTValid"][0].astype(bool) if ins.get("GTValid") else \
        jnp.ones(gt_box.shape[:2], bool)
    background = int(attrs.get("background_label", 0))
    thr = float(attrs.get("overlap_threshold", 0.5))
    npr = float(attrs.get("neg_pos_ratio", 3.0))
    loc_w = float(attrs.get("loc_loss_weight", 1.0))
    conf_w = float(attrs.get("conf_loss_weight", 1.0))
    match_type = attrs.get("match_type", "per_prediction")
    mining_type = attrs.get("mining_type", "max_negative")
    sample_size = int(attrs.get("sample_size", 0))
    if gt_label.ndim == 3:
        gt_label = gt_label.squeeze(-1)
    gt_label = gt_label.astype(jnp.int32)

    def match_one(gb, gv):
        return _match_priors(pairwise_iou(gb, prior), gv, match_type, thr)[0]

    midx = lax.stop_gradient(jax.vmap(match_one)(gt_box, gt_valid))  # [B, M]
    matched = midx >= 0
    safe = jnp.maximum(midx, 0)

    # per-prior class targets
    tgt_label = jnp.take_along_axis(gt_label, safe, axis=1)
    tgt_label = jnp.where(matched, tgt_label, background)

    # softmax CE per prior
    logz = jax.nn.logsumexp(conf, axis=-1)
    picked = jnp.take_along_axis(conf, tgt_label[..., None], axis=-1).squeeze(-1)
    ce = logz - picked  # [B, M]

    # hard-negative mining on the conf loss (stop-gradient, like the
    # reference which mines on an auxiliary loss evaluation)
    neg_mask = _mine_negatives(lax.stop_gradient(ce), matched, npr,
                               mining_type, sample_size)
    num_pos = jnp.sum(matched.astype(jnp.int32), axis=1, keepdims=True)

    conf_loss = jnp.where(matched | neg_mask, ce, 0.0)

    # localization targets: encode matched gt against priors (center-size)
    gt_matched = jnp.take_along_axis(gt_box, safe[..., None], axis=1)  # [B,M,4]
    pcx, pcy, pw, ph = _corner_to_center(prior)
    tcx, tcy, tw, th = _corner_to_center(gt_matched)
    dx = (tcx - pcx[None]) / pw[None]
    dy = (tcy - pcy[None]) / ph[None]
    dw = jnp.log(jnp.maximum(tw / pw[None], 1e-10))
    dh = jnp.log(jnp.maximum(th / ph[None], 1e-10))
    loc_tgt = lax.stop_gradient(jnp.stack([dx, dy, dw, dh], axis=-1) / pvar)

    diff = jnp.abs(loc - loc_tgt)
    sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5).sum(-1)  # [B, M]
    loc_loss = jnp.where(matched, sl1, 0.0)

    total = loc_w * loc_loss + conf_w * conf_loss  # [B, M]
    denom = jnp.maximum(jnp.sum(num_pos).astype(total.dtype), 1.0)
    return {"Loss": [jnp.sum(total) / denom]}


@register_op("detection_map",
             inputs=("DetectRes", "Label", "PosCount", "TruePos", "FalsePos"),
             outputs=("MAP",), no_grad=True)
def detection_map(ctx, ins, attrs):
    """Mean average precision over detections (<- detection_map_op.cc).

    DetectRes: [B, D, 6] rows [label, score, x1, y1, x2, y2] (label -1 =
    empty slot); Label: [B, G, 6] rows [label, x1, y1, x2, y2, is_difficult]
    (label -1 = empty).  Single-batch AP ('integral' or '11point'); the
    streaming PosCount/TruePos/FalsePos accumulation of the reference is
    handled host-side by metrics.DetectionMAP.
    """
    det = ins["DetectRes"][0]
    gt = ins["Label"][0]
    overlap_thr = float(attrs.get("overlap_threshold", 0.5))
    ap_type = attrs.get("ap_type", "integral")
    evaluate_difficult = bool(attrs.get("evaluate_difficult", True))
    num_classes = int(attrs["class_num"])
    b, d, _ = det.shape
    g = gt.shape[1]

    def ap_for_class(cls):
        # ground truth of this class per image: [B, G]
        gt_mask = (gt[..., 0] == cls) & (gt[..., 0] >= 0)
        difficult = gt[..., 5] > 0 if gt.shape[-1] > 5 else jnp.zeros_like(gt_mask)
        if not evaluate_difficult:
            count_mask = gt_mask & ~difficult
        else:
            count_mask = gt_mask
        npos = jnp.sum(count_mask)

        det_mask = det[..., 0] == cls  # [B, D]
        scores = jnp.where(det_mask, det[..., 1], -jnp.inf)

        # per image: match detections (descending score) to gt, mark tp/fp
        def per_image(dets, dmask, gts, gmask, diff):
            order = jnp.argsort(-jnp.where(dmask, dets[:, 1], -jnp.inf))
            dboxes = dets[order, 2:6]
            dvalid = dmask[order]
            iou = pairwise_iou(dboxes, gts[:, 1:5])  # [D, G]
            iou = jnp.where(gmask[None, :], iou, -1.0)

            def body(i, state):
                used, tp, fp = state
                best_j = jnp.argmax(jnp.where(used, -1.0, iou[i]))
                best = jnp.where(used[best_j], -1.0, iou[i, best_j])
                hit = (best >= overlap_thr) & dvalid[i]
                is_diff = diff[best_j]
                skip = hit & is_diff & (not evaluate_difficult)
                tp = tp.at[i].set(jnp.where(hit & ~skip, 1.0, 0.0))
                fp = fp.at[i].set(jnp.where(dvalid[i] & ~hit & ~skip, 1.0, 0.0))
                used = used.at[best_j].set(used[best_j] | hit)
                return used, tp, fp

            used0 = jnp.zeros((g,), bool)
            _, tp_s, fp_s = lax.fori_loop(
                0, dets.shape[0], body,
                (used0, jnp.zeros((dets.shape[0],)), jnp.zeros((dets.shape[0],))))
            # un-sort back to original rows
            tp = jnp.zeros_like(tp_s).at[order].set(tp_s)
            fp = jnp.zeros_like(fp_s).at[order].set(fp_s)
            return tp, fp

        tp, fp = jax.vmap(per_image)(det, det_mask, gt, gt_mask, difficult)
        flat_scores = scores.reshape(-1)
        order = jnp.argsort(-flat_scores)
        tp = tp.reshape(-1)[order]
        fp = fp.reshape(-1)[order]
        valid = jnp.isfinite(flat_scores[order])
        ctp = jnp.cumsum(tp)
        cfp = jnp.cumsum(fp)
        recall = ctp / jnp.maximum(npos, 1)
        precision = ctp / jnp.maximum(ctp + cfp, 1e-10)
        if ap_type == "11point":
            pts = jnp.linspace(0.0, 1.0, 11)
            pmax = jax.vmap(
                lambda t: jnp.max(jnp.where(valid & (recall >= t), precision, 0.0))
            )(pts)
            ap = jnp.mean(pmax)
        else:
            dr = jnp.diff(jnp.concatenate([jnp.zeros((1,)), recall]))
            ap = jnp.sum(jnp.where(valid, dr * precision, 0.0))
        return jnp.where(npos > 0, ap, jnp.nan), npos > 0

    background = int(attrs.get("background_label", 0))
    classes = jnp.asarray(
        [cls for cls in range(num_classes) if cls != background], jnp.int32)
    # one traced copy of the matching loop, vmapped over the class axis —
    # program size stays constant in num_classes
    aps, has = jax.vmap(ap_for_class)(classes)
    mAP = jnp.sum(jnp.where(has, aps, 0.0)) / jnp.maximum(jnp.sum(has), 1)
    return {"MAP": [mAP]}

"""Neural-net structural ops: conv / pool / normalization / dropout /
embedding lookup.

<- paddle/fluid/operators/{conv,conv_transpose,pool,batch_norm,layer_norm,
lrn,dropout,lookup_table,one_hot}_op.cc. Data layout is NCHW to match the
reference's Python API; XLA re-lays-out for the MXU internally, so there is
no reason to diverge from the reference's user-visible convention.

Convs lower to ``lax.conv_general_dilated`` — exactly the HLO the TPU's MXU
wants — instead of im2col+GEMM (the reference's math/im2col.cc path).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.ir import GRAD_SUFFIX, grad_var_name
from ..core.registry import register_op
from ._amp import low_precision as _low_prec


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


@register_op("conv2d", inputs=("Input", "Filter", "Bias"), outputs=("Output",),
             diff_inputs=("Input", "Filter", "Bias"))
def conv2d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]  # x: NCHW, w: OIHW
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1) or 1
    acc = jnp.promote_types(x.dtype, w.dtype)
    amp = getattr(ctx, "amp", False) and jnp.issubdtype(acc, jnp.floating)
    if amp:
        # bf16 operands AND bf16 result: activations stay bf16 end-to-end
        # (half the HBM traffic of a per-layer f32 cast-back), master weights
        # stay f32 in the scope — the vjp of the f32->bf16 weight cast
        # accumulates the weight grad back to f32 automatically. Unlike the
        # dot ops we can NOT request an f32 accumulator here: lax's conv
        # transpose rule requires cotangent and operand dtypes to match, so
        # preferred_element_type must equal the operand dtype for the vjp to
        # exist. On TPU the MXU accumulates f32 internally regardless; only
        # CPU/interpret AMP paths see bf16 accumulation (test tolerances
        # absorb it).
        x = x.astype(jnp.bfloat16)
        w = w.astype(jnp.bfloat16)
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dilations,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups,
        preferred_element_type=None if amp else acc,
    )
    if not amp:
        out = out.astype(acc)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        out = out + ins["Bias"][0].reshape(1, -1, 1, 1).astype(out.dtype)
    return {"Output": [out]}


@register_op("depthwise_conv2d", inputs=("Input", "Filter"), outputs=("Output",))
def depthwise_conv2d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    attrs = dict(attrs)
    attrs["groups"] = x.shape[1]
    return conv2d(ctx, {"Input": [x], "Filter": [w], "Bias": [None]}, attrs)


@register_op("conv2d_transpose", inputs=("Input", "Filter"), outputs=("Output",))
def conv2d_transpose(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]  # w: IOHW in reference transpose
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    out = lax.conv_transpose(
        x,
        w,
        strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dilations,
        dimension_numbers=("NCHW", "IOHW", "NCHW"),
    )
    return {"Output": [out]}


@register_op("conv3d", inputs=("Input", "Filter"), outputs=("Output",))
def conv3d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]  # NCDHW / OIDHW
    s = attrs.get("strides", [1, 1, 1])
    p = attrs.get("paddings", [0, 0, 0])
    d = attrs.get("dilations", [1, 1, 1])
    out = lax.conv_general_dilated(
        x, w, tuple(s), [(pp, pp) for pp in p], rhs_dilation=tuple(d),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=attrs.get("groups", 1) or 1,
    )
    return {"Output": [out]}


def _ceil_extra(size, k, p, s):
    """Extra right/bottom padding so reduce_window (floor) matches ceil_mode."""
    floor_out = (size + 2 * p - k) // s + 1
    ceil_out = -((size + 2 * p - k) // -s) + 1
    return (ceil_out - floor_out) * s


def _ntuple(v, n):
    v = list(v) if isinstance(v, (list, tuple)) else [v]
    return tuple(int(x) for x in (v * n if len(v) == 1 else v))


def _pool_impl(x, attrs, nsp=2):
    """Shared N-spatial-dim pooling (pool2d over NCHW, pool3d over NCDHW)."""
    ptype = attrs.get("pooling_type", "max")
    ksize = _ntuple(attrs.get("ksize", [2] * nsp), nsp)
    strides = _ntuple(attrs.get("strides", [1] * nsp), nsp)
    pads = _ntuple(attrs.get("paddings", [0] * nsp), nsp)
    if attrs.get("global_pooling", False):
        ksize = x.shape[2:]
        strides = (1,) * nsp
        pads = (0,) * nsp
    extra = [0] * nsp
    if attrs.get("ceil_mode", False):
        extra = [_ceil_extra(x.shape[2 + i], ksize[i], pads[i], strides[i])
                 for i in range(nsp)]
    window = (1, 1) + tuple(ksize)
    strides_full = (1, 1) + tuple(strides)
    padding = ((0, 0), (0, 0)) + tuple(
        (pads[i], pads[i] + extra[i]) for i in range(nsp))
    if ptype == "max":
        out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides_full, padding)
    else:
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides_full, padding)
        if attrs.get("exclusive", True) and (any(pads) or any(extra)):
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides_full, padding)
            out = summed / counts
        else:
            out = summed / int(np.prod(ksize))
    return out


@register_op("pool2d", inputs=("X",), outputs=("Out",))
def pool2d(ctx, ins, attrs):
    return {"Out": [_pool_impl(ins["X"][0], attrs, nsp=2)]}


def _pool_window_positions(x, ksize, strides):
    """Global flat (h*W+w) index of each element of each pooling window.

    Returns patches [n, c, kh*kw, oh, ow] and the matching global index map
    [kh*kw, oh, ow] so argmax picks parity-faithful max_pool_with_index masks
    (<- pool_with_index_op.cc: mask = offset within the input feature plane).
    """
    n, c, h, w = x.shape
    kh, kw = ksize
    sh, sw = strides
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW")
    )  # [n, c*kh*kw, oh, ow]
    oh, ow = patches.shape[2], patches.shape[3]
    patches = patches.reshape(n, c, kh * kw, oh, ow)
    wins = jnp.arange(kh * kw)
    wi, wj = wins // kw, wins % kw
    base_i = jnp.arange(oh)[:, None] * sh
    base_j = jnp.arange(ow)[None, :] * sw
    # [kh*kw, oh, ow]
    gidx = (wi[:, None, None] + base_i[None]) * w + (wj[:, None, None] + base_j[None])
    return patches, gidx


@register_op("pool2d_with_index", inputs=("X",), outputs=("Out", "Mask"),
             diff_inputs=("X",))
def pool2d_with_index(ctx, ins, attrs):
    x = ins["X"][0]
    ksize = _pair(attrs.get("ksize", [2, 2]))
    strides = _pair(attrs.get("strides", ksize))
    patches, gidx = _pool_window_positions(x, ksize, strides)
    arg = jnp.argmax(patches, axis=2)  # [n, c, oh, ow]
    out = jnp.max(patches, axis=2)
    mask = jnp.take_along_axis(
        jnp.broadcast_to(gidx[None, None], patches.shape[:2] + gidx.shape),
        arg[:, :, None], axis=2,
    ).squeeze(2)
    return {"Out": [out], "Mask": [mask.astype(jnp.int32)]}


@register_op("unpool", inputs=("X", "Indices"), outputs=("Out",), diff_inputs=("X",))
def unpool(ctx, ins, attrs):
    """Scatter pooled values back to the positions recorded in Indices
    (<- unpool_op.cc)."""
    x, idx = ins["X"][0], ins["Indices"][0]
    n, c, h, w = x.shape
    oh, ow = attrs.get("unpooled_height"), attrs.get("unpooled_width")
    if oh is None or ow is None:
        s = _pair(attrs.get("strides", [2, 2]))
        oh, ow = h * s[0], w * s[1]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    flat = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1).astype(jnp.int32),
    ].set(x.reshape(n, c, -1))
    return {"Out": [flat.reshape(n, c, oh, ow)]}


@register_op(
    "batch_norm",
    inputs=("X", "Scale", "Bias", "Mean", "Variance"),
    outputs=("Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"),
    diff_inputs=("X", "Scale", "Bias"),
)
def batch_norm(ctx, ins, attrs):
    """Train mode computes batch stats and updates running stats functionally
    (MeanOut/VarianceOut carry the same var names as Mean/Variance, so the
    executor's env update is the in-place semantics of batch_norm_op.cc)."""
    x, scale, bias = ins["X"][0], ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) or ctx.is_test
    layout = attrs.get("data_layout", "NCHW")
    axes = tuple(i for i in range(x.ndim) if i != (1 if layout == "NCHW" else x.ndim - 1))
    shape_bcast = [1] * x.ndim
    shape_bcast[1 if layout == "NCHW" else x.ndim - 1] = -1

    # stats and the normalization arithmetic run in f32 even when the
    # activations flow in bf16 (AMP): the reductions need the mantissa, the
    # elementwise chain fuses into the producing conv either way, and only
    # the bf16 result is materialized in HBM
    xf = x.astype(jnp.float32) if _low_prec(x.dtype) else x

    if is_test:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = mean
        saved_var = var
    else:
        # single-pass stats (E[x], E[x^2] in one read of x, f32 accumulation)
        # instead of mean+var's two passes: BN is HBM-bound, measured ~9%
        # whole-model win on ResNet-50; same formula as batch_norm_op.cc
        use_mean = jnp.mean(xf, axis=axes)
        # clamp: f32 cancellation can push E[x^2]-mean^2 slightly negative
        use_var = jnp.maximum(
            jnp.mean(xf * xf, axis=axes) - use_mean * use_mean, 0.0)
        mean_out = momentum * mean + (1 - momentum) * lax.stop_gradient(use_mean)
        var_out = momentum * var + (1 - momentum) * lax.stop_gradient(use_var)
        saved_mean = use_mean
        saved_var = use_var
    inv = lax.rsqrt(use_var + eps)
    y = (xf - use_mean.reshape(shape_bcast)) * inv.reshape(shape_bcast) * scale.reshape(
        shape_bcast
    ) + bias.reshape(shape_bcast)
    y = y.astype(x.dtype)
    return {
        "Y": [y],
        "MeanOut": [mean_out],
        "VarianceOut": [var_out],
        "SavedMean": [saved_mean],
        "SavedVariance": [saved_var],
    }


def _ln_grad_maker(op, no_grad_set):
    """Explicit grad: rebuilds xhat in the backward from the (bf16) input
    and the saved per-row Mean/Variance instead of keeping an f32 residual.
    The generic vjp saved (xf - mean) — a full f32 copy of the activation —
    for EVERY layer_norm (17 of them on the bench transformer ≈ 0.5 GB of
    residual writes+reads per step, hlo_audit r5); here the backward's
    only large read is the bf16 x that is already resident."""
    inputs = {
        "X": list(op.inputs["X"]),
        "Scale": list(op.inputs.get("Scale", [])),
        "Bias": list(op.inputs.get("Bias", [])),
        # programs that only declared Y (OpTest one-op programs) omit the
        # saved stats; the grad kernel recomputes them from X
        "Mean": list(op.outputs.get("Mean", [])),
        "Variance": list(op.outputs.get("Variance", [])),
        "Y@GRAD": [grad_var_name(n) for n in op.outputs["Y"]],
        # rare but public: a consumer of the stats outputs contributes
        # gradient through them too (autodiff nulls these when unused)
        "Mean@GRAD": [grad_var_name(n)
                      for n in op.outputs.get("Mean", [])],
        "Variance@GRAD": [grad_var_name(n)
                          for n in op.outputs.get("Variance", [])],
    }
    outputs = {}
    for slot in ("X", "Scale", "Bias"):
        names = op.inputs.get(slot, [])
        outputs[slot + "@GRAD"] = [
            "" if (not n or n in no_grad_set) else grad_var_name(n)
            for n in names]
    return [{"type": "layer_norm_grad", "inputs": inputs,
             "outputs": outputs, "attrs": dict(op.attrs)}]


@register_op("layer_norm", inputs=("X", "Scale", "Bias"),
             outputs=("Y", "Mean", "Variance"), diff_inputs=("X", "Scale", "Bias"),
             grad_maker=_ln_grad_maker)
def layer_norm(ctx, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    # f32 statistics on low-precision activations, but each as its OWN
    # cast->reduce chain with a single consumer (the CE-head recipe,
    # ops/loss.py): an up-front shared astype materializes a full f32 copy
    # of the activation, separate chains fuse into passes reading bf16
    # directly. Single-pass E[x²] stats; clamp f32 cancellation.
    lp = _low_prec(x.dtype)
    mean = jnp.mean(x.astype(jnp.float32) if lp else x, axis=axes,
                    keepdims=True)
    xsq = x.astype(jnp.float32) * x.astype(jnp.float32) if lp else x * x
    var = jnp.maximum(
        jnp.mean(xsq, axis=axes, keepdims=True) - mean * mean, 0.0)
    xf = x.astype(jnp.float32) if lp else x
    y = (xf - mean) * lax.rsqrt(var + eps)
    scale = ins["Scale"][0] if ins.get("Scale") and ins["Scale"][0] is not None else None
    bias = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    norm_shape = x.shape[begin:]
    if scale is not None:
        y = y * scale.reshape((1,) * begin + norm_shape)
    if bias is not None:
        y = y + bias.reshape((1,) * begin + norm_shape)
    y = y.astype(x.dtype)
    return {"Y": [y], "Mean": [mean.squeeze(axes)], "Variance": [var.squeeze(axes)]}


@register_op(
    "layer_norm_grad",
    inputs=("X", "Scale", "Bias", "Mean", "Variance", "Y@GRAD",
            "Mean@GRAD", "Variance@GRAD"),
    outputs=("X@GRAD", "Scale@GRAD", "Bias@GRAD"),
    no_grad=True,
)
def layer_norm_grad(ctx, ins, attrs):
    """dX/dScale/dBias from x + saved row stats (no activation residual):
    xhat = (x - mean) * rsqrt(var + eps)
    dScale = sum_rows(g * xhat); dBias = sum_rows(g)
    dX = inv * (dxhat - mean_f(dxhat) - xhat * mean_f(dxhat * xhat))
    with dxhat = g * scale, means over the normalized axes per row.
    Cotangents through the Mean/Variance OUTPUTS (rare, but they are
    public op outputs) add dmean/n and dvar * 2(x - mean)/n."""
    x = ins["X"][0]
    g = ins["Y@GRAD"][0]
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    norm_shape = x.shape[begin:]
    lead = tuple(range(begin))
    kd = {"axis": axes, "keepdims": True}
    scale = ins["Scale"][0] if ins.get("Scale") and ins["Scale"][0] is not None else None
    bias_wanted = bool(ins.get("Bias")) and ins["Bias"][0] is not None
    if g is None:
        gf = jnp.zeros(x.shape, jnp.float32)
    else:
        gf = g.astype(jnp.float32)
    stat_shape = x.shape[:begin] + (1,) * len(axes)
    if ins.get("Mean") and ins["Mean"][0] is not None:
        mean = ins["Mean"][0].reshape(stat_shape).astype(jnp.float32)
        var = ins["Variance"][0].reshape(stat_shape).astype(jnp.float32)
    else:  # stats not saved by the forward program: recompute from X
        xf32 = x.astype(jnp.float32)
        mean = jnp.mean(xf32, **kd)
        var = jnp.maximum(jnp.mean(xf32 * xf32, **kd) - mean * mean, 0.0)
    inv = lax.rsqrt(var + eps)
    xhat = (x.astype(jnp.float32) - mean) * inv
    out = {}
    if scale is not None:
        out["Scale@GRAD"] = [jnp.sum(gf * xhat, axis=lead).reshape(
            scale.shape).astype(scale.dtype)]
        dxhat = gf * scale.reshape((1,) * begin + norm_shape).astype(
            jnp.float32)
    else:
        dxhat = gf
    if bias_wanted:
        b = ins["Bias"][0]
        out["Bias@GRAD"] = [jnp.sum(gf, axis=lead).reshape(
            b.shape).astype(b.dtype)]
    dx = inv * (dxhat - jnp.mean(dxhat, **kd)
                - xhat * jnp.mean(dxhat * xhat, **kd))
    n_feat = 1
    for a in axes:
        n_feat *= x.shape[a]
    for slot, jac in (("Mean@GRAD", lambda dm: dm / n_feat),
                      ("Variance@GRAD",
                       lambda dv: dv * 2.0 * (x.astype(jnp.float32) - mean)
                       / n_feat)):
        if ins.get(slot) and ins[slot][0] is not None:
            dstat = ins[slot][0].reshape(stat_shape).astype(jnp.float32)
            dx = dx + jac(dstat)
    out["X@GRAD"] = [dx.astype(x.dtype)]
    return out


@register_op("lrn", inputs=("X",), outputs=("Out", "MidOut"), diff_inputs=("X",))
def lrn(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = x * x
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i : i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": [x * mid ** (-beta)], "MidOut": [mid]}


def _dropout_grad_maker(op, no_grad_set):
    return [
        {
            "type": "dropout_grad",
            "inputs": {
                "Mask": list(op.outputs["Mask"]),
                "Out@GRAD": [grad_var_name(n) for n in op.outputs["Out"]],
            },
            "outputs": {"X@GRAD": [
                "" if n in no_grad_set else grad_var_name(n) for n in op.inputs["X"]
            ]},
            "attrs": dict(op.attrs),
        }
    ]


@register_op("dropout", inputs=("X",), outputs=("Out", "Mask"),
             stochastic=True, grad_maker=_dropout_grad_maker)
def dropout(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False) or ctx.is_test
    if is_test or p == 0.0:
        # reference's downgrade-in-infer: scale by (1-p) at inference
        mode = attrs.get("dropout_implementation", "downgrade_in_infer")
        out = x if mode == "upscale_in_train" else x * (1.0 - p)
        return {"Out": [out], "Mask": [jnp.ones_like(x)]}
    from .basic import _op_key

    keep = jax.random.bernoulli(_op_key(ctx, attrs), 1.0 - p, x.shape)
    mode = attrs.get("dropout_implementation", "downgrade_in_infer")
    if mode == "upscale_in_train":
        mask = keep.astype(x.dtype) / (1.0 - p)
    else:
        mask = keep.astype(x.dtype)
    return {"Out": [x * mask], "Mask": [mask]}


@register_op("dropout_grad", inputs=("Mask", "Out@GRAD"), outputs=("X@GRAD",),
             no_grad=True)
def dropout_grad(ctx, ins, attrs):
    """Backward reuses the saved mask — never re-drawn (cf. dropout_op.cc)."""
    return {"X@GRAD": [ins["Out@GRAD"][0] * ins["Mask"][0]]}


def _lookup_table_grad_maker(op, no_grad_set):
    """``is_sparse=False``: generic vjp (gather backward = dense
    scatter-add). ``is_sparse=True``: the SelectedRows path
    (<- lookup_table_op.cc GradVarTypeInference switching W@GRAD to
    SelectedRows + sgd/adam SelectedRows kernels, sgd_op.cc:72-76) —
    the grad stays (rows, ids) and the optimizer touches only gathered
    rows. On a [32k, 1024] bench-transformer table the dense path costs a
    full-table scatter-add (0.63 ms) + whole-table Adam (1.26 ms); the
    sparse path replaces both with passes over the ~8k touched rows."""
    from ..core.registry import default_grad_op_descs

    if not op.attrs.get("is_sparse", False):
        return default_grad_op_descs(op, no_grad_set)
    w = op.inputs["W"][0]
    if w in no_grad_set:
        return []
    return [{
        "type": "lookup_table_grad_sparse",
        "inputs": {
            "W": list(op.inputs["W"]),
            "Ids": list(op.inputs["Ids"]),
            "Out@GRAD": [grad_var_name(n) for n in op.outputs["Out"]],
        },
        "outputs": {
            "W@GRAD": [grad_var_name(w)],
            "W@GRAD@IDS": [grad_var_name(w) + "@IDS"],
        },
        "attrs": dict(op.attrs),
    }]


@register_op("lookup_table", inputs=("W", "Ids"), outputs=("Out",),
             diff_inputs=("W",), grad_maker=_lookup_table_grad_maker)
def lookup_table(ctx, ins, attrs):
    """Embedding lookup (<- lookup_table_op.cc). The generic vjp turns the
    gather's backward into a scatter-add — the dense equivalent of the
    reference's SelectedRows sparse gradient; ``is_sparse=True`` keeps the
    gradient as (rows, ids) instead (see _lookup_table_grad_maker)."""
    w, ids = ins["W"][0], ins["Ids"][0]
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze_last:
        ids = ids.squeeze(-1)
    padding_idx = attrs.get("padding_idx", -1)
    out = jnp.take(w, ids.astype(jnp.int32), axis=0)
    if (getattr(ctx, "amp", False)
            and jnp.issubdtype(w.dtype, jnp.floating)
            and not _low_prec(w.dtype)):
        # AMP: emit bf16 activations — cast the gathered rows, never the
        # whole master table (which would materialize a full bf16 copy of
        # the largest parameter); the vjp upcasts the row grads to f32
        # before the scatter-add, so grad accumulation stays f32
        out = out.astype(jnp.bfloat16)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return {"Out": [out]}


@register_op("lookup_table_grad_sparse",
             inputs=("W", "Ids", "Out@GRAD"),
             outputs=("W@GRAD", "W@GRAD@IDS"), no_grad=True)
def lookup_table_grad_sparse(ctx, ins, attrs):
    """SelectedRows gradient: (row values [N_flat, E] f32, ids [N_flat]
    int32), duplicates NOT merged — the optimizer's sparse path merges
    (<- the reference's MergeAdd in selected_rows_functor running inside
    the optimizer kernels). padding_idx rows get zero grad, matching the
    dense vjp of the output mask."""
    ids, g = ins["Ids"][0], ins["Out@GRAD"][0]
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.squeeze(-1)
    dim = g.shape[-1]
    flat_ids = ids.reshape(-1).astype(jnp.int32)
    rows = g.reshape(-1, dim).astype(jnp.float32)  # f32 accumulation
    padding_idx = attrs.get("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        rows = jnp.where((flat_ids == padding_idx)[:, None], 0.0, rows)
    return {"W@GRAD": [rows], "W@GRAD@IDS": [flat_ids]}


@register_op("one_hot", inputs=("X",), outputs=("Out",), no_grad=True)
def one_hot(ctx, ins, attrs):
    x = ins["X"][0]
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x.squeeze(-1)
    return {"Out": [jax.nn.one_hot(x.astype(jnp.int32), attrs["depth"], dtype=jnp.float32)]}


@register_op("embedding", inputs=("W", "Ids"), outputs=("Out",), diff_inputs=("W",))
def embedding(ctx, ins, attrs):
    return lookup_table(ctx, ins, attrs)


@register_op("bilinear_interp", inputs=("X",), outputs=("Out",))
def bilinear_interp(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    oh = attrs.get("out_h")
    ow = attrs.get("out_w")
    n, c, h, w = x.shape
    out = jax.image.resize(x, (n, c, oh, ow), method="bilinear")
    return {"Out": [out]}


@register_op("nearest_interp", inputs=("X",), outputs=("Out",))
def nearest_interp(ctx, ins, attrs):
    x = ins["X"][0]
    n, c, _, _ = x.shape
    out = jax.image.resize(x, (n, c, attrs.get("out_h"), attrs.get("out_w")), method="nearest")
    return {"Out": [out]}


@register_op("im2sequence", inputs=("X",), outputs=("Out",))
def im2sequence(ctx, ins, attrs):
    """Image patches -> sequence rows (<- im2sequence_op.cc), dense layout."""
    x = ins["X"][0]
    kh, kw = _pair(attrs.get("kernels", [1, 1]))
    sh, sw = _pair(attrs.get("strides", [1, 1]))
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW")
    )  # [n, c*kh*kw, oh, ow]
    out = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, c * kh * kw)
    return {"Out": [out]}


@register_op("conv_shift", inputs=("X", "Y"), outputs=("Out",))
def conv_shift(ctx, ins, attrs):
    """Circular correlation (<- conv_shift_op.cc)."""
    x, y = ins["X"][0], ins["Y"][0]
    m = y.shape[1]
    half = m // 2
    idx = (jnp.arange(x.shape[1])[:, None] + jnp.arange(m)[None, :] - half) % x.shape[1]
    return {"Out": [jnp.einsum("bnm,bm->bn", x[:, idx], y)]}


@register_op("row_conv", inputs=("X", "Filter"), outputs=("Out",))
def row_conv(ctx, ins, attrs):
    """Lookahead row convolution over time-major input [T, D] per sequence
    (dense batched form: [N, T, D]; <- row_conv_op.cc)."""
    x, f = ins["X"][0], ins["Filter"][0]  # f: [future_context, D]
    k = f.shape[0]
    pad = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = sum(pad[:, i : i + x.shape[1]] * f[i] for i in range(k))
    return {"Out": [out]}


@register_op("pool3d", inputs=("X",), outputs=("Out",))
def pool3d(ctx, ins, attrs):
    """3-D pooling over NCDHW (<- pool_op.cc 3-D registration)."""
    return {"Out": [_pool_impl(ins["X"][0], attrs, nsp=3)]}


@register_op("spp", inputs=("X",), outputs=("Out",), diff_inputs=("X",))
def spp(ctx, ins, attrs):
    """Spatial pyramid pooling (<- spp_op.cc): pyramid level i pools onto a
    2^i x 2^i grid (adaptive window), levels flattened + concatenated to
    [N, C * (4^height - 1) / 3]."""
    x = ins["X"][0]
    n, c, h, w = x.shape
    height = attrs.get("pyramid_height", 2)
    ptype = attrs.get("pooling_type", "max")
    outs = []
    for lvl in range(height):
        bins = 2 ** lvl
        # kernel = stride = ceil(size/bins), symmetric-ish padding so the
        # bins tile the (padded) plane exactly (<- spp_op.cc kernel/padding)
        kh, kw = -(-h // bins), -(-w // bins)
        ph = (kh * bins - h + 1) // 2 if kh * bins > h else 0
        pw = (kw * bins - w + 1) // 2 if kw * bins > w else 0
        window = (1, 1, kh, kw)
        strides = (1, 1, kh, kw)
        padding = ((0, 0), (0, 0), (ph, kh * bins - h - ph), (pw, kw * bins - w - pw))
        if ptype == "max":
            o = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, padding)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
            cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                    strides, padding)
            o = s / cnt
        outs.append(o[:, :, :bins, :bins].reshape(n, -1))
    return {"Out": [jnp.concatenate(outs, axis=1)]}


@register_op("random_crop", inputs=("X", "Seed"), outputs=("Out", "SeedOut"),
             no_grad=True, stochastic=True)
def random_crop(ctx, ins, attrs):
    """Random spatial crop (<- random_crop_op.cc): crops the trailing dims of
    every batch element to attrs['shape'] at a random offset. When a Seed
    tensor is provided, offsets derive deterministically from it (the
    reference's seed-engine contract: same seed -> same crops) and SeedOut
    carries seed+1 so chained crops differ; otherwise the executor's
    functional PRNG drives the crop."""
    x = ins["X"][0]
    crop = list(attrs["shape"])
    k = len(crop)
    lead = x.shape[: x.ndim - k]
    seed_in = ins["Seed"][0] if ins.get("Seed") and ins["Seed"][0] is not None else None
    if seed_in is not None:
        key = jax.random.PRNGKey(seed_in.reshape(-1)[0].astype(jnp.uint32))
    else:
        key = ctx.next_key()
    maxs = jnp.array([x.shape[x.ndim - k + i] - crop[i] for i in range(k)], jnp.int32)
    nbatch = int(np.prod(lead)) if lead else 1
    offs = jax.random.randint(key, (nbatch, k), 0, maxs + 1, jnp.int32)
    flat = x.reshape((nbatch,) + x.shape[x.ndim - k:])

    def crop_one(xi, oi):
        return lax.dynamic_slice(xi, tuple(oi), tuple(crop))

    out = jax.vmap(crop_one)(flat, offs).reshape(tuple(lead) + tuple(crop))
    seed_out = (seed_in.reshape(-1)[:1] + 1 if seed_in is not None
                else jnp.zeros((1,), jnp.int32))
    return {"Out": [out], "SeedOut": [seed_out]}

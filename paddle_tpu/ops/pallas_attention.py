"""Pallas TPU flash attention (forward + backward kernels).

The reference has no attention op at all — its transformer benchmark builds
attention from matmul+softmax primitives (SURVEY.md §5.7). Here attention is
a first-class op whose forward is a Pallas kernel: per (batch*head, q-block)
grid cell, K/V stream through VMEM in blocks under an online-softmax
accumulator, so the [Tq, Tk] logits matrix never materializes in HBM —
the flash-attention memory profile the MXU wants. The forward also emits
the per-query logsumexp (LSE), and the backward is the FlashAttention-2
recipe: one kernel accumulates dQ over K-blocks, a second accumulates
dK/dV over Q-blocks, both reconstructing P = exp(logits - lse) from the
saved LSE instead of storing the attention matrix.

On non-TPU backends the same kernels run in interpreter mode (tests), so
numerical behavior is identical everywhere.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..core.ir import grad_var_name
from ..core.registry import register_op

_NEG_INF = -1e30

# the pre-PR-12 fixed schedule: one 512-token q/k block pair. Still the
# fallback everywhere; since PR 12 the knobs are a TUNABLE SURFACE — any
# knob left None is filled from the persistent TuningDB (resolve below),
# which `tools/perf_lab.py tune` populates from measured sweeps targeting
# the probe_fa_gap short-sequence gap (the ~3x small-grid tax at T=1024).
DEFAULT_Q_BLOCK = 512
DEFAULT_K_BLOCK = 512


def _interpret_default():
    # interpret anywhere except a real TPU (jax.default_device overrides
    # the backend the computation actually lands on)
    dev = jax.config.jax_default_device
    platform = dev.platform if dev is not None else jax.default_backend()
    return platform != "tpu"


def _fit_block(t, blk):
    """Largest viable Pallas block size for a length-t axis: a divisor of t
    not exceeding the requested block, preferring lane-aligned (×128) then
    sublane-aligned (×8) sizes. Returns None when no aligned divisor exists
    (truly ragged length) — only then is the dense fallback justified.
    Without this, a T divisible by 128 but not by the 512 default (768,
    1280, ring-attention shards of those) would silently take the O(T²)
    dense path and defeat the op's memory guarantee. A requested block that
    divides T exactly is always honored (the pre-r3 contract), so explicit
    q_block/k_block choices and small-T routings are unchanged."""
    blk = min(blk, t)
    if t % blk == 0:
        return blk
    for align in (128, 8):
        for b in range(blk - blk % align, 0, -align):
            if t % b == 0:
                return b
    return None


def _causal_mask3(logits, qi, q_block, j, block_k, hb, bq):
    """[hb, bq, bk] variant for multi-head blocks (same mask per head)."""
    shape = (hb, bq, block_k)
    q_pos = qi * q_block + lax.broadcasted_iota(jnp.int32, shape, 1)
    k_pos = j * block_k + lax.broadcasted_iota(jnp.int32, shape, 2)
    return jnp.where(q_pos >= k_pos, logits, _NEG_INF)


def _heads_per_block(h, d, hpb, t):
    """How many heads share one grid cell (default 128//d, clamped to a
    divisor of h). Small heads (d < 128) leave the MXU contraction
    half-filled and double the sequential grid; batching 128//d heads per
    cell amortizes the per-cell loop/DMA overhead. Measured at MODEL level
    (transformer_lm d_model=1024 n_heads=16, slope-timed, spread <0.2 ms):
    hb=2 86.3 ms/step vs hb=1 97.6 ms — 13% faster; with the native-bf16
    operand fix below the pair lifts d_head=64 from r3's 36% to ~41% MFU.
    (Microbench A/B under tunnel jitter is NOT reliable for this decision —
    tools/probe_small_head.py spreads swung 3x; trust the model bench.)
    ``hpb`` overrides; the pack must divide the head count, and the
    default backs off when the packed full-T K/V blocks would crowd VMEM
    (long-context shards keep hb=1 rather than risking a Mosaic OOM)."""
    if hpb is None:
        hpb = max(1, 128 // max(d, 1))
        # the dkv backward holds FOUR full-T [hb, t, d] bf16 blocks per
        # cell (Q, K, V, dO) — twice the forward's K+V — so budget that,
        # staying well under the ~16 MB VMEM for double-buffering and the
        # f32 logits/accumulators
        while hpb > 1 and hpb * t * d * 2 * 4 > 4 * 1024 * 1024:
            hpb //= 2
    hpb = max(1, min(hpb, h))
    while h % hpb:
        hpb -= 1
    return hpb


def _causal_hi(qi, q_block, block_k, n_blocks):
    """First K-block index fully above the causal diagonal for q-block qi —
    the exclusive upper bound of the K-loop (FlashAttention-2 bound)."""
    return jnp.minimum(n_blocks, ((qi + 1) * q_block + block_k - 1) // block_k)


# ---------------------------------------------------------------------------
# tunable schedule surface (PR 12): q_block × k_block × heads_per_block
# ---------------------------------------------------------------------------


def flash_key(t, h, d):
    """The flash kernels' TuningDB shape bucket: (T, H, D), batch-free —
    block/pack viability and the per-cell schedule depend on the sequence
    layout, not on how many (batch × head) grid rows repeat it."""
    return (int(t), int(h), int(d))


def resolve_flash_config(t, h, d, dtype, q_block=None, k_block=None,
                         heads_per_block=None):
    """Fill unpinned (None) flash schedule knobs from the tuning DB.

    Explicit choices always win (the pre-PR-12 contract: a caller-pinned
    q_block is honored exactly; ``heads_per_block="auto"`` is the explicit
    spelling of the `_heads_per_block` auto-pack, for callers — the
    probe_fa_gap baseline — that must pin the DEFAULT schedule rather than
    leave the knob tunable). On a non-TPU backend nothing is consulted
    and the 512/512/auto defaults apply, so CPU programs are byte-identical
    with or without a warm DB — only a fresh, adopted, current-backend
    entry (written by `perf_lab.py tune` on a measured >5% win) changes
    the schedule. Returns ``(q_block, k_block, heads_per_block)`` with
    ``heads_per_block`` possibly None (= auto-pack)."""
    explicit_auto = heads_per_block == "auto"
    if explicit_auto:
        heads_per_block = None
    if (q_block is None or k_block is None
            or (heads_per_block is None and not explicit_auto)) \
            and not _interpret_default():
        from ..core.registry import tuned_op_config

        cfg = tuned_op_config("flash_attention", flash_key(t, h, d),
                              str(jnp.dtype(dtype))) or {}

        def tuned_int(name):
            # a hand-edited DB value that isn't a positive int must mean
            # "untuned", not a TypeError inside _fit_block at trace time
            v = cfg.get(name)
            return int(v) if isinstance(v, int) and v > 0 else None

        if q_block is None:
            q_block = tuned_int("q_block")
        if k_block is None:
            k_block = tuned_int("k_block")
        if heads_per_block is None and not explicit_auto:
            heads_per_block = tuned_int("heads_per_block")
    return (q_block or DEFAULT_Q_BLOCK, k_block or DEFAULT_K_BLOCK,
            heads_per_block)


def flash_candidates(t, h, d):
    """The sweep's search space over the flash schedule surface: aligned
    (q_block, k_block) pairs dividing T × viable head packs (power-of-two
    divisors of H under the dkv backward's VMEM budget — the same 4 MB
    full-T bound ``_heads_per_block`` backs off on). Deterministic order;
    the 512/512/auto default is the baseline, not a member."""
    blocks = [blk for blk in (128, 256, 512, 1024)
              if blk <= t and t % blk == 0]
    if not blocks:
        fb = _fit_block(t, DEFAULT_Q_BLOCK)
        blocks = [fb] if fb else []
    hpbs, hpb = [], 1
    while hpb <= h:
        if h % hpb == 0 and (hpb == 1
                             or hpb * t * d * 2 * 4 <= 4 * 1024 * 1024):
            hpbs.append(hpb)
        hpb *= 2
    return [{"q_block": qb, "k_block": kb, "heads_per_block": hb}
            for qb in blocks for kb in blocks for hb in hpbs]


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_k,
                  causal, q_block):
    """One grid cell = ``hb`` heads x one q-block. All matmuls are batched
    over the leading head dim (hb=1 reproduces the classic layout; hb>1 is
    the small-head packing — see _heads_per_block)."""
    qi = pl.program_id(1)
    # matmul operands stay in their native (bf16 under AMP) dtype — the MXU
    # multiplies bf16 natively and accumulates f32 via
    # preferred_element_type; upcasting operands to f32 forces multi-pass
    # f32 matmuls at a fraction of peak (measured 2.2 -> 1.1 ms on the
    # B8 T1024 H16 D64 fwd+bwd microbench). Softmax statistics stay f32.
    q = q_ref[0]  # [hb, bq, d]
    hb, bq, d = q.shape
    t = k_ref.shape[2]
    n_blocks = t // block_k
    bdims = (((2,), (2,)), ((0,), (0,)))   # contract d, batch heads

    def body(j, carry):
        o, m, l = carry
        k = k_ref[0, :, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, :, pl.ds(j * block_k, block_k), :]
        logits = jax.lax.dot_general(
            q, k, bdims,
            preferred_element_type=jnp.float32) * scale  # [hb, bq, bk] f32
        if causal:
            logits = _causal_mask3(logits, qi, q_block, j, block_k, hb, bq)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((2,), (1,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        o_new = o * alpha[..., None] + pv
        return o_new, m_new, l_new

    o0 = jnp.zeros((hb, bq, d), jnp.float32)
    m0 = jnp.full((hb, bq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((hb, bq), jnp.float32)
    # causal: K-blocks entirely above the diagonal contribute nothing — skip
    # them (roughly halves the FLOPs; FlashAttention-2 loop bounds)
    hi = _causal_hi(qi, q_block, block_k, n_blocks) if causal else n_blocks
    o, m, l = lax.fori_loop(0, hi, body, (o0, m0, l0))
    l_safe = jnp.maximum(l, 1e-20)
    o_ref[0] = (o / l_safe[..., None]).astype(o_ref.dtype)
    # lse is laid out [bh/hb, hb, n_q_blocks, q_block]; the out block spans
    # ALL q-blocks (full last-two dims — the Mosaic sublane/lane rule) and
    # each sequential grid step writes its own row
    lse_ref[0, :, qi] = (m + jnp.log(l_safe)).astype(lse_ref.dtype)


def flash_attention_fwd(q, k, v, causal=False, scale=None,
                        q_block=None, k_block=None, interpret=None,
                        return_lse=False, heads_per_block=None):
    """q,k,v: [B, T, H, D] -> out [B, T, H, D] (and lse [B, T, H]).
    ``q_block``/``k_block``/``heads_per_block`` left None resolve through
    the tuning DB (TPU only) and fall back to the 512/512/auto defaults."""
    b, t, h, d = q.shape
    sc = scale if scale is not None else 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    q_block, k_block, heads_per_block = resolve_flash_config(
        t, h, d, q.dtype, q_block, k_block, heads_per_block)
    q_block = _fit_block(t, q_block)
    k_block = _fit_block(t, k_block)
    if q_block is None or k_block is None:
        # ragged tail: fall back to the dense path
        if not return_lse:
            from ..parallel.context_parallel import dense_attention

            return dense_attention(q, k, v, causal=causal, scale=scale)
        return _dense_attention_with_lse(q, k, v, causal, sc)
    hb = _heads_per_block(h, d, heads_per_block, t)
    g = b * h // hb

    def fold(x):
        return jnp.moveaxis(x, 2, 1).reshape(g, hb, t, d)

    qh, kh, vh = fold(q), fold(k), fold(v)

    kernel = functools.partial(_flash_kernel, scale=sc, block_k=k_block,
                               causal=causal, q_block=q_block)
    out, lse = pl.pallas_call(
        kernel,
        grid=(g, t // q_block),
        in_specs=[
            pl.BlockSpec((1, hb, q_block, d), lambda bh, i: (bh, 0, i, 0)),
            pl.BlockSpec((1, hb, t, d), lambda bh, i: (bh, 0, 0, 0)),
            pl.BlockSpec((1, hb, t, d), lambda bh, i: (bh, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hb, q_block, d), lambda bh, i: (bh, 0, i, 0)),
            pl.BlockSpec((1, hb, t // q_block, q_block),
                         lambda bh, i: (bh, 0, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, hb, t, d), q.dtype),
            jax.ShapeDtypeStruct((g, hb, t // q_block, q_block),
                                 jnp.float32),
        ],
        interpret=interpret,
    )(qh, kh, vh)
    out = jnp.moveaxis(out.reshape(b, h, t, d), 1, 2)
    if not return_lse:
        return out
    lse = jnp.moveaxis(lse.reshape(b, h, t), 1, 2)  # [B, T, H]
    return out, lse


def _dense_attention_with_lse(q, k, v, causal, sc):
    """One [B,H,T,T] logits pass yielding both the attention output and its
    per-query logsumexp (the fallback when the Pallas layout can't apply)."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sc
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    lse = jax.nn.logsumexp(logits, axis=-1)  # [B,H,T]
    p = jnp.exp(logits - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
    return out, jnp.moveaxis(lse, 1, 2)  # out [B,T,H,D], lse [B,T,H]


# ---------------------------------------------------------------------------
# backward (FlashAttention-2): dQ kernel over K-blocks, dK/dV kernel over
# Q-blocks; P is reconstructed from the saved LSE, delta = rowsum(dO * O).
# ---------------------------------------------------------------------------


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, scale, block_k, causal, q_block):
    qi = pl.program_id(1)
    q = q_ref[0]      # [hb, bq, d] native dtype (bf16 under AMP)
    do = do_ref[0]    # [hb, bq, d]
    lse = lse_ref[0, :, qi].astype(jnp.float32)      # [hb, bq]
    delta = delta_ref[0, :, qi].astype(jnp.float32)  # [hb, bq]
    hb, bq, d = q.shape
    t = k_ref.shape[2]
    n_blocks = t // block_k
    bdims = (((2,), (2,)), ((0,), (0,)))

    def body(j, dq):
        k = k_ref[0, :, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, :, pl.ds(j * block_k, block_k), :]
        logits = jax.lax.dot_general(
            q, k, bdims, preferred_element_type=jnp.float32) * scale
        if causal:
            logits = _causal_mask3(logits, qi, q_block, j, block_k, hb, bq)
        p = jnp.exp(logits - lse[..., None])                 # [hb, bq, bk]
        dp = jax.lax.dot_general(do, v, bdims,
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale             # [hb, bq, bk]
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    hi = _causal_hi(qi, q_block, block_k, n_blocks) if causal else n_blocks
    dq = lax.fori_loop(0, hi, body, jnp.zeros((hb, bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, scale, block_q, causal, k_block):
    ki = pl.program_id(1)
    k = k_ref[0]  # [hb, bk, d] native dtype (bf16 under AMP)
    v = v_ref[0]  # [hb, bk, d]
    hb, bk, d = k.shape
    t = q_ref.shape[2]
    n_blocks = t // block_q
    bdims = (((2,), (2,)), ((0,), (0,)))

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, :, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, :, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, :, i].astype(jnp.float32)      # [hb, bq]
        delta = delta_ref[0, :, i].astype(jnp.float32)  # [hb, bq]
        logits = jax.lax.dot_general(
            q, k, bdims, preferred_element_type=jnp.float32) * scale
        if causal:
            logits = _causal_mask3(logits, i, block_q, ki, bk, hb, block_q)
        p = jnp.exp(logits - lse[..., None])             # [hb, bq, bk]
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, bdims,
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale
        dk = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((hb, bk, d), jnp.float32)
    dv0 = jnp.zeros((hb, bk, d), jnp.float32)
    # causal: Q-blocks entirely before this K-block see none of it — skip
    lo = (ki * k_block) // block_q if causal else 0
    dk, dv = lax.fori_loop(lo, n_blocks, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _dense_bwd_with_lse(q, k, v, out, lse, do, causal, sc):
    """FA-2 backward math in dense form, honoring the PROVIDED lse — the
    probabilities p = exp(s - lse) may be normalized against a *global*
    softmax (ring attention blocks), so this must not renormalize locally.
    q/do: [B,Tq,H,D]; k/v: [B,Tk,H,D]; out/lse from the global merge."""
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * sc
    p = jnp.exp(s - jnp.moveaxis(lse, 1, 2)[..., None])  # [B,H,Tq,Tk]
    if causal:
        tq, tk = p.shape[-2], p.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        p = jnp.where(mask[None, None], p, 0.0)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
    dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vf)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # [B,Tq,H]
    ds = p * (dp - jnp.moveaxis(delta, 1, 2)[..., None]) * sc
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kf)
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def flash_attention_bwd(q, k, v, out, lse, do, causal=False, scale=None,
                        q_block=None, k_block=None, interpret=None,
                        heads_per_block=None):
    """FlashAttention-2 backward. All of q/k/v/out/do: [B, T, H, D];
    lse: [B, T, H]. Returns (dq, dk, dv). The provided lse is honored as-is
    (it may be a globally-merged ring LSE), including in the ragged-shape
    dense fallback. None knobs resolve like the forward's (the lse is a
    per-query scalar whose [n_q, q_block] staging is a pure reshape, so
    fwd and bwd need not even agree on blocks to stay correct)."""
    b, t, h, d = q.shape
    sc = scale if scale is not None else 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    q_block, k_block, heads_per_block = resolve_flash_config(
        t, h, d, q.dtype, q_block, k_block, heads_per_block)
    q_block = _fit_block(t, q_block)
    k_block = _fit_block(t, k_block)
    if q_block is None or k_block is None:
        return _dense_bwd_with_lse(q, k, v, out, lse, do, causal, sc)
    hb = _heads_per_block(h, d, heads_per_block, t)
    g = b * h // hb

    def fold(x):
        return jnp.moveaxis(x, 2, 1).reshape(g, hb, t, -1)

    qh, kh, vh, doh = fold(q), fold(k), fold(v), fold(do)
    # lse/delta in the [g, hb, n_q_blocks, q_block] layout the kernels
    # block on
    n_q = t // q_block
    lseh = jnp.moveaxis(lse, 2, 1).reshape(g, hb, n_q, q_block)
    delta = jnp.sum(doh.astype(jnp.float32)
                    * fold(out).astype(jnp.float32),
                    axis=-1).reshape(g, hb, n_q, q_block)

    dq_kernel = functools.partial(_flash_bwd_dq_kernel, scale=sc,
                                  block_k=k_block, causal=causal,
                                  q_block=q_block)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(g, t // q_block),
        in_specs=[
            pl.BlockSpec((1, hb, q_block, d), lambda bh, i: (bh, 0, i, 0)),
            pl.BlockSpec((1, hb, t, d), lambda bh, i: (bh, 0, 0, 0)),
            pl.BlockSpec((1, hb, t, d), lambda bh, i: (bh, 0, 0, 0)),
            pl.BlockSpec((1, hb, q_block, d), lambda bh, i: (bh, 0, i, 0)),
            pl.BlockSpec((1, hb, n_q, q_block), lambda bh, i: (bh, 0, 0, 0)),
            pl.BlockSpec((1, hb, n_q, q_block), lambda bh, i: (bh, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hb, q_block, d),
                               lambda bh, i: (bh, 0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((g, hb, t, d), q.dtype),
        interpret=interpret,
    )(qh, kh, vh, doh, lseh, delta)

    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, scale=sc,
                                   block_q=q_block, causal=causal,
                                   k_block=k_block)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(g, t // k_block),
        in_specs=[
            pl.BlockSpec((1, hb, t, d), lambda bh, j: (bh, 0, 0, 0)),
            pl.BlockSpec((1, hb, k_block, d), lambda bh, j: (bh, 0, j, 0)),
            pl.BlockSpec((1, hb, k_block, d), lambda bh, j: (bh, 0, j, 0)),
            pl.BlockSpec((1, hb, t, d), lambda bh, j: (bh, 0, 0, 0)),
            pl.BlockSpec((1, hb, n_q, q_block), lambda bh, j: (bh, 0, 0, 0)),
            pl.BlockSpec((1, hb, n_q, q_block), lambda bh, j: (bh, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hb, k_block, d), lambda bh, j: (bh, 0, j, 0)),
            pl.BlockSpec((1, hb, k_block, d), lambda bh, j: (bh, 0, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, hb, t, d), k.dtype),
            jax.ShapeDtypeStruct((g, hb, t, d), v.dtype),
        ],
        interpret=interpret,
    )(qh, kh, vh, doh, lseh, delta)

    def unfold(x):
        return jnp.moveaxis(x.reshape(b, h, t, d), 1, 2)

    return unfold(dq), unfold(dk), unfold(dv)


def _dense_bwd(q, k, v, do, causal, scale):
    from ..parallel.context_parallel import dense_attention

    _, vjp = jax.vjp(
        lambda q, k, v: dense_attention(q, k, v, causal=causal, scale=scale),
        q, k, v)
    return vjp(do)


# ---------------------------------------------------------------------------
# op registration
# ---------------------------------------------------------------------------


def _flash_grad_maker(op, no_grad_set):
    return [{
        "type": "flash_attention_grad",
        "inputs": {
            "Q": list(op.inputs["Q"]),
            "K": list(op.inputs["K"]),
            "V": list(op.inputs["V"]),
            "Out": list(op.outputs["Out"]),
            "LSE": list(op.outputs.get("LSE", [])),
            "Out@GRAD": [grad_var_name(n) for n in op.outputs["Out"]],
        },
        "outputs": {
            s + "@GRAD": ["" if n in no_grad_set else grad_var_name(n)
                          for n in op.inputs[s]]
            for s in ("Q", "K", "V")
        },
        "attrs": dict(op.attrs),
    }]


@register_op("flash_attention", inputs=("Q", "K", "V"), outputs=("Out", "LSE"),
             grad_maker=_flash_grad_maker)
def flash_attention_op(ctx, ins, attrs):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    causal = attrs.get("causal", False)
    scale = attrs.get("scale")
    if getattr(ctx, "in_remat", False):
        # inside a recompute segment the segment body is differentiated by
        # jax.vjp directly (not via IR grad ops), and a bare pallas_call has
        # no AD rule — so use the custom_vjp entry point: remat replays the
        # Pallas forward as a unit and the FA-2 backward kernels provide the
        # grads. The LSE residual is grad-irrelevant here (grads flow
        # through the custom_vjp, and nothing outside the segment reads the
        # LSE of an op inside it), so emit a stop_gradient placeholder
        # rather than paying a second pass to extract it. NaN, not zeros:
        # if the no-outside-reader assumption is ever violated the consumer
        # fails loudly instead of silently computing with zeros.
        out = flash_attention(q, k, v, causal, scale,
                              attrs.get("q_block"),
                              attrs.get("k_block"),
                              attrs.get("heads_per_block"))
        lse = lax.stop_gradient(jnp.full(q.shape[:3], jnp.nan, jnp.float32))
        return {"Out": [out], "LSE": [lse]}
    out, lse = flash_attention_fwd(
        q, k, v, causal=causal, scale=scale,
        q_block=attrs.get("q_block"), k_block=attrs.get("k_block"),
        return_lse=True, heads_per_block=attrs.get("heads_per_block"),
    )
    return {"Out": [out], "LSE": [lse]}


@register_op("flash_attention_grad",
             inputs=("Q", "K", "V", "Out", "LSE", "Out@GRAD"),
             outputs=("Q@GRAD", "K@GRAD", "V@GRAD"), no_grad=True)
def flash_attention_grad_op(ctx, ins, attrs):
    """FlashAttention-2 backward kernels (dense-vjp fallback for ragged
    shapes or remat segments)."""
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    g = ins["Out@GRAD"][0]
    causal = attrs.get("causal", False)
    scale = attrs.get("scale")
    out = ins["Out"][0] if ins.get("Out") and ins["Out"][0] is not None else None
    lse = ins["LSE"][0] if ins.get("LSE") and ins["LSE"][0] is not None else None
    if out is None or lse is None or getattr(ctx, "in_remat", False):
        gq, gk, gv = _dense_bwd(q, k, v, g, causal, scale)
    else:
        gq, gk, gv = flash_attention_bwd(
            q, k, v, out, lse, g, causal=causal, scale=scale,
            q_block=attrs.get("q_block"),
            k_block=attrs.get("k_block"),
            heads_per_block=attrs.get("heads_per_block"))
    return {"Q@GRAD": [gq], "K@GRAD": [gk], "V@GRAD": [gv]}


# ---------------------------------------------------------------------------
# jax-level differentiable entry point: pallas_call has no automatic jvp/vjp,
# so raw-jax users (and future ring/flash composition) get a custom_vjp
# pairing the forward and FA-2 backward kernels. The IR-level op above keeps
# its own grad maker (the executor path doesn't go through jax.grad).
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, scale=None, q_block=None,
                    k_block=None, heads_per_block=None):
    """Differentiable flash attention over [B, T, H, D] (jax.grad-ready).
    None block knobs resolve through the tuning DB, else 512/512/auto."""
    return flash_attention_fwd(q, k, v, causal=causal, scale=scale,
                               q_block=q_block, k_block=k_block,
                               heads_per_block=heads_per_block)


def _fa_fwd(q, k, v, causal, scale, q_block, k_block, heads_per_block):
    out, lse = flash_attention_fwd(q, k, v, causal=causal, scale=scale,
                                   q_block=q_block, k_block=k_block,
                                   return_lse=True,
                                   heads_per_block=heads_per_block)
    # Name the kernel outputs for selective remat: under
    # layers.recompute(policy="flash") (save_only_these_names) the segment
    # replay keeps these two residuals and NEVER re-runs the Pallas
    # forward in the backward — the r4 longcontext profile's biggest
    # unexplored delta ("rematerializes as a UNIT that no policy can
    # split", docs/perf.md). Outside a named policy checkpoint_name is
    # identity.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, q_block, k_block, heads_per_block, res, g):
    q, k, v, out, lse = res
    return flash_attention_bwd(q, k, v, out, lse, g, causal=causal,
                               scale=scale, q_block=q_block, k_block=k_block,
                               heads_per_block=heads_per_block)


flash_attention.defvjp(_fa_fwd, _fa_bwd)

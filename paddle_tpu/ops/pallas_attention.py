"""Pallas TPU flash attention.

The reference has no attention op at all — its transformer benchmark builds
attention from matmul+softmax primitives (SURVEY.md §5.7). Here attention is
a first-class op whose forward is a Pallas kernel: per (batch*head, q-block)
grid cell, K/V stream through VMEM in blocks under an online-softmax
accumulator, so the [Tq, Tk] logits matrix never materializes in HBM —
the flash-attention memory profile the MXU wants.

Backward (round 1): recompute through the dense formulation under jax.vjp —
correct, and XLA still fuses it reasonably; a Pallas backward kernel is a
planned optimization.

On non-TPU backends the same kernel runs in interpreter mode (tests), so
numerical behavior is identical everywhere.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..core.ir import grad_var_name
from ..core.registry import register_op

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, block_k, causal, q_block):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [bq, d]
    bq, d = q.shape
    t = k_ref.shape[1]
    n_blocks = t // block_k

    def body(j, carry):
        o, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            q_pos = qi * q_block + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            logits = jnp.where(q_pos >= k_pos, logits, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        o_new = o * alpha[:, None] + pv
        return o_new, m_new, l_new

    o0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    o, m, l = lax.fori_loop(0, n_blocks, body, (o0, m0, l0))
    o_ref[0] = (o / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)


def flash_attention_fwd(q, k, v, causal=False, scale=None,
                        q_block=128, k_block=128, interpret=None):
    """q,k,v: [B, T, H, D] -> [B, T, H, D]."""
    b, t, h, d = q.shape
    sc = scale if scale is not None else 1.0 / (d ** 0.5)
    if interpret is None:
        # interpret anywhere except a real TPU (jax.default_device overrides
        # the backend the computation actually lands on)
        dev = jax.config.jax_default_device
        platform = dev.platform if dev is not None else jax.default_backend()
        interpret = platform != "tpu"
    q_block = min(q_block, t)
    k_block = min(k_block, t)
    if t % q_block or t % k_block:
        # ragged tail: fall back to the dense path
        from ..parallel.context_parallel import dense_attention

        return dense_attention(q, k, v, causal=causal, scale=scale)

    qh = jnp.moveaxis(q, 2, 1).reshape(b * h, t, d)
    kh = jnp.moveaxis(k, 2, 1).reshape(b * h, t, d)
    vh = jnp.moveaxis(v, 2, 1).reshape(b * h, t, d)

    kernel = functools.partial(_flash_kernel, scale=sc, block_k=k_block,
                               causal=causal, q_block=q_block)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // q_block),
        in_specs=[
            pl.BlockSpec((1, q_block, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, t, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, q_block, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qh, kh, vh)
    return jnp.moveaxis(out.reshape(b, h, t, d), 1, 2)


def _flash_grad_maker(op, no_grad_set):
    return [{
        "type": "flash_attention_grad",
        "inputs": {
            "Q": list(op.inputs["Q"]),
            "K": list(op.inputs["K"]),
            "V": list(op.inputs["V"]),
            "Out@GRAD": [grad_var_name(n) for n in op.outputs["Out"]],
        },
        "outputs": {
            s + "@GRAD": ["" if n in no_grad_set else grad_var_name(n)
                          for n in op.inputs[s]]
            for s in ("Q", "K", "V")
        },
        "attrs": dict(op.attrs),
    }]


@register_op("flash_attention", inputs=("Q", "K", "V"), outputs=("Out",),
             grad_maker=_flash_grad_maker)
def flash_attention_op(ctx, ins, attrs):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    if getattr(ctx, "in_remat", False):
        # inside a recompute segment: pallas_call can't trace under
        # jax.checkpoint — use the exact XLA-composed attention instead
        from ..parallel.context_parallel import dense_attention

        return {"Out": [dense_attention(q, k, v,
                                        causal=attrs.get("causal", False),
                                        scale=attrs.get("scale"))]}
    return {"Out": [flash_attention_fwd(
        q, k, v,
        causal=attrs.get("causal", False),
        scale=attrs.get("scale"),
        q_block=attrs.get("q_block", 128),
        k_block=attrs.get("k_block", 128),
    )]}


@register_op("flash_attention_grad",
             inputs=("Q", "K", "V", "Out@GRAD"),
             outputs=("Q@GRAD", "K@GRAD", "V@GRAD"), no_grad=True)
def flash_attention_grad_op(ctx, ins, attrs):
    """Backward: dense recompute under jax.vjp (flash bwd kernel planned)."""
    from ..parallel.context_parallel import dense_attention

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    g = ins["Out@GRAD"][0]
    _, vjp = jax.vjp(
        lambda q, k, v: dense_attention(q, k, v,
                                        causal=attrs.get("causal", False),
                                        scale=attrs.get("scale")),
        q, k, v)
    gq, gk, gv = vjp(g)
    return {"Q@GRAD": [gq], "K@GRAD": [gk], "V@GRAD": [gv]}

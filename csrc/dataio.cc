// Native data pipeline: buddy-allocated batch buffers + multi-file
// shuffle/batch/prefetch readers over RecordIO files.
//
// Re-implements, TPU-host-side, the reference's native data plane:
//   * memory/detail/buddy_allocator.h:33 (BuddyAllocator over a SystemAllocator
//     arena) -> `pt_buddy_*`: power-of-two buddy system backing the batch
//     staging buffers handed to the feeder (the role pinned host memory
//     played for GPU transfers).
//   * operators/reader/create_shuffle_reader_op.cc (buffered shuffle),
//     create_batch_reader_op.cc (batch assembly),
//     create_double_buffer_reader_op.cc:39 + blocking_queue.h (prefetch
//     thread + bounded queue), open_files/multi-file reading ->
//     `dio_pipeline_*`: worker threads scan RecordIO shards, shuffle within a
//     reservoir, pack fixed-size records into contiguous batch buffers.
//
// Records must be fixed-size (record_bytes) — the dense-tensor case the
// batcher packs without copies on the Python side; the variable-size case
// stays on the per-record rio_* API.
#include "recordio.cc"  // reuse crc/scanner + the extern "C" record API

#include <algorithm>
#include <atomic>
#include <random>

namespace {

// --- buddy allocator (<- memory/detail/buddy_allocator.h) ------------------
struct Buddy {
  std::vector<uint8_t> arena;
  size_t min_log2;
  size_t levels;                          // arena_log2 - min_log2 + 1
  std::vector<std::vector<size_t>> free_; // per-level free block offsets
  // offset -> level, for frees and double-free detection
  std::vector<int8_t> level_of;           // indexed by offset >> min_log2
  std::mutex mu;
  size_t used = 0;

  static size_t log2ceil(size_t v) {
    size_t l = 0;
    while ((size_t(1) << l) < v) l++;
    return l;
  }

  Buddy(size_t total, size_t min_block) {
    size_t total_log2 = log2ceil(total);
    min_log2 = log2ceil(min_block < 16 ? 16 : min_block);
    if (total_log2 < min_log2) total_log2 = min_log2;
    arena.resize(size_t(1) << total_log2);
    levels = total_log2 - min_log2 + 1;
    free_.resize(levels);
    free_[levels - 1].push_back(0);  // one max-size block
    level_of.assign(size_t(1) << (total_log2 - min_log2), -1);
  }

  void* alloc(size_t n) {
    if (n == 0) n = 1;
    size_t want = log2ceil(n);
    if (want < min_log2) want = min_log2;
    size_t lvl = want - min_log2;
    if (lvl >= levels) return nullptr;
    std::lock_guard<std::mutex> g(mu);
    size_t l = lvl;
    while (l < levels && free_[l].empty()) l++;
    if (l == levels) return nullptr;  // out of memory
    size_t off = free_[l].back();
    free_[l].pop_back();
    while (l > lvl) {  // split down, freeing the upper buddy
      l--;
      size_t buddy_off = off + (size_t(1) << (l + min_log2));
      free_[l].push_back(buddy_off);
    }
    level_of[off >> min_log2] = static_cast<int8_t>(lvl);
    used += size_t(1) << (lvl + min_log2);
    return arena.data() + off;
  }

  int free_block(void* p) {
    std::lock_guard<std::mutex> g(mu);
    size_t off = static_cast<uint8_t*>(p) - arena.data();
    size_t idx = off >> min_log2;
    if (idx >= level_of.size() || level_of[idx] < 0) return -1;  // bad/double free
    size_t lvl = level_of[idx];
    level_of[idx] = -1;
    used -= size_t(1) << (lvl + min_log2);
    // coalesce with free buddies upward (<- buddy_allocator merge)
    while (lvl + 1 < levels) {
      size_t size = size_t(1) << (lvl + min_log2);
      size_t buddy = off ^ size;
      auto& fl = free_[lvl];
      auto it = std::find(fl.begin(), fl.end(), buddy);
      if (it == fl.end()) break;
      fl.erase(it);
      off = std::min(off, buddy);
      lvl++;
    }
    free_[lvl].push_back(off);
    return 0;
  }
};

// --- shuffle/batch/prefetch pipeline ---------------------------------------
struct Pipeline {
  std::vector<std::string> files;
  uint32_t record_bytes;
  uint32_t batch_size;
  uint32_t shuffle_buf;  // 0 = no shuffle
  bool drop_last;
  Buddy* buddy;          // owns batch buffers
  bool own_buddy;

  std::deque<uint8_t*> ready;  // filled batch buffers
  size_t capacity = 8;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  bool done = false;
  // read lock-free by the worker's scan loop; also part of cv predicates
  std::atomic<bool> closed{false};
  std::string error;
  std::thread worker;
  uint8_t* current = nullptr;    // buffer owned by the consumer
  uint8_t* tail_buf = nullptr;   // the one zero-padded short batch, if any
  uint32_t tail_count = 0;       // its true record count

  ~Pipeline() {
    {
      std::lock_guard<std::mutex> g(mu);
      closed = true;
    }
    cv_push.notify_all();
    cv_pop.notify_all();
    if (worker.joinable()) worker.join();
    if (current) buddy->free_block(current);
    for (auto* b : ready) buddy->free_block(b);
    if (own_buddy) delete buddy;
  }

  void emit(uint8_t* buf) {
    std::unique_lock<std::mutex> lk(mu);
    cv_push.wait(lk, [this] { return closed || ready.size() < capacity; });
    if (closed) {
      buddy->free_block(buf);
      return;
    }
    ready.push_back(buf);
    cv_pop.notify_one();
  }

  void run(uint64_t seed) {
    std::mt19937_64 rng(seed);
    std::vector<std::vector<uint8_t>> reservoir;  // shuffle buffer
    uint8_t* batch = nullptr;
    uint32_t in_batch = 0;

    auto push_record = [&](const uint8_t* rec) {
      if (!batch) {
        batch = static_cast<uint8_t*>(
            buddy->alloc(size_t(batch_size) * record_bytes));
        if (!batch) {
          std::lock_guard<std::mutex> g(mu);
          error = "buddy arena exhausted";
          closed = true;
          return false;
        }
        in_batch = 0;
      }
      memcpy(batch + size_t(in_batch) * record_bytes, rec, record_bytes);
      if (++in_batch == batch_size) {
        emit(batch);
        batch = nullptr;
      }
      return true;
    };

    auto feed = [&](const uint8_t* rec) {
      if (shuffle_buf == 0) return push_record(rec);
      if (reservoir.size() < shuffle_buf) {
        reservoir.emplace_back(rec, rec + record_bytes);
        return true;
      }
      // swap a random resident out (create_shuffle_reader buffered shuffle)
      size_t j = rng() % reservoir.size();
      std::vector<uint8_t> out = std::move(reservoir[j]);
      reservoir[j].assign(rec, rec + record_bytes);
      return push_record(out.data());
    };

    std::vector<size_t> order(files.size());
    for (size_t i = 0; i < order.size(); i++) order[i] = i;
    if (shuffle_buf) std::shuffle(order.begin(), order.end(), rng);

    for (size_t fi : order) {
      if (closed) break;
      void* sc = rio_scanner_open(files[fi].c_str());
      if (!sc) {
        // like the CRC path: stop emitting entirely — draining the reservoir
        // would hand the consumer shuffled partial data before the error
        std::lock_guard<std::mutex> g(mu);
        error = "cannot open " + files[fi];
        goto finish;
      }
      uint32_t len;
      const uint8_t* rec;
      while (!closed && (rec = rio_next(sc, &len)) != nullptr) {
        if (len != record_bytes) {
          std::lock_guard<std::mutex> g(mu);
          error = "record size mismatch in " + files[fi];
          rio_scanner_close(sc);
          goto finish;
        }
        if (!feed(rec)) break;
      }
      // nullptr from rio_next is EOF only when the scanner reports no
      // error; a CRC/truncation failure must not silently truncate data
      const char* scan_err = rio_scanner_error(sc);
      if (scan_err && *scan_err) {
        std::lock_guard<std::mutex> g(mu);
        error = std::string(scan_err) + " in " + files[fi];
        rio_scanner_close(sc);
        goto finish;
      }
      rio_scanner_close(sc);
    }
    // drain the reservoir in random order
    if (shuffle_buf) {
      std::shuffle(reservoir.begin(), reservoir.end(), rng);
      for (auto& r : reservoir) {
        if (closed) break;
        if (!push_record(r.data())) break;
      }
    }
    if (batch && !closed) {
      if (drop_last || in_batch == 0) {
        buddy->free_block(batch);
      } else {
        // zero-pad the tail so the buffer is fully defined; tag the buffer
        // itself with its true count BEFORE emitting so the consumer can
        // never observe it untagged (timing-independent, unlike inferring
        // from done/queue-empty)
        memset(batch + size_t(in_batch) * record_bytes, 0,
               size_t(batch_size - in_batch) * record_bytes);
        {
          std::lock_guard<std::mutex> g(mu);
          tail_buf = batch;
          tail_count = in_batch;
        }
        emit(batch);
      }
      batch = nullptr;
    }
  finish : {
    std::lock_guard<std::mutex> g(mu);
    done = true;
    cv_pop.notify_all();
  }
  }
};

}  // namespace

extern "C" {

// ---- buddy allocator ----
void* pt_buddy_create(uint64_t total_bytes, uint64_t min_block) {
  return new Buddy(total_bytes, min_block);
}
void* pt_buddy_alloc(void* h, uint64_t n) { return static_cast<Buddy*>(h)->alloc(n); }
int pt_buddy_free(void* h, void* p) { return static_cast<Buddy*>(h)->free_block(p); }
uint64_t pt_buddy_used(void* h) {
  auto* b = static_cast<Buddy*>(h);
  std::lock_guard<std::mutex> g(b->mu);
  return b->used;
}
uint64_t pt_buddy_capacity(void* h) { return static_cast<Buddy*>(h)->arena.size(); }
void pt_buddy_destroy(void* h) { delete static_cast<Buddy*>(h); }

// ---- pipeline ----
// paths: '\n'-separated file list. Returns nullptr on immediate failure.
void* dio_pipeline_open(const char* paths, uint32_t record_bytes,
                        uint32_t batch_size, uint32_t shuffle_buf,
                        uint64_t seed, uint32_t capacity, int drop_last,
                        uint64_t arena_bytes) {
  auto* p = new Pipeline();
  const char* s = paths;
  while (*s) {
    const char* e = strchr(s, '\n');
    if (!e) e = s + strlen(s);
    if (e > s) p->files.emplace_back(s, e - s);
    s = *e ? e + 1 : e;
  }
  if (p->files.empty() || record_bytes == 0 || batch_size == 0) {
    delete p;
    return nullptr;
  }
  p->record_bytes = record_bytes;
  p->batch_size = batch_size;
  p->shuffle_buf = shuffle_buf;
  p->drop_last = drop_last != 0;
  if (capacity) p->capacity = capacity;
  // buddy blocks are power-of-two: size the arena in rounded-up blocks so
  // capacity+2 batches always fit
  size_t block = size_t(1) << Buddy::log2ceil(size_t(batch_size) * record_bytes);
  size_t need = block * (p->capacity + 2);
  if (arena_bytes < need) arena_bytes = need;
  p->buddy = new Buddy(arena_bytes, 256);
  p->own_buddy = true;
  p->worker = std::thread([p, seed] { p->run(seed); });
  return p;
}

// Blocking: returns the next batch buffer (batch_size*record_bytes bytes,
// valid until the following call) or nullptr at end/error. *count receives
// the number of real records in the batch (== batch_size except a padded
// final batch).
const uint8_t* dio_pipeline_next(void* h, uint32_t* count) {
  auto* p = static_cast<Pipeline*>(h);
  std::unique_lock<std::mutex> lk(p->mu);
  if (p->current) {
    auto* c = p->current;
    p->current = nullptr;
    lk.unlock();
    p->buddy->free_block(c);
    lk.lock();
  }
  p->cv_pop.wait(lk, [p] { return p->done || !p->ready.empty(); });
  if (p->ready.empty()) return nullptr;
  p->current = p->ready.front();
  p->ready.pop_front();
  p->cv_push.notify_one();
  // the padded tail batch is tagged by pointer; every other batch is full
  *count = (p->current == p->tail_buf) ? p->tail_count : p->batch_size;
  return p->current;
}

const char* dio_pipeline_error(void* h) {
  auto* p = static_cast<Pipeline*>(h);
  std::lock_guard<std::mutex> g(p->mu);
  return p->error.c_str();
}

uint64_t dio_pipeline_mem_used(void* h) {
  auto* p = static_cast<Pipeline*>(h);
  std::lock_guard<std::mutex> g(p->buddy->mu);
  return p->buddy->used;
}

void dio_pipeline_close(void* h) { delete static_cast<Pipeline*>(h); }

}  // extern "C"

// RecordIO: chunked binary record file format + threaded prefetch loader.
//
// Native re-implementation of the reference's recordio library
// (paddle/fluid/recordio/{header,chunk,writer,scanner}.h, ~710 LoC) and the
// prefetching side of the reader op stack
// (operators/reader/create_double_buffer_reader_op.cc:39 — a background
// thread filling a blocking queue; operators/reader/blocking_queue.h).
//
// File layout:
//   [8-byte magic "PTRIO\x01\0\0"]
//   chunk*:
//     u32 num_records | u32 payload_len | u32 crc32(payload) | payload
//     payload = (u32 record_len | bytes)*
//
// Exposed as a C API consumed from Python via ctypes
// (paddle_tpu/recordio.py). No Python objects cross the boundary: records
// are length-prefixed byte buffers.
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr char kMagic[8] = {'P', 'T', 'R', 'I', 'O', 1, 0, 0};
constexpr uint32_t kDefaultChunkRecords = 1000;
constexpr size_t kDefaultChunkBytes = 1 << 20;

// --- crc32 (IEEE, table-driven) ------------------------------------------
uint32_t crc_table[256];
bool crc_init_done = [] {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  return true;
}();

uint32_t crc32(const uint8_t* buf, size_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Writer {
  FILE* f = nullptr;
  std::vector<uint8_t> payload;
  uint32_t num_records = 0;
  uint32_t max_records = kDefaultChunkRecords;
  size_t max_bytes = kDefaultChunkBytes;

  void flush_chunk() {
    if (num_records == 0) return;
    uint32_t len = static_cast<uint32_t>(payload.size());
    uint32_t crc = crc32(payload.data(), payload.size());
    fwrite(&num_records, 4, 1, f);
    fwrite(&len, 4, 1, f);
    fwrite(&crc, 4, 1, f);
    fwrite(payload.data(), 1, payload.size(), f);
    payload.clear();
    num_records = 0;
  }
};

struct Scanner {
  FILE* f = nullptr;
  std::vector<uint8_t> chunk;       // current decoded payload
  size_t pos = 0;                   // cursor within chunk
  uint32_t remaining = 0;           // records left in current chunk
  std::string error;

  bool load_chunk() {
    uint32_t hdr[3];
    if (fread(hdr, 4, 3, f) != 3) return false;  // EOF
    chunk.resize(hdr[1]);
    if (fread(chunk.data(), 1, hdr[1], f) != hdr[1]) {
      error = "truncated chunk";
      return false;
    }
    if (crc32(chunk.data(), chunk.size()) != hdr[2]) {
      error = "crc mismatch";
      return false;
    }
    remaining = hdr[0];
    pos = 0;
    return true;
  }
};

// --- threaded prefetch loader --------------------------------------------
struct Loader {
  std::deque<std::vector<uint8_t>> queue;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  size_t capacity = 64;
  bool done = false;
  bool closed = false;
  std::thread worker;
  std::vector<uint8_t> current;  // last record handed to the consumer

  ~Loader() {
    {
      std::lock_guard<std::mutex> g(mu);
      closed = true;
    }
    cv_push.notify_all();
    cv_pop.notify_all();
    if (worker.joinable()) worker.join();
  }
};

}  // namespace

extern "C" {

// ---- writer ----
void* rio_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  fwrite(kMagic, 1, 8, f);
  auto* w = new Writer();
  w->f = f;
  return w;
}

int rio_write(void* handle, const uint8_t* data, uint32_t len) {
  auto* w = static_cast<Writer*>(handle);
  if (!w || !w->f) return -1;
  uint32_t l = len;
  const uint8_t* lp = reinterpret_cast<const uint8_t*>(&l);
  w->payload.insert(w->payload.end(), lp, lp + 4);
  w->payload.insert(w->payload.end(), data, data + len);
  w->num_records++;
  if (w->num_records >= w->max_records || w->payload.size() >= w->max_bytes)
    w->flush_chunk();
  return 0;
}

void rio_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  if (!w) return;
  w->flush_chunk();
  fclose(w->f);
  delete w;
}

// ---- scanner ----
void* rio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  char magic[8];
  if (fread(magic, 1, 8, f) != 8 || memcmp(magic, kMagic, 8) != 0) {
    fclose(f);
    return nullptr;
  }
  auto* s = new Scanner();
  s->f = f;
  return s;
}

// Returns pointer to record bytes (valid until next call) or nullptr at EOF/
// error; *len receives the record size.
const uint8_t* rio_next(void* handle, uint32_t* len) {
  auto* s = static_cast<Scanner*>(handle);
  if (!s) return nullptr;
  while (s->remaining == 0) {
    if (!s->load_chunk()) return nullptr;
  }
  uint32_t l;
  memcpy(&l, s->chunk.data() + s->pos, 4);
  const uint8_t* rec = s->chunk.data() + s->pos + 4;
  s->pos += 4 + l;
  s->remaining--;
  *len = l;
  return rec;
}

const char* rio_scanner_error(void* handle) {
  auto* s = static_cast<Scanner*>(handle);
  return s ? s->error.c_str() : "null scanner";
}

void rio_scanner_close(void* handle) {
  auto* s = static_cast<Scanner*>(handle);
  if (!s) return;
  fclose(s->f);
  delete s;
}

// ---- prefetch loader ----
void* rio_loader_open(const char* path, uint32_t capacity) {
  auto* ld = new Loader();
  if (capacity) ld->capacity = capacity;
  std::string p(path);
  ld->worker = std::thread([ld, p]() {
    void* sc = rio_scanner_open(p.c_str());
    if (sc) {
      uint32_t len;
      const uint8_t* rec;
      while ((rec = rio_next(sc, &len)) != nullptr) {
        std::unique_lock<std::mutex> lk(ld->mu);
        ld->cv_push.wait(lk, [ld] {
          return ld->closed || ld->queue.size() < ld->capacity;
        });
        if (ld->closed) break;
        ld->queue.emplace_back(rec, rec + len);
        ld->cv_pop.notify_one();
      }
      rio_scanner_close(sc);
    }
    std::lock_guard<std::mutex> g(ld->mu);
    ld->done = true;
    ld->cv_pop.notify_all();
  });
  return ld;
}

// Blocking pop; returns nullptr when the file is exhausted.
const uint8_t* rio_loader_next(void* handle, uint32_t* len) {
  auto* ld = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(ld->mu);
  ld->cv_pop.wait(lk, [ld] { return ld->done || !ld->queue.empty(); });
  if (ld->queue.empty()) return nullptr;
  ld->current = std::move(ld->queue.front());
  ld->queue.pop_front();
  ld->cv_push.notify_one();
  *len = static_cast<uint32_t>(ld->current.size());
  return ld->current.data();
}

void rio_loader_close(void* handle) { delete static_cast<Loader*>(handle); }

}  // extern "C"
